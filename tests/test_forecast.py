"""Forecast subsystem: ring-buffer history, the three JAX forecasters
(jit-compiled, exact on their model classes), the predictive policy's
warm-up/conservative/scoreboard behavior, and the loop integration —
a predictive loop scales up before the backlog the reactive loop waits for.
"""

import numpy as np
import pytest

from kube_sqs_autoscaler_tpu.core.clock import FakeClock
from kube_sqs_autoscaler_tpu.core.events import (
    CompositeTickObserver,
    TickRecord,
)
from kube_sqs_autoscaler_tpu.core.loop import ControlLoop, LoopConfig
from kube_sqs_autoscaler_tpu.core.policy import PolicyConfig
from kube_sqs_autoscaler_tpu.forecast import (
    DepthHistory,
    EwmaForecaster,
    HoltForecaster,
    LeastSquaresForecaster,
    PredictivePolicy,
    ReactivePolicy,
    make_forecaster,
)
from kube_sqs_autoscaler_tpu.metrics import FakeQueueService, QueueMetricSource
from kube_sqs_autoscaler_tpu.scale import FakeDeploymentAPI, PodAutoScaler

# --------------------------------------------------------------------------
# DepthHistory


def test_history_fills_then_wraps_chronologically():
    h = DepthHistory(capacity=4)
    for i in range(7):
        h.observe(float(i), float(i * 10))
    times, depths, n = h.snapshot()
    assert n == 4
    assert times.tolist() == [3.0, 4.0, 5.0, 6.0]
    assert depths.tolist() == [30.0, 40.0, 50.0, 60.0]


def test_history_partial_snapshot_pads_with_newest_sample():
    h = DepthHistory(capacity=4)
    h.observe(1.0, 5.0)
    h.observe(2.0, 7.0)
    times, depths, n = h.snapshot()
    assert n == 2
    assert times.tolist() == [1.0, 2.0, 2.0, 2.0]
    assert depths.tolist() == [5.0, 7.0, 7.0, 7.0]


def test_with_sample_is_pure_and_drops_oldest_when_full():
    h = DepthHistory(capacity=3)
    for i in range(3):
        h.observe(float(i), float(i))
    times, depths, n = h.with_sample(3.0, 99.0)
    assert n == 3
    assert times.tolist() == [1.0, 2.0, 3.0]
    assert depths.tolist() == [1.0, 2.0, 99.0]
    assert len(h) == 3  # unchanged
    assert h.snapshot()[0].tolist() == [0.0, 1.0, 2.0]


def test_history_is_fed_from_tick_records_and_skips_metric_errors():
    h = DepthHistory(capacity=8)
    h.on_tick(TickRecord(start=5.0, num_messages=120))
    h.on_tick(TickRecord(start=10.0, metric_error="boom"))  # no observation
    h.on_tick(TickRecord(start=15.0, num_messages=130))
    times, depths, n = h.snapshot()
    assert n == 2
    assert times[:2].tolist() == [5.0, 15.0]
    assert depths[:2].tolist() == [120.0, 130.0]


def test_history_rejects_tiny_capacity():
    with pytest.raises(ValueError):
        DepthHistory(capacity=1)


# --------------------------------------------------------------------------
# Forecasters


def linear_history(capacity=32, n=12, dt=5.0, start=100.0, slope=4.0):
    h = DepthHistory(capacity=capacity)
    for i in range(n):
        h.observe(i * dt, start + slope * (i * dt))
    return h.snapshot()


def test_forecasters_are_jit_compiled():
    from kube_sqs_autoscaler_tpu.forecast import forecasters

    for fn in (forecasters._ewma_level, forecasters._holt_forecast,
               forecasters._lstsq_forecast):
        # the jit wrapper exposes lower(); a plain function doesn't
        assert hasattr(fn, "lower")


def test_pure_step_functions_match_their_jitted_wrappers():
    # sim/compiled.py inlines the pure functions inside its episode scan;
    # the live path calls the jitted wrappers.  Same function object
    # underneath, same numbers out — the compiled sim's fidelity gate
    # leans on this equivalence.
    import numpy as np
    import jax.numpy as jnp

    from kube_sqs_autoscaler_tpu.forecast import forecasters

    times64, depths64, n = linear_history(n=20, slope=4.0)
    times = jnp.asarray(times64 - times64[n - 1])
    depths = jnp.asarray(depths64)
    pairs = [
        (forecasters.ewma_level(depths, n, 0.3),
         forecasters._ewma_level(depths, n, 0.3)),
        (forecasters.holt_forecast(times, depths, n, 30.0, 0.5, 0.3),
         forecasters._holt_forecast(times, depths, n, 30.0, 0.5, 0.3)),
        (forecasters.lstsq_forecast(times, depths, n, 30.0, 12),
         forecasters._lstsq_forecast(times, depths, n, 30.0, 12)),
    ]
    for pure, jitted in pairs:
        assert np.asarray(pure) == np.asarray(jitted)


def test_lstsq_is_exact_on_a_linear_trend():
    times, depths, n = linear_history(slope=4.0)
    pred = LeastSquaresForecaster(window=8).predict(times, depths, n, 30.0)
    last = depths[n - 1]
    assert pred == pytest.approx(last + 4.0 * 30.0, rel=1e-4)


def test_holt_tracks_a_linear_trend():
    times, depths, n = linear_history(n=20, slope=4.0)
    pred = HoltForecaster().predict(times, depths, n, 30.0)
    last = depths[n - 1]
    # converging, not exact: within 15% of the true extrapolation step
    assert pred == pytest.approx(last + 4.0 * 30.0, rel=0.15)
    assert pred > last  # and definitely trending up


def test_ewma_converges_to_a_constant_level():
    h = DepthHistory(capacity=32)
    for i in range(20):
        h.observe(float(i * 5), 250.0)
    times, depths, n = h.snapshot()
    assert EwmaForecaster().predict(times, depths, n, 60.0) == pytest.approx(
        250.0, rel=1e-5
    )


def test_forecasts_clamp_at_zero_on_steep_drains():
    h = DepthHistory(capacity=16)
    for i in range(8):
        h.observe(i * 5.0, max(0.0, 700.0 - 100.0 * i))  # -20 msg/s
    times, depths, n = h.snapshot()
    for forecaster in (HoltForecaster(), LeastSquaresForecaster(window=8)):
        assert forecaster.predict(times, depths, n, 120.0) >= 0.0


def test_forecasters_handle_degenerate_histories():
    h = DepthHistory(capacity=8)
    h.observe(5.0, 100.0)
    h.observe(5.0, 100.0)  # coincident timestamps
    times, depths, n = h.snapshot()
    for forecaster in (EwmaForecaster(), HoltForecaster(),
                       LeastSquaresForecaster()):
        value = forecaster.predict(times, depths, n, 30.0)
        assert np.isfinite(value)
        assert value >= 0.0


def test_trend_forecasters_survive_large_clock_epochs():
    # SystemClock.now() is monotonic seconds since boot: at ~2.7e8 s the
    # raw stamps are not representable 5 s apart in float32.  Times are
    # centered in float64 before the jit boundary, so predictions must
    # match the epoch-0 ones.
    offset = 2.7e8
    h0, h1 = DepthHistory(capacity=32), DepthHistory(capacity=32)
    for i in range(12):
        h0.observe(i * 5.0, 100.0 + 4.0 * (i * 5.0))
        h1.observe(offset + i * 5.0, 100.0 + 4.0 * (i * 5.0))
    for forecaster in (HoltForecaster(), LeastSquaresForecaster(window=8)):
        base = forecaster.predict(*h0.snapshot(), 30.0)
        shifted = forecaster.predict(*h1.snapshot(), 30.0)
        assert shifted == pytest.approx(base, rel=1e-3), forecaster.name


def test_make_forecaster_registry():
    assert make_forecaster("ewma").name == "ewma"
    assert make_forecaster("holt").name == "holt"
    assert make_forecaster("lstsq").name == "lstsq"
    with pytest.raises(ValueError):
        make_forecaster("arima")


# --------------------------------------------------------------------------
# PredictivePolicy


def ramping_policy(conservative=True, min_samples=3):
    h = DepthHistory(capacity=32)
    return PredictivePolicy(
        LeastSquaresForecaster(window=16), h,
        horizon=30.0, min_samples=min_samples, conservative=conservative,
    ), h


def test_policy_passes_through_until_warm():
    policy, history = ramping_policy(min_samples=3)
    assert policy.effective_messages(0.0, 50) == 50
    assert policy.last_prediction is None
    history.observe(0.0, 50.0)
    assert policy.effective_messages(5.0, 54) == 54  # still n=2 < 3
    history.observe(5.0, 54.0)
    # third sample: forecasting starts
    effective = policy.effective_messages(10.0, 58)
    assert policy.last_prediction is not None
    assert effective >= 58


def test_policy_forecasts_ahead_on_a_ramp():
    policy, history = ramping_policy(conservative=False)
    for i in range(10):
        history.observe(i * 5.0, 50.0 + 4.0 * i * 5.0)
    now, observed = 50.0, 250
    effective = policy.effective_messages(now, observed)
    # slope 4 msg/s, horizon 30 s => ~120 ahead of the observation
    assert effective == pytest.approx(observed + 120, abs=5)


def test_conservative_policy_never_goes_below_observation():
    policy, history = ramping_policy(conservative=True)
    for i in range(10):
        history.observe(i * 5.0, max(0.0, 500.0 - 40.0 * i))  # steep drain
    assert policy.effective_messages(50.0, 100) == 100  # forecast < observed


def test_policy_scores_matured_forecasts():
    policy, history = ramping_policy(conservative=False)
    for i in range(6):
        history.observe(i * 5.0, 100.0)
    policy.effective_messages(30.0, 100)  # forecast for t=60
    assert policy.last_abs_error is None
    for t in (35.0, 40.0, 45.0, 50.0, 55.0):
        history.observe(t, 100.0)
        policy.effective_messages(t, 100)
    history.observe(60.0, 130.0)
    policy.effective_messages(60.0, 130)  # t=60 forecast matures here
    assert policy.last_abs_error == pytest.approx(30.0, abs=1.0)


def test_policy_rejects_negative_horizon():
    with pytest.raises(ValueError):
        PredictivePolicy(EwmaForecaster(), horizon=-1.0)


def test_reactive_policy_is_identity():
    policy = ReactivePolicy()
    assert policy.effective_messages(123.0, 77) == 77


# --------------------------------------------------------------------------
# Loop integration


def _episode(depth_policy, depths, up=100, poll=5.0):
    """Run one episode over a queue-depth trace; returns (api, loop, clock)."""
    api = FakeDeploymentAPI.with_deployments("ns", 1, "deploy")
    scaler = PodAutoScaler(
        client=api, max=20, min=1, scale_up_pods=1, scale_down_pods=1,
        deployment="deploy", namespace="ns",
    )
    queue = FakeQueueService.with_depths(depths[0])
    clock = FakeClock()
    loop = ControlLoop(
        scaler,
        QueueMetricSource(client=queue, queue_url="q"),
        LoopConfig(
            poll_interval=poll,
            policy=PolicyConfig(
                scale_up_messages=up, scale_down_messages=10,
                scale_up_cooldown=10.0, scale_down_cooldown=30.0,
            ),
        ),
        clock=clock,
        observer=depth_policy.history if depth_policy else None,
        depth_policy=depth_policy,
    )
    for i, depth in enumerate(depths):
        clock.at(float(i) * poll, lambda d=depth: queue.set_depths(d))
    return api, loop, clock


def test_predictive_loop_fires_before_the_reactive_threshold():
    # depth ramps 0, 20, 40, ... (+4 msg/s): crosses the 100-message gate
    # at t=25s.  With a 30 s horizon the predictive loop sees >= 100 one
    # horizon earlier and scales while the reactive loop still idles.
    depths = [20 * i for i in range(12)]

    def first_scale_time(depth_policy):
        api, loop, clock = _episode(depth_policy, depths)
        replicas_at: list[tuple[float, int]] = []
        original_tick = loop.tick

        def recording_tick(state):
            new_state = original_tick(state)
            replicas_at.append((clock.now(), api.replicas("deploy")))
            return new_state

        loop.tick = recording_tick
        loop.run(max_ticks=len(depths))
        return next((t for t, r in replicas_at if r > 1), None)

    reactive_t = first_scale_time(None)
    predictive_t = first_scale_time(
        PredictivePolicy(
            LeastSquaresForecaster(window=8), DepthHistory(capacity=16),
            horizon=30.0, min_samples=3,
        )
    )
    assert reactive_t is not None and predictive_t is not None
    assert predictive_t < reactive_t


def test_depth_policy_failure_falls_back_to_observed_depth():
    class ExplodingPolicy:
        history = None

        def effective_messages(self, now, num_messages):
            raise RuntimeError("forecast kaboom")

    api, loop, _ = _episode(ExplodingPolicy(), [500] * 3)
    loop.run(max_ticks=3)
    # the loop survived AND still scaled up reactively on the raw depth
    assert api.replicas("deploy") > 1


def test_failing_policy_does_not_export_a_stale_forecast():
    # succeeds twice (leaving last_prediction set), then explodes forever:
    # failing ticks must not carry the old forecast on their records.
    class FlakyPolicy:
        def __init__(self):
            self.inner = PredictivePolicy(
                LeastSquaresForecaster(window=8), DepthHistory(capacity=16),
                horizon=30.0, min_samples=2,
            )
            self.history = self.inner.history
            self.calls = 0

        @property
        def last_prediction(self):
            return self.inner.last_prediction

        @property
        def last_abs_error(self):
            return self.inner.last_abs_error

        def effective_messages(self, now, num_messages):
            self.calls += 1
            if self.calls > 2:
                raise RuntimeError("forecast kaboom")
            return self.inner.effective_messages(now, num_messages)

    records = []

    class Recorder:
        def on_tick(self, record):
            records.append(record)

    policy = FlakyPolicy()
    api, loop, _ = _episode(policy, [100, 120, 140, 160])
    loop.observer = CompositeTickObserver([policy.history, Recorder()])
    loop.run(max_ticks=4)
    assert records[1].predicted_messages is not None  # warm, succeeded
    for record in records[2:]:  # policy raising: observed depth, no forecast
        assert record.predicted_messages is None
        assert record.forecast_error is None
        assert record.decision_messages == record.num_messages


def test_tick_record_carries_forecast_fields():
    records = []

    class Recorder:
        def on_tick(self, record):
            records.append(record)

    policy = PredictivePolicy(
        LeastSquaresForecaster(window=8), DepthHistory(capacity=16),
        horizon=30.0, min_samples=2,
    )
    api, loop, _ = _episode(policy, [100, 120, 140, 160])
    loop.observer = CompositeTickObserver([policy.history, Recorder()])
    loop.run(max_ticks=4)
    assert len(records) == 4
    warm = [r for r in records if r.predicted_messages is not None]
    assert warm, "policy never warmed up in 4 ticks"
    for record in records:
        assert record.decision_messages is not None
        assert record.decision_messages >= record.num_messages


def test_composite_observer_isolates_failures():
    class Bad:
        def on_tick(self, record):
            raise RuntimeError("observer kaboom")

    seen = []

    class Good:
        def on_tick(self, record):
            seen.append(record)

    composite = CompositeTickObserver([Bad(), Good()])
    composite.on_tick(TickRecord(start=0.0, num_messages=5))
    assert len(seen) == 1
