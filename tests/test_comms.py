"""Scheduled collectives (comms/): typed transfer ops, the
CollectiveScheduler's coalescing/accounting, the engine seam's
byte-identity contract (comms off OR merely attached-but-idle must
change nothing, counters included), and the overlap win (comms on =
strictly fewer blocking host transfers, same replies).
"""

import time

import pytest

np = pytest.importorskip("numpy")
jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from kube_sqs_autoscaler_tpu.comms import (  # noqa: E402
    EVACUATION_KV,
    HANDOFF_KV,
    PREFIX_INSTALL,
    SETTLE_PULL,
    SIZE_BUCKET_LABELS,
    SMALL_OP_BYTES,
    CollectiveScheduler,
    TransferOp,
    array_nbytes,
    settle_pull_op,
    size_bucket,
)
from kube_sqs_autoscaler_tpu.obs.lifecycle import (  # noqa: E402
    LifecycleRegistry,
    phase_durations,
    transfer_spans,
)
from kube_sqs_autoscaler_tpu.workloads.model import (  # noqa: E402
    ModelConfig,
    init_params,
)

PROMPT, TOKENS, BLOCK = 8, 5, 2


@pytest.fixture(scope="module")
def tiny():
    config = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_seq_len=PROMPT + TOKENS, dtype=jnp.float32,
    )
    return init_params(jax.random.key(0), config), config


def prompts_for(n, seed=7, vocab=64):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(1, vocab, rng.integers(2, PROMPT + 1))
        .astype(np.int32)
        for _ in range(n)
    ]


# ---------------------------------------------------------------------------
# The op taxonomy
# ---------------------------------------------------------------------------


def test_size_buckets_are_total_and_ordered():
    assert size_bucket(1) == "le4k"
    assert size_bucket(1 << 12) == "le4k"
    assert size_bucket((1 << 12) + 1) == "le64k"
    assert size_bucket(1 << 20) == "le1m"
    assert size_bucket((1 << 20) + 1) == "gt1m"
    assert set(SIZE_BUCKET_LABELS) == {"le4k", "le64k", "le1m", "gt1m"}


def test_transfer_op_smallness_and_coalesce_key():
    small = TransferOp(SETTLE_PULL, "host", nbytes=64)
    big = TransferOp(EVACUATION_KV, "shard:1", nbytes=SMALL_OP_BYTES + 1)
    assert small.small and not big.small
    assert small.coalesce_key() == ("host", SETTLE_PULL)
    assert big.coalesce_key() == ("shard:1", EVACUATION_KV)


def test_array_nbytes_walks_nested_structures():
    a = jnp.zeros((2, 3), jnp.float32)
    assert array_nbytes(a) == 24
    assert array_nbytes([{"k": a, "v": a}, {"k": a, "v": a}]) == 96


def test_settle_pull_op_dispatch_starts_async_copies():
    arrays = (jnp.arange(4, dtype=jnp.int32), jnp.ones((2,), jnp.float32))
    op = settle_pull_op(arrays, rids=("r1",))
    assert op.kind == SETTLE_PULL
    assert op.nbytes == 16 + 8
    assert not op.dispatched
    op.dispatch()  # must not raise (async copy or no-op fallback)
    assert np.asarray(arrays[0]).tolist() == [0, 1, 2, 3]


# ---------------------------------------------------------------------------
# The scheduler: coalescing, counters, lifecycle stamps
# ---------------------------------------------------------------------------


def test_flush_coalesces_small_same_destination_ops():
    c = CollectiveScheduler()
    for _ in range(3):
        c.submit(TransferOp(SETTLE_PULL, "host", nbytes=128))
    c.submit(TransferOp(SETTLE_PULL, "host", nbytes=SMALL_OP_BYTES + 1))
    c.submit(TransferOp(SETTLE_PULL, "shard:1", nbytes=128))
    n = c.flush(overlapped=True)
    # 3 small same-dest ops -> ONE dispatch; the large op and the
    # other-destination op dispatch on their own
    assert n == 3
    cc = c.counters()
    assert cc["transfer_dispatches"] == 3
    assert cc["dispatched_ops"] == 5
    assert cc["coalesced_ops"] == 3
    assert cc["overlapped_transfers_total"] == 5
    assert cc["transfer_bytes"] == 3 * 128 + SMALL_OP_BYTES + 1 + 128
    assert cc["pending"] == 0


def test_record_counts_one_dispatch_and_stamps_the_trace():
    reg = LifecycleRegistry(now_fn=time.perf_counter)
    c = CollectiveScheduler(lifecycle=reg)
    t0 = reg.now_fn()
    op = c.record(EVACUATION_KV, "shard:0", nbytes=2048,
                  rids=("req-1",), t0=t0)
    assert op.dispatched and op.finished
    cc = c.counters()
    assert cc["transfer_dispatches"] == 1
    assert cc["by_kind"][EVACUATION_KV] == 1
    (trace,) = reg.open_traces()
    (span,) = transfer_spans(trace)
    assert span[0] == t0 and span[1] >= t0


def test_finish_is_idempotent_and_none_safe():
    c = CollectiveScheduler()
    c.finish(None)  # no-op
    op = TransferOp(SETTLE_PULL, "host", nbytes=8)
    c.submit(op)
    c.flush()
    c.finish(op)
    c.finish(op)
    assert c.counters()["finished_ops"] == 1


def test_disabled_scheduler_declines_settle_pulls():
    c = CollectiveScheduler(enabled=False)
    assert c.settle_pull((jnp.zeros((2,), jnp.int32),)) is None
    assert c.flush() == 0
    assert c.counters()["transfer_dispatches"] == 0


def test_register_flushes_from_the_event_scheduler():
    from kube_sqs_autoscaler_tpu.core.clock import FakeClock
    from kube_sqs_autoscaler_tpu.sched import EventScheduler

    c = CollectiveScheduler()
    c.submit(TransferOp(SETTLE_PULL, "host", nbytes=8))
    sched = EventScheduler(FakeClock())
    c.register(sched, period=1.0)
    sched.run(max_events=1)
    assert c.counters()["flushes"] >= 1
    assert c.counters()["pending"] == 0


# ---------------------------------------------------------------------------
# The obs seam: transfer durations, SLO attribution, the trace lane
# ---------------------------------------------------------------------------


def test_phase_durations_gains_a_transfer_axis():
    reg = LifecycleRegistry(now_fn=lambda: 0.0)
    reg.stamp("r", "arrival", t=0.0)
    reg.stamp("r", "prefill", t=1.0)
    reg.stamp("r", "transfer", t=1.5)
    reg.stamp("r", "transfer_done", t=1.9)
    reg.stamp("r", "first_token", t=2.0)
    (trace,) = reg.open_traces()
    durations = phase_durations(trace)
    assert durations["transfer"] == pytest.approx(0.4)
    assert transfer_spans(trace) == [(1.5, 1.9)]


def test_attribute_slo_names_transfer_bound_requests():
    clock = [0.0]
    reg = LifecycleRegistry(now_fn=lambda: clock[0])
    reg.stamp("r", "arrival", t=0.0)
    reg.stamp("r", "prefill", t=0.1)
    reg.stamp("r", "first_token", t=0.2)
    reg.stamp("r", "completed", t=1.0)
    # the transfer window dwarfs every chained phase: a transfer-bound
    # request the analyzer must name as such
    reg.stamp("r", "transfer", t=0.2)
    reg.stamp("r", "transfer_done", t=5.0)
    clock[0] = 5.2
    reg.settle("r")
    report = reg.attribute_slo(1.0)
    assert report["dominant"] == "transfer"
    assert report["by_phase"] == {"transfer": 1}


def test_request_trace_exports_transfer_spans_on_their_own_lane():
    from kube_sqs_autoscaler_tpu.obs.trace import (
        _REQUEST_LANES,
        request_trace_events,
    )

    assert "transfer" in _REQUEST_LANES
    clock = [0.0]
    reg = LifecycleRegistry(now_fn=lambda: clock[0])
    reg.stamp("r", "arrival", t=0.0)
    reg.stamp("r", "prefill", t=1.0)
    reg.stamp("r", "first_token", t=1.1)
    reg.stamp("r", "transfer", t=1.2)
    reg.stamp("r", "transfer_done", t=1.8)
    reg.stamp("r", "completed", t=3.0)
    clock[0] = 3.0
    reg.settle("r")
    events = request_trace_events(reg.done_traces(), time_origin=0.0)
    lanes = {e["tid"]: e for e in events if e.get("ph") == "X"}
    tid, _ = _REQUEST_LANES["transfer"]
    xfer = [e for e in events
            if e.get("ph") == "X" and e["tid"] == tid]
    assert len(xfer) == 1
    # absolute-time placement: the span sits INSIDE the decode window
    decode_tid, _ = _REQUEST_LANES["decode"]
    (decode,) = [e for e in events
                 if e.get("ph") == "X" and e["tid"] == decode_tid]
    assert decode["ts"] <= xfer[0]["ts"]
    assert (xfer[0]["ts"] + xfer[0]["dur"]
            <= decode["ts"] + decode["dur"])
    assert lanes  # at least one span lane rendered


# ---------------------------------------------------------------------------
# The engine seam: byte identity off, strictly fewer blocking syncs on
# ---------------------------------------------------------------------------


def _block_episode(tiny, comms):
    from kube_sqs_autoscaler_tpu.workloads.continuous import (
        ContinuousBatcher,
    )

    params, config = tiny
    b = ContinuousBatcher(params, config, batch_size=2,
                          prompt_len=PROMPT, generate_tokens=TOKENS,
                          decode_block=BLOCK)
    if comms is not None:
        b.attach_comms(comms)
    queue = list(enumerate(prompts_for(4)))
    results = {}
    for _ in range(300):
        while queue and b.free_slots:
            idx, ids = queue.pop(0)
            b.submit(ids, payload=idx)
        for idx, toks in b.step():
            results[idx] = tuple(int(t) for t in toks)
        if not queue and b.active == 0:
            break
    return results, b.host_transfers, b.decode_dispatches


def test_block_engine_comms_identical_replies_fewer_blocking_syncs(tiny):
    r_off, ht_off, dd_off = _block_episode(tiny, None)
    c = CollectiveScheduler()
    r_on, ht_on, dd_on = _block_episode(tiny, c)
    assert r_on == r_off  # exact greedy parity
    assert dd_on == dd_off  # identical device-dispatch schedule
    assert ht_on < ht_off  # the overlap win
    cc = c.counters()
    assert cc["overlapped_transfers_total"] >= 1
    assert cc["by_kind"][SETTLE_PULL] >= 1
    assert cc["pending"] == 0


def test_attached_but_disabled_comms_is_byte_identical(tiny):
    r_off, ht_off, dd_off = _block_episode(tiny, None)
    c = CollectiveScheduler(enabled=False)
    r_on, ht_on, dd_on = _block_episode(tiny, c)
    assert (r_on, ht_on, dd_on) == (r_off, ht_off, dd_off)
    cc = c.counters()
    assert cc["transfer_dispatches"] == 0
    assert cc["submitted_ops"] == 0


def _sharded_evac_episode(tiny, comms, lifecycle=None):
    from kube_sqs_autoscaler_tpu.workloads.shard_plane import (
        ShardedBatcher,
    )

    params, config = tiny
    plane = ShardedBatcher(params, config, shards=2, shard_slots=2,
                           prompt_len=PROMPT, generate_tokens=TOKENS,
                           decode_block=BLOCK)
    if lifecycle is not None:
        plane.lifecycle = lifecycle
    if comms is not None:
        plane.attach_comms(comms)
    ps = prompts_for(6)
    queue = [(ids, {"MessageId": f"r{i}"}) for i, ids in enumerate(ps)]
    results = {}

    def collect(finished):
        for payload, toks in finished:
            results[payload["MessageId"]] = tuple(int(t) for t in toks)

    def fill():
        n = min(len(queue), len(plane.free_slots))
        if n:
            plane.submit_many(queue[:n])
            del queue[:n]

    fill()
    collect(plane.step())
    collect(plane.step())
    evacuated = plane.take_shard_inflight(1)
    resumes = [
        (ps[int(payload["MessageId"][1:])], payload, produced, budget, t)
        for payload, produced, budget, t in evacuated
    ]
    for _ in range(400):
        fill()
        if resumes and plane.free_slots:
            admitted = plane.submit_resume(resumes)
            del resumes[:len(admitted)]
        collect(plane.step())
        if not queue and not resumes and plane.active == 0:
            break
    return results, plane.host_transfers


def test_sharded_evacuation_comms_parity_and_transfer_stamps(tiny):
    r_off, ht_off = _sharded_evac_episode(tiny, None)
    assert len(r_off) == 6  # exactly once through the evacuation
    reg = LifecycleRegistry(now_fn=time.perf_counter)
    c = CollectiveScheduler(lifecycle=reg)
    r_on, ht_on = _sharded_evac_episode(tiny, c, lifecycle=reg)
    assert r_on == r_off
    assert ht_on < ht_off
    cc = c.counters()
    assert cc["by_kind"][EVACUATION_KV] == 1
    # the satellite-6 bugfix: evacuation lands as per-request transfer
    # stamps (so attribute_slo can name transfer-bound requests), not
    # merely a fleet instant
    traces = reg.open_traces() + reg.done_traces()
    evacuated = [t for t in traces
                 if t.notes.get("transfer_evacuation_kv")]
    assert evacuated
    assert all(transfer_spans(t) for t in evacuated)


def test_handoff_records_transfer_and_stamps_requests(tiny):
    from kube_sqs_autoscaler_tpu.planes.engine import DecodePlaneBatcher
    from kube_sqs_autoscaler_tpu.workloads.continuous import (
        ContinuousBatcher,
    )

    params, config = tiny
    reg = LifecycleRegistry(now_fn=time.perf_counter)
    c = CollectiveScheduler(lifecycle=reg)
    donor = ContinuousBatcher(params, config, 2, PROMPT, TOKENS,
                              decode_block=BLOCK)
    donor.submit_many([
        (ids, {"MessageId": f"p{i}"})
        for i, ids in enumerate(prompts_for(2))
    ])
    donor._settle_pending_firsts()
    records = [
        (row, slot.payload, list(slot.produced), slot.budget,
         slot.submitted_at, slot.tenant)
        for row, slot in enumerate(donor.slots)
        if slot.busy and slot.produced and not slot.done
    ]
    plane = DecodePlaneBatcher(params, config, shards=2, shard_slots=1,
                               prompt_len=PROMPT,
                               generate_tokens=TOKENS,
                               decode_block=BLOCK)
    plane.lifecycle = reg
    plane.attach_comms(c)
    rows = plane.submit_handoff(donor, records)
    assert len(rows) == len(records) == 2
    assert c.counters()["by_kind"][HANDOFF_KV] == 1
    traces = reg.open_traces() + reg.done_traces()
    stamped = [t for t in traces if transfer_spans(t)]
    assert len(stamped) == 2
    assert all(t.notes.get("transfer_handoff_kv") for t in stamped)


def test_prefix_pool_install_records_a_transfer(tiny):
    from kube_sqs_autoscaler_tpu.workloads.tenancy import (
        PrefixPool,
        prefix_pool_key,
    )

    params, config = tiny
    pool = PrefixPool(params, config, entries=2, prefix_len=4)
    c = CollectiveScheduler()
    pool.comms = c
    rng = np.random.default_rng(3)
    ids = rng.integers(1, 64, 4).astype(np.int32)
    pool.acquire(0, prefix_pool_key("a", ids), ids)
    assert c.counters()["by_kind"][PREFIX_INSTALL] == 1
    pool.acquire(0, prefix_pool_key("a", ids), ids)  # hit: no new op
    assert c.counters()["by_kind"][PREFIX_INSTALL] == 1


# ---------------------------------------------------------------------------
# The comms bench: tier-1 smoke (timing gates off), full battery slow
# ---------------------------------------------------------------------------


def test_comms_bench_smoke(tmp_path):
    import json

    import bench

    out = tmp_path / "BENCH_comms.json"
    summary = bench.run_comms_suite(str(out), timing_gates=False)
    assert summary["metric"] == "comms_blocking_transfers_saved"
    assert summary["value"] > 0
    artifact = json.loads(out.read_text())
    assert artifact["suite"] == "comms"
    evac = artifact["evacuation"]
    assert evac["comms_on"]["host_transfers"] < (
        evac["baseline"]["host_transfers"]
    )
    assert evac["comms_on"]["tokens"] == evac["baseline"]["tokens"]
    assert evac["comms_counters"]["overlapped_transfers_total"] >= 1
    assert evac["overlapping_spans"] >= 1
    hand = artifact["handoff"]
    assert hand["comms_on"]["host_transfers"] < (
        hand["baseline"]["host_transfers"]
    )
    assert not artifact["mesh"]["ran"]  # timing battery is slow-tier


@pytest.mark.slow
def test_comms_bench_full_battery(tmp_path):
    import json

    import bench

    out = tmp_path / "BENCH_comms_full.json"
    bench.run_comms_suite(str(out))
    artifact = json.loads(out.read_text())
    mesh = artifact["mesh"]
    assert mesh["ran"]
    rates = [p["tokens_per_second"] for p in mesh["scaling_curve"]]
    assert rates == sorted(rates)
