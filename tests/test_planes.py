"""The disaggregated prefill/decode planes and fleet-wide speculation.

Tier-1 (tiny model, CPU JAX): the decode-plane engine (plain parity
against the sharded gang, gang-stepped draft-and-verify parity with
per-tenant accept accounting, the drain-to-plain speculative flip, the
KV-handoff transport and its validation), the DisaggregatedPool fleet
cycle (exactly-once through the shuttle, decode-cadence decoupling, a
prefill kill mid-handoff, a visibility-timeout redelivery racing a row
the decode plane already owns), the durable plane-state surface, the
``plane_ratio``/``speculative`` knob routing, the plane gauge families,
and the ``--suite disagg`` bench smoke (timing gates off).  The full
battery — the committed ``BENCH_r20.json`` with the TTFT/tokens-per-
second win gates — runs in the slow tier.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import jax.numpy as jnp  # noqa: E402

from kube_sqs_autoscaler_tpu.core.clock import FakeClock  # noqa: E402
from kube_sqs_autoscaler_tpu.metrics.fake import FakeMessageQueue  # noqa: E402
from kube_sqs_autoscaler_tpu.obs import WorkloadMetrics  # noqa: E402
from kube_sqs_autoscaler_tpu.planes import (  # noqa: E402
    DecodePlaneBatcher,
    DisaggregatedPool,
    PrefillWorker,
)
from kube_sqs_autoscaler_tpu.sched.knobs import (  # noqa: E402
    KNOB_PLANE_RATIO,
    KNOB_SPECULATIVE,
    KnobActuator,
    KnobError,
)
from kube_sqs_autoscaler_tpu.workloads.continuous import (  # noqa: E402
    ContinuousBatcher,
)
from kube_sqs_autoscaler_tpu.workloads.model import (  # noqa: E402
    ModelConfig,
    init_params,
)
from kube_sqs_autoscaler_tpu.workloads.service import (  # noqa: E402
    ServiceConfig,
    collect_replies,
)
from kube_sqs_autoscaler_tpu.workloads.shard_plane import (  # noqa: E402
    ShardedBatcher,
)
from kube_sqs_autoscaler_tpu.workloads.tenancy import (  # noqa: E402
    TenancyConfig,
)

PROMPT, TOKENS, BLOCK, SPEC = 8, 8, 2, 3


@pytest.fixture(scope="module")
def tiny():
    config = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_seq_len=PROMPT + TOKENS + 2 * SPEC, dtype=jnp.float32,
    )
    params = init_params(jax.random.key(0), config)
    return params, config


def make_decode_plane(tiny, *, donor=None, draft_enabled=None):
    params, config = tiny
    plane = DecodePlaneBatcher(
        params, config, shards=2, shard_slots=2,
        prompt_len=PROMPT, generate_tokens=TOKENS, decode_block=BLOCK,
        spec_layers=1, spec_tokens=SPEC, draft_enabled=draft_enabled,
    )
    if donor is not None:
        plane.adopt_engine(donor)
    return plane


@pytest.fixture(scope="module")
def plane_donor(tiny):
    """One warmed decode plane the engine tests adopt, so the module
    pays each compiled program once."""
    return make_decode_plane(tiny)


@pytest.fixture(scope="module")
def prefill_donor(tiny):
    """One warmed plain batcher shaped like a prefill replica."""
    params, config = tiny
    return ContinuousBatcher(
        params, config, 2, PROMPT, TOKENS, decode_block=BLOCK,
    )


def prompts_for(n, seed=7):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(1, 64, rng.integers(2, PROMPT + 1)).astype(np.int32)
        for _ in range(n)
    ]


def drain(plane, max_steps=300):
    out = {}
    for _ in range(max_steps):
        for payload, tokens in plane.step():
            out[payload] = list(tokens)
        if plane.active == 0:
            break
    return out


@pytest.fixture(scope="module")
def expected(tiny, plane_donor):
    """Reference outputs for ``prompts_for(4)`` from the plain sharded
    gang — the parity target every decode-plane mode must match."""
    params, config = tiny
    control = ShardedBatcher(
        params, config, shards=2, shard_slots=2,
        prompt_len=PROMPT, generate_tokens=TOKENS, decode_block=BLOCK,
    )
    control.submit_many(
        [(ids, f"p{i}") for i, ids in enumerate(prompts_for(4))]
    )
    return drain(control)


# ---------------------------------------------------------------------------
# The decode-plane engine
# ---------------------------------------------------------------------------


def test_plane_plain_parity(tiny, plane_donor, expected):
    plane = make_decode_plane(tiny, donor=plane_donor, draft_enabled=False)
    plane.submit_many(
        [(ids, f"p{i}") for i, ids in enumerate(prompts_for(4))]
    )
    assert drain(plane) == expected
    assert plane.spec_rounds == 0  # plain rows pay zero spec dispatches


def test_plane_spec_parity_and_accept_accounting(
    tiny, plane_donor, expected,
):
    plane = make_decode_plane(tiny, donor=plane_donor)
    assert plane.draft_enabled  # a drafted plane defaults to drafting
    assert plane.accept_rate() is None  # no rounds yet
    rows = plane.submit_many(
        [(ids, f"p{i}") for i, ids in enumerate(prompts_for(4))]
    )
    plane.tag_tenant(rows, ["a", "a", "b", "b"])
    assert drain(plane) == expected  # greedy draft-and-verify is exact
    assert plane.spec_rounds > 0
    overall = plane.accept_rate()
    assert 0.0 < overall <= 1.0
    assert plane.recent_accept_rate() is not None
    for tenant in ("a", "b"):
        rate = plane.accept_rate(tenant)
        assert rate is not None and 0.0 <= rate <= 1.0
    assert plane.accept_rate("never-seen") is None


def test_drain_to_plain_flip_mid_flight(tiny, plane_donor, expected):
    plane = make_decode_plane(tiny, donor=plane_donor)
    prompts = prompts_for(4)
    plane.submit_many([(ids, f"q{i}") for i, ids in enumerate(prompts[:2])])
    plane.step()  # the drafted rows are mid-flight
    plane.set_speculative(False)
    plane.submit_many([(ids, f"r{i}") for i, ids in enumerate(prompts[2:])])
    # in-flight rows keep their admitted mode, new rows landed plain
    assert plane._slot_spec.count(True) == 2
    out = drain(plane)
    assert out == {
        **{f"q{i}": expected[f"p{i}"] for i in range(2)},
        **{f"r{i}": expected[f"p{i + 2}"] for i in range(2)},
    }
    assert plane.spec_flips == 1
    plane.set_speculative(False)  # no-op: not a flip
    assert plane.spec_flips == 1
    plane.set_speculative(True)
    assert plane.spec_flips == 2


def test_plane_validates(tiny, plane_donor):
    params, config = tiny
    with pytest.raises(ValueError, match="max_seq_len"):
        DecodePlaneBatcher(
            params, config, shards=2, shard_slots=2,
            prompt_len=PROMPT, generate_tokens=TOKENS + 1,
            decode_block=BLOCK, spec_layers=1, spec_tokens=SPEC,
        )
    with pytest.raises(ValueError, match="decode-plane donor"):
        plane = make_decode_plane(tiny)
        plane.adopt_engine(
            ShardedBatcher(
                params, config, shards=2, shard_slots=2,
                prompt_len=PROMPT, generate_tokens=TOKENS,
                decode_block=BLOCK,
            )
        )


def _handoff_records(donor):
    return [
        (row, slot.payload, list(slot.produced), slot.budget,
         slot.submitted_at, slot.tenant)
        for row, slot in enumerate(donor.slots)
        if slot.busy and slot.produced and not slot.done
        and len(slot.produced) < slot.budget
    ]


@pytest.mark.parametrize("drafted", [False, True], ids=["plain", "spec"])
def test_handoff_adopts_prefill_rows(
    tiny, plane_donor, prefill_donor, expected, drafted,
):
    donor = prefill_donor
    prompts = prompts_for(4)
    donor.submit_many([(ids, f"p{i}") for i, ids in enumerate(prompts[:2])])
    donor._settle_pending_firsts()  # first tokens only — no decode steps
    records = _handoff_records(donor)
    assert len(records) == 2
    assert all(len(produced) == 1 for _, _, produced, _, _, _ in records)

    plane = make_decode_plane(tiny, donor=plane_donor,
                              draft_enabled=drafted)
    rows = plane.submit_handoff(donor, records)
    assert plane.kv_transfers == 2
    for row in rows:
        assert plane.slots[row].ttft_done  # TTFT was timed at prefill
        assert plane._slot_spec[row] is drafted
    for row, _ in zip(range(len(donor.slots)), records):
        donor.slots[row].busy = False  # what complete_handoff does
    donor._invalidate_admission_cache()
    out = drain(plane)
    # the adopted rows decode exactly what the fused engine produces
    assert out == {f"p{i}": expected[f"p{i}"] for i in range(2)}
    if drafted:
        assert plane.spec_rounds > 0


def test_handoff_validates(tiny, plane_donor, prefill_donor):
    plane = make_decode_plane(tiny, donor=plane_donor)
    ids = prompts_for(1)[0]
    finished = [(0, "p", list(range(TOKENS)), TOKENS, 0.0, None)]
    with pytest.raises(ValueError, match="started, unfinished"):
        plane.submit_handoff(prefill_donor, finished)
    too_many = [
        (0, f"p{i}", [1], TOKENS, 0.0, None)
        for i in range(len(plane.slots) + 1)
    ]
    with pytest.raises(RuntimeError, match="no free slot"):
        plane.submit_handoff(prefill_donor, too_many)
    with pytest.raises(ValueError, match="layout-identical"):
        params, config = tiny
        other = ContinuousBatcher(
            params,
            ModelConfig(
                vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                d_ff=64, max_seq_len=PROMPT + TOKENS + 2 * SPEC,
                dtype=jnp.float32,
            ),
            2, PROMPT, TOKENS, decode_block=BLOCK,
        )
        plane.submit_handoff(other, [(0, "p", [1], TOKENS, 0.0, None)])
    assert plane.kv_transfers == 0  # nothing moved


# ---------------------------------------------------------------------------
# The disaggregated pool: one admission surface, two actuated planes
# ---------------------------------------------------------------------------


def service_config(**overrides):
    base = dict(
        queue_url="disagg://q", batch_size=2, seq_len=PROMPT,
        generate_tokens=TOKENS, decode_block=BLOCK, shards=2,
        result_queue_url="disagg://r",
    )
    base.update(overrides)
    return ServiceConfig(**base)


def make_disagg(tiny, *, queue_url, visibility=30.0, draft_enabled=None,
                donor=None, tenants=("t",), min=2, max=2, **pool_kwargs):
    params, config = tiny
    clock = FakeClock()
    queue = FakeMessageQueue(visibility_timeout=visibility,
                             now_fn=clock.now)
    results = FakeMessageQueue(now_fn=clock.now)
    service = service_config(
        queue_url=queue_url, result_queue_url=f"{queue_url}-r",
    )
    pool = DisaggregatedPool.serving(
        queue, params, config, service, result_queue=results,
        min=min, max=max, decode_shards=2, spec_layers=1,
        spec_tokens=SPEC, draft_enabled=draft_enabled,
        tenancy=TenancyConfig(tenants=tuple(tenants)),
        clock=clock, now_fn=clock.now,
        prefill_engine_source=(
            donor.engine_donor() if donor is not None else None
        ),
        decode_engine_source=(
            donor.decode.batcher if donor is not None else None
        ),
        **pool_kwargs,
    )
    return pool, clock, queue, results, service


@pytest.fixture(scope="module")
def pool_donor(tiny):
    """One warmed disaggregated pool whose engines every pool test
    adopts (prefill insert programs + decode gang/spec/handoff)."""
    pool, _, _, _, _ = make_disagg(tiny, queue_url="disagg://donor")
    return pool


def send(queue, queue_url, ids, tenant="t"):
    return queue.send_message(
        queue_url,
        json.dumps({"tenant": tenant, "ids": [int(i) for i in ids]}),
    )


def drive(pool, clock, *, until, max_cycles=200, on_cycle=None):
    for cycle in range(max_cycles):
        if on_cycle is not None:
            on_cycle(cycle)
        pool.run_cycle()
        clock.advance(0.2)
        if until():
            return cycle + 1
    raise AssertionError(
        f"pool did not converge in {max_cycles} cycles: "
        f"processed={pool.processed} idle={pool.idle}"
    )


def test_pool_exactly_once_through_the_shuttle(tiny, pool_donor):
    pool, clock, queue, results, service = make_disagg(
        tiny, queue_url="disagg://e2e", donor=pool_donor,
    )
    to_send = prompts_for(12, seed=31)
    sent = []

    def on_cycle(_):
        if to_send:
            sent.append(
                send(queue, "disagg://e2e", to_send.pop(0))
            )

    drive(pool, clock,
          until=lambda: not to_send and pool.processed >= 12 and pool.idle,
          on_cycle=on_cycle)
    replies, duplicates = collect_replies(results, service.result_queue_url)
    assert set(replies) == set(sent)
    assert duplicates == 0
    assert pool.kv_handoffs_total >= 12
    # the decode plane consumed only handoffs, never the queue
    assert pool.decode.batcher.kv_transfers == pool.kv_handoffs_total
    # TTFT lives on the prefill plane (arrival-stamped under tenancy)
    ttfts = [
        t for r in pool.members
        for t in r.worker.batcher.tenant_ttft.get("t", ())
    ]
    assert ttfts and all(t >= 0.0 for t in ttfts)


def test_decode_cadence_decouples_from_poll_cadence(tiny, pool_donor):
    # same burst, same hardware: gang cadence 2 (default) sustains the
    # full pipeline rate; cadence 1 leaves the classic insert/settle
    # bubble.  The disaggregation win the bench quantifies, pinned here
    # at the cycle level.
    cycles = {}
    for cadence in (1, 2):
        pool, clock, queue, results, _ = make_disagg(
            tiny, queue_url=f"disagg://cad{cadence}", donor=pool_donor,
            draft_enabled=False, decode_steps_per_cycle=cadence,
        )
        sent = [
            send(queue, f"disagg://cad{cadence}", ids)
            for ids in prompts_for(16, seed=33)
        ]
        cycles[cadence] = drive(
            pool, clock,
            until=lambda: pool.processed >= 16 and pool.idle,
        )
        replies, duplicates = collect_replies(
            results, f"disagg://cad{cadence}-r"
        )
        assert set(replies) == set(sent) and duplicates == 0
    assert cycles[2] < cycles[1]
    with pytest.raises(ValueError, match="decode_steps_per_cycle"):
        make_disagg(
            tiny, queue_url="disagg://cad0", donor=pool_donor,
            decode_steps_per_cycle=0,
        )


def test_prefill_kill_mid_handoff_redispatches(tiny, pool_donor):
    # cadence 1 strands started rows on their prefill replica while the
    # decode plane is busy — the kill lands mid-handoff for real
    pool, clock, queue, results, service = make_disagg(
        tiny, queue_url="disagg://kill", donor=pool_donor,
        draft_enabled=False, decode_steps_per_cycle=1,
    )
    to_send = prompts_for(14, seed=35)
    sent = []
    state = {"killed": None}

    def on_cycle(_):
        for _ in range(2):
            if to_send:
                sent.append(send(queue, "disagg://kill", to_send.pop(0)))
        if state["killed"] is None:
            victims = [
                r for r in pool.members
                if r.state == "serving" and r.worker.batcher.active > 0
            ]
            if victims:
                state["killed"] = victims[-1].index
                victims[-1].worker.kill()

    drive(pool, clock,
          until=lambda: not to_send and pool.processed >= 14 and pool.idle,
          on_cycle=on_cycle)
    assert state["killed"] is not None
    replies, duplicates = collect_replies(results, service.result_queue_url)
    assert set(replies) == set(sent)
    assert duplicates == 0
    assert pool.redispatched_total > 0  # the kill stranded real rows


def test_redelivery_racing_decode_owned_row_stays_exactly_once(
    tiny, pool_donor,
):
    # a visibility-timeout redelivery of a request the decode plane
    # already owns re-prefills and re-hands off; the shared reply
    # registry suppresses whichever reply lands second
    pool, clock, queue, results, service = make_disagg(
        tiny, queue_url="disagg://race", donor=pool_donor,
        draft_enabled=False,
    )
    sent = [
        send(queue, "disagg://race", ids)
        for ids in prompts_for(6, seed=37)
    ]
    state = {"redelivered": False}

    def on_cycle(_):
        decode = pool.decode.batcher
        if not state["redelivered"] and decode.active > 0:
            state["redelivered"] = True
            for slot in decode.slots:
                if slot.busy and slot.payload:
                    queue.change_message_visibility(
                        "disagg://race", slot.payload["ReceiptHandle"], 0,
                    )

    def queue_drained():
        attrs = queue.get_queue_attributes("disagg://race", ["All"])
        return (attrs["ApproximateNumberOfMessages"] == "0"
                and attrs["ApproximateNumberOfMessagesNotVisible"] == "0")

    drive(pool, clock,
          until=lambda: pool.processed >= 6 and pool.idle
          and queue_drained(),
          on_cycle=on_cycle)
    assert state["redelivered"]
    assert pool.duplicates_suppressed > 0
    replies, duplicates = collect_replies(results, service.result_queue_url)
    assert set(replies) == set(sent)
    assert duplicates == 0


# ---------------------------------------------------------------------------
# Durable plane state
# ---------------------------------------------------------------------------


def test_export_import_carries_plane_state(tiny, pool_donor):
    pool, clock, queue, results, _ = make_disagg(
        tiny, queue_url="disagg://dur", donor=pool_donor,
    )
    sent = [
        send(queue, "disagg://dur", ids) for ids in prompts_for(4, seed=39)
    ]
    drive(pool, clock, until=lambda: pool.processed >= 4 and pool.idle)
    pool.decode.batcher.set_speculative(False)  # a measured decision
    state = pool.export_state()
    assert state["kv_handoffs_total"] == pool.kv_handoffs_total > 0
    assert state["draft_enabled"] is False

    fresh, _, _, _, _ = make_disagg(
        tiny, queue_url="disagg://dur2", donor=pool_donor,
    )
    assert fresh.decode.batcher.draft_enabled  # drafted by default
    flips_before = fresh.decode.batcher.spec_flips
    fresh.import_state(json.loads(json.dumps(state)))
    assert fresh.kv_handoffs_total == pool.kv_handoffs_total
    # the drafting decision survived the restart — silently (a
    # rehydration is not a knob flip and must not count one)
    assert fresh.decode.batcher.draft_enabled is False
    assert fresh.decode.batcher.spec_flips == flips_before
    # the reply registry rode along: the answered requests stay answered
    assert all(fresh.already_replied(m) for m in sent)


# ---------------------------------------------------------------------------
# Knob routing and plane gauges
# ---------------------------------------------------------------------------


def test_knobs_route_to_the_right_plane(tiny, pool_donor):
    pool, clock, queue, results, _ = make_disagg(
        tiny, queue_url="disagg://knob", donor=pool_donor, min=1, max=3,
    )
    actuator = KnobActuator(
        pool, armed=(KNOB_SPECULATIVE, KNOB_PLANE_RATIO), clock=clock,
    )
    # speculative routes to the ONE decode-plane worker
    assert actuator.set(KNOB_SPECULATIVE, False)
    (change,) = actuator.apply()
    assert change["knob"] == KNOB_SPECULATIVE
    assert pool.decode.batcher.draft_enabled is False
    assert pool.decode.batcher.spec_flips == 1
    # plane_ratio walks the prefill plane through its own Scaler
    assert actuator.set(KNOB_PLANE_RATIO, 3)
    (change,) = actuator.apply()
    assert change["knob"] == KNOB_PLANE_RATIO and change["value"] == 3
    assert pool.replicas == 3
    assert pool.decode_pool.replicas == 2  # the decode plane unmoved
    with pytest.raises(KnobError, match="plane_ratio"):
        actuator.set(KNOB_PLANE_RATIO, 7)  # outside [min, max]


def test_plane_gauges_exported(tiny, pool_donor):
    pool, clock, queue, results, _ = make_disagg(
        tiny, queue_url="disagg://obs", donor=pool_donor,
        tenants=("a", "b"),
    )
    metrics = WorkloadMetrics()
    pool.attach_metrics(metrics)
    pool.decode.attach_metrics(metrics)
    to_send = prompts_for(8, seed=41)
    sent = []

    def on_cycle(cycle):
        if to_send:
            sent.append(send(queue, "disagg://obs", to_send.pop(0),
                             tenant="ab"[cycle % 2]))

    drive(pool, clock,
          until=lambda: not to_send and pool.processed >= 8 and pool.idle,
          on_cycle=on_cycle)
    text = metrics.render()
    assert "plane_prefill_replicas 2.0" in text
    assert "plane_decode_shards 2.0" in text
    assert "plane_kv_transfers_total" in text
    assert 'speculative_accept_rate{tenant="a"}' in text
    assert 'speculative_accept_rate{tenant="b"}' in text


def test_serving_requires_a_drafted_decode_plane(tiny):
    params, config = tiny
    with pytest.raises(ValueError, match="draft_enabled=False"):
        DisaggregatedPool.serving(
            FakeMessageQueue(), params, config, service_config(),
            min=1, max=1, decode_shards=2, spec_layers=0,
        )


# ---------------------------------------------------------------------------
# The disagg bench: tier-1 smoke (timing gates off), full battery slow
# ---------------------------------------------------------------------------


def test_disagg_bench_smoke(tmp_path):
    import bench

    out = tmp_path / "BENCH_disagg.json"
    summary = bench.run_disagg_suite(str(out), timing_gates=False)
    assert summary["metric"] == "disagg_ttft_win"
    artifact = json.loads(out.read_text())
    assert artifact["suite"] == "disagg"
    for name, episode in artifact["episodes"].items():
        assert episode["answered"] == episode["requests"], name
        assert episode["duplicates"] == 0, name
    assert artifact["episodes"]["disagg"]["kv_handoffs"] > 0
    kill = artifact["episodes"]["prefill-kill"]["kill"]
    assert kill["inflight_rows"] > 0
    assert kill["kv_handoffs_after"] > kill["kv_handoffs_before"]
    values = [c["value"] for c in artifact["flip_changes"]]
    assert True in values and False in values  # both flip directions
    probe = artifact["probe"]
    assert probe["accept_rate_friendly"] > probe["accept_rate_hostile"]


@pytest.mark.slow
def test_disagg_bench_full_battery(tmp_path):
    import bench

    out = tmp_path / "BENCH_disagg_full.json"
    summary = bench.run_disagg_suite(str(out))
    artifact = json.loads(out.read_text())
    fused = artifact["episodes"]["fused"]
    disagg = artifact["episodes"]["disagg"]
    assert disagg["ttft_p99_s"] < fused["ttft_p99_s"]
    assert disagg["tokens_per_second"] >= fused["tokens_per_second"]
    assert summary["vs_baseline"] > 1.0
