"""Mesh parity: the pooled admission insert and the gang step must be
byte-identical across mesh sizes 1/2/4 on the forced multi-device CPU
mesh (conftest forks 8 host devices via
``--xla_force_host_platform_device_count``).  Skips cleanly when the
platform could not fork devices.
"""

import pytest

np = pytest.importorskip("numpy")
jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from kube_sqs_autoscaler_tpu.workloads.model import (  # noqa: E402
    ModelConfig,
    init_params,
)

PREFIX, PROMPT, TOKENS, BLOCK = 4, 6, 4, 2

needs_devices = pytest.mark.skipif(
    len(jax.devices()) < 4,
    reason="platform could not fork >= 4 host devices",
)


@pytest.fixture(scope="module")
def tiny():
    config = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_seq_len=PREFIX + PROMPT + TOKENS, dtype=jnp.float32,
    )
    return init_params(jax.random.key(0), config), config


def _mesh(n_devices):
    """A ``(data, seq, model)`` mesh over the first ``n_devices``
    forked host devices — model axis 2 whenever it fits."""
    from kube_sqs_autoscaler_tpu.workloads.train import make_mesh

    return make_mesh(
        devices=jax.devices()[:n_devices],
        model_parallel=(2 if n_devices >= 2 else 1),
    )


def _pooled_requests(rng_seed=5, n=6):
    rng = np.random.default_rng(rng_seed)
    prefix = {
        "a": rng.integers(1, 64, PREFIX).astype(np.int32),
        "b": rng.integers(1, 64, PREFIX).astype(np.int32),
    }
    reqs = []
    for i in range(n):
        tenant = "a" if i % 2 == 0 else "b"
        prompt = rng.integers(
            1, 64, rng.integers(2, PROMPT + 1)
        ).astype(np.int32)
        reqs.append((tenant, prefix[tenant], prompt, {"MessageId": f"r{i}"}))
    return reqs


def _pooled_episode(tiny, mesh, batch_size):
    from kube_sqs_autoscaler_tpu.workloads.continuous import (
        ContinuousBatcher,
    )
    from kube_sqs_autoscaler_tpu.workloads.tenancy import TenancyConfig

    params, config = tiny
    batcher = ContinuousBatcher(
        params, config, batch_size=batch_size, prompt_len=PROMPT,
        generate_tokens=TOKENS, mesh=mesh,
        tenancy=TenancyConfig(
            tenants=("a", "b"), prefix_pool=batch_size,
            prefix_len=PREFIX,
        ),
    )
    queue = _pooled_requests()
    results = {}
    for _ in range(300):
        n = min(len(queue), len(batcher.free_slots))
        if n:
            batcher.submit_many_prefixed(queue[:n])
            del queue[:n]
        for payload, toks in batcher.step():
            results[payload["MessageId"]] = tuple(int(t) for t in toks)
        if not queue and batcher.active == 0:
            break
    pool = batcher.prefix_pool
    return results, pool.installs, pool.hits


@needs_devices
@pytest.mark.parametrize("n_devices", [1, 2, 4])
def test_pooled_insert_byte_identical_across_mesh_sizes(
    tiny, n_devices,
):
    # reference: the single-chip pooled path (mesh=None) — per-request
    # greedy outputs are scheduling-independent, so every mesh size
    # must reproduce them bit for bit, pool odometers included
    reference, ref_installs, ref_hits = _pooled_episode(tiny, None, 3)
    mesh = _mesh(n_devices)
    batch = 3 * mesh.shape["data"]
    results, installs, hits = _pooled_episode(tiny, mesh, batch)
    assert results == reference
    assert (installs, hits) == (ref_installs, ref_hits)


def _gang_episode(tiny, mesh):
    from kube_sqs_autoscaler_tpu.workloads.shard_plane import (
        ShardedBatcher,
    )

    params, config = tiny
    plane = ShardedBatcher(
        params, config, shards=2, shard_slots=2, prompt_len=PROMPT,
        generate_tokens=TOKENS, decode_block=BLOCK, mesh=mesh,
    )
    rng = np.random.default_rng(11)
    prompts = [
        rng.integers(1, 64, rng.integers(2, PROMPT + 1)).astype(np.int32)
        for _ in range(6)
    ]
    queue = [(ids, f"r{i}") for i, ids in enumerate(prompts)]
    results = {}
    for _ in range(300):
        n = min(len(queue), len(plane.free_slots))
        if n:
            plane.submit_many(queue[:n])
            del queue[:n]
        for payload, toks in plane.step():
            results[payload] = tuple(int(t) for t in toks)
        if not queue and plane.active == 0:
            break
    return results


@needs_devices
@pytest.mark.parametrize("n_devices", [1, 2, 4])
def test_gang_step_byte_identical_across_mesh_sizes(tiny, n_devices):
    reference = _gang_episode(tiny, None)
    assert len(reference) == 6
    assert _gang_episode(tiny, _mesh(n_devices)) == reference


@needs_devices
def test_pool_layout_must_divide_the_model_axis(tiny):
    # heads=3 cannot split over a model axis of 2: startup validation,
    # not a silent XLA pad-and-reshard on every admission gather
    from kube_sqs_autoscaler_tpu.workloads.continuous import (
        ContinuousBatcher,
    )
    from kube_sqs_autoscaler_tpu.workloads.tenancy import TenancyConfig

    config = ModelConfig(
        vocab_size=64, d_model=33, n_heads=3, n_layers=1, d_ff=64,
        max_seq_len=16, dtype=jnp.float32,
    )
    params = init_params(jax.random.key(0), config)
    with pytest.raises(ValueError, match="model axis"):
        ContinuousBatcher(
            params, config, batch_size=4, prompt_len=4,
            generate_tokens=4, mesh=_mesh(2),
            tenancy=TenancyConfig(
                tenants=("a",), prefix_pool=4, prefix_len=4,
            ),
        )


@needs_devices
def test_mesh_pool_layers_stay_sharded_after_install(tiny):
    # the donated install write must preserve the pool rows' mesh
    # placement (a resharding here would put every later gather back
    # on one chip)
    from kube_sqs_autoscaler_tpu.workloads.tenancy import (
        PrefixPool,
        prefix_pool_key,
    )

    params, config = tiny
    mesh = _mesh(2)
    pool = PrefixPool(
        params, config, entries=2, prefix_len=PREFIX, mesh=mesh,
    )
    rng = np.random.default_rng(3)
    ids = rng.integers(1, 64, PREFIX).astype(np.int32)
    pool.acquire(0, prefix_pool_key("a", ids), ids)
    expected = pool.layer_shardings(mesh)
    for layer, specs in zip(pool.layers, expected):
        for name, buf in layer.items():
            assert buf.sharding == specs[name], name
