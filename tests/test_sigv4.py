"""SigV4 signer tests against AWS's published worked example.

The golden vector is the documented ``GET iam.amazonaws.com ListUsers``
example from the AWS General Reference "signature v4 signing process" docs
(credentials AKIDEXAMPLE / wJalrXUtnFEMI..., date 20150830T123600Z), whose
expected signature is published as
``5d672d79c15b13162d9279b0855cfba6789a8edb4c82c400e06b5924a6f2b5d7``.
"""

from kube_sqs_autoscaler_tpu.utils.sigv4 import (
    Credentials,
    SignableRequest,
    sign_request,
)

GOLDEN_CREDS = Credentials(
    access_key_id="AKIDEXAMPLE",
    secret_access_key="wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
)


def test_golden_iam_listusers_signature():
    request = SignableRequest(
        method="GET",
        url="https://iam.amazonaws.com/?Action=ListUsers&Version=2010-05-08",
        headers={"Content-Type": "application/x-www-form-urlencoded; charset=utf-8"},
        body=b"",
    )
    signed = sign_request(
        request, GOLDEN_CREDS, "us-east-1", "iam", "20150830T123600Z"
    )
    assert signed.headers["Authorization"] == (
        "AWS4-HMAC-SHA256 "
        "Credential=AKIDEXAMPLE/20150830/us-east-1/iam/aws4_request, "
        "SignedHeaders=content-type;host;x-amz-date, "
        "Signature=5d672d79c15b13162d9279b0855cfba6789a8edb4c82c400e06b5924a6f2b5d7"
    )


def test_signature_is_deterministic_and_does_not_mutate_input():
    request = SignableRequest(
        method="POST",
        url="https://sqs.us-east-1.amazonaws.com/",
        headers={"Content-Type": "application/x-amz-json-1.0"},
        body=b'{"QueueUrl": "q"}',
    )
    a = sign_request(request, GOLDEN_CREDS, "us-east-1", "sqs", "20260729T000000Z")
    b = sign_request(request, GOLDEN_CREDS, "us-east-1", "sqs", "20260729T000000Z")
    assert a.headers["Authorization"] == b.headers["Authorization"]
    assert "Authorization" not in request.headers  # input untouched


def test_session_token_is_signed_when_present():
    creds = Credentials("AKID", "secret", session_token="tok123")
    signed = sign_request(
        SignableRequest(method="POST", url="https://sqs.us-east-1.amazonaws.com/"),
        creds,
        "us-east-1",
        "sqs",
        "20260729T000000Z",
    )
    assert signed.headers["x-amz-security-token"] == "tok123"
    assert "x-amz-security-token" in signed.headers["Authorization"]


def test_body_changes_signature():
    base = SignableRequest(
        method="POST", url="https://sqs.us-east-1.amazonaws.com/", body=b"a"
    )
    other = SignableRequest(
        method="POST", url="https://sqs.us-east-1.amazonaws.com/", body=b"b"
    )
    sig_a = sign_request(base, GOLDEN_CREDS, "r", "sqs", "20260729T000000Z")
    sig_b = sign_request(other, GOLDEN_CREDS, "r", "sqs", "20260729T000000Z")
    assert sig_a.headers["Authorization"] != sig_b.headers["Authorization"]
