"""Compiled simulator fidelity: the `lax.scan` twin must reproduce the
real-`ControlLoop` Python simulator tick-for-tick.

This is the tentpole's non-negotiable gate (ISSUE 3): every observed
depth, every gate-thresholded decision, both gate outcomes, and the
replica trajectory must agree exactly — for reactive and all three
predictive forecasters, across the full default scenario battery.  The
same check runs in ``bench.py --suite sweep`` before any sweep number is
recorded.
"""

import pytest

from kube_sqs_autoscaler_tpu.core.loop import LoopConfig
from kube_sqs_autoscaler_tpu.core.policy import PolicyConfig
from kube_sqs_autoscaler_tpu.sim import SimConfig, Simulation
from kube_sqs_autoscaler_tpu.sim.compiled import (
    encode_config,
    episode_ticks,
    run_compiled,
    run_compiled_one,
    run_episodes,
    verify_fidelity,
)
from kube_sqs_autoscaler_tpu.sim.evaluate import default_battery, score_result
from kube_sqs_autoscaler_tpu.sim.scenarios import BurstArrival, RampArrival


def short_loop(poll=5.0):
    return LoopConfig(
        poll_interval=poll,
        policy=PolicyConfig(
            scale_up_messages=100, scale_down_messages=10,
            scale_up_cooldown=10.0, scale_down_cooldown=30.0,
        ),
    )


def test_fidelity_full_battery_reactive_and_all_forecasters():
    # The acceptance gate itself: 4 scenarios x (reactive + ewma + holt +
    # lstsq), tick-for-tick.  Any divergence message is the test output.
    report = verify_fidelity()
    assert report.episodes == 16
    assert report.ticks == 16 * 180
    assert report.ok, "\n".join(report.format_divergences(20))


def test_fidelity_covers_nondefault_sweep_knobs():
    # The sweep tunes thresholds/cooldowns/scale-step/horizon/history —
    # none of which the default battery episodes vary.  Pin the compiled
    # twin on a sample of that region (including a mixed history
    # capacity, which forces a second compiled batch) so a semantic
    # drift confined to a non-default knob cannot hide from the gate.
    from kube_sqs_autoscaler_tpu.sim.sweep import SweepPoint

    scenarios = default_battery()[:2]  # step + ramp keep this fast
    points = [
        SweepPoint(scale_up_messages=50, scale_up_cooldown=20.0,
                   scale_up_pods=2, policy="holt", horizon=45.0),
        SweepPoint(scale_up_messages=200, scale_down_messages=20,
                   scale_down_cooldown=60.0, policy="reactive"),
        SweepPoint(scale_up_pods=3, policy="lstsq", horizon=15.0,
                   history=64),
        SweepPoint(scale_up_messages=50, policy="ewma", horizon=15.0),
    ]
    extra = [
        (f"{scenario.name}/{point.label()}", point.to_config(scenario))
        for scenario in scenarios
        for point in points
    ]
    report = verify_fidelity(
        scenarios=scenarios, forecasters=(), extra_episodes=extra
    )
    assert report.episodes == 2 + len(extra)
    assert report.ok, "\n".join(report.format_divergences(20))


def test_fidelity_report_formats_divergences_with_episode_labels():
    from kube_sqs_autoscaler_tpu.sim.compiled import FidelityReport
    from kube_sqs_autoscaler_tpu.sim.replay import Divergence

    report = FidelityReport(
        episodes=1,
        ticks=3,
        divergences=[("ramp/reactive", Divergence(2, "up", "fire", "idle"))],
    )
    assert not report.ok
    lines = report.format_divergences()
    assert lines == [
        "ramp/reactive: tick 2: up recorded='fire' replayed='idle'"
    ]


def test_seed_constant_world_matches_python_exactly():
    # The seed's plain-float arrival_rate path uses its own net-rate
    # expression; the compiled twin must reproduce its timeline
    # sample-for-sample, including float times and int depths.
    config = SimConfig(
        arrival_rate=120.0, service_rate_per_replica=10.0, duration=400.0,
        initial_replicas=1, max_pods=50, loop=short_loop(poll=1.0),
    )
    python = Simulation(config).run()
    compiled = run_compiled_one(config)
    assert compiled.timeline == python.timeline
    assert compiled.final_replicas == python.final_replicas
    assert compiled.max_depth == python.max_depth
    assert compiled.ticks == python.ticks


def test_compiled_result_scores_like_the_battery():
    scenario = default_battery()[0]
    config = SimConfig(
        arrival_rate=scenario.arrival,
        service_rate_per_replica=scenario.service_rate_per_replica,
        duration=scenario.duration,
        initial_replicas=scenario.initial_replicas,
        min_pods=scenario.min_pods,
        max_pods=scenario.max_pods,
        loop=scenario.loop,
    )
    python_row = score_result(Simulation(config).run(), scenario.slo_depth)
    compiled_row = score_result(run_compiled_one(config), scenario.slo_depth)
    assert compiled_row == python_row


def test_recorded_arrival_from_a_journal_sweeps_through_compiled():
    # Host-side arrival precomputation means ANY ArrivalProcess works —
    # including the piecewise process replay infers from a flight journal,
    # closing the loop from incident journal to compiled parameter sweep.
    from kube_sqs_autoscaler_tpu.sim.replay import RecordedArrival

    arrival = RecordedArrival(
        times=(0.0, 50.0, 100.0), rates=(20.0, 150.0, 30.0)
    )
    config = SimConfig(
        arrival_rate=arrival, service_rate_per_replica=10.0, duration=300.0,
        initial_replicas=2, max_pods=20, loop=short_loop(),
    )
    python = Simulation(config).run()
    compiled = run_compiled_one(config)
    assert compiled.timeline == python.timeline
    assert compiled.final_replicas == python.final_replicas


def test_predictive_compiled_episode_matches_python_on_a_short_ramp():
    config = SimConfig(
        arrival_rate=RampArrival(
            start_rate=10.0, end_rate=150.0, t_start=30.0, t_end=300.0
        ),
        service_rate_per_replica=10.0, duration=300.0,
        initial_replicas=1, max_pods=25, loop=short_loop(),
        policy="predictive", forecaster="holt", forecast_horizon=30.0,
        forecast_history=64,
    )
    python = Simulation(config).run()
    compiled = run_compiled_one(config)
    assert compiled.timeline == python.timeline
    assert compiled.final_replicas == python.final_replicas


def test_batch_rejects_mixed_tick_counts_and_capacities():
    base = dict(
        arrival_rate=50.0, service_rate_per_replica=10.0,
        initial_replicas=1, loop=short_loop(),
    )
    with pytest.raises(ValueError, match="tick count"):
        run_compiled([
            SimConfig(duration=300.0, **base),
            SimConfig(duration=600.0, **base),
        ])
    with pytest.raises(ValueError, match="forecast_history"):
        run_compiled([
            SimConfig(duration=300.0, forecast_history=64, **base),
            SimConfig(duration=300.0, forecast_history=128, **base),
        ])


def test_encode_rejects_unknown_policy_and_forecaster():
    base = dict(arrival_rate=50.0, duration=100.0, loop=short_loop())
    with pytest.raises(ValueError, match="policy"):
        encode_config(SimConfig(policy="quantum", **base))
    with pytest.raises(ValueError, match="forecaster"):
        encode_config(
            SimConfig(policy="predictive", forecaster="oracle", **base)
        )


def test_episode_ticks_matches_simulation_run():
    config = SimConfig(arrival_rate=10.0, duration=42.0, loop=short_loop())
    assert episode_ticks(config) == Simulation(config).run().ticks


def test_compiled_episode_exposes_gate_enums():
    from kube_sqs_autoscaler_tpu.core.policy import Gate

    config = SimConfig(
        arrival_rate=BurstArrival(
            base=20.0, burst_rate=200.0, period=120.0, burst_len=30.0,
            first_burst=30.0,
        ),
        service_rate_per_replica=10.0, duration=300.0,
        initial_replicas=1, max_pods=20, loop=short_loop(),
    )
    (episode,) = run_episodes([config])
    gates = {episode.gates(i) for i in range(len(episode.observed))}
    ups = {up for up, _ in gates}
    assert Gate.FIRE in ups  # the burst must trip the up gate
    assert all(isinstance(up, Gate) and isinstance(dn, Gate)
               for up, dn in gates)
