"""KubeDeploymentAPI tests against a local HTTP double of the apiserver's
apps/v1 Deployment endpoints, plus config-resolution tests mirroring the
reference's KUBE_CONFIG_PATH / in-cluster / panic behavior
(scale/scale.go:31-52).
"""

import json

import pytest

from kube_sqs_autoscaler_tpu.core.types import ScaleError
from kube_sqs_autoscaler_tpu.scale.actuator import PodAutoScaler
from kube_sqs_autoscaler_tpu.scale.kube import (
    ClusterConfig,
    KubeApiError,
    KubeConfigError,
    KubeDeploymentAPI,
    load_config,
    load_kubeconfig,
)

from .httptestserver import Reply, LocalHttpServer


def deployment_body(name="workers", namespace="prod", replicas=3, rv="100"):
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": namespace, "resourceVersion": rv},
        "spec": {"replicas": replicas, "selector": {"matchLabels": {"app": name}}},
        "status": {"replicas": replicas},
    }


class FakeApiServer:
    """Scriptable apps/v1 Deployment endpoints over LocalHttpServer."""

    def __init__(self, deployments: dict[str, dict]):
        self.deployments = deployments

    def __call__(self, exchange):
        parts = exchange.path.strip("/").split("/")
        # apis/apps/v1/namespaces/{ns}/deployments/{name}
        if parts[:4] != ["apis", "apps", "v1", "namespaces"] or parts[5] != "deployments":
            return Reply.json({"message": "not found"}, status=404)
        name = parts[6]
        if exchange.method == "GET":
            if name not in self.deployments:
                return Reply.json(
                    {"kind": "Status", "message": f'deployments.apps "{name}" not found'},
                    status=404,
                )
            return Reply.json(self.deployments[name])
        if exchange.method == "PUT":
            if name not in self.deployments:
                return Reply.json({"kind": "Status", "message": "not found"}, status=404)
            self.deployments[name] = json.loads(exchange.body)
            return Reply.json(self.deployments[name])
        return Reply.json({"message": "method not allowed"}, status=405)


def make_api(server_url, namespace="prod"):
    return KubeDeploymentAPI(
        namespace=namespace, config=ClusterConfig(server=server_url, token="tok-abc")
    )


def test_get_parses_deployment():
    fake = FakeApiServer({"workers": deployment_body(replicas=7)})
    with LocalHttpServer(fake) as server:
        deployment = make_api(server.url).get("workers")
    assert deployment.name == "workers"
    assert deployment.namespace == "prod"
    assert deployment.replicas == 7
    exchange = server.exchanges[0]
    assert exchange.path == "/apis/apps/v1/namespaces/prod/deployments/workers"
    assert exchange.headers["Authorization"] == "Bearer tok-abc"


def test_update_puts_full_object():
    fake = FakeApiServer({"workers": deployment_body(replicas=3)})
    with LocalHttpServer(fake) as server:
        api = make_api(server.url)
        deployment = api.get("workers")
        api.update(deployment.with_replicas(5))
    put = server.exchanges[-1]
    assert put.method == "PUT"
    body = json.loads(put.body)
    # full-object read-modify-write: everything round-trips, replicas changed
    assert body["spec"]["replicas"] == 5
    assert body["spec"]["selector"] == {"matchLabels": {"app": "workers"}}
    assert body["metadata"]["resourceVersion"] == "100"
    assert fake.deployments["workers"]["spec"]["replicas"] == 5


def test_actuator_end_to_end_over_http():
    # The production PodAutoScaler driving the real REST client against the
    # fake apiserver: 3 -> 4 -> 5 -> clamp no-op (scale/scale_test.go:14-33
    # over a socket instead of an in-memory fake).
    fake = FakeApiServer({"workers": deployment_body(replicas=3)})
    with LocalHttpServer(fake) as server:
        scaler = PodAutoScaler(
            client=make_api(server.url), max=5, min=1, scale_up_pods=1,
            scale_down_pods=1, deployment="workers", namespace="prod",
        )
        scaler.scale_up()
        assert fake.deployments["workers"]["spec"]["replicas"] == 4
        scaler.scale_up()
        assert fake.deployments["workers"]["spec"]["replicas"] == 5
        scaler.scale_up()  # boundary no-op, no PUT
        assert fake.deployments["workers"]["spec"]["replicas"] == 5
        scaler.scale_down()
        assert fake.deployments["workers"]["spec"]["replicas"] == 4
    puts = [e for e in server.exchanges if e.method == "PUT"]
    assert len(puts) == 3


def test_missing_deployment_becomes_scale_error_with_reference_context():
    fake = FakeApiServer({})
    with LocalHttpServer(fake) as server:
        scaler = PodAutoScaler(
            client=make_api(server.url), max=5, min=1, scale_up_pods=1,
            scale_down_pods=1, deployment="ghost", namespace="prod",
        )
        with pytest.raises(ScaleError, match="no scale up occurred"):
            scaler.scale_up()


def test_http_error_carries_status_and_message():
    fake = FakeApiServer({})
    with LocalHttpServer(fake) as server:
        with pytest.raises(KubeApiError, match="not found") as info:
            make_api(server.url).get("ghost")
    assert info.value.status == 404


def test_transport_error_is_kube_api_error():
    api = KubeDeploymentAPI(
        namespace="prod",
        config=ClusterConfig(server="http://127.0.0.1:1"),
        timeout=0.5,
    )
    with pytest.raises(KubeApiError, match="failed"):
        api.get("workers")


def test_load_kubeconfig_current_context(tmp_path):
    config_file = tmp_path / "kubeconfig"
    config_file.write_text(
        """
apiVersion: v1
kind: Config
current-context: prod-ctx
contexts:
- name: prod-ctx
  context: {cluster: prod-cluster, user: prod-user}
- name: other
  context: {cluster: other-cluster, user: other-user}
clusters:
- name: prod-cluster
  cluster: {server: "https://10.0.0.1:6443", insecure-skip-tls-verify: true}
- name: other-cluster
  cluster: {server: "https://10.9.9.9:6443"}
users:
- name: prod-user
  user: {token: sekrit}
- name: other-user
  user: {}
"""
    )
    config = load_kubeconfig(config_file)
    assert config.server == "https://10.0.0.1:6443"
    assert config.token == "sekrit"
    assert config.skip_tls_verify is True


def test_kube_config_path_env_selects_kubeconfig(tmp_path, monkeypatch):
    config_file = tmp_path / "kubeconfig"
    config_file.write_text(
        """
current-context: c
contexts: [{name: c, context: {cluster: cl, user: u}}]
clusters: [{name: cl, cluster: {server: "http://localhost:8080"}}]
users: [{name: u, user: {}}]
"""
    )
    monkeypatch.setenv("KUBE_CONFIG_PATH", str(config_file))
    assert load_config().server == "http://localhost:8080"


def test_config_failure_raises_reference_panic_message(monkeypatch):
    # scale/scale.go:35 panics with this exact message on config failure;
    # no kubeconfig and no in-cluster env must be fatal at construction.
    monkeypatch.setenv("KUBE_CONFIG_PATH", "/does/not/exist")
    with pytest.raises(KubeConfigError, match="Failed to configure incluster or local config"):
        load_config()
    monkeypatch.delenv("KUBE_CONFIG_PATH")
    monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
    with pytest.raises(KubeConfigError, match="Failed to configure incluster or local config"):
        load_config()
