"""The train→serve loop: a trainer checkpoint restored by the serving
worker (manifest-driven architecture, orbax weight restore, sharded
serving) produces the trained model's outputs — closing the
controller-scales-workers-that-serve-the-trained-model story.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kube_sqs_autoscaler_tpu.workloads.__main__ import main as worker_main
from kube_sqs_autoscaler_tpu.workloads.checkpoint import (
    TrainCheckpointer,
    load_model_manifest,
    save_model_manifest,
)
from kube_sqs_autoscaler_tpu.workloads.trainer import main as trainer_main

TINY_TRAIN = [
    "--vocab-size", "256", "--d-model", "64", "--n-heads", "4",
    "--n-layers", "2", "--d-ff", "128", "--seq-len", "32",
    "--batch-size", "8", "--learning-rate", "1e-2", "--log-every", "1",
]


def test_manifest_roundtrip(tmp_path):
    from kube_sqs_autoscaler_tpu.workloads.llama import LlamaConfig
    from kube_sqs_autoscaler_tpu.workloads.model import ModelConfig

    gpt = ModelConfig(vocab_size=128, d_model=64, n_heads=2, n_layers=1,
                      d_ff=128, max_seq_len=32)
    save_model_manifest(tmp_path, "gpt", gpt)
    family, restored = load_model_manifest(tmp_path)
    assert family == "gpt" and restored == gpt

    llama = LlamaConfig(vocab_size=128, d_model=64, n_heads=4, n_kv_heads=2,
                        n_layers=1, d_ff=96, max_seq_len=32)
    save_model_manifest(tmp_path, "llama", llama)
    family, restored = load_model_manifest(tmp_path)
    assert family == "llama" and restored == llama


@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_worker_serves_trained_weights_end_to_end(tmp_path, family):
    """train N steps → checkpoint → worker restores and serves → the
    restored weights equal the trainer's final weights (not random init),
    and the worker's demo drain completes on them."""
    ckpt = str(tmp_path / "ckpt")
    result = trainer_main(
        TINY_TRAIN + ["--family", family, "--steps", "2",
                      "--checkpoint-dir", ckpt]
    )
    assert result["final_step"] == 2

    # what the worker restores must match the trainer's saved weights
    man_family, config = load_model_manifest(ckpt)
    assert man_family == family
    from kube_sqs_autoscaler_tpu.workloads.train import make_mesh

    mesh = make_mesh(jax.devices()[:1], model_parallel=1)
    served = TrainCheckpointer(ckpt).restore_params(mesh, man_family, config)

    if family == "llama":
        from kube_sqs_autoscaler_tpu.workloads.llama import (
            init_llama_params as init_fn,
            llama_forward as forward_fn,
        )
    else:
        from kube_sqs_autoscaler_tpu.workloads.model import (
            forward as forward_fn,
            init_params as init_fn,
        )
    fresh = init_fn(jax.random.key(0), config)  # the trainer's seed-0 init
    # training moved the weights: restored != init, proving the worker is
    # not silently serving random weights
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(served), jax.tree.leaves(fresh))
    )

    # the worker binary end to end: --demo drain against the checkpoint
    worker_main(["--demo", "4", "--checkpoint-dir", ckpt,
                 "--batch-size", "4", "--seq-len", "16"])

    # output parity: a direct forward on the restored weights matches the
    # trained model's forward (same tokens, bit-for-bit params)
    tokens = jax.random.randint(jax.random.key(3), (2, 16), 0,
                                config.vocab_size, jnp.int32)
    direct = forward_fn(served, tokens, config)
    assert np.isfinite(np.asarray(direct)).all()


def test_sharded_serving_matches_single_chip(tmp_path):
    """--model-parallel serving (make_forward_step + serving fns) returns
    the same logits/tokens as the single-chip path on restored weights."""
    ckpt = str(tmp_path / "ckpt")
    trainer_main(TINY_TRAIN + ["--family", "llama", "--steps", "2",
                               "--checkpoint-dir", ckpt])
    _, config = load_model_manifest(ckpt)

    from kube_sqs_autoscaler_tpu.workloads.llama import (
        llama_forward,
        llama_generate_jit,
        make_llama_serving_fns,
    )
    from kube_sqs_autoscaler_tpu.workloads.train import (
        make_forward_step,
        make_mesh,
    )

    mesh = make_mesh(jax.devices(), model_parallel=2)  # data=4 x model=2
    params = TrainCheckpointer(ckpt).restore_params(mesh, "llama", config)
    tokens = jax.random.randint(jax.random.key(5), (4, 16), 0,
                                config.vocab_size, jnp.int32)

    fwd = make_forward_step(mesh, config, params, forward_fn=llama_forward)
    sharded_logits = np.asarray(fwd(params, tokens))
    single_logits = np.asarray(llama_forward(params, tokens, config))
    # bf16 compute: sharded all-reduce orderings reassociate fp adds
    np.testing.assert_allclose(sharded_logits, single_logits,
                               rtol=2e-2, atol=2e-2)
    # the worker-observable behavior (greedy next token) is identical
    np.testing.assert_array_equal(
        sharded_logits[:, -1].argmax(-1), single_logits[:, -1].argmax(-1)
    )

    _, _, gen = make_llama_serving_fns(mesh, config, params)
    lengths = jnp.full((tokens.shape[0],), tokens.shape[1], jnp.int32)
    sharded_out = np.asarray(
        gen(params, tokens, jax.random.key(0), lengths, 4)
    )
    single_out = np.asarray(llama_generate_jit(params, tokens, 4, config))
    np.testing.assert_array_equal(sharded_out, single_out)

    # eos through the llama sharded contract too (VERDICT r3 #4)
    eos = int(single_out[0, 0])
    sharded_eos = np.asarray(gen(
        params, tokens, jax.random.key(0), lengths, 4, 0.0, 0, 1.0, eos
    ))
    single_eos = np.asarray(llama_generate_jit(
        params, tokens, 4, config, eos_id=eos
    ))
    np.testing.assert_array_equal(sharded_eos, single_eos)
    assert (sharded_eos[0] == eos).all()  # row 0 finished at its 1st token


def test_worker_sharded_demo_runs(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    trainer_main(TINY_TRAIN + ["--steps", "2", "--checkpoint-dir", ckpt])
    worker_main(["--demo", "8", "--checkpoint-dir", ckpt,
                 "--model-parallel", "2", "--batch-size", "4",
                 "--seq-len", "16", "--generate-tokens", "4"])


def test_pipeline_trained_checkpoint_serves(tmp_path):
    """pp-trained checkpoints close the train→serve loop too: the manifest
    records the stage-stacked layout, restore_params converts it to the
    flat layers/wqkv serving layout, and the converted weights produce the
    same logits as the pipelined forward did at train time."""
    from kube_sqs_autoscaler_tpu.workloads.checkpoint import (
        load_model_layout,
    )
    from kube_sqs_autoscaler_tpu.workloads.model import forward
    from kube_sqs_autoscaler_tpu.workloads.train import make_mesh

    ckpt = str(tmp_path / "ckpt")
    result = trainer_main(
        TINY_TRAIN + ["--steps", "2", "--pipe-parallel", "2",
                      "--pipe-microbatches", "2", "--checkpoint-dir", ckpt]
    )
    assert result["final_step"] == 2
    layout = load_model_layout(ckpt)
    assert layout == {"kind": "pipeline", "n_stages": 2}

    man_family, config = load_model_manifest(ckpt)
    mesh = make_mesh(jax.devices()[:1], model_parallel=1)
    served = TrainCheckpointer(ckpt).restore_params(
        mesh, man_family, config, layout=layout
    )
    # flat serving layout, fused wqkv
    assert "layers" in served and "stages" not in served
    assert "wqkv" in served["layers"][0]
    assert len(served["layers"]) == config.n_layers

    # trained weights, not init: compare against the pipeline init
    from kube_sqs_autoscaler_tpu.workloads.pipeline import (
        init_pipeline_params,
        unstack_layers,
    )

    fresh = unstack_layers(
        init_pipeline_params(jax.random.key(0), config, n_stages=2)
    )
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(served), jax.tree.leaves(fresh))
    )

    # the worker binary serves it end to end
    worker_main(["--demo", "4", "--checkpoint-dir", ckpt,
                 "--batch-size", "4", "--seq-len", "16"])

    tokens = jax.random.randint(jax.random.key(3), (2, 16), 0,
                                config.vocab_size, jnp.int32)
    assert np.isfinite(np.asarray(forward(served, tokens, config))).all()


def test_resume_pipeline_dir_without_pipe_flag_fails_fast(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    trainer_main(TINY_TRAIN + ["--steps", "2", "--pipe-parallel", "2",
                               "--pipe-microbatches", "2",
                               "--checkpoint-dir", ckpt])
    with pytest.raises(SystemExit, match="layout"):
        trainer_main(TINY_TRAIN + ["--steps", "1", "--checkpoint-dir", ckpt,
                                   "--resume"])
