"""AwsSqsService tests against a local HTTP double of the SQS JSON API,
plus credential-chain and region-resolution unit tests.  No real AWS.
"""

import json

import pytest

from kube_sqs_autoscaler_tpu.core.types import MetricError
from kube_sqs_autoscaler_tpu.metrics.queue import QueueMetricSource
from kube_sqs_autoscaler_tpu.metrics.sqs_aws import (
    AwsError,
    AwsSqsService,
    CredentialsError,
    region_from_queue_url,
    resolve_credentials,
)
from kube_sqs_autoscaler_tpu.utils.sigv4 import Credentials

from .httptestserver import Reply, LocalHttpServer

CREDS = Credentials("AKIDTEST", "secret")


def test_get_queue_attributes_roundtrip():
    def handler(exchange):
        body = json.loads(exchange.body)
        assert body["QueueUrl"].endswith("/123/my-queue")
        assert body["AttributeNames"] == ["ApproximateNumberOfMessages"]
        return Reply.json({"Attributes": {"ApproximateNumberOfMessages": "42"}})

    with LocalHttpServer(handler) as server:
        service = AwsSqsService(
            region="us-east-1", credentials=CREDS, endpoint=server.url
        )
        attributes = service.get_queue_attributes(
            f"{server.url}/123/my-queue", ["ApproximateNumberOfMessages"]
        )
    assert attributes == {"ApproximateNumberOfMessages": "42"}

    exchange = server.exchanges[0]
    assert exchange.method == "POST"
    assert exchange.headers["X-Amz-Target"] == "AmazonSQS.GetQueueAttributes"
    assert exchange.headers["Content-Type"] == "application/x-amz-json-1.0"
    auth = exchange.headers["Authorization"]
    assert auth.startswith("AWS4-HMAC-SHA256 Credential=AKIDTEST/")
    assert "/us-east-1/sqs/aws4_request" in auth
    assert "x-amz-date" in auth  # signed headers include the date


def test_full_metric_source_over_http():
    # End-to-end: QueueMetricSource -> AwsSqsService -> HTTP -> sum
    def handler(exchange):
        return Reply.json(
            {
                "Attributes": {
                    "ApproximateNumberOfMessages": "10",
                    "ApproximateNumberOfMessagesDelayed": "10",
                    "ApproximateNumberOfMessagesNotVisible": "10",
                }
            }
        )

    with LocalHttpServer(handler) as server:
        source = QueueMetricSource(
            client=AwsSqsService(
                region="us-east-1", credentials=CREDS, endpoint=server.url
            ),
            queue_url=f"{server.url}/123/q",
        )
        assert source.num_messages() == 30


def test_service_error_becomes_metric_error():
    def handler(exchange):
        return Reply.json(
            {"__type": "com.amazonaws.sqs#QueueDoesNotExist"}, status=400
        )

    with LocalHttpServer(handler) as server:
        source = QueueMetricSource(
            client=AwsSqsService(
                region="us-east-1", credentials=CREDS, endpoint=server.url
            ),
            queue_url=f"{server.url}/123/q",
        )
        with pytest.raises(MetricError, match="Failed to get messages in SQS"):
            source.num_messages()


def test_message_operations_roundtrip():
    # send/receive/delete/change-visibility speak the same signed JSON
    # protocol with the right X-Amz-Target per action
    state = {"deleted": [], "visibility": []}

    def handler(exchange):
        target = exchange.headers["X-Amz-Target"]
        body = json.loads(exchange.body)
        if target == "AmazonSQS.SendMessage":
            assert body["MessageBody"] == "[1, 2, 3]"
            return Reply.json({"MessageId": "m-1"})
        if target == "AmazonSQS.ReceiveMessage":
            assert 1 <= body["MaxNumberOfMessages"] <= 10  # SQS hard limit
            # the --request-ttl deadline needs the queue's send stamp
            assert body["AttributeNames"] == ["SentTimestamp"]
            return Reply.json(
                {"Messages": [
                    {"ReceiptHandle": "rh-1", "Body": "[1, 2, 3]",
                     "Attributes": {"SentTimestamp": "1700000000000"}},
                    # SQS may omit Attributes (e.g. a proxy that strips
                    # them); the adapter must not invent the key
                    {"ReceiptHandle": "rh-2", "Body": "[4]"},
                ]}
            )
        if target == "AmazonSQS.DeleteMessage":
            state["deleted"].append(body["ReceiptHandle"])
            return Reply.json({})
        if target == "AmazonSQS.ChangeMessageVisibility":
            state["visibility"].append(
                (body["ReceiptHandle"], body["VisibilityTimeout"])
            )
            return Reply.json({})
        raise AssertionError(f"unexpected target {target}")

    with LocalHttpServer(handler) as server:
        service = AwsSqsService(
            region="us-east-1", credentials=CREDS, endpoint=server.url
        )
        url = f"{server.url}/123/q"
        assert service.send_message(url, "[1, 2, 3]") == "m-1"
        messages = service.receive_messages(url, max_messages=16)  # clamped
        assert messages == [
            {"MessageId": "", "ReceiptHandle": "rh-1", "Body": "[1, 2, 3]",
             "Attributes": {"SentTimestamp": "1700000000000"}},
            {"MessageId": "", "ReceiptHandle": "rh-2", "Body": "[4]"},
        ]
        service.delete_message(url, "rh-1")
        service.change_message_visibility(url, "rh-2", 0)
    assert state["deleted"] == ["rh-1"]
    assert state["visibility"] == [("rh-2", 0)]
    for exchange in server.exchanges:
        assert exchange.headers["Authorization"].startswith("AWS4-HMAC-SHA256")


def test_transport_error_is_aws_error():
    service = AwsSqsService(
        region="us-east-1", credentials=CREDS, endpoint="http://127.0.0.1:1",
        timeout=0.5,
    )
    with pytest.raises(AwsError, match="request failed"):
        service.get_queue_attributes("http://127.0.0.1:1/q", ["A"])


def test_region_from_queue_url():
    assert (
        region_from_queue_url("https://sqs.eu-west-2.amazonaws.com/1/q") == "eu-west-2"
    )
    assert region_from_queue_url("http://127.0.0.1:999/1/q") is None


def test_region_resolution_order(monkeypatch):
    monkeypatch.setenv("AWS_REGION", "ap-south-1")
    service = AwsSqsService(credentials=CREDS)
    assert service._resolve_region("http://host/q") == "ap-south-1"
    monkeypatch.delenv("AWS_REGION")
    monkeypatch.delenv("AWS_DEFAULT_REGION", raising=False)
    assert (
        AwsSqsService(credentials=CREDS)._resolve_region(
            "https://sqs.us-west-2.amazonaws.com/1/q"
        )
        == "us-west-2"
    )
    with pytest.raises(AwsError, match="Cannot determine AWS region"):
        AwsSqsService(credentials=CREDS)._resolve_region("http://host/q")


def test_credentials_from_env(monkeypatch):
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AKIDENV")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "s3cret")
    monkeypatch.setenv("AWS_SESSION_TOKEN", "tok")
    creds = resolve_credentials(allow_imds=False)
    assert creds == Credentials("AKIDENV", "s3cret", "tok")


def test_credentials_from_shared_file(monkeypatch, tmp_path):
    monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)
    monkeypatch.delenv("AWS_SECRET_ACCESS_KEY", raising=False)
    creds_file = tmp_path / "credentials"
    creds_file.write_text(
        "[default]\n"
        "aws_access_key_id = AKIDFILE\n"
        "aws_secret_access_key = filesecret\n"
        "\n"
        "[other]\n"
        "aws_access_key_id = AKIDOTHER\n"
        "aws_secret_access_key = othersecret\n"
    )
    monkeypatch.setenv("AWS_SHARED_CREDENTIALS_FILE", str(creds_file))
    assert resolve_credentials(allow_imds=False).access_key_id == "AKIDFILE"
    monkeypatch.setenv("AWS_PROFILE", "other")
    assert resolve_credentials(allow_imds=False).access_key_id == "AKIDOTHER"


def test_no_credentials_anywhere_raises(monkeypatch, tmp_path):
    monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)
    monkeypatch.delenv("AWS_SECRET_ACCESS_KEY", raising=False)
    monkeypatch.delenv("AWS_PROFILE", raising=False)
    monkeypatch.setenv("AWS_SHARED_CREDENTIALS_FILE", str(tmp_path / "missing"))
    with pytest.raises(CredentialsError):
        resolve_credentials(allow_imds=False)
