"""Tiny in-process HTTP server for client tests.

Python's answer to Go's ``httptest``: a ThreadingHTTPServer on a random
localhost port, with the handler delegating to a per-test callable so tests
can assert on requests and script responses.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable


@dataclass
class Exchange:
    method: str
    path: str
    headers: dict[str, str]
    body: bytes


@dataclass
class Reply:
    status: int = 200
    body: bytes = b"{}"
    content_type: str = "application/json"

    @classmethod
    def json(cls, obj, status: int = 200) -> "Reply":
        return cls(status=status, body=json.dumps(obj).encode("utf-8"))


@dataclass
class LocalHttpServer:
    handler: Callable[[Exchange], Reply]
    exchanges: list[Exchange] = field(default_factory=list)

    def __enter__(self) -> "LocalHttpServer":
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def _serve(self) -> None:
                length = int(self.headers.get("Content-Length") or 0)
                exchange = Exchange(
                    method=self.command,
                    path=self.path,
                    headers={k: v for k, v in self.headers.items()},
                    body=self.rfile.read(length) if length else b"",
                )
                outer.exchanges.append(exchange)
                reply = outer.handler(exchange)
                self.send_response(reply.status)
                self.send_header("Content-Type", reply.content_type)
                self.send_header("Content-Length", str(len(reply.body)))
                self.end_headers()
                self.wfile.write(reply.body)

            do_GET = do_POST = do_PUT = do_DELETE = do_PATCH = _serve

            def log_message(self, *args) -> None:  # keep test output clean
                pass

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        self.url = f"http://127.0.0.1:{self._server.server_address[1]}"
        return self

    def __exit__(self, *exc) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)
