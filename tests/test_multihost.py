"""Two-process multi-host data-path test (VERDICT round-2 item 8).

Launches two REAL Python processes that form a jax.distributed cluster
over CPU (4 forced host devices each = 8 global), build one global
``("data", "seq", "model")`` mesh spanning both, and push the synthetic
input pipeline through ``prefetch_to_mesh`` against the global batch
sharding — the only environment where per-host-array vs global-sharding
mismatches can surface (the in-process 8-device suite cannot see them).

Success = both children bootstrap (process_count == 2, 8 global devices),
both run 2 sharded train steps, and both report the SAME loss (the loss
is a replicated scalar produced by a psum over the whole mesh — a
mismatch means the hosts trained on inconsistent shards).
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

_CHILD = Path(__file__).with_name("multihost_child.py")


def _free_port() -> int:
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    return port


def test_two_process_data_path_and_train_step():
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(_CHILD)],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                env=env,
                cwd=str(_CHILD.parent.parent),
            )
        )
    outputs = []
    for proc in procs:
        out, _ = proc.communicate(timeout=240)
        outputs.append(out)
    for pid, (proc, out) in enumerate(zip(procs, outputs)):
        assert proc.returncode == 0, (
            f"child {pid} failed (rc={proc.returncode}):\n{out[-3000:]}"
        )
    losses = []
    for pid, out in enumerate(outputs):
        assert f"BOOT process={pid}/2 global_devices=8" in out, out[-2000:]
        loss_lines = [l for l in out.splitlines() if l.startswith("LOSS ")]
        assert loss_lines, f"child {pid} printed no loss:\n{out[-2000:]}"
        losses.append(float(loss_lines[-1].split()[1]))
    # replicated psum-produced scalar: must be identical across hosts
    assert losses[0] == pytest.approx(losses[1], abs=0.0), losses
