"""``python -m kube_sqs_autoscaler_tpu`` — the controller binary entry point
(reference: the ``/kube-sqs-autoscaler`` static binary, ``Dockerfile:9``).
"""

from .cli import main

main()
