"""WorkerPool: the serving fleet behind the ``scale`` actuator seam.

The control plane (PRs 1-4) actuates a replica *integer*; the serving
engine (PR 5) is one in-process worker.  This module fuses them: a
:class:`WorkerPool` is a :class:`~..core.types.Scaler` whose
``scale_up``/``scale_down`` spin real
:class:`~.worker.FleetWorker` replicas up and down, so the unchanged
:class:`~..core.loop.ControlLoop` — forecasting, resilience, journal,
replay and all — drives a measurable serving fleet instead of a number.
The fleet is the deployment.

Semantics mirror :class:`~..scale.actuator.PodAutoScaler` exactly
through the seam (the contract test in
``tests/test_actuator_contract.py`` pins this): step by
``scale_up_pods``/``scale_down_pods`` clamped to ``[min, max]``,
boundary no-ops are *success* (the policy refreshes its cooldown on
them), failures raise :class:`~..core.types.ScaleError` and change
nothing.

Robustness model (the tentpole):

- **spin-up is O(1) host work** — a new replica shares the pool's
  already-built (optionally int8-quantized) params by reference and
  adopts the donor replica's compiled programs
  (:meth:`~..workloads.continuous.ContinuousBatcher.adopt_engine`); it
  pays only its own KV-cache allocation, never a model rebuild or an XLA
  recompile (BLITZSCALE, PAPERS.md);
- **drain is graceful** — ``scale_down`` marks the newest replicas
  draining: they stop admitting, keep stepping their in-flight slots,
  and retire once empty.  A drain that exceeds
  ``drain_timeout_cycles`` hands its un-finished requests back to the
  queue (``change_message_visibility(0)`` when the queue supports it)
  so survivors pick them up — giving up never loses work;
- **the supervisor loses nothing** — a killed replica (or a hung one,
  caught by the progress watchdog after ``hang_grace_cycles`` busy
  cycles without a token) is declared dead; its un-replied in-flight
  requests are re-dispatched to surviving replicas, and the pool-level
  reply registry guarantees a request the dead replica already answered
  is never answered twice (the same registry dedups visibility-timeout
  redeliveries).  The fleet degrades to fewer replicas rather than
  stalling — respawning is the control loop's job, through the same
  gates as any other scale-up;
- **the router spreads traffic** — each fleet cycle steps serving
  replicas freest-first, each pulling at most its free-slot count from
  the shared queue, with re-dispatched orphans admitted ahead of fresh
  queue traffic.

Everything is synchronous and deterministic: faults are flag flips at
known cycles (:class:`~..sim.faults.FleetFaultPlan`), not process
murder, so the chaos battery's zero-lost / zero-duplicate gates are
replayable.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

from ..core.clock import Clock, SystemClock
from ..core.types import ScaleError

log = logging.getLogger(__name__)

# The constructor's min/max keyword names (chosen for PodAutoScaler
# field parity) shadow the builtins in signatures — alias them so the
# clamp math inside methods stays unambiguous.
builtins_min = min
builtins_max = max

def _free_count(batcher) -> int:
    """Admission capacity as a bare count.  The router and the orphan
    dispatcher only need HOW MANY slots a replica offers — the sharded
    plane's ``free_slots`` property additionally pays a freest-first
    ordering merge per read, so count-only reads go through
    ``_free_slot_count`` when the batcher provides it (contract-test
    stubs carry a plain ``free_slots`` list and fall back)."""
    counter = getattr(batcher, "_free_slot_count", None)
    return counter() if counter is not None else len(batcher.free_slots)


# Lifecycle states a replica moves through (exported as the
# fleet_replica_state gauge; codes are stable dashboard contract).
SERVING = "serving"
DRAINING = "draining"
DEAD = "dead"
STOPPED = "stopped"
REPLICA_STATE_CODES = {SERVING: 0, DRAINING: 1, DEAD: 2, STOPPED: 3}


@dataclass(frozen=True)
class FleetEvent:
    """One supervisor decision, timestamped on the pool's clock — the
    fleet's analogue of a :class:`~..core.events.TickRecord`, exported
    as Chrome-trace instants (:func:`~..obs.trace.instant_trace_events`)."""

    name: str  # replica-spawn | replica-kill | replica-drain-start | ...
    t: float
    args: dict = field(default_factory=dict)


class _BoundedSet:
    """Insertion-ordered set with a capacity: the reply registry.

    Request ids are unique per queue, so membership only ever needs to
    cover the recent past (a redelivery horizon); bounding it keeps a
    long-lived fleet's memory flat."""

    def __init__(self, capacity: int) -> None:
        self._capacity = capacity
        self._set: set = set()
        self._order: deque = deque()

    def add(self, item) -> None:
        if item in self._set:
            return
        self._set.add(item)
        self._order.append(item)
        while len(self._order) > self._capacity:
            self._set.discard(self._order.popleft())

    def __contains__(self, item) -> bool:
        return item in self._set

    def __len__(self) -> int:
        return len(self._order)

    def items(self) -> list:
        """Insertion-ordered contents (durable-state serialization)."""
        return list(self._order)


class FleetPoolBase:
    """Plumbing shared by the two fleet actuators (:class:`WorkerPool`
    and :class:`~.sharded.ShardedWorkerPool`): the bounded exactly-once
    reply registry, the :class:`FleetEvent` stream + Chrome-trace
    export, and the contract tests' one-shot failure-injection seams —
    single-sourced so a fix to the zero-duplicate guarantee can never
    apply to one actuator and silently miss the other."""

    def __init__(
        self,
        *,
        clock: Clock | None = None,
        replied_capacity: int = 65536,
    ) -> None:
        self.clock = clock or SystemClock()
        self.events: deque[FleetEvent] = deque(maxlen=4096)
        self.cycle = 0
        self.metrics = None
        # request-lifecycle registry (obs/lifecycle.py): one registry
        # for the WHOLE fleet — a request's chain threads through
        # whichever members touch it; attach_lifecycle propagates to
        # every current and future member.  None = tracing off.
        self.lifecycle = None
        self._replied = _BoundedSet(replied_capacity)
        self.duplicates_suppressed = 0
        # test seams, mirroring the fakes' error injection hooks
        self.fail_next_up: Exception | None = None
        self.fail_next_down: Exception | None = None

    def _injected_failure(self, direction: str) -> None:
        """Raise (once) the armed ``fail_next_up``/``fail_next_down``
        error as a :class:`ScaleError`, changing nothing — the contract
        tests' failure seam."""
        attr = f"fail_next_{direction}"
        err = getattr(self, attr)
        if err is not None:
            setattr(self, attr, None)
            raise ScaleError(f"Failed to scale {direction}") from err

    # -- reply registry (the zero-duplicate guarantee) -------------------

    def already_replied(self, rid: str) -> bool:
        return rid in self._replied

    def mark_replied(self, rid: str) -> None:
        self._replied.add(rid)

    def note_duplicate(self, rid: str) -> None:
        self.duplicates_suppressed += 1
        log.info("Suppressed duplicate reply for request %s", rid)

    # -- durable-state surface (core/durable.py StateProvider) -----------
    #
    # The registry is the WORST thing a controller restart used to lose
    # (ISSUE 14): the serving substrate is at-least-once, so a request
    # answered just before the crash can still have a redelivered copy
    # in the queue — a restarted pool with an empty registry re-answers
    # it, and the consumer sees two replies for one request id.

    def export_state(self) -> dict:
        # capacity is NOT serialized: the restarted pool's constructor
        # owns the bound, and re-adding through the bounded set below
        # reproduces the exact eviction state under whatever bound the
        # new boot configured
        return {
            "records": len(self._replied),
            "replied": self._replied.items(),
            "duplicates_suppressed": self.duplicates_suppressed,
        }

    def import_state(
        self, state: dict, *, rebase: float = 0.0,
        now: float | None = None, max_age_s: float = 0.0,
    ) -> int:
        """Restore the reply registry bitwise (insertion order and the
        capacity bound both survive — re-adding through the bounded set
        reproduces the exact eviction state a continuous pool would
        have).  Request ids are opaque; nothing here is clock-based."""
        del rebase, now, max_age_s
        recovered = 0
        for rid in state.get("replied") or ():
            self._replied.add(rid)
            recovered += 1
        self.duplicates_suppressed = int(
            state.get("duplicates_suppressed", 0) or 0
        )
        return recovered

    # -- event stream ----------------------------------------------------

    def _event(self, name: str, **args) -> None:
        self.events.append(FleetEvent(name, self.clock.now(), args))

    def trace_events(self, time_origin: float | None = None) -> list[dict]:
        """The pool's decisions as Chrome-trace instant events (merge
        into a tick trace via ``to_chrome_trace(..., extra_events=...)``)."""
        from ..obs.trace import instant_trace_events

        return instant_trace_events(self.events, time_origin)


class Replica:
    """One supervised fleet member: a worker plus its lifecycle state."""

    def __init__(self, index: int, worker: Any, spawned_at: float) -> None:
        self.index = index
        self.worker = worker
        self.state = SERVING
        self.spawned_at = spawned_at
        self.drain_started_cycle: int | None = None
        # progress watchdog (hang detection)
        self.last_progress = -1
        self.stalled_cycles = 0
        # idle-wedge watchdog: refill-pass liveness while HOLDING no work
        self.last_refills: int | None = None
        self.idle_stalled_cycles = 0

    def progress(self) -> int:
        """Monotone progress signal: tokens emitted + requests settled."""
        return self.worker.batcher.tokens_emitted + self.worker.processed


class WorkerPool(FleetPoolBase):
    """A supervised pool of serving replicas behind the Scaler seam.

    ``replica_factory(pool)`` builds one replica worker (the real thing:
    :meth:`serving` wires a :class:`~.worker.FleetWorker`; the contract
    test substitutes a featherweight stub — the pool itself is JAX-free).
    ``min``/``max``/``scale_up_pods``/``scale_down_pods`` mirror
    :class:`~..scale.actuator.PodAutoScaler`'s fields.
    """

    def __init__(
        self,
        replica_factory: Callable[["WorkerPool"], Any],
        *,
        min: int,
        max: int,
        scale_up_pods: int = 1,
        scale_down_pods: int = 1,
        initial: int | None = None,
        clock: Clock | None = None,
        hang_grace_cycles: int = 3,
        drain_timeout_cycles: int | None = None,
        replied_capacity: int = 65536,
    ) -> None:
        if not 1 <= min <= max:
            raise ValueError(f"need 1 <= min ({min}) <= max ({max})")
        if scale_up_pods < 1 or scale_down_pods < 1:
            raise ValueError("scale step sizes must be >= 1")
        if hang_grace_cycles < 2:
            # one no-progress cycle is legitimate (the block engine's
            # dispatch-ahead consumes block N one cycle after dispatch)
            raise ValueError("hang_grace_cycles must be >= 2")
        super().__init__(clock=clock, replied_capacity=replied_capacity)
        self.replica_factory = replica_factory
        self.min = min
        self.max = max
        self.scale_up_pods = scale_up_pods
        self.scale_down_pods = scale_down_pods
        self.hang_grace_cycles = hang_grace_cycles
        self.drain_timeout_cycles = drain_timeout_cycles
        # live replicas plus a bounded tail of recently-retired/dead ones
        # (postmortem introspection + their final gauges); older corpses
        # are pruned each cycle with their counters folded into
        # _retired_processed so a long-lived, high-churn fleet stays flat
        self.members: list[Replica] = []
        self.retired_keep = 32
        # live count of DEAD/STOPPED members, maintained at the state
        # transitions so the per-cycle prune pass can SKIP its members
        # scan entirely while nothing exceeds retired_keep — the
        # common case is every cycle of a healthy fleet (per-cycle
        # bookkeeping audit, ROADMAP item 1)
        self._retired_members = 0
        self._retired_processed = 0
        self._retired_tenant: dict[str, int] = {}
        self._next_index = 0
        self._spawn_ordinal = 0  # factory invocations (pre-commit safe)
        self._orphans: list[dict] = []  # re-dispatch queue (priority)
        self.redispatched_total = 0
        self.released_total = 0
        if initial is None:
            initial = min
        if not min <= initial <= max:
            raise ValueError(
                f"initial ({initial}) must be within [min, max]"
            )
        for _ in range(initial):
            self._spawn()

    # ------------------------------------------------------------------
    # The Scaler seam (PodAutoScaler parity — pinned by contract test)
    # ------------------------------------------------------------------

    @property
    def replicas(self) -> int:
        """Serving replica count — the fleet's ``spec.replicas``.

        Draining replicas are already excluded (like pods past their
        deletion timestamp: still finishing work, no longer capacity the
        policy should count)."""
        return sum(1 for r in self.members if r.state == SERVING)

    def scale_up(self) -> None:
        self._injected_failure("up")
        current = self.replicas
        if current >= self.max:
            log.info(
                "More than max replicas serving. No scale up. Replicas: %d",
                current,
            )
            return
        target = builtins_min(current + self.scale_up_pods, self.max)
        # build-then-commit so a factory failure changes NOTHING, like
        # PodAutoScaler's single read-modify-write (the parity contract:
        # a failed scale leaves the replica count exactly as it was)
        workers = []
        try:
            for _ in range(target - current):
                workers.append(self.replica_factory(self))
        except Exception as err:
            for worker in workers:
                worker.stop()
            raise ScaleError("Failed to scale up") from err
        for worker in workers:
            self._add_replica(worker)
        log.info("Scale up successful. Replicas: %d", self.replicas)

    def scale_down(self) -> None:
        self._injected_failure("down")
        current = self.replicas
        if current <= self.min:
            log.info(
                "Less than min replicas serving. No scale down. "
                "Replicas: %d",
                current,
            )
            return
        target = builtins_max(current - self.scale_down_pods, self.min)
        for _ in range(current - target):
            self._drain_one()
        log.info("Scale down successful. Replicas: %d", self.replicas)

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------

    def _spawn(self) -> Replica:
        return self._add_replica(self.replica_factory(self))

    def _add_replica(self, worker: Any) -> Replica:
        replica = Replica(self._next_index, worker, self.clock.now())
        self._next_index += 1
        self.members.append(replica)
        if self.lifecycle is not None:
            attach = getattr(worker, "attach_lifecycle", None)
            if attach is not None:
                attach(self.lifecycle)
        self._event("replica-spawn", replica=replica.index)
        return replica

    def _drain_one(self) -> None:
        # newest serving replica first (its cache is coldest; the oldest
        # replicas keep their momentum)
        replica = builtins_max(
            (r for r in self.members if r.state == SERVING),
            key=lambda r: r.index,
        )
        replica.state = DRAINING
        replica.worker.admitting = False
        replica.drain_started_cycle = self.cycle
        self._event(
            "replica-drain-start", replica=replica.index,
            inflight=replica.worker.batcher.active,
        )

    def engine_donor(self):
        """The batcher whose compiled programs a new replica adopts:
        any existing member's (compiled executables are state-free, so
        even a dead replica can donate).  ``None`` for the first spawn —
        it pays the one compile the whole fleet then shares."""
        for replica in self.members:
            return replica.worker.batcher
        return None

    def kill_worker(self, index: int) -> None:
        """Deterministic fault injection: crash replica ``index`` NOW
        (flag flip, not process murder — see :mod:`..sim.faults`).  The
        next :meth:`run_cycle`'s supervisor pass re-dispatches its
        un-replied in-flight requests to survivors."""
        self._member(index).worker.kill()

    def hang_worker(self, index: int) -> None:
        """Deterministic fault injection: wedge replica ``index`` — it
        looks alive but makes no progress until the watchdog declares it
        dead after ``hang_grace_cycles`` busy cycles."""
        self._member(index).worker.hang()

    def kill_admission_shard(self, shard: int) -> int:
        """Deterministic fault injection
        (``FleetFaultPlan.admission_kills``): kill admission shard
        ``shard`` on every replica running a sharded admission plane —
        the staging failure domain, not the engine's.  Staged requests
        hand back via ``change_message_visibility(0)`` and the shard
        rehydrates next cycle.  Fails loudly when no replica runs one
        (a plan that kills nobody would gate nothing).  Returns the
        total hand-back count."""
        released, hit = 0, False
        for replica in self.members:
            worker = replica.worker
            if hasattr(getattr(worker, "_fair", None), "kill_shard"):
                released += worker.kill_admission_shard(shard)
                hit = True
        if not hit:
            raise ValueError(
                "no replica runs a sharded admission plane "
                "(tenancy.admission_shards must be >= 2)"
            )
        return released

    def partition_admission_shard(
        self, shard: int, partitioned: bool = True,
    ) -> None:
        """Deterministic fault injection
        (``FleetFaultPlan.admission_partitions``): gossip-partition (or
        heal) admission shard ``shard`` on every replica running a
        sharded admission plane."""
        hit = False
        for replica in self.members:
            worker = replica.worker
            if hasattr(getattr(worker, "_fair", None), "partition_shard"):
                worker.partition_admission_shard(shard, partitioned)
                hit = True
        if not hit:
            raise ValueError(
                "no replica runs a sharded admission plane "
                "(tenancy.admission_shards must be >= 2)"
            )

    def _member(self, index: int) -> Replica:
        for replica in self.members:
            if replica.index == index:
                return replica
        raise ValueError(f"no replica with index {index}")

    # ------------------------------------------------------------------
    # The fleet cycle: supervise -> route -> serve -> retire
    # ------------------------------------------------------------------

    def run_cycle(self) -> int:
        """One fleet cycle; returns requests completed across replicas."""
        self.cycle += 1
        self._supervise()
        done = 0
        # ONE state-partition pass per cycle (this loop used to re-scan
        # `self.members` — live replicas plus the bounded retired tail —
        # once per state it routed), so cycle cost stays flat however
        # much retirement history the bounded tail holds
        serving: list[Replica] = []
        draining: list[Replica] = []
        for replica in self.members:
            if replica.state == SERVING:
                serving.append(replica)
            elif replica.state == DRAINING:
                draining.append(replica)
        # router: freest replica first, so a refill cycle spreads the
        # queue's head across the fleet instead of soaking one replica
        # (count-only read: the ordering merge is the admission's cost)
        serving.sort(
            key=lambda r: _free_count(r.worker.batcher), reverse=True
        )
        for replica in serving:
            if self._orphans:
                self._dispatch_orphans(replica)
            done += replica.worker.run_once()
        for replica in draining:
            done += replica.worker.run_once()
            if replica.worker.batcher.active == 0:
                # nothing in flight: the drain is complete (hung or not —
                # an empty wedged replica has nothing left to lose)
                self._retire(replica, released=0)
            elif (
                self.drain_timeout_cycles is not None
                and replica.drain_started_cycle is not None
                and self.cycle - replica.drain_started_cycle
                >= self.drain_timeout_cycles
            ):
                # the drain stalled: hand un-finished requests back to
                # the queue so survivors pick them up, then retire
                released = replica.worker.release_inflight()
                self.released_total += released
                self._retire(replica, released=released)
        self._prune_retired()
        self._update_metrics()
        return done

    def _supervise(self) -> None:
        """Declare killed/hung replicas dead and queue their failover.

        Two watchdogs cover the two ways a wedge can look:

        - **busy wedge** — the replica HOLDS work (``active > 0``) but
          its token/settle progress froze: dead after
          ``hang_grace_cycles`` stalled cycles (one stall cycle is
          legitimate — the block engine's dispatch-ahead lag);
        - **idle wedge** — the replica holds nothing, so token progress
          proves nothing.  A *healthy* idle serving replica still runs
          its refill pass every cycle (poll, poll-backoff tick, or
          full-slots early-out — ``ContinuousWorker.refill_cycles``
          counts all three), while a wedged ``run_once`` never reaches
          it.  A serving, admitting replica whose refill counter
          freezes while idle is declared dead after the same grace.
          This closes the PR 6 blind spot where an idle wedge was only
          bounded by the router's next orphan dispatch.  Draining
          replicas are exempt (they stop refilling by design — an idle
          one retires via the drain path the same cycle anyway).
        """
        for replica in self.members:
            if replica.state not in (SERVING, DRAINING):
                continue
            worker = replica.worker
            if worker.killed:
                self._declare_dead(replica, cause="killed")
                continue
            progress = replica.progress()
            if worker.batcher.active > 0 and progress == replica.last_progress:
                replica.stalled_cycles += 1
                if replica.stalled_cycles >= self.hang_grace_cycles:
                    self._declare_dead(replica, cause="hung")
                    continue
            else:
                replica.stalled_cycles = 0
            replica.last_progress = progress
            refills = getattr(worker, "refill_cycles", None)
            if (
                refills is not None
                and replica.state == SERVING
                and getattr(worker, "admitting", True)
                and worker.batcher.active == 0
            ):
                if refills == replica.last_refills:
                    replica.idle_stalled_cycles += 1
                    if replica.idle_stalled_cycles >= self.hang_grace_cycles:
                        self._declare_dead(replica, cause="hung-idle")
                        continue
                else:
                    replica.idle_stalled_cycles = 0
            else:
                replica.idle_stalled_cycles = 0
            replica.last_refills = refills

    def _declare_dead(self, replica: Replica, cause: str) -> None:
        replica.state = DEAD
        self._retired_members += 1
        replica.worker.killed = True  # a hung replica must never step again
        orphans = replica.worker.take_inflight()
        self.redispatched_total += len(orphans)
        self._orphans.extend(orphans)
        self._event(
            "replica-kill", replica=replica.index, cause=cause,
            redispatched=len(orphans),
        )
        log.warning(
            "Replica %d declared dead (%s); re-dispatching %d in-flight "
            "request(s) to %d survivor(s)",
            replica.index, cause, len(orphans), self.replicas,
        )

    def _dispatch_orphans(self, replica: Replica) -> None:
        free = _free_count(replica.worker.batcher)
        if free <= 0:
            return
        take, self._orphans = self._orphans[:free], self._orphans[free:]
        if take:
            if self.lifecycle is not None:
                from ..workloads.service import request_id

                for message in take:
                    # the chain continues on the survivor: _admit will
                    # re-stamp admitted/prefill (re-stamps append; the
                    # FIRST occurrences keep the original timeline)
                    self.lifecycle.note(
                        request_id(message), "redispatched"
                    )
            replica.worker._admit(take)
            self._event(
                "redispatch", replica=replica.index, requests=len(take),
            )

    def _retire(self, replica: Replica, *, released: int) -> None:
        if replica.state != DEAD:  # a dead replica is already counted
            self._retired_members += 1
        replica.state = STOPPED
        replica.worker.stop()
        self._event(
            "replica-drain-done", replica=replica.index, released=released,
        )

    # ------------------------------------------------------------------
    # Introspection / observability (reply registry + event stream live
    # on FleetPoolBase, shared with the sharded plane's pool)
    # ------------------------------------------------------------------

    def next_spawn_ordinal(self) -> int:
        """Monotone per-factory-call counter (distinct even for builds
        that later roll back) — :meth:`serving` derives each replica's
        sampling seed from it so sampled fleets draw independent PRNG
        streams instead of every replica replaying one seed."""
        ordinal = self._spawn_ordinal
        self._spawn_ordinal += 1
        return ordinal

    def _prune_retired(self) -> None:
        """Drop all but the newest ``retired_keep`` DEAD/STOPPED
        replicas, folding their settle counts into the retired total.
        (Pruned indices disappear from ``members`` — ``kill_worker`` on
        one raises, as killing a corpse should.)  Skips the members
        scan entirely while nothing exceeds ``retired_keep`` (the
        ``_retired_members`` counter is maintained at the lifecycle
        transitions), so a healthy fleet's cycle never pays it."""
        if self._retired_members <= self.retired_keep:
            return
        retired = [
            r for r in self.members if r.state in (DEAD, STOPPED)
        ]
        for replica in retired[: -self.retired_keep or None]:
            self._retired_processed += replica.worker.processed
            counts = getattr(replica.worker, "completed_by_tenant", {})
            if counts:
                # deferred import: workloads pulls jax and the bare
                # fleet seam must stay importable without it; only
                # tenancy pools (real serving workers) reach this
                from ..workloads.service import bounded_tenant_key

                for tenant, count in counts.items():
                    # re-apply the per-worker label-cardinality bound
                    # at the pool fold: every fresh replica accepts up
                    # to MAX_TENANT_SERIES NEW labels, so an unbounded
                    # fold would grow ~512 entries per retired replica
                    # under churn with adversarial unique labels
                    tenant = bounded_tenant_key(
                        tenant, self._retired_tenant
                    )
                    self._retired_tenant[tenant] = (
                        self._retired_tenant.get(tenant, 0) + count
                    )
            self.members.remove(replica)
            self._retired_members -= 1

    @property
    def processed(self) -> int:
        """Requests settled over the fleet's lifetime (dead, retired,
        and long-pruned replicas included; duplicate-suppressed settles
        excluded — this counts uniquely answered requests)."""
        return self._retired_processed + sum(
            r.worker.processed for r in self.members
        )

    @property
    def completed_by_tenant(self) -> dict[str, int]:
        """Uniquely-answered completions per tenant over the fleet's
        lifetime.  Exactly-once by construction: each worker counts a
        tenant completion only on a settle that actually answered (the
        pool registry's duplicate-suppression path returns before the
        counter), so visibility-timeout redeliveries and dead-replica
        re-dispatches never double-book a tenant."""
        totals = dict(self._retired_tenant)
        for replica in self.members:
            for tenant, count in getattr(
                replica.worker, "completed_by_tenant", {}
            ).items():
                totals[tenant] = totals.get(tenant, 0) + count
        return totals

    def staged_by_tenant(self) -> dict[str, int]:
        """Live per-tenant staged depths aggregated across the fleet's
        serving/draining replicas (empty with tenancy off) — the
        forecaster seam's WHO-is-arriving signal: feed it to
        :class:`~..forecast.tenants.TenantAwareDepth` so the control
        loop weighs a tight-SLO tenant's backlog harder than a batch
        tenant's.  Pure host bookkeeping (each worker's fair-admission
        ``depths()``), bounded by the workers' own label-cardinality
        bounds."""
        totals: dict[str, int] = {}
        for replica in self.members:
            if replica.state not in (SERVING, DRAINING):
                continue
            fair = getattr(replica.worker, "_fair", None)
            if fair is None:
                continue
            for tenant, depth in fair.depths().items():
                totals[tenant] = totals.get(tenant, 0) + depth
        return totals

    @property
    def idle(self) -> bool:
        """Nothing in flight anywhere and nothing awaiting re-dispatch.
        Fair-admission staging counts as in flight: a staged message's
        receipt handle is live, so a pool declared idle with staged
        work would strand it for the full visibility timeout."""
        return not self._orphans and all(
            r.worker.batcher.active == 0
            and getattr(r.worker, "staged", 0) == 0
            for r in self.members
            if r.state in (SERVING, DRAINING)
        )

    def stop_all(self) -> None:
        """Stop every replica (draining ones release their in-flight
        requests back to the queue first — shutdown never loses work)."""
        for replica in self.members:
            if replica.state in (SERVING, DRAINING):
                released = replica.worker.release_inflight()
                self.released_total += released
                self._retire(replica, released=released)
        self._update_metrics()

    def attach_metrics(self, metrics) -> None:
        """Refresh per-replica fleet gauges into a
        :class:`~..obs.prometheus.WorkloadMetrics` registry every cycle:
        ``fleet_replica_state`` / ``fleet_replica_tokens_per_second`` /
        ``fleet_replica_active_slots`` (labeled by replica), plus
        ``fleet_replicas_draining`` and the
        ``fleet_requests_redispatched_total`` counter."""
        self.metrics = metrics
        self._update_metrics()

    def attach_lifecycle(self, registry) -> None:
        """Wire ONE :class:`~..obs.LifecycleRegistry` through every
        current member (and, via :meth:`_add_replica`, every future
        spawn): a request's phase chain must thread through whichever
        replicas touch it — admission on one, evacuation, re-dispatch
        and settle on another — so the registry is fleet-scoped, never
        per-replica.  ``getattr``-guarded: bench stub workers without
        the hook simply stay untraced."""
        self.lifecycle = registry
        for replica in self.members:
            attach = getattr(replica.worker, "attach_lifecycle", None)
            if attach is not None:
                attach(registry)

    def _update_metrics(self) -> None:
        if self.metrics is None:
            return
        now = time.perf_counter()
        for replica in self.members:
            labels = (("replica", str(replica.index)),)
            worker = replica.worker
            served_since = getattr(worker, "_served_since", None)
            rate = 0.0
            if served_since is not None and now > served_since:
                rate = worker.batcher.tokens_emitted / (now - served_since)
            self.metrics.set_gauge(
                "fleet_replica_state",
                REPLICA_STATE_CODES[replica.state],
                "Replica lifecycle state (0=serving, 1=draining, 2=dead, "
                "3=stopped).",
                labels=labels,
            )
            self.metrics.set_gauge(
                "fleet_replica_tokens_per_second", rate,
                "Generated tokens per second over this replica's serving "
                "lifetime.",
                labels=labels,
            )
            self.metrics.set_gauge(
                "fleet_replica_active_slots", worker.batcher.active,
                "Decode slots currently holding an in-flight request on "
                "this replica.",
                labels=labels,
            )
        self.metrics.set_gauge(
            "fleet_replicas_draining",
            sum(1 for r in self.members if r.state == DRAINING),
            "Replicas draining (finishing in-flight work, not admitting).",
        )
        self.metrics.set_gauge(
            "fleet_requests_redispatched_total", self.redispatched_total,
            "In-flight requests re-dispatched from dead replicas to "
            "survivors.",
            kind="counter",
        )
        # TTFT histograms: replicas never get a worker-level metrics
        # registry (their unlabeled gauges would collide), but the
        # cumulative histogram families merge correctly — drain every
        # member's pending samples into the pool's registry
        from ..workloads.continuous import drain_ttft_histograms

        for replica in self.members:
            batcher = getattr(replica.worker, "batcher", None)
            if batcher is not None:
                drain_ttft_histograms(batcher, self.metrics)

    # ------------------------------------------------------------------
    # Real-fleet construction
    # ------------------------------------------------------------------

    @classmethod
    def serving(
        cls,
        queue,
        params,
        model_config,
        service_config,
        *,
        min: int,
        max: int,
        family: str = "gpt",
        tokenizer=None,
        result_queue=None,
        mesh=None,
        engine_source=None,
        tenancy=None,
        **pool_kwargs,
    ) -> "WorkerPool":
        """A pool of real :class:`~.worker.FleetWorker` replicas over one
        shared queue.  ``params`` may be the plain bf16 tree or an
        int8-quantized one (:mod:`..workloads.quantize`) — replicas share
        whichever by reference; only the FIRST replica compiles, the
        rest adopt its programs.  ``engine_source`` seeds even the first
        replica from an external donor batcher (e.g. a previous pool
        over the same params), making whole-pool startup compile-free.

        Sampled serving (``temperature > 0``): each replica gets
        ``sample_seed + spawn_ordinal`` so the fleet draws independent
        PRNG streams — one shared seed would make every replica replay
        the same randomness.  The seed is not an engine static, so
        adoption is unaffected."""
        import dataclasses

        def factory(pool: "WorkerPool"):
            from .worker import FleetWorker

            seeded = dataclasses.replace(
                service_config,
                sample_seed=service_config.sample_seed
                + pool.next_spawn_ordinal(),
            )
            return FleetWorker(
                queue, params, model_config, seeded,
                family=family, tokenizer=tokenizer,
                result_queue=result_queue, mesh=mesh,
                pool=pool, tenancy=tenancy,
                engine_source=pool.engine_donor() or engine_source,
            )

        return cls(factory, min=min, max=max, **pool_kwargs)


class FleetDriver:
    """Interleaves fleet serving cycles with real control-loop ticks.

    The fleet's analogue of :class:`~..sim.simulator.Simulation`: the
    loop under drive is the REAL :class:`~..core.loop.ControlLoop`
    (``loop.tick`` on its own clock, one tick per ``poll_interval``),
    the actuator is the pool, and the world between ticks is actual
    serving.  ``loop=None`` drives the pool alone (the chaos episodes
    that need no autoscaler).  ``cycle_dt > 0`` advances a
    :class:`~..core.clock.FakeClock` that much virtual time per cycle —
    the deterministic demo mode; ``0`` reads real time (the bench).
    ``fault_plan`` applies a :class:`~..sim.faults.FleetFaultPlan`'s
    kills/hangs at their scheduled cycles.

    **Controller crashes** (ISSUE 14): a
    :class:`~..core.durable.ControllerCrash` escaping ``loop.tick`` —
    injected by a :class:`~..sim.faults.CrashPlan` at any of its named
    crash points — kills the whole controller process: loop AND pool
    (they share it).  With a ``restart`` factory the driver then models
    Kubernetes restarting the pod: it stops nothing (the dead pool's
    in-flight work is simply abandoned to the queue's visibility
    timeout, like real process death), advances ``downtime_s`` of
    virtual time, asks the factory for a fresh ``(pool, loop)`` —
    typically rehydrating from a :class:`~..core.durable`
    snapshot — and resumes the episode.  Without a factory the crash
    propagates (a crash the episode did not expect must fail it).
    ``tick_index`` counts tick *attempts* across restarts — the index
    a ``CrashPlan`` keys on.
    """

    def __init__(
        self,
        pool: WorkerPool,
        loop=None,
        *,
        cycle_dt: float = 0.0,
        fault_plan=None,
        crash_plan=None,
        restart: Callable[[], tuple] | None = None,
        downtime_s: float = 0.0,
    ) -> None:
        self.pool = pool
        self.loop = loop
        self.cycle_dt = cycle_dt
        self.fault_plan = fault_plan
        # the CrashPlan is consulted here only for its TICK-BOUNDARY
        # kills (after journal + snapshot); the mid-tick crash points
        # raise from inside the loop via the sim.faults wrappers
        self.crash_plan = crash_plan
        self.restart = restart
        self.downtime_s = downtime_s
        self.ticks = 0
        self.tick_index = 0  # tick ATTEMPTS, crashed ones included
        self.crashes = 0
        self.restarts = 0

    def _crash_restart(self, clock):
        """One controller death + pod restart (see class docstring)."""
        from ..core.durable import ControllerCrash

        self.crashes += 1
        if self.restart is None:
            raise ControllerCrash(
                "controller crashed with no restart factory"
            )
        log.warning(
            "Controller crashed at tick %d; restarting after %.1fs",
            self.tick_index, self.downtime_s,
        )
        if self.downtime_s:
            clock.advance(self.downtime_s)  # FakeClock only
        self.pool, self.loop = self.restart()
        self.restarts += 1
        return self.loop.initial_policy_state()

    def run(
        self,
        *,
        until_processed: int | None = None,
        max_cycles: int = 100_000,
        until: Callable[[], bool] | None = None,
    ) -> dict:
        """Drive until ``until_processed`` requests settled and the fleet
        is idle (or ``max_cycles``); returns summary stats.  ``until``
        replaces the stop condition with an arbitrary predicate,
        evaluated after each cycle (e.g. "all replies collected AND the
        fleet scaled back down to min")."""
        from ..core.durable import ControllerCrash

        clock = self.loop.clock if self.loop is not None else self.pool.clock
        state = None
        next_tick = None
        if self.loop is not None:
            state = self.loop.initial_policy_state()
            next_tick = clock.now() + self.loop.config.poll_interval
        trajectory: list[int] = []
        cycles = 0
        for _ in range(max_cycles):
            if self.fault_plan is not None:
                self.fault_plan.apply(self.pool.cycle, self.pool)
            self.pool.run_cycle()
            cycles += 1
            if self.cycle_dt:
                clock.advance(self.cycle_dt)  # FakeClock only
            if self.loop is not None and clock.now() >= next_tick:
                self.tick_index += 1
                try:
                    state = self.loop.tick(state)
                except ControllerCrash:
                    state = self._crash_restart(clock)
                else:
                    self.loop.ticks += 1
                    self.ticks += 1
                    trajectory.append(self.pool.replicas)
                    if self.crash_plan is not None and \
                            self.crash_plan.boundary_crash(
                                self.tick_index - 1):
                        # tick-boundary kill: journal line AND snapshot
                        # landed; the restart must be seamless
                        state = self._crash_restart(clock)
                # re-anchor rather than accumulate: a long serve cycle
                # must not cause a burst of catch-up ticks
                next_tick = clock.now() + self.loop.config.poll_interval
            if until is not None:
                if until():
                    break
            elif (
                until_processed is not None
                and self.pool.processed >= until_processed
                and self.pool.idle
            ):
                break
        return {
            "cycles": cycles,
            "ticks": self.ticks,
            "processed": self.pool.processed,
            "replica_trajectory": trajectory,
            "final_replicas": self.pool.replicas,
            "crashes": self.crashes,
            "restarts": self.restarts,
        }
