"""The serving fleet: ControlLoop-actuated ContinuousWorker replicas.

``WorkerPool`` implements the :class:`~..core.types.Scaler` seam over a
pool of in-process serving replicas — the subsystem that closes the loop
between the autoscaling control plane and the serving engine (ROADMAP
item 1).  ``FleetDriver`` interleaves serving cycles with real control
ticks; ``FleetWorker`` is imported lazily (it pulls the JAX serving
stack) so the pool, driver, and contract tests stay control-plane-light.
"""

from .pool import (
    DEAD,
    DRAINING,
    REPLICA_STATE_CODES,
    SERVING,
    STOPPED,
    FleetDriver,
    FleetEvent,
    Replica,
    WorkerPool,
)
from .sharded import (
    INACTIVE,
    PROBING,
    QUARANTINED,
    SHARD_HEALTH_CODES,
    SHARD_STATE_CODES,
    ShardedWorkerPool,
)

__all__ = [
    "DEAD",
    "DRAINING",
    "INACTIVE",
    "PROBING",
    "QUARANTINED",
    "REPLICA_STATE_CODES",
    "SERVING",
    "SHARD_HEALTH_CODES",
    "SHARD_STATE_CODES",
    "STOPPED",
    "FleetDriver",
    "FleetEvent",
    "Replica",
    "ShardedWorkerPool",
    "WorkerPool",
]
