"""``make fleet-demo``: one deterministic FakeClock fleet episode.

Walks the whole crash-safe serving story on a virtual clock — the real
:class:`~..core.loop.ControlLoop` autoscaling a real
:class:`~.pool.WorkerPool` of serving replicas over one shared queue:

1. **spawn** — backlog trips the up gate; new replicas share the
   already-built params by reference and adopt the first replica's
   compiled programs (no model rebuild, no recompile);
2. **kill** — a :class:`~..sim.faults.FleetFaultPlan` kills a busy
   replica mid-episode; the supervisor re-dispatches its un-replied
   in-flight requests to survivors;
3. **re-dispatch / dedup** — every request is answered exactly once
   (zero lost, zero duplicated replies), redeliveries and failover
   notwithstanding;
4. **drain** — the drained queue trips the down gate; replicas stop
   admitting, finish their in-flight slots, and retire; the fleet
   returns to min.

Exit 0 = every milestone observed; exit 2 = unexpected trajectory (the
``make chaos-demo`` / ``make replay-demo`` contract).  Runs the real JAX
serving engine on a tiny model (CPU-friendly, ~seconds); only the
*clocks* are virtual.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Sequence

from ..core.clock import FakeClock
from ..core.loop import ControlLoop, LoopConfig
from ..core.policy import PolicyConfig
from ..metrics.fake import FakeMessageQueue
from ..metrics.queue import QueueMetricSource
from ..sim.faults import FleetFaultPlan
from .pool import DRAINING, SERVING, FleetDriver, WorkerPool

MESSAGES = 12
KILL_CYCLE = 8
KILL_REPLICA = 1


def _demo_episode():
    import jax
    import numpy as np

    from ..workloads.model import ModelConfig, init_params
    from ..workloads.service import ServiceConfig, collect_replies

    model = ModelConfig(
        vocab_size=128, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_seq_len=6 + 24,
    )
    params = init_params(jax.random.key(0), model)
    clock = FakeClock()
    # virtual-time visibility: an in-flight message outliving 30 virtual
    # seconds is redelivered — which the reply dedup must absorb
    queue = FakeMessageQueue(visibility_timeout=30.0, now_fn=clock.now)
    results = FakeMessageQueue(now_fn=clock.now)
    config = ServiceConfig(
        queue_url="fleet://demo", batch_size=2, seq_len=6,
        generate_tokens=24, decode_block=4,
        result_queue_url="fleet://demo-results",
    )
    rng = np.random.default_rng(7)
    sent = [
        queue.send_message(
            "fleet://demo",
            json.dumps(rng.integers(1, model.vocab_size, 5).tolist()),
        )
        for _ in range(MESSAGES)
    ]
    pool = WorkerPool.serving(
        queue, params, model, config, result_queue=results,
        min=1, max=3, clock=clock, drain_timeout_cycles=200,
    )
    loop = ControlLoop(
        pool,
        QueueMetricSource(queue, "fleet://demo",
                          ("ApproximateNumberOfMessages",)),
        LoopConfig(
            poll_interval=1.0,
            policy=PolicyConfig(
                scale_up_messages=4, scale_down_messages=1,
                scale_up_cooldown=1.0, scale_down_cooldown=2.0,
            ),
        ),
        clock=clock,
    )
    plan = FleetFaultPlan(kills=((KILL_CYCLE, KILL_REPLICA),))
    driver = FleetDriver(pool, loop, cycle_dt=0.5, fault_plan=plan)
    stats = driver.run(
        max_cycles=600,
        until=lambda: (
            pool.processed >= MESSAGES
            and pool.idle
            and pool.replicas == pool.min
            and not any(r.state == DRAINING for r in pool.members)
        ),
    )
    replies, duplicates = collect_replies(results, "fleet://demo-results")
    return pool, params, stats, sent, replies, duplicates


def _check_demo(pool, params, stats, sent, replies, duplicates) -> list[str]:
    """The expected trajectory, as individually reportable milestones."""
    problems: list[str] = []

    def expect(condition: bool, message: str) -> None:
        if not condition:
            problems.append(message)

    names = [e.name for e in pool.events]
    # 1. spawn: the backlog scaled the fleet past one replica, and
    #    spin-up shared the already-built weights + compiled programs
    expect(names.count("replica-spawn") >= 2,
           "the up gate never spawned a second replica")
    expect(max(stats["replica_trajectory"], default=0) >= 2,
           "the replica trajectory never reached 2")
    expect(
        all(r.worker.batcher.params is params for r in pool.members),
        "a replica rebuilt its params instead of sharing the pool's",
    )
    engines = {id(r.worker.batcher._insert_many) for r in pool.members}
    expect(
        len(engines) == 1,
        "replicas compiled separate engines instead of adopting one",
    )
    # 2. kill: the fault plan fired on a busy replica and the supervisor
    #    re-dispatched its in-flight work
    kills = [e for e in pool.events if e.name == "replica-kill"]
    expect(bool(kills), "the kill was never detected")
    expect(
        any(e.args.get("redispatched", 0) > 0 for e in kills),
        "the killed replica had no in-flight requests to re-dispatch "
        "(tune KILL_CYCLE)",
    )
    # 3. lossless + dedup: every request answered exactly once
    expect(
        len(replies) == len(sent),
        f"lost replies: {len(replies)}/{len(sent)} requests answered",
    )
    expect(duplicates == 0,
           f"{duplicates} duplicate reply(ies) reached the consumer")
    expect(
        set(replies) == set(sent),
        "reply request_ids do not match the sent MessageIds",
    )
    # 4. drain: the down gate retired the extra replicas gracefully
    expect("replica-drain-start" in names, "no replica ever drained")
    expect("replica-drain-done" in names, "no drain ever completed")
    expect(
        pool.replicas == pool.min,
        f"fleet did not return to min={pool.min} "
        f"(serving {pool.replicas})",
    )
    expect(
        sum(1 for r in pool.members if r.state == SERVING) == pool.min,
        "serving-state accounting disagrees with the replicas property",
    )
    # the supervisor's decisions must be exportable on the tick timeline
    expect(
        bool(pool.trace_events()),
        "the fleet produced no Chrome-trace instant events",
    )
    return problems


def main(argv: Sequence[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="Deterministic fleet episode: spawn -> kill -> "
        "re-dispatch -> drain — fails on any missing milestone."
    )
    parser.parse_args(argv)
    pool, params, stats, sent, replies, duplicates = _demo_episode()
    problems = _check_demo(pool, params, stats, sent, replies, duplicates)
    print(
        json.dumps(
            {
                "cycles": stats["cycles"],
                "ticks": stats["ticks"],
                "requests": len(sent),
                "replies": len(replies),
                "duplicate_replies": duplicates,
                "duplicates_suppressed": pool.duplicates_suppressed,
                "redispatched": pool.redispatched_total,
                "replica_trajectory": stats["replica_trajectory"],
                "final_replicas": pool.replicas,
                "events": [e.name for e in pool.events],
                "ok": not problems,
            }
        )
    )
    for line in problems:
        print(f"unexpected trajectory: {line}", file=sys.stderr)
    return 0 if not problems else 2


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
