"""FleetWorker: a :class:`~..workloads.continuous.ContinuousWorker` that
serves as one supervised replica of a :class:`~.pool.WorkerPool`.

It IS the production continuous worker — same batcher, same engine
cycle, same at-least-once settle discipline — extended with exactly the
hooks a supervised fleet member needs:

- **admission gate** (``admitting``): a draining replica stops pulling
  queue traffic but keeps stepping its in-flight slots to completion;
- **deterministic fault injection** (``killed``/``hung`` flags flipped by
  :meth:`~.pool.WorkerPool.kill_worker` /
  :meth:`~.pool.WorkerPool.hang_worker`): the fleet chaos battery's
  analogue of :mod:`..sim.faults` — a flag flip at a known cycle is
  replayable where process murder is not.  A killed replica never steps
  again; a hung one looks alive but makes no progress until the pool's
  watchdog declares it dead;
- **reply dedup** through the pool's registry: the serving system is
  at-least-once (replies are sent *before* the input is deleted), so a
  request redelivered by the queue's visibility timeout — or
  re-dispatched from a dead replica — can reach two replicas.  The FIRST
  completed settle wins; any later completion deletes its input copy
  without replying, so consumers never see two answers for one request
  id;
- **in-flight handoff** (:meth:`take_inflight`): when the supervisor
  declares this replica dead, its un-replied busy slots' messages are
  re-dispatched to survivors (their device state is abandoned — greedy
  decoding restarts from the prompt and produces the identical
  continuation).

Construction shares the pool's already-built params by reference and
adopts the donor replica's compiled programs
(:meth:`~..workloads.continuous.ContinuousBatcher.adopt_engine`), so
spin-up does no model rebuild and no recompile — O(1) host work plus the
replica's own KV-cache allocation.
"""

from __future__ import annotations

import logging

from ..workloads.continuous import ContinuousWorker
from ..workloads.service import request_id

log = logging.getLogger(__name__)


class FleetWorker(ContinuousWorker):
    """One supervised fleet replica (see module docstring)."""

    def __init__(self, *args, pool=None, engine_source=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._pool = pool
        if engine_source is not None:
            # BLITZSCALE-style spin-up: reuse the donor's compiled
            # insert/decode programs — a new replica pays cache
            # allocation, never a retrace or recompile
            self.batcher.adopt_engine(engine_source)
        self.admitting = True
        self.killed = False
        self.hung = False

    # -- fault injection (pool.kill_worker / pool.hang_worker) ----------

    def kill(self) -> None:
        """Deterministic crash: the replica never steps again; its
        un-replied in-flight requests await :meth:`take_inflight`."""
        self.killed = True

    def hang(self) -> None:
        """Deterministic wedge: cycles become no-ops (the replica looks
        alive but makes no progress) until the watchdog declares it
        dead."""
        self.hung = True

    # -- supervised engine cycle ----------------------------------------

    def run_once(self) -> int:
        if self.killed or self.hung:
            # a dead replica must not touch the queue or its device
            # state; a hung one consumes the cycle without progress —
            # exactly what the pool's progress watchdog keys on
            return 0
        return super().run_once()

    def _refill(self) -> int:
        if not self.admitting:
            return 0  # draining: finish in-flight slots, admit nothing
        return super()._refill()

    # -- reply dedup through the pool registry --------------------------

    def _settle(self, message, tokens, *, error=None,
                counted: bool = True) -> bool:
        if self._pool is not None:
            rid = request_id(message)
            if self._pool.already_replied(rid):
                # a redelivered / re-dispatched copy of a request that
                # was already answered: consume the duplicate input,
                # never send a second reply.  It must not count toward
                # `processed` either — when this settle came from the
                # completion loop (`counted`), run_once is about to add
                # one for it, and completion criteria (the driver's
                # `pool.processed >= N`) must count UNIQUE requests, or
                # a suppressed duplicate could stand in for a real one
                # still waiting in the queue.  Admission-time settles
                # (TTL sheds, malformed drops) were never going to be
                # counted, so there is nothing to cancel out.
                self.queue.delete_message(
                    self.config.queue_url, message["ReceiptHandle"]
                )
                self._pool.note_duplicate(rid)
                if self.lifecycle is not None:
                    # close the duplicate copy's open trace WITHOUT a
                    # reply stamp: the completeness audit counts exactly
                    # one reply-stamped trace per answered request, and
                    # this branch is what keeps the second copy from
                    # minting one
                    self.lifecycle.duplicate(rid)
                if counted:
                    self.processed -= 1
                return False
        answered = super()._settle(
            message, tokens, error=error, counted=counted
        )
        if self._pool is not None:
            self._pool.mark_replied(request_id(message))
        return answered

    # -- failover handoff ------------------------------------------------

    def take_inflight(self) -> list[dict]:
        """Remove and return the un-replied in-flight messages (busy
        slots' payloads, admission order).  Called once by the
        supervisor when this replica is declared dead; the slots are
        freed (their requests now live elsewhere — a dead replica must
        not keep reporting them as active) and the device state is
        abandoned with the replica, which never steps again."""
        from ..workloads.continuous import _Slot

        messages = []
        for row, slot in enumerate(self.batcher.slots):
            if slot.busy:
                messages.append(slot.payload)
                self.batcher.slots[row] = _Slot()
        self.batcher._invalidate_admission_cache()
        # fair-admission staging holds received-but-unadmitted messages
        # (live receipt handles): they are in-flight work too — strand
        # them and a dead replica's staged requests wait out the full
        # visibility timeout instead of failing over with its slots
        if self._fair is not None:
            for _tenant, item in self._fair.pick(self._fair.staged):
                messages.append(item[3])
        return messages

    def release_inflight(self) -> int:
        """Hand every un-replied in-flight request back to the queue
        (the drain-timeout path): make each message visible again NOW
        when the queue supports ``change_message_visibility``, else rely
        on its visibility timeout.  Returns the number released."""
        messages = self.take_inflight()
        nack = getattr(self.queue, "change_message_visibility", None)
        for message in messages:
            if nack is not None:
                nack(self.config.queue_url, message["ReceiptHandle"], 0)
        return len(messages)
