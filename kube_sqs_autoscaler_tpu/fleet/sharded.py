"""ShardedWorkerPool: the Scaler seam over shard-active mask flips.

The :class:`~.pool.WorkerPool` scales capacity by spawning/draining
whole worker replicas — real robustness (a replica can die), but every
serving cycle steps N engines from Python.  This pool is the sharded
actuation mode: ONE gang-stepped worker
(:class:`~..workloads.shard_plane.ShardedBatcher` behind a
:class:`~.worker.FleetWorker`) holds ``shards`` engine shards, and
``scale_up``/``scale_down`` flip device-side shard-active masks — O(1),
no spawn, no rebuild, no recompile — while the UNCHANGED
:class:`~..core.loop.ControlLoop` drives the same
:class:`~..core.types.Scaler` seam (PodAutoScaler parity pinned by the
actuator contract test, exactly like the replica pool):

- step by ``scale_up_pods``/``scale_down_pods`` clamped to
  ``[min, max]``; boundary no-ops are success; injected failures raise
  :class:`~..core.types.ScaleError` and change nothing;
- ``scale_down`` DRAINS: the newest serving shards stop admitting
  instantly (mask flip — the router and the device summary skip them)
  but their in-flight slots decode to completion; a drained-empty shard
  retires to inactive.  ``scale_up`` resurrects draining shards first
  (cancelling a drain is the same O(1) flip), then activates inactive
  ones lowest-index first;
- replies stay exactly-once on the at-least-once queue through the same
  bounded reply registry the replica pool uses (the worker is a
  :class:`~.worker.FleetWorker`, so visibility-timeout redeliveries
  dedup identically).

What the mask flip does NOT re-drive: shard state never moves — there
is no weight broadcast, no cache migration, no engine adoption, because
every shard lives inside the one already-compiled gang program.  The
trade against the replica pool is isolation: shards share a process and
a device program, so there is no kill/hang failover INSIDE the plane —
whole-plane crashes are the queue's visibility timeout's job, and
mixed deployments (several sharded planes under one replica pool)
compose the two seams.
"""

from __future__ import annotations

import logging
from typing import Any, Callable

from ..core.clock import Clock
from .pool import DRAINING, SERVING, FleetPoolBase

log = logging.getLogger(__name__)

builtins_min = min
builtins_max = max

# The third shard state: mask off, nothing in flight.  (A shard is never
# DEAD/STOPPED — it has no process to lose.)
INACTIVE = "inactive"
# Fault states (the shard-level failure domain): QUARANTINED = a health
# sentinel indicted the shard — masked out of admission, its live rows
# evacuated, excluded from scale_up resurrection; PROBING = the breaker's
# half-open twin — one request is let through, and the same sentinels
# that indicted the shard decide re-quarantine vs. re-admission.
QUARANTINED = "quarantined"
PROBING = "probing"
SHARD_STATE_CODES = {
    SERVING: 0, DRAINING: 1, INACTIVE: 2, QUARANTINED: 3, PROBING: 4,
}

# shard_health gauge codes (0 = healthy keeps dashboards' zero-is-good)
SHARD_HEALTH_CODES = {
    SERVING: 0, DRAINING: 0, INACTIVE: 0, PROBING: 1, QUARANTINED: 2,
}


class ShardedWorkerPool(FleetPoolBase):
    """A Scaler whose replica count is the active-shard count of one
    gang-stepped serving plane.

    ``worker_factory(pool)`` builds THE worker (called once; real
    fleets wire a :class:`~.worker.FleetWorker` over a sharded batcher
    via :meth:`serving`, the contract test substitutes a featherweight
    stub).  ``max`` defaults to — and may not exceed — the batcher's
    allocated shard count: activation is a mask flip, so capacity
    beyond the allocation would need a real spawn (that is the replica
    pool's job).
    """

    def __init__(
        self,
        worker_factory: Callable[["ShardedWorkerPool"], Any],
        *,
        min: int,
        max: int | None = None,
        scale_up_pods: int = 1,
        scale_down_pods: int = 1,
        initial: int | None = None,
        clock: Clock | None = None,
        replied_capacity: int = 65536,
        hang_grace_cycles: int = 3,
        probe_after_cycles: int = 8,
    ) -> None:
        if scale_up_pods < 1 or scale_down_pods < 1:
            raise ValueError("scale step sizes must be >= 1")
        if hang_grace_cycles < 2:
            # one no-progress settle is legitimate (the gang engine's
            # dispatch-ahead settles block N one cycle after dispatch,
            # and a just-admitted budget-1 row contributes no block
            # tokens at all) — same floor as the replica watchdog
            raise ValueError("hang_grace_cycles must be >= 2")
        if probe_after_cycles < 1:
            raise ValueError("probe_after_cycles must be >= 1")
        super().__init__(clock=clock, replied_capacity=replied_capacity)
        self.worker = worker_factory(self)
        self.shards = self.worker.batcher.shards
        # this pool IS the recovery authority: settled blocks from a
        # NaN-flagged shard are discarded (never reach a slot) because
        # quarantine + evacuation re-decode the rows from their last
        # clean token.  Contract-test stubs have no such surface.
        if hasattr(self.worker.batcher, "discard_bad_blocks"):
            self.worker.batcher.discard_bad_blocks = True
        if max is None:
            max = self.shards
        if not 1 <= min <= max:
            raise ValueError(f"need 1 <= min ({min}) <= max ({max})")
        if max > self.shards:
            raise ValueError(
                f"max ({max}) exceeds the plane's allocated shards "
                f"({self.shards}); activation is a mask flip, not a spawn"
            )
        self.min = min
        self.max = max
        self.scale_up_pods = scale_up_pods
        self.scale_down_pods = scale_down_pods
        if initial is None:
            initial = min
        if not min <= initial <= max:
            raise ValueError(
                f"initial ({initial}) must be within [min, max]"
            )
        self.hang_grace_cycles = hang_grace_cycles
        self.probe_after_cycles = probe_after_cycles
        # the shard-level chaos ledger: quarantines, evacuations, queue
        # hand-backs, and probe re-admissions over the plane's lifetime
        self.quarantined_total = 0
        self.rows_evacuated_total = 0
        self.released_total = 0
        self.readmitted_total = 0
        self._quarantined_at: dict[int, int] = {}
        # shards that were DRAINING when quarantined: a passed probe
        # must resume the drain the Scaler ordered, not silently undo a
        # scale_down by re-admitting the shard to SERVING
        self._drain_on_readmit: set[int] = set()
        self.shard_states = [
            SERVING if s < initial else INACTIVE for s in range(self.shards)
        ]
        for s in range(self.shards):
            self.worker.batcher.set_shard_active(s, s < initial)
            if s < initial:
                self._event("shard-activate", shard=s)

    # ------------------------------------------------------------------
    # The Scaler seam (PodAutoScaler parity — pinned by contract test)
    # ------------------------------------------------------------------

    @property
    def replicas(self) -> int:
        """Active shard count — the plane's ``spec.replicas``.  Draining
        shards are excluded, like the replica pool's DRAINING members."""
        return sum(1 for st in self.shard_states if st == SERVING)

    def scale_up(self) -> None:
        self._injected_failure("up")
        current = self.replicas
        if current >= self.max:
            log.info(
                "More than max shards active. No scale up. Shards: %d",
                current,
            )
            return
        target = builtins_min(current + self.scale_up_pods, self.max)
        # resurrect draining shards first (newest drain first — their
        # slots are warmest and cancelling a drain is the same O(1)
        # flip), then activate inactive shards lowest-index first
        draining = [
            s for s in reversed(range(self.shards))
            if self.shard_states[s] == DRAINING
        ]
        inactive = [
            s for s in range(self.shards)
            if self.shard_states[s] == INACTIVE
        ]
        for shard in (draining + inactive)[: target - current]:
            self.shard_states[shard] = SERVING
            self.worker.batcher.set_shard_active(shard, True)
            self._event("shard-activate", shard=shard)
        log.info("Scale up successful. Shards: %d", self.replicas)

    def scale_down(self) -> None:
        self._injected_failure("down")
        current = self.replicas
        if current <= self.min:
            log.info(
                "Less than min shards active. No scale down. Shards: %d",
                current,
            )
            return
        target = builtins_max(current - self.scale_down_pods, self.min)
        serving = [
            s for s in reversed(range(self.shards))
            if self.shard_states[s] == SERVING
        ]
        for shard in serving[: current - target]:
            # newest shard first, mirroring the replica pool's drain
            # order; the mask flip stops admission instantly, in-flight
            # slots finish on the gang step
            self.shard_states[shard] = DRAINING
            self.worker.batcher.set_shard_active(shard, False)
            self._event(
                "shard-drain-start", shard=shard,
                inflight=self.worker.batcher.shard_busy(shard),
            )
        log.info("Scale down successful. Shards: %d", self.replicas)

    # ------------------------------------------------------------------
    # The serving cycle
    # ------------------------------------------------------------------

    def run_cycle(self) -> int:
        """One plane cycle: ONE worker cycle (refill + gang step +
        settle) however many shards are active, then the shard-level
        supervision pass — quarantine any shard the health sentinels
        indict (detect → quarantine → evacuate), advance the probe
        state machine, and retire any draining shard that emptied.
        Returns requests completed."""
        self.cycle += 1
        done = self.worker.run_once()
        self._supervise_shards()
        for shard, state in enumerate(self.shard_states):
            if state == DRAINING and self.worker.batcher.shard_busy(shard) == 0:
                self.shard_states[shard] = INACTIVE
                self._event("shard-deactivate", shard=shard)
        self._probe_shards()
        self._update_metrics()
        return done

    # ------------------------------------------------------------------
    # The shard failure domain: detect -> quarantine -> evacuate ->
    # probe -> readmit (the PR 4 breaker's closed/open/half-open cycle,
    # re-expressed over device-side shard health sentinels)
    # ------------------------------------------------------------------

    def _supervise_shards(self) -> None:
        """Quarantine every shard the batcher's settle-time sentinels
        indict.  Detection is the batcher's (the flags ride the one
        combined settle transfer); actuation — mask flip, evacuation,
        probe scheduling — is this pool's."""
        batcher = self.worker.batcher
        suspects = getattr(batcher, "shard_suspects", None)
        if suspects is None:  # contract-test stubs have no health surface
            return
        for shard, cause in suspects(self.hang_grace_cycles):
            if self.shard_states[shard] == QUARANTINED:
                continue
            self._quarantine(shard, cause)

    def _quarantine(self, shard: int, cause: str) -> None:
        batcher = self.worker.batcher
        if self.shard_states[shard] == DRAINING:
            # remember the Scaler's intent; a PROBING re-quarantine
            # keeps whatever was remembered the first time
            self._drain_on_readmit.add(shard)
        elif self.shard_states[shard] == SERVING:
            self._drain_on_readmit.discard(shard)
        self.shard_states[shard] = QUARANTINED
        self._quarantined_at[shard] = self.cycle
        # the mask flip stops the router AND re-asserts the device bit
        # (healing a corrupted mask is the same write as draining)
        batcher.set_shard_active(shard, False)
        batcher.shard_probing[shard] = False
        batcher.clear_shard_health(shard)
        self.quarantined_total += 1
        evacuated, released = self.worker.evacuate_shard(shard)
        self.rows_evacuated_total += evacuated
        self.released_total += released
        self._event(
            "shard-quarantine", shard=shard, cause=cause,
            evacuated=evacuated, released=released,
        )
        log.warning(
            "Shard %d quarantined (%s); evacuated %d row(s) to healthy "
            "shards, released %d to the queue",
            shard, cause, evacuated, released,
        )

    def _probe_shards(self) -> None:
        """Advance quarantined shards toward re-admission: after
        ``probe_after_cycles`` a quarantined shard turns PROBING (mask
        back on, router capacity 1); a probing shard whose probe block
        settled clean — busy rows, real progress, no NaN flag — is
        re-admitted to SERVING.  A probe that trips a sentinel goes
        straight back to QUARANTINED via the supervision pass, timer
        reset.  A shard that was DRAINING when it fell sick resumes the
        drain instead of returning to SERVING (the probe's one request
        is the only admission it ever gets): quarantine must not
        silently undo a scale_down the Scaler ordered."""
        batcher = self.worker.batcher
        for shard, state in enumerate(self.shard_states):
            if state == QUARANTINED:
                if (self.cycle - self._quarantined_at[shard]
                        >= self.probe_after_cycles):
                    self.shard_states[shard] = PROBING
                    batcher.set_shard_active(shard, True)
                    batcher.shard_probing[shard] = True
                    self._event("shard-probe", shard=shard)
            elif state == PROBING:
                bad = batcher.last_health_bad
                clean = (
                    batcher.last_settle_busy[shard] > 0
                    and batcher.shard_stall_cycles[shard] == 0
                    and not (bad is not None and bool(bad[shard]))
                    # the verdict needs evidence the DECODE path worked:
                    # gang-block tokens, or the probe request finishing
                    # outright (a budget-1 row never enters a gang block
                    # — its completion IS the shard's whole job).  An
                    # admission-insert first token alone proves nothing
                    # about a still-faulted gang program.
                    and (batcher.shard_last_gang_progress[shard] > 0
                         or batcher.shard_last_completed[shard] > 0)
                )
                if clean:
                    resume_drain = shard in self._drain_on_readmit
                    batcher.shard_probing[shard] = False
                    self.readmitted_total += 1
                    if resume_drain:
                        # healthy again, but the Scaler had drained it:
                        # stop admitting and let run_cycle retire it to
                        # inactive once the probe row finishes
                        self._drain_on_readmit.discard(shard)
                        self.shard_states[shard] = DRAINING
                        batcher.set_shard_active(shard, False)
                    else:
                        self.shard_states[shard] = SERVING
                    self._event("shard-readmit", shard=shard,
                                resumed_drain=resume_drain)
                    log.info(
                        "Shard %d passed its probe; %s", shard,
                        "resuming its drain" if resume_drain
                        else "re-admitted",
                    )

    # -- deterministic fault injection (sim.faults.FleetFaultPlan) -------

    def poison_shard(self, shard: int, poisoned: bool = True) -> None:
        """Chaos seam: NaN-poison (or heal) the shard's decode logits."""
        self.worker.batcher.inject_poison(shard, poisoned)

    def wedge_shard(self, shard: int, wedged: bool = True) -> None:
        """Chaos seam: freeze (or un-freeze) the shard's gang results."""
        self.worker.batcher.inject_wedge(shard, wedged)

    def corrupt_shard_mask(self, shard: int) -> None:
        """Chaos seam: flip the shard's DEVICE admission bit off while
        the host still believes it admits."""
        self.worker.batcher.corrupt_active_mask(shard)

    def kill_admission_shard(self, shard: int) -> int:
        """Chaos seam (``FleetFaultPlan.admission_kills``): kill one
        ADMISSION shard — staging, not engine, failure domain; staged
        requests hand back via ``change_message_visibility(0)`` and
        the shard rehydrates next cycle.  Requires
        ``tenancy.admission_shards >= 2``."""
        return self.worker.kill_admission_shard(shard)

    def partition_admission_shard(
        self, shard: int, partitioned: bool = True,
    ) -> None:
        """Chaos seam (``FleetFaultPlan.admission_partitions``):
        gossip-partition (or heal) one admission shard."""
        self.worker.partition_admission_shard(shard, partitioned)

    @property
    def processed(self) -> int:
        return self.worker.processed

    @property
    def completed_by_tenant(self) -> dict[str, int]:
        """Uniquely-answered completions per tenant (the plane has one
        worker; the exactly-once discipline is the same registry-backed
        settle path as the replica pool's)."""
        return dict(getattr(self.worker, "completed_by_tenant", {}))

    @property
    def idle(self) -> bool:
        return (self.worker.batcher.active == 0
                and getattr(self.worker, "staged", 0) == 0)

    def stop_all(self) -> None:
        """Stop the plane, releasing un-finished in-flight requests back
        to the queue (shutdown never loses work — same contract as the
        replica pool's stop_all)."""
        release = getattr(self.worker, "release_inflight", None)
        if release is not None:
            release()
        self.worker.stop()
        for shard, state in enumerate(self.shard_states):
            if state in (SERVING, DRAINING, PROBING, QUARANTINED):
                self.shard_states[shard] = INACTIVE
                self.worker.batcher.set_shard_active(shard, False)
            # a later scale_up must get a full-capacity shard, not one
            # still capped to the half-open probe's single slot
            self.worker.batcher.shard_probing[shard] = False
        self._drain_on_readmit.clear()
        self._quarantined_at.clear()
        self._update_metrics()

    # ------------------------------------------------------------------
    # Durable-state surface: the base class serializes the exactly-once
    # reply registry; the sharded plane has exactly ONE worker, so its
    # admission accounting (DRR/EDF deficits + urgency credits, flood
    # classification, overload-ladder tier, sticky tenant homes) rides
    # the same section.  Shard lifecycle states deliberately do NOT:
    # the restarted plane's masks are the observed world, and the
    # autoscaler re-derives the shard count through the ordinary gates.
    # ------------------------------------------------------------------

    def export_state(self) -> dict:
        state = super().export_state()
        admission = getattr(self.worker, "export_admission_state", None)
        if admission is not None:
            state["admission"] = admission()
            state["records"] += state["admission"].get("records", 0)
        return state

    def import_state(
        self, state: dict, *, rebase: float = 0.0,
        now: float | None = None, max_age_s: float = 0.0,
    ) -> int:
        recovered = super().import_state(
            state, rebase=rebase, now=now, max_age_s=max_age_s
        )
        admission = state.get("admission")
        importer = getattr(self.worker, "import_admission_state", None)
        if importer is not None and isinstance(admission, dict):
            recovered += importer(
                admission, rebase=rebase, now=now, max_age_s=max_age_s
            )
        return recovered

    # ------------------------------------------------------------------
    # Observability (the reply registry and the FleetEvent stream —
    # including the exactly-once protocol the FleetWorker settle path
    # speaks — live on FleetPoolBase, shared with WorkerPool)
    # ------------------------------------------------------------------

    def attach_lifecycle(self, registry) -> None:
        """Wire a :class:`~..obs.LifecycleRegistry` through the sharded
        worker's stamp sites (admission, emit, settle, evacuation) —
        the sharded plane is ONE worker, so the whole plane shares the
        pool's registry."""
        self.lifecycle = registry
        attach = getattr(self.worker, "attach_lifecycle", None)
        if attach is not None:
            attach(registry)

    def attach_metrics(self, metrics) -> None:
        """Refresh the per-shard gauge family (``shard_active``,
        ``shard_active_slots``, ``shard_tokens_per_second``,
        ``shard_health``) plus the pool-level chaos counters
        (``shard_quarantined_total``, ``rows_evacuated_total``) into a
        :class:`~..obs.prometheus.WorkloadMetrics` registry each cycle."""
        self.metrics = metrics
        self._update_metrics()

    def _update_metrics(self) -> None:
        if self.metrics is None:
            return
        batcher = self.worker.batcher
        served_since = getattr(self.worker, "_served_since", None)
        for row in batcher.shard_stats(served_since):
            state = self.shard_states[row["shard"]]
            self.metrics.set_shard_gauges(
                row["shard"],
                active=state in (SERVING, PROBING),
                active_slots=row["active_slots"],
                tokens_per_second=row["tokens_per_second"],
                health=SHARD_HEALTH_CODES[state],
            )
        self.metrics.set_gauge(
            "shard_quarantined_total", self.quarantined_total,
            "Shards quarantined by the health sentinels (poisoned "
            "logits, no progress, admission-mask mismatch) over the "
            "plane's lifetime.",
            kind="counter",
        )
        self.metrics.set_gauge(
            "rows_evacuated_total", self.rows_evacuated_total,
            "In-flight rows moved off quarantined shards onto healthy "
            "ones (re-prefilled mid-request; un-evacuable rows are "
            "released to the queue instead).",
            kind="counter",
        )

    # ------------------------------------------------------------------
    # Real-plane construction
    # ------------------------------------------------------------------

    @classmethod
    def serving(
        cls,
        queue,
        params,
        model_config,
        service_config,
        *,
        min: int,
        max: int | None = None,
        shards: int | None = None,
        family: str = "gpt",
        tokenizer=None,
        result_queue=None,
        mesh=None,
        engine_source=None,
        now_fn=None,
        tenancy=None,
        **pool_kwargs,
    ) -> "ShardedWorkerPool":
        """One gang-stepped :class:`~.worker.FleetWorker` whose batcher
        stacks ``shards`` engine shards of ``service_config.batch_size``
        slots each (``shards`` defaults to ``service_config.shards``,
        which defaults to ``max``).  ``engine_source`` seeds the plane
        from an external sharded donor batcher (compile-free startup,
        same contract as the replica pool); ``now_fn`` is the worker's
        request-TTL clock."""
        import dataclasses

        if shards is None:
            shards = (
                service_config.shards if service_config.shards > 1
                else (max or service_config.shards)
            )
        seeded = dataclasses.replace(service_config, shards=shards)

        def factory(pool: "ShardedWorkerPool"):
            from .worker import FleetWorker

            return FleetWorker(
                queue, params, model_config, seeded,
                family=family, tokenizer=tokenizer,
                result_queue=result_queue, mesh=mesh, pool=pool,
                engine_source=engine_source, now_fn=now_fn,
                tenancy=tenancy,
                # force the gang engine even for a one-shard plane (the
                # worker's auto-pick would build the plain batcher,
                # which has no shard surface to actuate)
                sharded=True,
            )

        return cls(factory, min=min, max=max, **pool_kwargs)
