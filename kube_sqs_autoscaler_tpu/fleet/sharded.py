"""ShardedWorkerPool: the Scaler seam over shard-active mask flips.

The :class:`~.pool.WorkerPool` scales capacity by spawning/draining
whole worker replicas — real robustness (a replica can die), but every
serving cycle steps N engines from Python.  This pool is the sharded
actuation mode: ONE gang-stepped worker
(:class:`~..workloads.shard_plane.ShardedBatcher` behind a
:class:`~.worker.FleetWorker`) holds ``shards`` engine shards, and
``scale_up``/``scale_down`` flip device-side shard-active masks — O(1),
no spawn, no rebuild, no recompile — while the UNCHANGED
:class:`~..core.loop.ControlLoop` drives the same
:class:`~..core.types.Scaler` seam (PodAutoScaler parity pinned by the
actuator contract test, exactly like the replica pool):

- step by ``scale_up_pods``/``scale_down_pods`` clamped to
  ``[min, max]``; boundary no-ops are success; injected failures raise
  :class:`~..core.types.ScaleError` and change nothing;
- ``scale_down`` DRAINS: the newest serving shards stop admitting
  instantly (mask flip — the router and the device summary skip them)
  but their in-flight slots decode to completion; a drained-empty shard
  retires to inactive.  ``scale_up`` resurrects draining shards first
  (cancelling a drain is the same O(1) flip), then activates inactive
  ones lowest-index first;
- replies stay exactly-once on the at-least-once queue through the same
  bounded reply registry the replica pool uses (the worker is a
  :class:`~.worker.FleetWorker`, so visibility-timeout redeliveries
  dedup identically).

What the mask flip does NOT re-drive: shard state never moves — there
is no weight broadcast, no cache migration, no engine adoption, because
every shard lives inside the one already-compiled gang program.  The
trade against the replica pool is isolation: shards share a process and
a device program, so there is no kill/hang failover INSIDE the plane —
whole-plane crashes are the queue's visibility timeout's job, and
mixed deployments (several sharded planes under one replica pool)
compose the two seams.
"""

from __future__ import annotations

import logging
from typing import Any, Callable

from ..core.clock import Clock
from .pool import DRAINING, SERVING, FleetPoolBase

log = logging.getLogger(__name__)

builtins_min = min
builtins_max = max

# The third shard state: mask off, nothing in flight.  (A shard is never
# DEAD/STOPPED — it has no process to lose.)
INACTIVE = "inactive"
SHARD_STATE_CODES = {SERVING: 0, DRAINING: 1, INACTIVE: 2}


class ShardedWorkerPool(FleetPoolBase):
    """A Scaler whose replica count is the active-shard count of one
    gang-stepped serving plane.

    ``worker_factory(pool)`` builds THE worker (called once; real
    fleets wire a :class:`~.worker.FleetWorker` over a sharded batcher
    via :meth:`serving`, the contract test substitutes a featherweight
    stub).  ``max`` defaults to — and may not exceed — the batcher's
    allocated shard count: activation is a mask flip, so capacity
    beyond the allocation would need a real spawn (that is the replica
    pool's job).
    """

    def __init__(
        self,
        worker_factory: Callable[["ShardedWorkerPool"], Any],
        *,
        min: int,
        max: int | None = None,
        scale_up_pods: int = 1,
        scale_down_pods: int = 1,
        initial: int | None = None,
        clock: Clock | None = None,
        replied_capacity: int = 65536,
    ) -> None:
        if scale_up_pods < 1 or scale_down_pods < 1:
            raise ValueError("scale step sizes must be >= 1")
        super().__init__(clock=clock, replied_capacity=replied_capacity)
        self.worker = worker_factory(self)
        self.shards = self.worker.batcher.shards
        if max is None:
            max = self.shards
        if not 1 <= min <= max:
            raise ValueError(f"need 1 <= min ({min}) <= max ({max})")
        if max > self.shards:
            raise ValueError(
                f"max ({max}) exceeds the plane's allocated shards "
                f"({self.shards}); activation is a mask flip, not a spawn"
            )
        self.min = min
        self.max = max
        self.scale_up_pods = scale_up_pods
        self.scale_down_pods = scale_down_pods
        if initial is None:
            initial = min
        if not min <= initial <= max:
            raise ValueError(
                f"initial ({initial}) must be within [min, max]"
            )
        self.shard_states = [
            SERVING if s < initial else INACTIVE for s in range(self.shards)
        ]
        for s in range(self.shards):
            self.worker.batcher.set_shard_active(s, s < initial)
            if s < initial:
                self._event("shard-activate", shard=s)

    # ------------------------------------------------------------------
    # The Scaler seam (PodAutoScaler parity — pinned by contract test)
    # ------------------------------------------------------------------

    @property
    def replicas(self) -> int:
        """Active shard count — the plane's ``spec.replicas``.  Draining
        shards are excluded, like the replica pool's DRAINING members."""
        return sum(1 for st in self.shard_states if st == SERVING)

    def scale_up(self) -> None:
        self._injected_failure("up")
        current = self.replicas
        if current >= self.max:
            log.info(
                "More than max shards active. No scale up. Shards: %d",
                current,
            )
            return
        target = builtins_min(current + self.scale_up_pods, self.max)
        # resurrect draining shards first (newest drain first — their
        # slots are warmest and cancelling a drain is the same O(1)
        # flip), then activate inactive shards lowest-index first
        draining = [
            s for s in reversed(range(self.shards))
            if self.shard_states[s] == DRAINING
        ]
        inactive = [
            s for s in range(self.shards)
            if self.shard_states[s] == INACTIVE
        ]
        for shard in (draining + inactive)[: target - current]:
            self.shard_states[shard] = SERVING
            self.worker.batcher.set_shard_active(shard, True)
            self._event("shard-activate", shard=shard)
        log.info("Scale up successful. Shards: %d", self.replicas)

    def scale_down(self) -> None:
        self._injected_failure("down")
        current = self.replicas
        if current <= self.min:
            log.info(
                "Less than min shards active. No scale down. Shards: %d",
                current,
            )
            return
        target = builtins_max(current - self.scale_down_pods, self.min)
        serving = [
            s for s in reversed(range(self.shards))
            if self.shard_states[s] == SERVING
        ]
        for shard in serving[: current - target]:
            # newest shard first, mirroring the replica pool's drain
            # order; the mask flip stops admission instantly, in-flight
            # slots finish on the gang step
            self.shard_states[shard] = DRAINING
            self.worker.batcher.set_shard_active(shard, False)
            self._event(
                "shard-drain-start", shard=shard,
                inflight=self.worker.batcher.shard_busy(shard),
            )
        log.info("Scale down successful. Shards: %d", self.replicas)

    # ------------------------------------------------------------------
    # The serving cycle
    # ------------------------------------------------------------------

    def run_cycle(self) -> int:
        """One plane cycle: ONE worker cycle (refill + gang step +
        settle) however many shards are active, then retire any draining
        shard that emptied.  Returns requests completed."""
        self.cycle += 1
        done = self.worker.run_once()
        for shard, state in enumerate(self.shard_states):
            if state == DRAINING and self.worker.batcher.shard_busy(shard) == 0:
                self.shard_states[shard] = INACTIVE
                self._event("shard-deactivate", shard=shard)
        self._update_metrics()
        return done

    @property
    def processed(self) -> int:
        return self.worker.processed

    @property
    def idle(self) -> bool:
        return self.worker.batcher.active == 0

    def stop_all(self) -> None:
        """Stop the plane, releasing un-finished in-flight requests back
        to the queue (shutdown never loses work — same contract as the
        replica pool's stop_all)."""
        release = getattr(self.worker, "release_inflight", None)
        if release is not None:
            release()
        self.worker.stop()
        for shard, state in enumerate(self.shard_states):
            if state in (SERVING, DRAINING):
                self.shard_states[shard] = INACTIVE
                self.worker.batcher.set_shard_active(shard, False)
        self._update_metrics()

    # ------------------------------------------------------------------
    # Observability (the reply registry and the FleetEvent stream —
    # including the exactly-once protocol the FleetWorker settle path
    # speaks — live on FleetPoolBase, shared with WorkerPool)
    # ------------------------------------------------------------------

    def attach_metrics(self, metrics) -> None:
        """Refresh the per-shard gauge family (``shard_active``,
        ``shard_active_slots``, ``shard_tokens_per_second``) into a
        :class:`~..obs.prometheus.WorkloadMetrics` registry each cycle."""
        self.metrics = metrics
        self._update_metrics()

    def _update_metrics(self) -> None:
        if self.metrics is None:
            return
        batcher = self.worker.batcher
        served_since = getattr(self.worker, "_served_since", None)
        for row in batcher.shard_stats(served_since):
            self.metrics.set_shard_gauges(
                row["shard"],
                active=self.shard_states[row["shard"]] == SERVING,
                active_slots=row["active_slots"],
                tokens_per_second=row["tokens_per_second"],
            )

    # ------------------------------------------------------------------
    # Real-plane construction
    # ------------------------------------------------------------------

    @classmethod
    def serving(
        cls,
        queue,
        params,
        model_config,
        service_config,
        *,
        min: int,
        max: int | None = None,
        shards: int | None = None,
        family: str = "gpt",
        tokenizer=None,
        result_queue=None,
        mesh=None,
        **pool_kwargs,
    ) -> "ShardedWorkerPool":
        """One gang-stepped :class:`~.worker.FleetWorker` whose batcher
        stacks ``shards`` engine shards of ``service_config.batch_size``
        slots each (``shards`` defaults to ``service_config.shards``,
        which defaults to ``max``)."""
        import dataclasses

        if shards is None:
            shards = (
                service_config.shards if service_config.shards > 1
                else (max or service_config.shards)
            )
        seeded = dataclasses.replace(service_config, shards=shards)

        def factory(pool: "ShardedWorkerPool"):
            from .worker import FleetWorker

            return FleetWorker(
                queue, params, model_config, seeded,
                family=family, tokenizer=tokenizer,
                result_queue=result_queue, mesh=mesh, pool=pool,
                # force the gang engine even for a one-shard plane (the
                # worker's auto-pick would build the plain batcher,
                # which has no shard surface to actuate)
                sharded=True,
            )

        return cls(factory, min=min, max=max, **pool_kwargs)
