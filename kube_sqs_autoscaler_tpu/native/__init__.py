"""Native local queue: ctypes binding over ``localqueue.cpp``.

The C++ broker (built on demand with ``g++``; see the .cpp header for why
it exists) is exposed here as :class:`LocalQueue`, which speaks **both**
protocols the framework defines:

- the controller's :class:`~..metrics.queue.QueueService`
  (``get_queue_attributes``) — so ``QueueMetricSource`` can watch a local
  queue exactly like SQS, and
- the workers' :class:`~..workloads.service.MessageQueue`
  (``receive_messages`` / ``delete_message``) — so ``QueueWorker`` can
  drain one.

That makes the native broker a drop-in replacement for AWS SQS when
producer, queue, and TPU workers are co-located: the whole
autoscaling-plus-worker stack runs against it unchanged (see
``tests/test_native_queue.py`` for the closed loop).

Build model: one ``g++ -O2 -shared -fPIC`` invocation, cached in
``_build/`` next to this file and rebuilt when the source is newer.  No
pybind11 (not in this image); plain ``extern "C"`` + ctypes, which also
releases the GIL during blocking receives.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from contextlib import contextmanager
from pathlib import Path

_SRC = Path(__file__).with_name("localqueue.cpp")
_BUILD_DIR = Path(__file__).with_name("_build")
_LIB = _BUILD_DIR / "liblocalqueue.so"
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


class NativeUnavailableError(RuntimeError):
    """Raised when the native library cannot be built (no g++)."""


def build_shared_library(src: Path, lib_path: Path) -> ctypes.CDLL:
    """Compile-if-stale and dlopen one ``extern "C"`` source — the build
    model every native component shares (this queue, the token reader).

    One ``g++ -O2 -shared -fPIC -pthread`` invocation cached next to the
    source and rebuilt when the source is newer.  Concurrent builders
    (parallel pytest workers, several pods on a shared volume) each write
    a per-pid tmp file and the final ``os.replace`` is atomic, so a
    complete .so always wins.
    """
    if not lib_path.exists() or lib_path.stat().st_mtime < src.stat().st_mtime:
        lib_path.parent.mkdir(exist_ok=True)
        tmp = lib_path.parent / f"{lib_path.stem}.{os.getpid()}.so.tmp"
        cmd = [
            "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
            str(src), "-o", str(tmp),
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except FileNotFoundError as err:
            raise NativeUnavailableError(
                f"g++ not found; {src.name} unavailable"
            ) from err
        except subprocess.CalledProcessError as err:
            raise NativeUnavailableError(
                f"native build failed:\n{err.stderr}"
            ) from err
        os.replace(tmp, lib_path)
    return ctypes.CDLL(str(lib_path))


def load_library() -> ctypes.CDLL:
    """Build (if stale) and load the native library; cached per process."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        lib = build_shared_library(_SRC, _LIB)
        c = ctypes
        lib.lq_create.argtypes = [c.c_double]
        lib.lq_create.restype = c.c_void_p
        lib.lq_destroy.argtypes = [c.c_void_p]
        lib.lq_destroy.restype = None
        lib.lq_close.argtypes = [c.c_void_p]
        lib.lq_close.restype = None
        lib.lq_use_manual_clock.argtypes = [c.c_void_p, c.c_int]
        lib.lq_use_manual_clock.restype = None
        lib.lq_advance.argtypes = [c.c_void_p, c.c_double]
        lib.lq_advance.restype = None
        lib.lq_send.argtypes = [c.c_void_p, c.c_char_p, c.c_longlong, c.c_double]
        lib.lq_send.restype = c.c_longlong
        lib.lq_receive.argtypes = [
            c.c_void_p, c.c_double,
            c.POINTER(c.c_longlong), c.POINTER(c.c_longlong),
        ]
        lib.lq_receive.restype = c.c_int
        lib.lq_fetch_body.argtypes = [
            c.c_void_p, c.c_longlong, c.c_char_p, c.c_longlong,
        ]
        lib.lq_fetch_body.restype = c.c_longlong
        lib.lq_delete.argtypes = [c.c_void_p, c.c_longlong]
        lib.lq_delete.restype = c.c_int
        lib.lq_change_visibility.argtypes = [c.c_void_p, c.c_longlong, c.c_double]
        lib.lq_change_visibility.restype = c.c_int
        lib.lq_attributes.argtypes = [c.c_void_p, c.c_longlong * 3]
        lib.lq_attributes.restype = None
        _lib = lib
        return lib


def native_available() -> bool:
    """True if the native library is (or can be) built on this machine."""
    try:
        load_library()
        return True
    except NativeUnavailableError:
        return False


class LocalQueue:
    """One native queue.  Implements the controller's ``QueueService`` and
    the workers' ``MessageQueue`` protocols (the ``queue_url`` arguments
    those carry are accepted and ignored — a local queue *is* its handle).
    """

    def __init__(
        self, visibility_timeout: float = 30.0, manual_clock: bool = False
    ) -> None:
        self._lib = load_library()
        self._q = self._lib.lq_create(float(visibility_timeout))
        # active-call refcount: every native entry runs inside _native(),
        # so close() can wait until no thread is inside the C++ object
        # before freeing it (ctypes releases the GIL, so "null the handle
        # first" alone is not enough — a thread can have passed the handle
        # check but not yet entered the C function)
        self._cv = threading.Condition()
        self._active_calls = 0
        if manual_clock:
            self._lib.lq_use_manual_clock(self._q, 1)

    # --- lifecycle -------------------------------------------------------
    @contextmanager
    def _native(self):
        """Yield the handle while holding an active-call ref."""
        with self._cv:
            if self._q is None:
                raise ValueError("operation on closed LocalQueue")
            handle = self._q
            self._active_calls += 1
        try:
            yield handle
        finally:
            with self._cv:
                self._active_calls -= 1
                if self._active_calls == 0:
                    self._cv.notify_all()

    def close(self) -> None:
        with self._cv:
            if self._q is None:
                return
            # no new call can acquire the handle past this point
            handle, self._q = self._q, None
        # wake long-pollers (they see `closing` and return -1 promptly) ...
        self._lib.lq_close(handle)
        # ... then wait for every in-flight native call to exit the C++
        # object before freeing it
        with self._cv:
            self._cv.wait_for(lambda: self._active_calls == 0)
        self._lib.lq_destroy(handle)

    def __del__(self) -> None:  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self) -> "LocalQueue":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --- test clock ------------------------------------------------------
    def advance(self, seconds: float) -> None:
        """Advance the queue's manual clock (visibility/delay expiry)."""
        with self._native() as handle:
            self._lib.lq_advance(handle, float(seconds))

    # --- producer --------------------------------------------------------
    def send_message(
        self, queue_url: str = "", body: str = "", delay_s: float = 0.0
    ) -> str:
        data = body.encode()
        with self._native() as handle:
            msg_id = self._lib.lq_send(handle, data, len(data), float(delay_s))
        return f"msg-{msg_id}"

    # --- consumer (workers' MessageQueue protocol) -----------------------
    def receive_messages(
        self, queue_url: str = "", max_messages: int = 1, wait_time_s: int = 0
    ) -> list[dict]:
        out = []
        wait = float(wait_time_s)
        with self._native() as handle:
            for _ in range(max_messages):
                receipt = ctypes.c_longlong()
                length = ctypes.c_longlong()
                status = self._lib.lq_receive(
                    handle, wait, ctypes.byref(receipt), ctypes.byref(length)
                )
                if status != 0:
                    break
                wait = 0.0  # only the first receive of a batch long-polls
                buf = ctypes.create_string_buffer(int(length.value))
                n = self._lib.lq_fetch_body(
                    handle, receipt.value, buf, length.value
                )
                if n < 0:  # expired between receive and fetch (real clock)
                    continue
                out.append(
                    {
                        "ReceiptHandle": f"rh-{receipt.value}",
                        "Body": buf.raw[:n].decode(),
                    }
                )
        return out

    def delete_message(self, queue_url: str = "", receipt_handle: str = "") -> None:
        with self._native() as handle:
            self._lib.lq_delete(handle, self._parse_receipt(receipt_handle))

    def change_message_visibility(
        self, receipt_handle: str, timeout_s: float
    ) -> bool:
        with self._native() as handle:
            status = self._lib.lq_change_visibility(
                handle, self._parse_receipt(receipt_handle), float(timeout_s)
            )
        return status == 0

    # --- controller (QueueService protocol) ------------------------------
    def get_queue_attributes(
        self, queue_url: str = "", attribute_names: list | None = None
    ) -> dict:
        counts = (ctypes.c_longlong * 3)()
        with self._native() as handle:
            self._lib.lq_attributes(handle, counts)
        attributes = {
            "ApproximateNumberOfMessages": str(counts[0]),
            "ApproximateNumberOfMessagesDelayed": str(counts[1]),
            "ApproximateNumberOfMessagesNotVisible": str(counts[2]),
        }
        if attribute_names is None:
            return attributes
        return {
            name: attributes[name]
            for name in attribute_names
            if name in attributes
        }

    @staticmethod
    def _parse_receipt(receipt_handle: str) -> int:
        if receipt_handle.startswith("rh-"):
            try:
                return int(receipt_handle[3:])
            except ValueError:
                return -1  # malformed ("rh-abc") fails like unknown ones
        return -1  # unknown handles fail the delete, mirroring SQS
