"""ctypes binding for the native token-corpus reader (tokenreader.cpp).

Same build model as the local queue: one ``g++ -O2 -shared`` invocation
cached under ``_build/`` and rebuilt when the source is newer; plain
``extern "C"`` + ctypes (no pybind11 in this image), with the GIL
released during the native batch copy so the double-buffer thread's work
genuinely overlaps Python-side dispatch.
"""

from __future__ import annotations

import ctypes
import json
import threading
from pathlib import Path

import numpy as np

from . import NativeUnavailableError

_SRC = Path(__file__).with_name("tokenreader.cpp")
_BUILD_DIR = Path(__file__).with_name("_build")
_LIB = _BUILD_DIR / "libtokenreader.so"
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None

# metadata file written next to the shards (vocab size + dtype)
META_FILE = "meta.json"


def read_meta(directory: str | Path) -> dict:
    """The corpus metadata (``vocab_size``, ``dtype``) without touching
    the native reader — for cheap validation before shards are mmapped."""
    return json.loads((Path(directory) / META_FILE).read_text())

_OPEN_ERRORS = {
    -1: "bad arguments (no shards, or token dtype not uint16/int32)",
    -2: "shard open() failed",
    -3: "a shard holds fewer tokens than one training window",
    -4: "mmap failed",
}


def load_library() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        from . import build_shared_library

        lib = build_shared_library(_SRC, _LIB)
        c = ctypes
        lib.tr_open.argtypes = [
            c.POINTER(c.c_char_p), c.c_longlong, c.c_int, c.c_longlong,
            c.POINTER(c.c_longlong), c.POINTER(c.c_int),
        ]
        lib.tr_open.restype = c.c_void_p
        lib.tr_total_tokens.argtypes = [c.c_void_p]
        lib.tr_total_tokens.restype = c.c_longlong
        lib.tr_fill_batch.argtypes = [
            c.c_void_p, c.POINTER(c.c_int32), c.c_longlong, c.c_longlong,
            c.c_uint64, c.c_longlong,
        ]
        lib.tr_fill_batch.restype = c.c_int
        lib.tr_close.argtypes = [c.c_void_p]
        lib.tr_close.restype = None
        _lib = lib
        return lib


def write_token_shards(
    directory: str | Path,
    tokens,
    vocab_size: int,
    shard_tokens: int | None = None,
    dtype: str = "uint16",
) -> Path:
    """Write a token corpus in the reader's format: ``*.bin`` raw-token
    shards plus ``meta.json`` (vocab size + dtype).  The corpus-prep
    utility for tests, demos, and tokenizer pipelines."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    source = np.asarray(tokens)
    if source.size == 0:
        raise ValueError("empty token corpus (nothing to shard)")
    # validate BEFORE the cast: a silent wrap (old numpy) or an obscure
    # OverflowError (new numpy) would otherwise stand in for these
    # messages — and a wrapped corpus trains on garbage with no error
    # anywhere downstream
    if int(source.min()) < 0:
        raise ValueError(
            f"negative token ids (min {int(source.min())}) are not valid "
            "corpus tokens"
        )
    if dtype == "uint16":
        if vocab_size > 2**16:
            raise ValueError(
                f"vocab_size={vocab_size} does not fit uint16 tokens; "
                "pass dtype='int32'"
            )
        if int(source.max()) >= 2**16:
            raise ValueError(
                "token ids >= 2**16 do not fit uint16 shards; pass "
                "dtype='int32'"
            )
    arr = source.astype(np.uint16 if dtype == "uint16" else np.int32)
    shard_tokens = shard_tokens or len(arr)
    for i, start in enumerate(range(0, len(arr), shard_tokens)):
        (directory / f"shard_{i:05d}.bin").write_bytes(
            arr[start:start + shard_tokens].tobytes()
        )
    (directory / META_FILE).write_text(
        json.dumps({"vocab_size": int(vocab_size), "dtype": dtype}) + "\n"
    )
    return directory


class TokenReader:
    """Deterministic random-crop batches from an mmapped token corpus.

    ``batch(batch, seq, seed, step)`` returns int32 ``[batch, seq]``;
    the (seed, step, row) counter scheme makes every batch a pure
    function of its indices — a resumed trainer re-reads exactly the
    stream it would have seen (no cursor state to checkpoint).  The
    native side double-buffers: step N+1 is assembled on a worker
    thread while step N trains.
    """

    def __init__(self, directory: str | Path, min_window: int = 1):
        directory = Path(directory)
        meta_path = directory / META_FILE
        if not meta_path.exists():
            raise FileNotFoundError(
                f"{meta_path} not found — write shards with "
                "write_token_shards (raw *.bin tokens + meta.json)"
            )
        meta = read_meta(directory)
        self.vocab_size = int(meta["vocab_size"])
        dtype = meta.get("dtype", "uint16")
        if dtype not in ("uint16", "int32"):
            raise ValueError(f"unsupported corpus dtype {dtype!r}")
        paths = sorted(str(p).encode() for p in directory.glob("*.bin"))
        if not paths:
            raise FileNotFoundError(f"no *.bin shards under {directory}")
        self._lib = load_library()
        arr = (ctypes.c_char_p * len(paths))(*paths)
        total = ctypes.c_longlong()
        err = ctypes.c_int()
        self._h = self._lib.tr_open(
            arr, len(paths), 2 if dtype == "uint16" else 4,
            int(min_window), ctypes.byref(total), ctypes.byref(err),
        )
        if not self._h:
            raise ValueError(
                f"tr_open failed for {directory}: "
                f"{_OPEN_ERRORS.get(err.value, err.value)}"
            )
        self.total_tokens = int(total.value)

    def batch(self, batch: int, seq: int, seed: int, step: int) -> np.ndarray:
        out = np.empty((batch, seq), np.int32)
        status = self._lib.tr_fill_batch(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            batch, seq, seed & (2**64 - 1), step,
        )
        if status != 0:
            raise ValueError(
                f"batch(seq={seq}) exceeds the smallest shard's tokens "
                "(crops never span shard boundaries) or has a "
                "non-positive shape"
            )
        return out

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.tr_close(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
