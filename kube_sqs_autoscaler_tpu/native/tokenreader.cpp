// Native token-corpus reader: the trainer's data plane.
//
// Why native: the input pipeline must assemble [batch, seq] int32 windows
// from multi-GiB token shards every step without stealing Python time from
// the dispatch thread.  This reader mmaps the shards (the OS page cache is
// the shuffle buffer; no heap copy of the corpus), samples deterministic
// random crops with a splitmix64 counter scheme (seed, step, row) — so a
// resumed run reads exactly the batches the interrupted one would have —
// and double-buffers: a worker thread assembles batch N+1 while the
// caller consumes batch N (ctypes releases the GIL around the call, so
// the copy genuinely overlaps JAX dispatch).
//
// Exposed as plain extern "C" for ctypes (no pybind11 in this image);
// the Python binding lives in native/tokenreader.py.  File format:
// raw little-endian uint16 or uint32 tokens, any number of "*.bin"
// shards; shard boundaries are treated as a contiguous global stream
// (crops never span a boundary — see pick_offset).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Shard {
  const uint8_t* data = nullptr;  // mmapped
  size_t bytes = 0;
  long long tokens = 0;
  long long first = 0;  // global index of this shard's token 0
  int fd = -1;
};

// splitmix64: the standard 64-bit mixing function — a counter keyed by
// (seed, step, row) gives an independent, reproducible stream per row.
uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

struct Reader {
  std::vector<Shard> shards;
  int token_bytes = 2;
  long long total_tokens = 0;
  long long min_shard_tokens = 0;  // crop-safety bound for fill requests

  // double buffer: the worker fills `next` for key (step+1) while the
  // caller copies `ready` out
  std::mutex mu;
  std::condition_variable cv;
  std::thread worker;
  bool closing = false;
  bool job_pending = false;  // a request is queued, worker not started
  bool job_busy = false;     // worker is assembling the queued request
  // prefetched batch
  std::vector<int32_t> prefetched;
  long long prefetched_step = -1;
  long long pf_batch = 0, pf_seq = 0;
  uint64_t pf_seed = 0;
  // job request
  long long job_step = 0, job_batch = 0, job_seq = 0;
  uint64_t job_seed = 0;

  ~Reader() {
    {
      std::unique_lock<std::mutex> lock(mu);
      closing = true;
      cv.notify_all();
    }
    if (worker.joinable()) worker.join();
    for (auto& s : shards) {
      if (s.data) munmap(const_cast<uint8_t*>(s.data), s.bytes);
      if (s.fd >= 0) close(s.fd);
    }
  }

  const Shard& shard_for(long long global_token) const {
    // binary search over first-token prefix sums
    size_t lo = 0, hi = shards.size() - 1;
    while (lo < hi) {
      size_t mid = (lo + hi + 1) / 2;
      if (shards[mid].first <= global_token) lo = mid;
      else hi = mid - 1;
    }
    return shards[lo];
  }

  // Deterministic crop start for (seed, step, row): uniform over the
  // crops of the shard a uniform global token lands in, never spanning a
  // shard boundary (every shard must hold >= seq + 1 tokens — validated
  // at open).  +1: a training window of `seq` inputs needs seq tokens;
  // the LM shift happens on the logits, so windows are seq long here.
  long long pick_offset(uint64_t seed, long long step, long long row,
                        long long seq) const {
    uint64_t h = splitmix64(seed ^ splitmix64(
        static_cast<uint64_t>(step) * 0x100000001b3ULL ^
        static_cast<uint64_t>(row)));
    const Shard& s = shard_for(static_cast<long long>(
        h % static_cast<uint64_t>(total_tokens)));
    uint64_t crops = static_cast<uint64_t>(s.tokens - seq + 1);
    return s.first + static_cast<long long>(splitmix64(h) % crops);
  }

  void copy_window(long long global_start, long long seq,
                   int32_t* out) const {
    const Shard& s = shard_for(global_start);
    long long local = global_start - s.first;
    if (token_bytes == 2) {
      const uint16_t* src =
          reinterpret_cast<const uint16_t*>(s.data) + local;
      for (long long i = 0; i < seq; ++i) out[i] = src[i];
    } else {
      const int32_t* src =
          reinterpret_cast<const int32_t*>(s.data) + local;
      std::memcpy(out, src, sizeof(int32_t) * seq);
    }
  }

  void fill(int32_t* out, long long batch, long long seq, uint64_t seed,
            long long step) const {
    for (long long row = 0; row < batch; ++row) {
      copy_window(pick_offset(seed, step, row, seq), seq,
                  out + row * seq);
    }
  }

  void worker_loop() {
    std::unique_lock<std::mutex> lock(mu);
    while (true) {
      cv.wait(lock, [&] { return closing || job_pending; });
      if (closing) return;
      long long step = job_step, batch = job_batch, seq = job_seq;
      uint64_t seed = job_seed;
      job_pending = false;
      job_busy = true;  // callers wanting (step,batch,seq,seed) wait on us
      std::vector<int32_t> buf(
          static_cast<size_t>(batch) * static_cast<size_t>(seq));
      lock.unlock();
      fill(buf.data(), batch, seq, seed, step);  // shards are immutable
      lock.lock();
      prefetched = std::move(buf);
      prefetched_step = step;
      pf_batch = batch;
      pf_seq = seq;
      pf_seed = seed;
      job_busy = false;
      cv.notify_all();
    }
  }
};

}  // namespace

extern "C" {

// paths: n null-terminated shard paths.  token_bytes: 2 (uint16) or 4
// (int32).  min_tokens_per_shard: validation bound (seq) — shards
// smaller than this are an error (-3).  Returns a handle or null.
void* tr_open(const char** paths, long long n, int token_bytes,
              long long min_tokens_per_shard, long long* total_out,
              int* err_out) {
  auto fail = [&](int code) -> void* {
    if (err_out) *err_out = code;
    return nullptr;
  };
  if (n <= 0 || (token_bytes != 2 && token_bytes != 4)) return fail(-1);
  auto reader = new Reader();
  reader->token_bytes = token_bytes;
  long long first = 0;
  for (long long i = 0; i < n; ++i) {
    Shard s;
    s.fd = open(paths[i], O_RDONLY);
    if (s.fd < 0) {
      delete reader;
      return fail(-2);
    }
    struct stat st;
    fstat(s.fd, &st);
    s.bytes = static_cast<size_t>(st.st_size);
    s.tokens = static_cast<long long>(s.bytes) / token_bytes;
    if (s.tokens < min_tokens_per_shard) {
      close(s.fd);
      delete reader;
      return fail(-3);
    }
    s.data = static_cast<const uint8_t*>(
        mmap(nullptr, s.bytes, PROT_READ, MAP_PRIVATE, s.fd, 0));
    if (s.data == MAP_FAILED) {
      close(s.fd);
      delete reader;
      return fail(-4);
    }
    madvise(const_cast<uint8_t*>(s.data), s.bytes, MADV_RANDOM);
    s.first = first;
    first += s.tokens;
    reader->shards.push_back(s);
  }
  reader->total_tokens = first;
  reader->min_shard_tokens = reader->shards[0].tokens;
  for (const auto& s : reader->shards) {
    reader->min_shard_tokens = std::min(reader->min_shard_tokens, s.tokens);
  }
  reader->worker = std::thread(&Reader::worker_loop, reader);
  if (total_out) *total_out = first;
  if (err_out) *err_out = 0;
  return reader;
}

long long tr_total_tokens(void* handle) {
  return static_cast<Reader*>(handle)->total_tokens;
}

// Fill [batch, seq] int32 tokens for (seed, step).  Serves from the
// prefetch buffer when the worker already assembled this exact request,
// else assembles synchronously; either way kicks off a prefetch of
// step+1 before returning.  Returns 0, or -1 when `seq` exceeds the
// smallest shard (pick_offset's crops-per-shard count would underflow
// into an out-of-bounds read).
int tr_fill_batch(void* handle, int32_t* out, long long batch,
                  long long seq, uint64_t seed, long long step) {
  auto* r = static_cast<Reader*>(handle);
  if (seq < 1 || batch < 1 || seq > r->min_shard_tokens) return -1;
  bool served = false;
  {
    std::unique_lock<std::mutex> lock(r->mu);
    // if this exact request is queued or mid-assembly, wait for the
    // worker to publish it instead of duplicating the copy here
    r->cv.wait(lock, [&] {
      bool ours = r->job_step == step && r->job_batch == batch &&
                  r->job_seq == seq && r->job_seed == seed;
      return !(ours && (r->job_pending || r->job_busy));
    });
    if (r->prefetched_step == step && r->pf_batch == batch &&
        r->pf_seq == seq && r->pf_seed == seed) {
      std::memcpy(out, r->prefetched.data(),
                  sizeof(int32_t) * static_cast<size_t>(batch) *
                      static_cast<size_t>(seq));
      served = true;
    }
  }
  if (!served) r->fill(out, batch, seq, seed, step);
  {
    std::unique_lock<std::mutex> lock(r->mu);
    r->job_step = step + 1;
    r->job_batch = batch;
    r->job_seq = seq;
    r->job_seed = seed;
    r->job_pending = true;
    r->cv.notify_all();
  }
  return 0;
}

void tr_close(void* handle) { delete static_cast<Reader*>(handle); }

}  // extern "C"
