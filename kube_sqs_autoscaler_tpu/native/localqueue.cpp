// localqueue — native in-process message broker with SQS-shaped semantics.
//
// The reference (/root/reference) points its controller at AWS SQS over
// HTTPS (sqs/sqs.go:45-67) and has no native code at all (SURVEY.md §2
// native-code census).  This component is this framework's co-located
// alternative: when the queue feeding TPU workers lives in the same pod or
// host as the producers, a microsecond-latency native broker replaces the
// managed service while keeping the exact attribute/receive/delete surface
// the rest of the stack (QueueMetricSource, QueueWorker) already speaks —
// visible / delayed / not-visible counts, visibility timeouts with
// redelivery, and receipt-handle deletes.
//
// Concurrency: one mutex per queue; receivers may long-poll (lq_receive
// with wait_s > 0) on a condition_variable that send/delete/visibility
// changes signal.  The Python binding (native/__init__.py) calls through
// ctypes, which releases the GIL, so worker threads block here without
// stalling the interpreter.
//
// Time: steady_clock by default; lq_use_manual_clock/lq_advance switch a
// queue to a virtual clock so tests can replay visibility-timeout
// scenarios deterministically (the same injectable-clock philosophy as
// core/clock.py).

#include <algorithm>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Msg {
  long long id;
  std::string body;
};

struct Delayed {
  double ready_at;
  Msg msg;
};

struct Inflight {
  double deadline;
  Msg msg;
};

double real_now() {
  using namespace std::chrono;
  return duration<double>(steady_clock::now().time_since_epoch()).count();
}

}  // namespace

struct LocalQueue {
  std::mutex mu;
  std::condition_variable cv;
  std::deque<Msg> visible;
  std::vector<Delayed> delayed;
  std::unordered_map<long long, Inflight> inflight;
  long long next_msg_id = 0;
  long long next_receipt = 0;
  double visibility_timeout = 30.0;
  bool manual_clock = false;
  double manual_now = 0.0;
  // shutdown handshake: lq_destroy flips `closing`, wakes long-pollers,
  // and waits for `waiters` to drain before deleting (destroying a mutex
  // or condvar another thread is blocked on is undefined behavior)
  bool closing = false;
  int waiters = 0;

  double now() const { return manual_clock ? manual_now : real_now(); }

  // Move due delayed messages and expired in-flight messages back to
  // visible.  Expired receipts are re-queued in receipt order so
  // redelivery is deterministic.  Caller holds mu.
  void settle() {
    const double t = now();
    for (auto it = delayed.begin(); it != delayed.end();) {
      if (it->ready_at <= t) {
        visible.push_back(std::move(it->msg));
        it = delayed.erase(it);
      } else {
        ++it;
      }
    }
    std::vector<long long> expired;
    for (const auto& kv : inflight) {
      if (kv.second.deadline <= t) expired.push_back(kv.first);
    }
    std::sort(expired.begin(), expired.end());
    for (long long receipt : expired) {
      visible.push_back(std::move(inflight[receipt].msg));
      inflight.erase(receipt);
    }
    if (!expired.empty()) cv.notify_all();
  }
};

extern "C" {

LocalQueue* lq_create(double visibility_timeout_s) {
  auto* q = new LocalQueue();
  q->visibility_timeout = visibility_timeout_s;
  return q;
}

// Begin shutdown without freeing: mark the queue closing and wake
// long-pollers so they return promptly (-1).  The Python binding calls
// this first, then waits for its own active-call refcount to drain, then
// calls lq_destroy — so no thread can be inside the object when it is
// freed, even threads that had already passed the binding's handle check
// but not yet entered the C function.
void lq_close(LocalQueue* q) {
  if (q == nullptr) return;
  std::lock_guard<std::mutex> lock(q->mu);
  q->closing = true;
  q->cv.notify_all();
}

// Safe even with receivers blocked in lq_receive's long poll: wakes them,
// waits for them to leave the queue's mutex/condvar, then deletes.  The
// caller must still prevent *new* calls after destroy begins AND ensure
// no thread is still executing any lq_* entry on this queue (the Python
// binding's refcount in close() guarantees both).
void lq_destroy(LocalQueue* q) {
  if (q == nullptr) return;
  {
    std::unique_lock<std::mutex> lock(q->mu);
    q->closing = true;
    q->cv.notify_all();
    q->cv.wait(lock, [q] { return q->waiters == 0; });
  }
  delete q;
}

void lq_use_manual_clock(LocalQueue* q, int enable) {
  std::lock_guard<std::mutex> lock(q->mu);
  q->manual_clock = enable != 0;
}

void lq_advance(LocalQueue* q, double seconds) {
  {
    std::lock_guard<std::mutex> lock(q->mu);
    q->manual_now += seconds;
    q->settle();
  }
  q->cv.notify_all();
}

// Enqueue; delay_s > 0 parks the message as "delayed" first (SQS
// DelaySeconds).  Returns the message id.
long long lq_send(LocalQueue* q, const char* body, long long len,
                  double delay_s) {
  long long id;
  {
    std::lock_guard<std::mutex> lock(q->mu);
    id = ++q->next_msg_id;
    Msg m{id, std::string(body, static_cast<size_t>(len))};
    if (delay_s > 0.0) {
      q->delayed.push_back(Delayed{q->now() + delay_s, std::move(m)});
    } else {
      q->visible.push_back(std::move(m));
    }
  }
  q->cv.notify_one();
  return id;
}

// Pop one visible message into in-flight.  Blocks up to wait_s for a
// message (long polling; no blocking under the manual clock — virtual
// time only moves via lq_advance).  On success returns 0 and fills
// receipt_out/len_out; returns -1 if no message became visible in time.
int lq_receive(LocalQueue* q, double wait_s, long long* receipt_out,
               long long* len_out) {
  std::unique_lock<std::mutex> lock(q->mu);
  q->settle();
  if (q->visible.empty() && wait_s > 0.0 && !q->manual_clock &&
      !q->closing) {
    const auto deadline =
        std::chrono::steady_clock::now() +
        std::chrono::duration_cast<std::chrono::steady_clock::duration>(
            std::chrono::duration<double>(wait_s));
    // slice the wait so lazily-settled delayed/expired messages surface
    // without a dedicated timer thread
    ++q->waiters;
    while (q->visible.empty() && !q->closing &&
           std::chrono::steady_clock::now() < deadline) {
      q->cv.wait_for(lock, std::chrono::milliseconds(10));
      q->settle();
    }
    --q->waiters;
    q->cv.notify_all();  // let a pending lq_destroy proceed
  }
  if (q->closing || q->visible.empty()) return -1;
  Msg m = std::move(q->visible.front());
  q->visible.pop_front();
  const long long receipt = ++q->next_receipt;
  const long long len = static_cast<long long>(m.body.size());
  q->inflight.emplace(receipt,
                      Inflight{q->now() + q->visibility_timeout, std::move(m)});
  *receipt_out = receipt;
  *len_out = len;
  return 0;
}

// Copy the body of an in-flight receipt (it stays in-flight until deleted
// or expired).  Returns bytes copied, or -1 for an unknown receipt.
long long lq_fetch_body(LocalQueue* q, long long receipt, char* buf,
                        long long cap) {
  std::lock_guard<std::mutex> lock(q->mu);
  auto it = q->inflight.find(receipt);
  if (it == q->inflight.end()) return -1;
  const std::string& body = it->second.msg.body;
  const long long n = std::min<long long>(cap, body.size());
  std::memcpy(buf, body.data(), static_cast<size_t>(n));
  return n;
}

// Ack: drop an in-flight message for good.  0 on success, -1 if the
// receipt is unknown (already deleted or redelivered after expiry).
int lq_delete(LocalQueue* q, long long receipt) {
  std::lock_guard<std::mutex> lock(q->mu);
  return q->inflight.erase(receipt) ? 0 : -1;
}

// SQS ChangeMessageVisibility: reset an in-flight deadline (0 returns the
// message to visible immediately).  0 on success, -1 unknown receipt.
int lq_change_visibility(LocalQueue* q, long long receipt, double timeout_s) {
  std::lock_guard<std::mutex> lock(q->mu);
  auto it = q->inflight.find(receipt);
  if (it == q->inflight.end()) return -1;
  it->second.deadline = q->now() + timeout_s;
  q->settle();
  q->cv.notify_all();
  return 0;
}

// out[0]=visible, out[1]=delayed, out[2]=not-visible (in-flight) — the
// three default attributes the controller sums (sqs/sqs.go:28-33).
void lq_attributes(LocalQueue* q, long long out[3]) {
  std::lock_guard<std::mutex> lock(q->mu);
  q->settle();
  out[0] = static_cast<long long>(q->visible.size());
  out[1] = static_cast<long long>(q->delayed.size());
  out[2] = static_cast<long long>(q->inflight.size());
}

}  // extern "C"
