"""One event-driven scheduler for both control planes.

The autoscaler tick loop (:meth:`~..core.loop.ControlLoop.run`) and the
serving refill/step cycle (:meth:`~..fleet.pool.FleetDriver.run`) grew
up as two hand-rolled loops, each owning time its own way — a sleep
loop here, a cycle-advance-maybe-tick interleave there.  That made
"act *between* cycles" impossible to express: there was no seam where a
policy output could land other than the replica integer.  This module
is that seam: ONE priority-ordered event queue over ONE clock
(:class:`~..core.clock.FakeClock` or wall), with recurring and one-shot
events, deterministic ordering, and an explicit place between engine
cycles where a :class:`~.knobs.KnobActuator` can flip engine knobs at
safe points.

Event ordering contract (the determinism the tests pin):

- events execute in ``(due, priority, seq)`` order — earliest due time
  first; at equal due times the lowest priority number first; at equal
  priority, registration order (``seq``).  Two runs that register the
  same events over the same :class:`~..core.clock.FakeClock` execute
  them in the identical order — there is no other source of order.
- a **recurring** event reschedules itself after its callback returns:
  ``anchor="grid"`` at ``due + period`` (fixed cadence, catch-up runs
  back-to-back if the clock jumped), ``anchor="after"`` at
  ``clock.now() + period`` — the re-anchor-rather-than-accumulate rule
  both hand-rolled loops already used (a long tick/cycle must not cause
  a burst of catch-up events).
- the scheduler advances the clock only when the next event is in the
  future (``clock.sleep`` — virtual on a FakeClock, real otherwise).
  An event body that advances the clock itself (the fleet cycle's
  ``cycle_dt``) therefore owns that time exactly as
  :class:`~..fleet.pool.FleetDriver` did.

:func:`drive_loop` re-expresses ``ControlLoop.run`` as one registered
``control-tick`` event — same sleep-first cadence, same sticky-stop and
``max_ticks`` semantics, byte-identical tick records (pinned by test
and by the knobs bench's identity gate).  The fleet analogue lives in
:class:`~.fleet.ScheduledFleetDriver`.
"""

from __future__ import annotations

import heapq
import itertools
import logging
from collections import deque
from typing import Any, Callable

from ..core.clock import Clock, SystemClock

log = logging.getLogger(__name__)

#: Priority bands (lower runs first at equal due times).  Control ticks
#: outrank serving cycles so a tick that came due while a cycle advanced
#: the clock fires before the next cycle — exactly the FleetDriver
#: interleave (cycle, advance, *then* the due tick, then the next cycle).
PRIORITY_CONTROL = 0
PRIORITY_KNOB = 5
PRIORITY_CYCLE = 10
PRIORITY_TIMER = 20


class ScheduledEvent:
    """One queue entry: a named callback with a due time.

    Mutable on purpose — :meth:`EventScheduler.cancel` flips
    ``cancelled`` and the heap lazily discards it (cheaper and simpler
    than heap surgery, and cancellation order cannot perturb execution
    order of the survivors).
    """

    __slots__ = ("name", "fn", "due", "period", "priority", "seq",
                 "anchor", "cancelled", "runs")

    def __init__(self, name: str, fn: Callable[[], Any], due: float,
                 *, period: float | None = None, priority: int = 0,
                 seq: int = 0, anchor: str = "grid") -> None:
        if anchor not in ("grid", "after"):
            raise ValueError(f"anchor must be 'grid'/'after', got {anchor!r}")
        if period is not None and period < 0:
            raise ValueError(f"period must be >= 0, got {period}")
        self.name = name
        self.fn = fn
        self.due = float(due)
        self.period = period
        self.priority = priority
        self.seq = seq
        self.anchor = anchor
        self.cancelled = False
        self.runs = 0


class EventScheduler:
    """A deterministic priority-ordered event queue over one clock."""

    def __init__(self, clock: Clock | None = None,
                 trace_capacity: int = 4096) -> None:
        self.clock = clock or SystemClock()
        self._heap: list[tuple[float, int, int, ScheduledEvent]] = []
        self._seq = itertools.count()
        self._stop = False
        self.events_run = 0
        #: ``(due, name)`` of every executed event, bounded — the
        #: determinism tests compare two runs' traces for equality.
        self.trace: deque[tuple[float, str]] = deque(maxlen=trace_capacity)

    # -- registration ----------------------------------------------------

    def _push(self, event: ScheduledEvent) -> ScheduledEvent:
        heapq.heappush(
            self._heap, (event.due, event.priority, event.seq, event)
        )
        return event

    def at(self, name: str, when: float, fn: Callable[[], Any], *,
           priority: int = PRIORITY_TIMER) -> ScheduledEvent:
        """One-shot event at absolute clock time ``when`` (a past time
        fires on the next run step)."""
        return self._push(ScheduledEvent(
            name, fn, when, priority=priority, seq=next(self._seq),
        ))

    def after(self, name: str, delay: float, fn: Callable[[], Any], *,
              priority: int = PRIORITY_TIMER) -> ScheduledEvent:
        """One-shot event ``delay`` seconds from now."""
        return self.at(name, self.clock.now() + delay, fn,
                       priority=priority)

    def every(self, name: str, period: float, fn: Callable[[], Any], *,
              priority: int = PRIORITY_CYCLE, first_at: float | None = None,
              anchor: str = "grid") -> ScheduledEvent:
        """Recurring event.  First due at ``first_at`` (default:
        ``now + period``); see the module docstring for the two
        re-scheduling anchors."""
        due = self.clock.now() + period if first_at is None else first_at
        return self._push(ScheduledEvent(
            name, fn, due, period=period, priority=priority,
            seq=next(self._seq), anchor=anchor,
        ))

    def cancel(self, event: ScheduledEvent) -> None:
        """Cancel a registered event (idempotent; a recurring event
        stops rescheduling too)."""
        event.cancelled = True

    # -- execution -------------------------------------------------------

    def stop(self) -> None:
        """Ask :meth:`run` to return after the current event."""
        self._stop = True

    def reset_stop(self) -> None:
        self._stop = False

    @property
    def pending(self) -> int:
        """Live (non-cancelled) events still queued."""
        return sum(1 for *_k, e in self._heap if not e.cancelled)

    def run(self, *, max_events: int | None = None) -> int:
        """Execute events until the queue empties, :meth:`stop` is
        called, or ``max_events`` have run; returns how many ran.

        The wait-then-run step: if the head event is due in the future
        the scheduler blocks via ``clock.sleep`` (virtual on a
        FakeClock); an event whose callback moved the clock forward
        simply makes whatever is due next run without a wait.
        """
        ran = 0
        while self._heap and not self._stop:
            if max_events is not None and ran >= max_events:
                break
            due, _prio, _seq, event = self._heap[0]
            if event.cancelled:
                heapq.heappop(self._heap)
                continue
            now = self.clock.now()
            if due > now:
                self.clock.sleep(due - now)
            heapq.heappop(self._heap)
            self.trace.append((event.due, event.name))
            event.runs += 1
            self.events_run += 1
            ran += 1
            event.fn()
            if event.period is not None and not event.cancelled:
                event.due = (
                    event.due + event.period if event.anchor == "grid"
                    else self.clock.now() + event.period
                )
                event.seq = next(self._seq)
                self._push(event)
        return ran


def drive_loop(loop, *, max_ticks: int | None = None,
               scheduler: EventScheduler | None = None) -> Any:
    """Run a :class:`~..core.loop.ControlLoop` as a registered
    ``control-tick`` event — the sleep loop of ``ControlLoop.run``,
    re-expressed on the scheduler seam, byte-identical tick records.

    Semantics mirrored from ``run`` exactly: sleep *first* (the first
    tick lands one poll interval after start), a sticky :meth:`stop`
    requested mid-sleep skips the tick, ``max_ticks`` bounds the
    episode, and each call is a fresh episode whose state starts from
    :meth:`~..core.loop.ControlLoop.initial_policy_state`.  Returns the
    final policy state, like ``run``.

    On a caller-provided ``scheduler`` the episode owns that queue's
    run: the scheduler's stop flag is reset at episode start (a
    previous episode's stop must not silently zero this one — run()'s
    fresh-episode contract), and ending the episode (``max_ticks`` or
    ``loop.stop``) stops the current ``sched.run()`` — co-registered
    events resume on the caller's next ``run()`` call.
    """
    sched = scheduler or EventScheduler(loop.clock)
    sched.reset_stop()
    state = loop.initial_policy_state()
    if max_ticks is not None and max_ticks <= 0:
        return state
    box = {"state": state, "ticks": 0}

    def control_tick() -> None:
        if loop._stop.is_set():  # stop requested mid-sleep: skip the tick
            sched.stop()
            return
        box["state"] = loop.tick(box["state"])
        box["ticks"] += 1
        loop.ticks += 1
        if max_ticks is not None and box["ticks"] >= max_ticks:
            sched.stop()
        if loop._stop.is_set():
            sched.stop()

    event = sched.every(
        "control-tick", loop.config.poll_interval, control_tick,
        priority=PRIORITY_CONTROL, anchor="after",
    )
    if loop._stop.is_set():  # sticky pre-start stop, like run()
        sched.cancel(event)
        return box["state"]
    try:
        sched.run()
    finally:
        sched.cancel(event)
    return box["state"]
