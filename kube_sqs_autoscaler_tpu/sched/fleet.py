"""The fleet interleave as registered events: ScheduledFleetDriver.

:class:`~..fleet.pool.FleetDriver` interleaves fleet serving cycles
with control-loop ticks in one hand-rolled ``for`` loop.  This driver
re-expresses the SAME interleave as two recurring events on an
:class:`~.scheduler.EventScheduler` — ``fleet-cycle`` (supervise →
route → serve → retire, the untouched ``pool.run_cycle`` body) and
``control-tick`` (the untouched ``loop.tick`` body) — plus, when a
:class:`~.knobs.KnobActuator` is armed, a knob-application step at the
one provably safe instant: *between* cycles, after the previous cycle's
settle and before the next refill/dispatch.

Equivalence contract (hard-gated byte-identical by
``bench.py --suite knobs`` with knobs unarmed): tick records, dispatch/
transfer counters, replica trajectories, and replies are identical to
:class:`~..fleet.pool.FleetDriver` on the same episode, because the
bodies, their execution order, and every clock value they observe are
identical —

- each cycle event applies the fault plan, runs ``pool.run_cycle()``,
  and advances ``cycle_dt`` of virtual time, exactly like one
  ``FleetDriver`` iteration;
- the tick event is due at ``next_tick`` and, by priority, runs after
  the cycle that advanced the clock past it and before the next cycle —
  the ``clock.now() >= next_tick`` check position of the hand-rolled
  loop — then re-anchors to ``now + poll_interval``;
- the stop predicate is evaluated at the hand-rolled loop's exact check
  position: after the cycle when no tick is due, after the tick when
  one was.

Controller crashes (:class:`~..core.durable.ControllerCrash`) restart
through the inherited :meth:`~..fleet.pool.FleetDriver._crash_restart`
machinery — same factory contract, same downtime accounting, same
tick-attempt indexing — so the PR 13 restart battery runs unchanged
under the scheduler (pinned by test and by ``--suite restart``).
"""

from __future__ import annotations

import logging

from ..core.durable import ControllerCrash
from ..fleet.pool import FleetDriver
from .scheduler import (
    EventScheduler,
    PRIORITY_CONTROL,
    PRIORITY_CYCLE,
)

log = logging.getLogger(__name__)


class ScheduledFleetDriver(FleetDriver):
    """A :class:`~..fleet.pool.FleetDriver` whose interleave is owned by
    the event scheduler (see module docstring).

    ``knobs`` (a :class:`~.knobs.KnobActuator`) arms live engine-knob
    actuation: staged knob changes apply between cycles.  ``knob_policy``
    (anything with an ``evaluate()`` method, e.g.
    :class:`~.knobs.ReactiveKnobPolicy`) is consulted once per control
    tick — the policy-drives-engine seam — or once per cycle when the
    driver runs loopless.  Both default off, keeping the driver
    byte-identical to the hand-rolled one.
    """

    def __init__(self, pool, loop=None, *, knobs=None, knob_policy=None,
                 **kwargs) -> None:
        super().__init__(pool, loop, **kwargs)
        self.knobs = knobs
        self.knob_policy = knob_policy
        self.scheduler: EventScheduler | None = None

    def _crash_restart(self, clock):
        state = super()._crash_restart(clock)
        if self.knobs is not None:
            # the restart factory replaced the pool: the actuator must
            # actuate the LIVE plane, not the abandoned pre-crash one
            # (staged changes survive and land at the next safe point)
            self.knobs.retarget(self.pool)
        rebind = getattr(self.knob_policy, "rebind", None)
        if rebind is not None and self.loop is not None:
            brain = getattr(self.loop, "depth_policy", None)
            if brain is not None:
                # a learned knob adapter reads its deltas from the
                # loop's policy object — the restart rebuilt that too
                rebind(brain)
        return state

    def run(self, *, until_processed=None, max_cycles: int = 100_000,
            until=None) -> dict:
        clock = self.loop.clock if self.loop is not None else self.pool.clock
        sched = EventScheduler(clock)
        self.scheduler = sched
        box = {"state": None, "cycles": 0, "exhausted": False}
        trajectory: list[int] = []
        tick_event = None

        def check_stop() -> None:
            if until is not None:
                if until():
                    sched.stop()
                    return
            elif (
                until_processed is not None
                and self.pool.processed >= until_processed
                and self.pool.idle
            ):
                sched.stop()
                return
            if box["exhausted"]:
                sched.stop()

        def fleet_cycle() -> None:
            if self.fault_plan is not None:
                self.fault_plan.apply(self.pool.cycle, self.pool)
            if self.knobs is not None:
                # THE safe point: the previous cycle fully settled, the
                # next refill/dispatch not yet issued — staged knob
                # changes land here (re-dispatch-boundary knobs stage
                # onto the engine and complete inside its next step)
                self.knobs.apply()
            self.pool.run_cycle()
            box["cycles"] += 1
            if self.cycle_dt:
                clock.advance(self.cycle_dt)  # FakeClock only
            if box["cycles"] >= max_cycles:
                box["exhausted"] = True
            if self.knob_policy is not None and self.loop is None:
                self.knob_policy.evaluate()
            # the hand-rolled loop checks its stop predicate after the
            # tick when one is due; otherwise right here
            if tick_event is None or clock.now() < tick_event.due:
                check_stop()

        def control_tick() -> None:
            self.tick_index += 1
            try:
                box["state"] = self.loop.tick(box["state"])
            except ControllerCrash:
                box["state"] = self._crash_restart(clock)
            else:
                self.loop.ticks += 1
                self.ticks += 1
                trajectory.append(self.pool.replicas)
                if self.crash_plan is not None and \
                        self.crash_plan.boundary_crash(self.tick_index - 1):
                    # tick-boundary kill: journal line AND snapshot
                    # landed; the restart must be seamless
                    box["state"] = self._crash_restart(clock)
            if self.knob_policy is not None:
                # forecast/policy outputs actuate engine knobs: the
                # decision rides the control tick, the change lands at
                # the next between-cycles safe point
                self.knob_policy.evaluate()
            check_stop()

        if self.loop is not None:
            box["state"] = self.loop.initial_policy_state()
            tick_event = sched.every(
                "control-tick", self.loop.config.poll_interval,
                control_tick, priority=PRIORITY_CONTROL, anchor="after",
            )
        # period 0 + anchor "after": the cycle event is always due —
        # back-to-back cycles, with the cycle body itself advancing
        # cycle_dt of virtual time, exactly like the hand-rolled loop
        cycle_event = sched.every(
            "fleet-cycle", 0.0, fleet_cycle,
            priority=PRIORITY_CYCLE, anchor="after",
        )
        try:
            sched.run()
        finally:
            sched.cancel(cycle_event)
            if tick_event is not None:
                sched.cancel(tick_event)
        return {
            "cycles": box["cycles"],
            "ticks": self.ticks,
            "processed": self.pool.processed,
            "replica_trajectory": trajectory,
            "final_replicas": self.pool.replicas,
            "crashes": self.crashes,
            "restarts": self.restarts,
        }
