"""sched/ — one event-driven scheduler for both control planes, plus
the engine-knob actuation seam (ISSUE 15 / ROADMAP item 1).

- :mod:`.scheduler` — the priority-ordered event queue over one clock,
  and :func:`~.scheduler.drive_loop` (``ControlLoop.run`` as a
  registered event, byte-identical);
- :mod:`.fleet` — :class:`~.fleet.ScheduledFleetDriver`, the
  ``FleetDriver`` interleave as registered events with a between-cycle
  knob safe point;
- :mod:`.knobs` — :class:`~.knobs.KnobActuator` (journaled,
  snapshotted, gauge-exported live knob changes) and the reactive
  :class:`~.knobs.ReactiveKnobPolicy`.
"""

from .knobs import (  # noqa: F401
    ALL_KNOBS,
    CLI_KNOB_NAMES,
    KNOB_DECODE_BLOCK,
    KNOB_PREFIX_POOL,
    KNOB_SHARDS,
    KNOB_SLOT_LIMIT,
    KNOB_SPECULATIVE,
    KnobActuator,
    KnobError,
    LearnedKnobPolicy,
    ReactiveKnobPolicy,
    parse_knob_names,
)
from .scheduler import (  # noqa: F401
    EventScheduler,
    PRIORITY_CONTROL,
    PRIORITY_CYCLE,
    PRIORITY_KNOB,
    PRIORITY_TIMER,
    ScheduledEvent,
    drive_loop,
)

__all__ = [
    "ALL_KNOBS",
    "CLI_KNOB_NAMES",
    "EventScheduler",
    "KNOB_DECODE_BLOCK",
    "KNOB_PREFIX_POOL",
    "KNOB_SHARDS",
    "KNOB_SLOT_LIMIT",
    "KNOB_SPECULATIVE",
    "KnobActuator",
    "KnobError",
    "LearnedKnobPolicy",
    "PRIORITY_CONTROL",
    "PRIORITY_CYCLE",
    "PRIORITY_KNOB",
    "PRIORITY_TIMER",
    "ReactiveKnobPolicy",
    "ScheduledEvent",
    "ScheduledFleetDriver",
    "drive_loop",
    "parse_knob_names",
]


def __getattr__(name):
    # ScheduledFleetDriver pulls in fleet/ (and through it core.durable);
    # lazy so `from ..sched import EventScheduler` stays featherweight
    if name == "ScheduledFleetDriver":
        from .fleet import ScheduledFleetDriver

        return ScheduledFleetDriver
    raise AttributeError(name)
