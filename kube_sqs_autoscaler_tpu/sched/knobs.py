"""KnobActuator: policy outputs actuate engine knobs, live.

The :class:`~..core.types.Scaler` seam lets the control plane actuate
ONE integer — replica count.  Everything else that sets the fleet's
operating point (decode block size, per-shard admission width, shard
count, speculative round overlap, prefix-pool residency) was frozen at
construction: changing any of them meant a redeploy, even though the
engine can absorb each one as an O(1) host action at the right instant
(BLITZSCALE's reconfiguration argument, PAPERS.md).  This module is the
knob seam next to the Scaler seam: a :class:`KnobActuator` stages knob
changes and applies them **between engine cycles at safe points**, with
every change journaled (its own ``knob`` journal line kind),
snapshotted (a :class:`~..core.durable.DurableStateStore` provider, so
a restarted worker resumes its actuated operating point), exported as
``engine_knob{knob=...}`` gauges, and traced (``knob-*`` instants in
their own Chrome-trace category).

The knobs, and where each one is safe:

=============== =====================================================
``decode_block`` at the **re-dispatch boundary**: the engine stages the
                new size and completes the swap inside its next step —
                one cycle skips the dispatch-ahead so the in-flight
                block settles at the old size, then the next block
                dispatches at the new size (the compiled scan is
                shape-polymorphic in the key operand, so a new size is
                one cached retrace, not a rebuild).
``slot_limit``  between cycles: a pure host-side admission cap (per
                shard on the sharded plane).  Rows already above the
                limit finish — drain semantics, never a kill.
``shards``      between cycles: the existing drain/retire machinery —
                mask flips through
                :meth:`~..workloads.shard_plane.ShardedBatcher.
                set_shard_active`, or the supervising
                :class:`~..fleet.sharded.ShardedWorkerPool`'s scale
                path when one owns the plane (quarantine bookkeeping
                stays consistent).
``speculative`` between rounds.  On the fused spec engine: toggles the
                provably-safe second-round overlap (dispatch-ahead of
                draft-and-verify rounds).  On the decode plane
                (:class:`~..planes.engine.DecodePlaneBatcher`): flips
                draft-and-verify itself via the drain-to-plain path —
                in-flight rows finish in their admitted mode, new
                admissions land in the new one.  On a
                :class:`~..planes.pool.DisaggregatedPool` target the
                knob routes to the decode-plane worker.
``prefix_pool`` between cycles: moves the pool's residency ceiling
                within its allocated arena (shrink evicts LRU-cold
                entries; the ``>= per-shard slots`` floor that makes
                same-batch eviction corruption impossible still holds).
``plane_ratio`` between cycles: the disaggregated pool's prefill-plane
                replica count, walked through the pool's own Scaler
                state machine (spawn/drain, clamps respected).  At
                fixed decode shards this IS the prefill:decode ratio —
                the knob the two-plane economics tune.
=============== =====================================================

Arming is validated at CONSTRUCTION (the CLI turns these into startup
usage errors): the speculative knob without a draft engine — or with
beam slots — is rejected before anything serves, as is the shards knob
on an unsharded plane or the prefix-pool knob without a pool.
"""

from __future__ import annotations

import logging
from collections import deque
from dataclasses import dataclass, field

log = logging.getLogger(__name__)

KNOB_DECODE_BLOCK = "decode_block"
KNOB_SLOT_LIMIT = "slot_limit"
KNOB_SHARDS = "shards"
KNOB_SPECULATIVE = "speculative"
KNOB_PREFIX_POOL = "prefix_pool"
KNOB_PLANE_RATIO = "plane_ratio"

#: Every knob the actuator knows, in apply order (stable, test-pinned).
ALL_KNOBS = (
    KNOB_DECODE_BLOCK,
    KNOB_SLOT_LIMIT,
    KNOB_SHARDS,
    KNOB_SPECULATIVE,
    KNOB_PREFIX_POOL,
    KNOB_PLANE_RATIO,
)

#: CLI spelling (``--knobs decode-block,slot-limit,...``) -> knob name.
CLI_KNOB_NAMES = {name.replace("_", "-"): name for name in ALL_KNOBS}


class KnobError(ValueError):
    """A knob request the engine cannot honor (bad name, bad value, or
    an engine built without that knob's machinery)."""


def parse_knob_names(csv: str) -> tuple[str, ...]:
    """``--knobs`` CSV -> canonical knob names, order preserved,
    duplicates rejected (a duplicate is a typo, not an emphasis)."""
    names: list[str] = []
    for raw in csv.split(","):
        raw = raw.strip()
        if not raw:
            continue
        knob = CLI_KNOB_NAMES.get(raw, raw)
        if knob not in ALL_KNOBS:
            raise KnobError(
                f"unknown knob {raw!r} (choose from "
                f"{', '.join(sorted(CLI_KNOB_NAMES))})"
            )
        if knob in names:
            raise KnobError(f"knob {raw!r} listed twice")
        names.append(knob)
    if not names:
        raise KnobError("--knobs is empty")
    return tuple(names)


@dataclass(frozen=True)
class KnobEvent:
    """One applied knob change, timestamped on the actuator's clock —
    shaped like a :class:`~..fleet.pool.FleetEvent` so
    :func:`~..obs.trace.instant_trace_events` exports it (``knob-*``
    names land in their own ``"knob"`` trace category)."""

    name: str  # "knob-set"
    t: float
    args: dict = field(default_factory=dict)


class KnobActuator:
    """Stages and applies engine-knob changes at safe points.

    ``target`` is the worker whose engine the knobs drive — a
    :class:`~..workloads.continuous.ContinuousWorker` (or fleet
    subclass) — or a pool of them: a
    :class:`~..fleet.sharded.ShardedWorkerPool` (its one worker) or a
    :class:`~..fleet.pool.WorkerPool` (every serving/draining member;
    the shared ``ServiceConfig`` is updated too so replicas spawned
    AFTER a change construct at the actuated value and still adopt the
    donor's programs).

    ``journal`` (a :class:`~..obs.journal.TickJournal`) records one
    ``knob`` line per applied change; ``metrics`` (a
    :class:`~..obs.prometheus.WorkloadMetrics`) carries the
    ``engine_knob{knob=...}`` gauges; both optional.  The actuator is a
    :class:`~..core.durable.StateProvider`: its snapshot section is the
    actuated operating point, re-applied at the first safe point after
    a restart.
    """

    def __init__(
        self,
        target,
        *,
        armed=ALL_KNOBS,
        clock=None,
        journal=None,
        metrics=None,
    ) -> None:
        from ..core.clock import SystemClock

        self._target = target
        self.armed = tuple(armed)
        for knob in self.armed:
            if knob not in ALL_KNOBS:
                raise KnobError(f"unknown knob {knob!r}")
        self.clock = clock or SystemClock()
        self.journal = journal
        self.metrics = metrics
        self._staged: dict[str, object] = {}
        # every value this actuator has APPLIED, by knob — the reconcile
        # pass re-asserts these onto workers that drift (a replica
        # spawned after a slot_limit/prefix_pool change constructs at
        # the default; decode_block propagates through the shared
        # ServiceConfig, the host-side knobs need this)
        self._actuated: dict[str, object] = {}
        self.changes_total = 0
        self.changes: list[dict] = []
        self.events: deque[KnobEvent] = deque(maxlen=1024)
        # arm-time validation: a knob the engine cannot drive is a
        # construction error, never a mid-cycle traceback
        worker = self._primary()
        batcher = worker.batcher
        if KNOB_DECODE_BLOCK in self.armed and \
                not getattr(batcher, "_block_engine", False):
            raise KnobError(
                "the decode_block knob needs the block/gang decode "
                "engine (construct with decode_block > 1, or the "
                "sharded plane)"
            )
        if KNOB_SHARDS in self.armed and \
                not hasattr(batcher, "set_shard_active"):
            raise KnobError(
                "the shards knob needs the sharded serving plane "
                "(--shards)"
            )
        if KNOB_SPECULATIVE in self.armed:
            spec = self._spec_workers()
            spec_batcher = (spec[0] if spec else worker).batcher
            if getattr(spec_batcher, "beams", 1) > 1:
                raise KnobError(
                    "the speculative knob does not apply to beam slots"
                )
            if not (getattr(spec_batcher, "draft_layers", 0)
                    or getattr(spec_batcher, "spec_layers", 0)):
                raise KnobError(
                    "the speculative knob needs the draft-and-verify "
                    "engine (--speculative-draft-layers)"
                )
        if KNOB_PREFIX_POOL in self.armed and batcher.prefix_pool is None:
            raise KnobError(
                "the prefix_pool knob needs a prefix pool "
                "(--prefix-pool with tenancy)"
            )
        if KNOB_PLANE_RATIO in self.armed and self._disagg_pool() is None:
            raise KnobError(
                "the plane_ratio knob needs a disaggregated pool "
                "(planes.DisaggregatedPool)"
            )
        self.refresh_gauges()

    # -- targets ---------------------------------------------------------

    def _workers(self) -> list:
        """The live workers every applied change fans out to."""
        target = self._target
        if hasattr(target, "batcher"):  # a bare worker
            return [target]
        if hasattr(target, "members"):  # WorkerPool of replicas
            return [
                r.worker for r in target.members
                if r.state in ("serving", "draining")
            ]
        return [target.worker]  # ShardedWorkerPool

    def _primary(self):
        workers = self._workers()
        if not workers:
            raise KnobError("the knob target has no live workers")
        return workers[0]

    def retarget(self, target) -> None:
        """Point the actuator at a fresh target (a controller restart
        replaces the pool; the actuator must actuate the LIVE plane,
        not the abandoned pre-crash one).  Staged changes survive and
        apply to the new target at the next safe point;
        :class:`~.fleet.ScheduledFleetDriver` calls this from its
        crash-restart path."""
        self._target = target
        self.refresh_gauges()

    def _multi_replica(self) -> bool:
        return hasattr(self._target, "members")

    def _disagg_pool(self):
        """The DisaggregatedPool under actuation, when the target IS
        one (the plane_ratio knob's state machine; the speculative
        knob's route to the decode-plane worker)."""
        target = self._target
        if hasattr(target, "decode_pool"):
            return target
        return None

    def _spec_workers(self) -> list:
        """The workers whose engine owns the speculative knob: the one
        decode-plane worker on a disaggregated pool (prefill replicas
        run the plain insert and have no drafting surface), every live
        worker otherwise."""
        pool = self._disagg_pool()
        if pool is not None:
            return [pool.decode]
        return self._workers()

    def _shard_pool(self):
        """The ShardedWorkerPool supervising the plane, when the target
        IS one — the shards knob must go through its state machine so
        quarantine/drain bookkeeping stays consistent."""
        target = self._target
        if hasattr(target, "shard_states"):
            return target
        return None

    # -- staging + application -------------------------------------------

    def set(self, knob: str, value) -> bool:
        """Stage one knob change; applied at the next safe point
        (:meth:`apply`, wired between cycles by the scheduler).
        Returns True when the request stages a change, False when it
        is already the live value.  Raises :class:`KnobError` on an
        unarmed knob or an invalid value — validation happens HERE, at
        request time, never mid-cycle."""
        if knob not in self.armed:
            raise KnobError(f"knob {knob!r} is not armed ({self.armed})")
        value = self._validate(knob, value)
        if value == self._read(knob) and knob not in self._staged:
            return False
        self._staged[knob] = value
        return True

    def apply(self) -> list[dict]:
        """Apply every staged change — called between engine cycles
        (the scheduler's safe point).  Returns the changes applied.

        With NO live workers (a whole-fleet outage between kill and the
        loop's respawn), staged changes are kept for the next safe
        point instead of raising — knob actuation must never be the
        thing that kills a recovering fleet."""
        workers = self._workers()
        if workers:
            self._reconcile(workers)
        if not self._staged:
            return []
        if not workers:
            return []  # every replica dead: retry once the loop respawns
        staged, self._staged = self._staged, {}
        applied: list[dict] = []
        for knob in ALL_KNOBS:  # stable order, test-pinned
            if knob not in staged:
                continue
            value = staged[knob]
            previous = self._read(knob)
            if value == previous:
                continue
            self._apply_one(knob, value)
            self._actuated[knob] = value
            change = {
                "knob": knob,
                "value": value,
                "previous": previous,
                "t": self.clock.now(),
            }
            self.changes_total += 1
            self.changes.append(change)
            applied.append(change)
            self.events.append(
                KnobEvent("knob-set", change["t"], {
                    "knob": knob, "value": value, "previous": previous,
                })
            )
            if self.journal is not None:
                try:
                    self.journal.append_event("knob", change)
                except Exception:  # instrumentation must never kill serving
                    log.exception("knob journal write failed")
            log.info("Knob %s: %s -> %s", knob, previous, value)
        if applied:
            self.refresh_gauges()
        return applied

    @property
    def pending(self) -> dict:
        """Staged-but-unapplied knob requests (read-only view)."""
        return dict(self._staged)

    #: host-side per-worker knobs the reconcile pass re-asserts onto
    #: drifted workers (decode_block propagates through the shared
    #: ServiceConfig at spawn; shards is pool-level, never per-worker)
    _PER_WORKER_KNOBS = (
        KNOB_SLOT_LIMIT, KNOB_SPECULATIVE, KNOB_PREFIX_POOL,
    )

    def _reconcile(self, workers) -> None:
        """Re-assert every APPLIED knob value onto workers whose live
        value drifted — a replica spawned after a change constructs at
        the engine defaults, and without this the fleet runs
        split-brain until the knob next moves to a different value.
        Cheap host reads per cycle; writes only on actual drift."""
        for knob in self._PER_WORKER_KNOBS:
            if knob not in self._actuated:
                continue
            value = self._actuated[knob]
            targets = (
                self._spec_workers() if knob == KNOB_SPECULATIVE
                else workers
            )
            for worker in targets:
                try:
                    if self._read(knob, worker) != value:
                        self._apply_to_worker(knob, value, worker)
                except Exception:  # reconcile must never kill serving
                    log.exception(
                        "knob %s reconcile failed on a worker", knob
                    )

    # -- per-knob validation / read / write ------------------------------

    def _validate(self, knob: str, value):
        batcher = self._primary().batcher
        if knob == KNOB_DECODE_BLOCK:
            value = int(value)
            if value < 1:
                raise KnobError(f"decode_block must be >= 1, got {value}")
            if value < 2 and self._multi_replica():
                # a replica spawned at decode_block 1 builds the
                # single-step engine and cannot adopt a block donor —
                # the fleet-shared knob stays on the block engine
                raise KnobError(
                    "decode_block < 2 on a replica fleet would make "
                    "future spawns unable to adopt the donor engine"
                )
            return value
        if knob == KNOB_SLOT_LIMIT:
            value = int(value)
            per_shard = getattr(batcher, "shard_slots", len(batcher.slots))
            if not 0 <= value <= per_shard:
                raise KnobError(
                    f"slot_limit must be in [0, {per_shard}] "
                    f"(0 = unlimited), got {value}"
                )
            return value
        if knob == KNOB_SHARDS:
            value = int(value)
            shards = batcher.shards
            pool = self._shard_pool()
            low = pool.min if pool is not None else 1
            high = pool.max if pool is not None else shards
            if not low <= value <= high:
                raise KnobError(
                    f"shards must be in [{low}, {high}] (allocated "
                    f"{shards}), got {value}"
                )
            return value
        if knob == KNOB_SPECULATIVE:
            return bool(value)
        if knob == KNOB_PLANE_RATIO:
            value = int(value)
            pool = self._disagg_pool()
            if not pool.min <= value <= pool.max:
                raise KnobError(
                    f"plane_ratio (prefill replicas) must be in "
                    f"[{pool.min}, {pool.max}], got {value}"
                )
            return value
        if knob == KNOB_PREFIX_POOL:
            value = int(value)
            pool = batcher.prefix_pool
            floor = getattr(batcher, "shard_slots", len(batcher.slots))
            if not floor <= value <= pool.entries:
                # below the per-shard slot count one admission batch
                # could LRU-evict an entry another row of the SAME
                # batched insert still references (PR 10's corruption
                # invariant); above the allocation needs a realloc —
                # that is a redeploy, not a knob
                raise KnobError(
                    f"prefix_pool capacity must be in [{floor}, "
                    f"{pool.entries}] (per-shard slots .. allocated "
                    f"arena), got {value}"
                )
            return value
        raise KnobError(f"unknown knob {knob!r}")

    def _read(self, knob: str, worker=None):
        if knob == KNOB_PLANE_RATIO:
            return self._disagg_pool().replicas
        if knob == KNOB_SPECULATIVE and worker is None:
            spec = self._spec_workers()
            if spec:
                worker = spec[0]
        batcher = (worker or self._primary()).batcher
        if knob == KNOB_DECODE_BLOCK:
            pending = getattr(batcher, "_pending_decode_block", None)
            return pending if pending is not None else batcher.decode_block
        if knob == KNOB_SLOT_LIMIT:
            return batcher.slot_limit or 0
        if knob == KNOB_SHARDS:
            pool = self._shard_pool()
            if pool is not None:
                return pool.replicas
            return sum(1 for a in batcher.shard_admitting if a)
        if knob == KNOB_SPECULATIVE:
            if getattr(batcher, "spec_layers", 0):
                # the decode plane: the knob IS draft-and-verify (the
                # drain-to-plain mode switch), not the round overlap
                return bool(batcher.draft_enabled)
            return bool(batcher.spec_overlap)
        if knob == KNOB_PREFIX_POOL:
            return batcher.prefix_pool.capacity
        raise KnobError(f"unknown knob {knob!r}")

    def _apply_one(self, knob: str, value) -> None:
        workers = self._workers()
        if knob == KNOB_DECODE_BLOCK:
            for worker in workers:
                worker.batcher.request_decode_block(value)
                config = getattr(worker, "config", None)
                if config is not None and hasattr(config, "decode_block"):
                    # replicas spawned after this change construct at
                    # the actuated size and adopt the donor's programs
                    config.decode_block = value
            return
        if knob == KNOB_SLOT_LIMIT:
            for worker in workers:
                self._apply_to_worker(knob, value, worker)
            return
        if knob == KNOB_SHARDS:
            pool = self._shard_pool()
            if pool is not None:
                # through the Scaler-seam state machine (resurrect/
                # activate/drain ordering and quarantine exclusion all
                # preserved) — but at step size 1: the autoscaler's
                # scale_up_pods/scale_down_pods step toward the clamps,
                # and a multi-pod step can orbit the requested value
                # forever instead of landing on it
                saved = pool.scale_up_pods, pool.scale_down_pods
                pool.scale_up_pods = pool.scale_down_pods = 1
                try:
                    for _ in range(pool.shards):
                        if pool.replicas < value:
                            pool.scale_up()
                        elif pool.replicas > value:
                            pool.scale_down()
                        else:
                            break
                finally:
                    pool.scale_up_pods, pool.scale_down_pods = saved
                if pool.replicas != value:
                    log.warning(
                        "shards knob: pool settled at %d, wanted %d "
                        "(clamps/quarantine bound the reachable range)",
                        pool.replicas, value,
                    )
                return
            batcher = self._primary().batcher
            admitting = [
                s for s in range(batcher.shards)
                if batcher.shard_admitting[s]
            ]
            if len(admitting) < value:
                for s in range(batcher.shards):
                    if len(admitting) >= value:
                        break
                    if not batcher.shard_admitting[s]:
                        batcher.set_shard_active(s, True)
                        admitting.append(s)
            else:
                # drain newest-index first, mirroring the pool's order
                for s in reversed(admitting):
                    if len(admitting) <= value:
                        break
                    batcher.set_shard_active(s, False)
                    admitting.remove(s)
            return
        if knob == KNOB_PLANE_RATIO:
            # through the disaggregated pool's Scaler state machine
            # (spawn/drain ordering, clamps) at step size 1, exactly
            # like the shards knob's pool path
            pool = self._disagg_pool()
            saved = pool.scale_up_pods, pool.scale_down_pods
            pool.scale_up_pods = pool.scale_down_pods = 1
            try:
                for _ in range(pool.max):
                    if pool.replicas < value:
                        pool.scale_up()
                    elif pool.replicas > value:
                        pool.scale_down()
                    else:
                        break
            finally:
                pool.scale_up_pods, pool.scale_down_pods = saved
            if pool.replicas != value:
                log.warning(
                    "plane_ratio knob: pool settled at %d prefill "
                    "replicas, wanted %d",
                    pool.replicas, value,
                )
            return
        if knob in (KNOB_SPECULATIVE, KNOB_PREFIX_POOL):
            targets = (
                self._spec_workers() if knob == KNOB_SPECULATIVE
                else workers
            )
            for worker in targets:
                self._apply_to_worker(knob, value, worker)
            return
        raise KnobError(f"unknown knob {knob!r}")

    def _apply_to_worker(self, knob: str, value, worker) -> None:
        """One per-worker host knob write (the unit the reconcile pass
        re-asserts)."""
        if knob == KNOB_SLOT_LIMIT:
            worker.batcher.set_slot_limit(value or None)
        elif knob == KNOB_SPECULATIVE:
            worker.batcher.set_speculative(value)
        elif knob == KNOB_PREFIX_POOL:
            worker.batcher.prefix_pool.set_capacity(value)
        else:
            raise KnobError(f"knob {knob!r} is not per-worker")

    # -- observability ---------------------------------------------------

    def current(self) -> dict:
        """Live value of every armed knob (the gauges' source)."""
        return {knob: self._read(knob) for knob in self.armed}

    def refresh_gauges(self) -> None:
        if self.metrics is None:
            return
        try:
            values = self.current()
        except KnobError:
            return  # no live workers to read: keep the last export
        for knob, value in values.items():
            self.metrics.set_gauge(
                "engine_knob", float(int(value)),
                "Live engine-knob operating point, actuated between "
                "cycles at safe points (decode_block size, slot_limit "
                "admission cap (0 = unlimited), serving shards, "
                "speculative round overlap (0/1), prefix-pool "
                "residency capacity).",
                labels=(("knob", knob),),
            )
        self.metrics.set_gauge(
            "engine_knob_changes_total", self.changes_total,
            "Knob changes applied over the actuator's lifetime.",
            kind="counter",
        )

    def trace_events(self, time_origin: float | None = None) -> list[dict]:
        """Applied knob changes as Chrome-trace instants (their own
        ``knob`` category; merge via ``to_chrome_trace(...,
        extra_events=...)``)."""
        from ..obs.trace import instant_trace_events

        return instant_trace_events(self.events, time_origin)

    # -- durable-state surface (core/durable.py StateProvider) -----------

    def export_state(self) -> dict:
        values = self.current()
        # a pending staged value is the operator's latest intent — the
        # snapshot carries it so a crash between stage and apply still
        # lands the change after restart
        values.update(self._staged)
        return {"records": len(values), "knobs": values,
                "changes_total": self.changes_total}

    def import_state(
        self, state: dict, *, rebase: float = 0.0,
        now: float | None = None, max_age_s: float = 0.0,
    ) -> int:
        """Re-stage the snapshot's operating point; it re-applies at
        the first safe point after restart.  Knob values are not
        clocked, so rebase/age do not apply."""
        del rebase, now, max_age_s
        knobs = state.get("knobs")
        if not isinstance(knobs, dict):
            return 0
        recovered = 0
        for knob, value in knobs.items():
            if knob not in self.armed:
                continue
            try:
                self.set(knob, value)
            except KnobError as err:
                log.warning("dropping restored knob %s=%r (%s)",
                            knob, value, err)
                continue
            recovered += 1
        self.changes_total = int(state.get("changes_total", 0) or 0)
        return recovered


class ReactiveKnobPolicy:
    """A minimal depth-thresholded knob policy: deep backlog -> big
    decode block (amortize host overhead), shallow interactive traffic
    -> small block (tight TTFT floor).  Hysteresis between the two
    thresholds holds the current value.

    This is the knobs bench's adaptive driver and the CLI's default
    when ``--knobs`` arms ``decode-block``; the learned policy's knob
    head (:mod:`..learn.network`) plugs into the same
    ``actuator.set(...)`` seam.
    """

    def __init__(self, actuator: KnobActuator, depth_fn, *,
                 high: int, low: int, block_high: int = 16,
                 block_low: int = 2) -> None:
        if low > high:
            raise KnobError(f"need low ({low}) <= high ({high})")
        if block_low < 1 or block_high < block_low:
            raise KnobError(
                f"need 1 <= block_low ({block_low}) <= block_high "
                f"({block_high})"
            )
        self.actuator = actuator
        self.depth_fn = depth_fn
        self.high = high
        self.low = low
        self.block_high = block_high
        self.block_low = block_low
        self.decisions = 0

    def evaluate(self) -> None:
        """One decision: read the backlog signal, stage the block.
        ANY failure — a metric-read error from ``depth_fn`` (the
        control loop rides those out via its stale-hold path; the knob
        decision must too), or a whole-fleet outage with no live
        workers to validate against — skips the decision, never
        propagates: knob policy is advisory and must not be the thing
        that kills a serving fleet."""
        self.decisions += 1
        try:
            depth = self.depth_fn()
            if depth >= self.high:
                self.actuator.set(KNOB_DECODE_BLOCK, self.block_high)
            elif depth <= self.low:
                self.actuator.set(KNOB_DECODE_BLOCK, self.block_low)
        except Exception as err:
            log.warning("knob decision skipped: %s", err)


class LearnedKnobPolicy:
    """The learned knob head on the knob seam: a knob-headed
    :class:`~..learn.policy.LearnedPolicy` emits a ladder delta in
    {-1, 0, +1} each tick (``last_knob_delta``, from
    :func:`~..learn.network.knob_delta_decision`); this adapter walks
    the decode-block ladder by it and stages the result on the
    actuator.  The same ``evaluate()`` surface as
    :class:`ReactiveKnobPolicy`, so the scheduler wires either without
    caring which brain decided."""

    def __init__(self, actuator: KnobActuator, policy, *,
                 ladder: tuple[int, ...] = (1, 2, 4, 8, 16, 32)) -> None:
        if not ladder or list(ladder) != sorted(set(ladder)):
            raise KnobError(
                f"ladder must be strictly increasing, got {ladder}"
            )
        self.actuator = actuator
        self.policy = policy
        self.ladder = tuple(int(b) for b in ladder)
        self.decisions = 0

    def rebind(self, policy) -> None:
        """Point the adapter at a fresh brain — a controller restart
        rebuilds the LearnedPolicy; reading the dead one's frozen delta
        forever would walk the ladder to an extreme.
        :class:`~.fleet.ScheduledFleetDriver` calls this from its
        crash-restart path."""
        self.policy = policy

    def evaluate(self) -> None:
        self.decisions += 1
        try:
            # CONSUME the delta (take_knob_delta clears it): the
            # adapter runs every tick, including metric-failure ticks
            # where the policy made no new decision — a stale delta
            # must step the ladder at most once
            take = getattr(self.policy, "take_knob_delta", None)
            delta = (
                take() if take is not None
                else getattr(self.policy, "last_knob_delta", None)
            )
            if not delta:  # None (no tick yet / headless) or hold
                return
            current = self.actuator._read(KNOB_DECODE_BLOCK)
            # the highest rung <= current anchors the walk (a knob
            # value set off-ladder still steps sanely)
            idx = 0
            for i, rung in enumerate(self.ladder):
                if rung <= current:
                    idx = i
            idx = max(0, min(len(self.ladder) - 1, idx + int(delta)))
            self.actuator.set(KNOB_DECODE_BLOCK, self.ladder[idx])
        except Exception as err:
            # whole-fleet outage / broken brain: skip the decision,
            # never kill the fleet (same contract as ReactiveKnobPolicy)
            log.warning("knob decision skipped: %s", err)
