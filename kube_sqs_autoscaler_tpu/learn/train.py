"""Antithetic evolution strategies inside the compiled twin.

Why ES and not a policy gradient: the episode reward is dominated by a
``max`` over ticks (worst backlog) threaded through ``argmax`` actions,
integer replica steps, and threshold gates — a landscape of plateaus and
cliffs where per-step gradients are zero almost everywhere and the
simulator's bit-exactness engineering (f64 world, f32 features) leaves
no room for smoothing tricks.  ES needs only episode *scores*, which the
compiled scan already produces thousands-at-a-time in one device call
(:mod:`.rollout`); with a ~200-parameter network the search space is
small enough that a few dozen antithetic generations converge in
seconds.  (KIS-S reaches the same conclusion shape against a far slower
Kubernetes inference simulator — the simulator's speed, not the
estimator's elegance, is the binding constraint.)

Everything is seeded: perturbations come from one
``numpy.random.default_rng(seed)`` stream and the evaluation worlds are
deterministic, so a (seed, scenarios, config) triple always trains the
identical checkpoint — the bench artifact is reproducible, and a
reviewer can re-derive the published weights.

Reward: a weighted sum of the battery's own axes, each normalized by a
*reference scale* measured from the reactive policy on the same worlds —
max depth (dominant, matching the sweep's lexicographic priority), churn
(replica changes), time-over-SLO, and a small replica-seconds term so
"buy max_pods forever" is not a free lunch and the learned policy lands
on a defensible point of the depth-vs-cost front rather than a corner.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Sequence

import numpy as np

from .checkpoint import PolicyCheckpoint
from .network import DEFAULT_HIDDEN, init_params, param_count
from .rollout import (
    DEFAULT_HISTORY,
    DEFAULT_MIN_SAMPLES,
    evaluate_population,
    learned_config,
)


@dataclass(frozen=True)
class ESConfig:
    """One training run's knobs (defaults sized for the default battery)."""

    population: int = 32  # perturbations per generation (even: antithetic)
    generations: int = 40
    sigma: float = 0.1  # perturbation scale
    lr: float = 0.2  # step size on the rank-shaped gradient estimate
    seed: int = 0
    hidden: int = DEFAULT_HIDDEN
    history: int = DEFAULT_HISTORY
    min_samples: int = DEFAULT_MIN_SAMPLES
    # reward weights over reference-normalized axes
    depth_weight: float = 1.0
    churn_weight: float = 0.2
    slo_weight: float = 0.2
    replica_weight: float = 0.05

    def __post_init__(self):
        if self.population < 2 or self.population % 2:
            raise ValueError(
                f"population must be an even number >= 2, got"
                f" {self.population}"
            )
        if self.generations < 1:
            raise ValueError("generations must be >= 1")
        if self.sigma <= 0 or self.lr <= 0:
            raise ValueError("sigma and lr must be > 0")


@dataclass(frozen=True)
class RewardScales:
    """Per-scenario normalizers measured from the reactive reference."""

    depth: np.ndarray  # [E] reactive max depth (>= 1)
    duration: np.ndarray  # [E] episode seconds
    ticks: np.ndarray  # [E] episode ticks
    replica_budget: np.ndarray  # [E] max_pods * duration (replica-seconds)


def reference_scales(scenarios: Sequence[Any]) -> RewardScales:
    """Reactive-baseline scales for ``scenarios`` (one compiled batch)."""
    from ..sim.compiled import run_episodes_grouped
    from ..sim.evaluate import run_episode  # noqa: F401  (doc pointer)
    from ..sim.simulator import SimConfig

    configs = [
        SimConfig(
            arrival_rate=s.arrival,
            service_rate_per_replica=s.service_rate_per_replica,
            duration=s.duration,
            initial_replicas=s.initial_replicas,
            min_pods=s.min_pods,
            max_pods=s.max_pods,
            loop=s.loop,
        )
        for s in scenarios
    ]
    episodes = run_episodes_grouped(configs)
    return RewardScales(
        depth=np.maximum(
            np.asarray([e.result.max_depth for e in episodes]), 1.0
        ),
        duration=np.asarray([s.duration for s in scenarios], np.float64),
        ticks=np.asarray(
            [max(e.result.ticks, 1) for e in episodes], np.float64
        ),
        replica_budget=np.asarray(
            [max(s.max_pods * s.duration, 1.0) for s in scenarios],
            np.float64,
        ),
    )


def reward_vector(
    summaries: dict[str, np.ndarray],
    scales: RewardScales,
    config: ESConfig,
) -> np.ndarray:
    """``[P, E]`` episode summaries → ``[P]`` mean rewards (higher=better)."""
    cost = (
        config.depth_weight * summaries["max_depth"] / scales.depth
        + config.churn_weight * summaries["replica_changes"] / scales.ticks
        + config.slo_weight * summaries["time_over_slo"] / scales.duration
        + config.replica_weight
        * summaries["replica_seconds"]
        / scales.replica_budget
    )
    return -np.mean(cost, axis=1)


def _rank_utilities(rewards: np.ndarray) -> np.ndarray:
    """Centered rank shaping in ``[-0.5, 0.5]`` — scale-free fitness, so
    one catastrophic episode cannot dominate a generation's update."""
    n = rewards.shape[0]
    ranks = np.empty(n, dtype=np.float64)
    ranks[np.argsort(rewards)] = np.arange(n, dtype=np.float64)
    if n == 1:
        return np.zeros(1)
    return ranks / (n - 1) - 0.5


@dataclass
class TrainResult:
    """A finished run: the best checkpoint + the generation trail."""

    checkpoint: PolicyCheckpoint
    stats: list[dict] = field(default_factory=list)

    @property
    def reward_curve(self) -> list[float]:
        return [row["center_reward"] for row in self.stats]


def train(
    scenarios: Sequence[Any],
    config: ESConfig = ESConfig(),
    progress: Callable[[dict], None] | None = None,
) -> TrainResult:
    """Train a policy network on ``scenarios``; returns the best center.

    Each generation evaluates ``population`` antithetic perturbations
    *plus the current center* (one extra row in the same device call, so
    the selection signal costs nothing), updates the center along the
    rank-shaped ES gradient, and keeps the best center seen by training
    reward — held-out scenarios are deliberately NOT consulted here, so
    the bench's held-out gate stays an honest out-of-sample test.
    """
    scenarios = list(scenarios)
    scales = reference_scales(scenarios)
    dim = param_count(config.hidden)
    half = config.population // 2
    rng = np.random.default_rng(config.seed)
    center = init_params(config.seed, config.hidden).astype(np.float64)
    best_theta = center.copy()
    best_reward = -np.inf
    stats: list[dict] = []
    for generation in range(config.generations):
        eps = rng.standard_normal((half, dim))
        thetas = np.concatenate(
            [
                center[None, :] + config.sigma * eps,
                center[None, :] - config.sigma * eps,
                center[None, :],
            ]
        ).astype(np.float32)
        summaries = evaluate_population(
            thetas,
            scenarios,
            hidden=config.hidden,
            history=config.history,
            min_samples=config.min_samples,
        )
        rewards = reward_vector(summaries, scales, config)
        pop_rewards, center_reward = rewards[:-1], float(rewards[-1])
        utilities = _rank_utilities(pop_rewards)
        grad = (utilities[:half] - utilities[half:]) @ eps
        center = center + (config.lr / (config.population * config.sigma)) * grad
        if center_reward > best_reward:
            best_reward = center_reward
            best_theta = np.asarray(thetas[-1], np.float64)
        row = {
            "generation": generation,
            "center_reward": center_reward,
            "population_mean": float(np.mean(pop_rewards)),
            "population_best": float(np.max(pop_rewards)),
            "best_so_far": best_reward,
        }
        stats.append(row)
        if progress is not None:
            progress(row)
    # the final center is usually best, but the explicit argmax makes the
    # returned artifact invariant to a last-generation regression
    checkpoint = PolicyCheckpoint(
        theta=np.asarray(best_theta, np.float32),
        hidden=config.hidden,
        meta={
            "trainer": "antithetic-es",
            "twin": "fluid",
            "reward_units": "depth+churn+slo+replica-seconds (fluid)",
            "config": asdict(config),
            "forecast_history": config.history,
            "min_samples": config.min_samples,
            "scenarios": [s.name for s in scenarios],
            "best_train_reward": best_reward,
            "reward_curve": [
                round(row["center_reward"], 6) for row in stats
            ],
        },
    )
    return TrainResult(checkpoint=checkpoint, stats=stats)


__all__ = [
    "ESConfig",
    "RewardScales",
    "TrainResult",
    "learned_config",
    "reference_scales",
    "reward_vector",
    "train",
]
