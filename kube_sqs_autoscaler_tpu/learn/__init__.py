"""Learned autoscaling policy: train in the compiled twin, deploy on the loop.

ROADMAP item 2 (KIS-S, arxiv 2507.07932): the vmapped ``lax.scan``
simulator (:mod:`..sim.compiled`) is an RL environment in all but name —
thousands of (population × scenario) episodes evaluate in one device
call, so a seeded evolution-strategies search over a tiny policy network
costs seconds, not cluster-hours.  The package is four seams:

- :mod:`.network` — the decision arithmetic, exactly once: features over
  the shared ring-buffer history (``ewma_level``/``lstsq_slope``, the
  forecasters' own pure functions), a one-hidden-layer MLP, and the
  up/hold/down action expressed as an *effective queue depth* through
  the untouched reference gates;
- :mod:`.checkpoint` — the deployable artifact: versioned JSON with
  load-time validation and a content hash that names exactly which
  weights ran (journal meta, ``build_info{policy}``);
- :mod:`.policy` — :class:`LearnedPolicy`, the
  :class:`~..core.types.DepthPolicy` for the real ``ControlLoop``,
  bit-identical to the compiled scan (``verify_fidelity``-gated);
- :mod:`.rollout` / :mod:`.train` — population evaluation fused into the
  compiled episode scan, and the antithetic-sampled ES loop on top;
- :mod:`.serving` — the same ES loop inside the token-level SERVING
  twin (:mod:`..sim.twin`), reward in tokens/s + time-over-TTFT-SLO +
  shard churn; checkpoints carry their training-twin kind and every
  deployment seam enforces it at load time (``require_twin``).

Exports resolve lazily: :mod:`..sim.compiled` imports :mod:`.network`
(the shared decision function) while :mod:`.rollout` imports
``sim.compiled`` (the shared episode scan) — eager re-exports here would
make that mutual dependency a cycle.
"""

from __future__ import annotations

from typing import Any

_EXPORTS = {
    "LearnedPolicy": ("policy", "LearnedPolicy"),
    "PolicyCheckpoint": ("checkpoint", "PolicyCheckpoint"),
    "CheckpointError": ("checkpoint", "CheckpointError"),
    "SCHEMA_VERSION": ("checkpoint", "SCHEMA_VERSION"),
    "load_checkpoint": ("checkpoint", "load_checkpoint"),
    "save_checkpoint": ("checkpoint", "save_checkpoint"),
    "checkpoint_hash": ("checkpoint", "checkpoint_hash"),
    "init_params": ("network", "init_params"),
    "param_count": ("network", "param_count"),
    "evaluate_population": ("rollout", "evaluate_population"),
    "learned_config": ("rollout", "learned_config"),
    "ESConfig": ("train", "ESConfig"),
    "train": ("train", "train"),
    # the serving-twin trainer (reward in tokens/s + TTFT-SLO + churn;
    # see sim/twin and ARCHITECTURE.md "The serving twin")
    "ServingESConfig": ("serving", "ServingESConfig"),
    "train_serving": ("serving", "train_serving"),
    "evaluate_population_serving": (
        "rollout", "evaluate_population_serving",
    ),
    "checkpoint_twin": ("checkpoint", "checkpoint_twin"),
    "require_twin": ("checkpoint", "require_twin"),
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str) -> Any:
    try:
        module_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    module = importlib.import_module(f".{module_name}", __name__)
    value = getattr(module, attr)
    globals()[name] = value  # cache: subsequent lookups skip __getattr__
    return value


def __dir__() -> list[str]:
    return sorted(set(globals()) | set(_EXPORTS))
