"""Versioned JSON checkpoints for the learned autoscaling policy.

A checkpoint is the *deployable artifact*: the flat float32 parameter
vector plus the network geometry and feature-schema pins that give those
numbers meaning.  JSON on purpose — a policy small enough to train in the
compiled twin (~200 floats) does not need a binary format, and an
operator diffing two checkpoints in a code review should see numbers,
not bytes.

Round-trip exactness: parameters are float32, and every float32 is
exactly representable as a JSON double, so ``save → load`` reproduces
``theta`` bit-for-bit — :class:`~.policy.LearnedPolicy` decisions are
bitwise identical across the round trip (pinned in tests).

Validation happens at **load time, before the loop starts**: a missing
file, corrupt JSON, wrong kind, unknown schema version (including a
*future* one), geometry/parameter-count mismatch, or non-finite weights
all raise :class:`CheckpointError` with an operator-grade message — never
a mid-tick traceback.

``checkpoint_hash`` fingerprints the decision-relevant content
(canonical JSON of kind/schema/geometry/theta plus the effective
feature-window pins — free-form provenance metadata excluded).  The CLI
stamps it into ``build_info{policy}`` and the flight-journal meta so a
replayed incident knows exactly which weights ran.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from .network import DEFAULT_HIDDEN, N_FEATURES, param_count

#: Current checkpoint schema.  Version 1: flat one-hidden-layer MLP over
#: the 8-feature vector (``network.policy_features``'s declaration
#: order).  Bump ONLY with a loader for every prior version.
SCHEMA_VERSION = 1

#: ``kind`` discriminator: rejects feeding some other JSON artifact
#: (a BENCH file, a journal header) to ``--policy-checkpoint``.
KIND = "kube-sqs-autoscaler-tpu/learned-policy"


class CheckpointError(ValueError):
    """A checkpoint failed validation (missing/corrupt/incompatible)."""


#: Training-twin kinds.  A checkpoint's weights only mean something
#: relative to the world that trained them: a FLUID-twin policy reads
#: queue-depth features scaled by the reference gate thresholds and
#: actuates replica counts; a SERVING-twin policy reads the serving
#: plane's request-queue depth and actuates shard counts with reward in
#: tokens/s + time-over-TTFT-SLO + churn.  Deploying one where the
#: other is expected is silent garbage, so the kind is stamped into
#: checkpoint meta and enforced at LOAD time by every deployment seam
#: (``LearnedPolicy``, replay, the fluid rollout, the serving twin).
TWIN_FLUID = "fluid"
TWIN_SERVING = "serving"
TWIN_KINDS = (TWIN_FLUID, TWIN_SERVING)


def checkpoint_twin(checkpoint: "PolicyCheckpoint") -> str:
    """The twin kind a checkpoint was trained in.

    Missing stamp = ``fluid``: every checkpoint before the serving twin
    existed was trained in the fluid twin, so the default keeps old
    artifacts deployable without rewriting them.
    """
    return str(checkpoint.meta.get("twin", TWIN_FLUID))


def require_twin(
    checkpoint: "PolicyCheckpoint", expected: str, seam: str
) -> None:
    """Reject a checkpoint whose training twin doesn't match the
    deployment seam — a load-time :class:`CheckpointError` naming both
    sides, never silent garbage mid-tick."""
    kind = checkpoint_twin(checkpoint)
    if kind != expected:
        raise CheckpointError(
            f"checkpoint {checkpoint.hash} was trained in the {kind!r}"
            f" twin; {seam} deploys {expected!r}-twin checkpoints —"
            f" retrain for this seam (reward units:"
            f" {checkpoint.meta.get('reward_units', 'unrecorded')!r})"
        )


def require_no_knob_head(
    checkpoint: "PolicyCheckpoint", seam: str
) -> None:
    """Reject a knob-headed checkpoint at a seam that assumes the
    headless theta layout.  The knob head is GEOMETRY — it widens the
    output layer, changing what the flat theta means — so the compiled
    fluid/serving rollouts (which vmap homogeneous-geometry
    populations) must refuse it loudly until the knob-reward training
    loop lands (ROADMAP item 3), never mis-slice it silently."""
    if getattr(checkpoint, "knob_head", False):
        raise CheckpointError(
            f"checkpoint {checkpoint.hash} carries a knob-action head;"
            f" {seam} trains/evaluates the headless up/hold/down layout"
            " — deploy the knob head through LearnedPolicy +"
            " sched.KnobActuator instead"
        )


#: History-ring capacity the learned features run on, train and deploy.
#: Smaller than the forecasters' 128 default on purpose: the feature set
#: (EWMA level, 12-sample trend) saturates well below 64 samples, and
#: the scan's per-tick history roll is O(capacity) — at 64 a training
#: generation is ~2× cheaper.  Stamped into checkpoint meta by the
#: trainer so deployment rebuilds the identical feature window.
DEFAULT_HISTORY = 64

#: Reactive warm-up ticks before the network decides (same contract as
#: ``PredictivePolicy``); stamped into checkpoint meta alongside history.
DEFAULT_MIN_SAMPLES = 3


def checkpoint_history(checkpoint: PolicyCheckpoint) -> tuple[int, int]:
    """(history capacity, min_samples) a checkpoint was trained with.

    Read from checkpoint meta (the trainer stamps both); the defaults
    cover hand-built checkpoints.  Deployment MUST use these — the EWMA
    level feature sees the whole ring, so a different capacity silently
    changes what the trained weights mean.
    """
    return (
        int(checkpoint.meta.get("forecast_history", DEFAULT_HISTORY)),
        int(checkpoint.meta.get("min_samples", DEFAULT_MIN_SAMPLES)),
    )


@dataclass(frozen=True)
class PolicyCheckpoint:
    """One loaded (or freshly trained) policy checkpoint."""

    theta: np.ndarray  # float32, param_count(hidden, knob_head)
    hidden: int = DEFAULT_HIDDEN
    #: provenance: trainer config, seeds, scenario names, reward weights —
    #: free-form, excluded from the content hash
    meta: dict[str, Any] = field(default_factory=dict)
    #: the grown action space (ISSUE 15): three extra knob-delta output
    #: logits.  Geometry, not provenance — validated against the
    #: parameter count below and keyed into the content hash.
    knob_head: bool = False

    def __post_init__(self):
        theta = np.ascontiguousarray(self.theta, dtype=np.float32)
        object.__setattr__(self, "theta", theta)
        if self.hidden < 1:
            raise CheckpointError(f"hidden must be >= 1, got {self.hidden}")
        if not isinstance(self.knob_head, bool):
            raise CheckpointError(
                f"knob_head must be a bool, got {self.knob_head!r}"
            )
        expected = param_count(self.hidden, self.knob_head)
        if theta.shape != (expected,):
            raise CheckpointError(
                f"theta has {theta.size} parameters; hidden={self.hidden}"
                f" with knob_head={self.knob_head} needs exactly"
                f" {expected}"
            )
        if not np.isfinite(theta).all():
            raise CheckpointError("theta contains non-finite values")
        # The feature-window pins are decision-relevant (read by
        # checkpoint_history and hashed): a malformed value must be a
        # CheckpointError here, not an int() traceback mid-deployment.
        if not isinstance(self.meta, dict):
            raise CheckpointError(f"meta must be a mapping, got {self.meta!r}")
        for key, floor in (("forecast_history", 1), ("min_samples", 0)):
            if key in self.meta:
                value = self.meta[key]
                if (
                    not isinstance(value, int)
                    or isinstance(value, bool)
                    or value < floor
                ):
                    raise CheckpointError(
                        f"meta[{key!r}] must be an integer >= {floor},"
                        f" got {value!r}"
                    )
        if "twin" in self.meta and self.meta["twin"] not in TWIN_KINDS:
            raise CheckpointError(
                f"meta['twin'] must be one of {TWIN_KINDS}, got"
                f" {self.meta['twin']!r}"
            )

    @property
    def hash(self) -> str:
        """Content fingerprint (first 12 hex of sha256; see module doc)."""
        return checkpoint_hash(self)

    def to_dict(self) -> dict[str, Any]:
        data = {
            "kind": KIND,
            "schema": SCHEMA_VERSION,
            "hidden": int(self.hidden),
            "n_features": N_FEATURES,
            "theta": [float(w) for w in self.theta],
            "meta": self.meta,
        }
        if self.knob_head:
            # absent for headless checkpoints so pre-knob files (and
            # their byte-for-byte round trips) are untouched
            data["knob_head"] = True
        return data


def checkpoint_hash(checkpoint: PolicyCheckpoint) -> str:
    """sha256 over the canonical decision-relevant JSON, truncated to 12
    hex chars (enough to discriminate checkpoints in a label value).

    float32 -> Python float -> ``json.dumps`` is exact (every float32 is
    a representable double with an exact shortest-repr), so two
    checkpoints hash equal iff their decisions are bitwise equal.  The
    effective feature-window pins (``checkpoint_history``) are hashed
    too: the EWMA level feature sees the whole ring, so identical theta
    over a different window is a *different policy* — free-form
    provenance in ``meta`` stays excluded.
    """
    history, min_samples = checkpoint_history(checkpoint)
    content = {
        "kind": KIND,
        "schema": SCHEMA_VERSION,
        "hidden": int(checkpoint.hidden),
        "n_features": N_FEATURES,
        "forecast_history": history,
        "min_samples": min_samples,
        "theta": [float(w) for w in checkpoint.theta],
    }
    # the training twin is decision-relevant (it names the feature
    # semantics and actuation units); keyed in only for non-fluid kinds
    # so every pre-serving-twin checkpoint keeps its published hash
    if checkpoint_twin(checkpoint) != TWIN_FLUID:
        content["twin"] = checkpoint_twin(checkpoint)
    if checkpoint.knob_head:
        # geometry is decision-relevant; keyed in only when armed so
        # every headless checkpoint keeps its published hash
        content["knob_head"] = True
    canonical = json.dumps(content, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canonical.encode("utf-8")).hexdigest()[:12]


def save_checkpoint(path: str, checkpoint: PolicyCheckpoint) -> str:
    """Write ``checkpoint`` as versioned JSON; returns its content hash.

    Write-then-rename so a crash mid-write never leaves a torn file where
    a valid checkpoint used to be (the loader would reject the torn tail,
    but the *previous* weights would be gone).
    """
    data = checkpoint.to_dict()
    tmp = f"{path}.tmp"
    with open(tmp, "w") as fh:
        json.dump(data, fh, indent=1)
        fh.write("\n")
    os.replace(tmp, path)
    return checkpoint_hash(checkpoint)


def load_checkpoint(path: str) -> PolicyCheckpoint:
    """Load + validate a checkpoint; :class:`CheckpointError` on any defect.

    Every message names the path and the specific failure — this runs at
    CLI startup, where "reject before the loop starts" is the contract
    (a corrupt checkpoint must never surface as a mid-tick policy error
    silently falling back to reactive).
    """
    try:
        with open(path) as fh:
            data = json.load(fh)
    except FileNotFoundError:
        raise CheckpointError(f"checkpoint {path!r} does not exist") from None
    except (OSError, json.JSONDecodeError) as err:
        raise CheckpointError(
            f"checkpoint {path!r} is unreadable or corrupt: {err}"
        ) from None
    if not isinstance(data, dict):
        raise CheckpointError(
            f"checkpoint {path!r} is not a JSON object"
        )
    if data.get("kind") != KIND:
        raise CheckpointError(
            f"checkpoint {path!r} has kind {data.get('kind')!r}, expected"
            f" {KIND!r} (is this really a learned-policy checkpoint?)"
        )
    schema = data.get("schema")
    if not isinstance(schema, int) or schema < 1:
        raise CheckpointError(
            f"checkpoint {path!r} has invalid schema version {schema!r}"
        )
    if schema > SCHEMA_VERSION:
        raise CheckpointError(
            f"checkpoint {path!r} has schema version {schema}, newer than"
            f" this build supports ({SCHEMA_VERSION}) — upgrade the"
            " controller or re-train the policy"
        )
    if data.get("n_features") != N_FEATURES:
        raise CheckpointError(
            f"checkpoint {path!r} was trained on"
            f" {data.get('n_features')!r} features; this build's feature"
            f" vector has {N_FEATURES} — re-train"
        )
    hidden = data.get("hidden")
    if not isinstance(hidden, int):
        raise CheckpointError(
            f"checkpoint {path!r} has invalid hidden size {hidden!r}"
        )
    theta = data.get("theta")
    if not isinstance(theta, list) or not all(
        isinstance(w, (int, float)) and math.isfinite(w) for w in theta
    ):
        raise CheckpointError(
            f"checkpoint {path!r} theta must be a list of finite numbers"
        )
    meta = data.get("meta") or {}
    if not isinstance(meta, dict):
        raise CheckpointError(f"checkpoint {path!r} meta must be an object")
    knob_head = data.get("knob_head", False)
    if not isinstance(knob_head, bool):
        raise CheckpointError(
            f"checkpoint {path!r} knob_head must be a bool, got"
            f" {knob_head!r}"
        )
    try:
        return PolicyCheckpoint(
            theta=np.asarray(theta, dtype=np.float32),
            hidden=hidden,
            meta=meta,
            knob_head=knob_head,
        )
    except CheckpointError as err:
        raise CheckpointError(f"checkpoint {path!r}: {err}") from None
