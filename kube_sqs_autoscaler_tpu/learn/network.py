"""The learned autoscaling policy's decision arithmetic, exactly once.

A tiny MLP maps a fixed feature vector — observed depth, ring-buffer
history features (EWMA level + fitted trend, the same pure functions the
forecasters run), tracked replicas, and the two cooldown states — to one
of three actions: *scale down*, *hold*, *scale up*.  The action is then
expressed through the existing :class:`~..core.types.DepthPolicy` seam as
an **effective queue depth**: ``scale_up_messages`` to trip the up gate,
``scale_down_messages`` to trip the down gate, or a value strictly
between the thresholds to trip neither (:func:`hold_depth`).  Everything
downstream — inclusive thresholds, strictly-After cooldowns, the
up-cooling ``continue``, bound clamps — is the untouched reference gate
logic, so the network can decide *when* to scale but can never violate a
cooldown or a bound (the same guarantee :class:`~..forecast.predictive.
PredictivePolicy` rides).

**The fidelity contract.**  Training evaluates thousands of episodes
inside the compiled ``lax.scan`` simulator (:mod:`..sim.compiled`);
deployment runs one decision per tick on the real ``ControlLoop``.  Both
paths call :func:`learned_decision` — the same float32 ops in the same
order, the ``ewma_level``/``lstsq_slope`` pure functions shared with the
forecasters — so ``verify_fidelity`` can hold the learned policy to the
same 0-divergence gate every hand-written policy passes.  Keep this
module free of anything the scan cannot trace (no Python branches on
traced values, no host state).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..forecast.forecasters import ewma_level, lstsq_slope

#: Action codes — the argmax index over the network's output logits.
ACTION_DOWN, ACTION_HOLD, ACTION_UP = 0, 1, 2
N_ACTIONS = 3

#: Knob-head action codes (ISSUE 15: the action space grows past
#: up/hold/down).  A checkpoint saved with ``knob_head=True`` carries
#: three extra output logits whose argmax is a knob DELTA — step the
#: armed engine knob (decode block, by default) down / hold / up one
#: rung of its ladder.  The knob head shares the input layer and hidden
#: features with the replica head, so what the network learned about
#: backlog shape serves both actuators.
KNOB_ACTION_DOWN, KNOB_ACTION_HOLD, KNOB_ACTION_UP = 0, 1, 2
N_KNOB_ACTIONS = 3

#: The fixed feature vector (all float32, assembled in
#: :func:`policy_features` — keep the docstring there in sync).
N_FEATURES = 8

#: Checkpoint/network geometry default.
DEFAULT_HIDDEN = 16

#: History-feature smoothing parameters.  Deliberately the live
#: forecasters' defaults (``EwmaForecaster.alpha``,
#: ``LeastSquaresForecaster.window``) but pinned HERE as independent
#: constants: the features are part of the checkpoint schema — retuning a
#: forecaster default must never silently change what a saved policy's
#: weights mean.
FEATURE_ALPHA = 0.3
FEATURE_WINDOW = 12


def n_outputs(knob_head: bool = False) -> int:
    """Output-layer width: 3 replica actions, +3 knob actions with the
    knob head armed."""
    return N_ACTIONS + (N_KNOB_ACTIONS if knob_head else 0)


def param_count(hidden: int = DEFAULT_HIDDEN,
                knob_head: bool = False) -> int:
    """Flat parameter vector length for one hidden layer of ``hidden``."""
    outputs = n_outputs(knob_head)
    return hidden * N_FEATURES + hidden + outputs * hidden + outputs


def init_params(seed: int, hidden: int = DEFAULT_HIDDEN,
                knob_head: bool = False) -> np.ndarray:
    """Seeded float32 init (scaled normal) — deterministic per seed."""
    rng = np.random.default_rng(seed)
    theta = rng.standard_normal(
        param_count(hidden, knob_head)
    ).astype(np.float32)
    # modest fan-in scaling keeps tanh out of saturation at init
    theta[: hidden * N_FEATURES] *= np.float32(0.5 / np.sqrt(N_FEATURES))
    theta[hidden * N_FEATURES :] *= np.float32(0.5 / np.sqrt(hidden))
    return theta


def hold_depth(scale_up_messages: int, scale_down_messages: int) -> int:
    """An effective depth that trips *neither* gate.

    Strictly between the inclusive thresholds when the config leaves room
    (the reference default 10 < 55 < 100); with touching/inverted
    thresholds there is no neutral value, so the deterministic fallback
    ``down + 1`` applies (both the live policy and the compiled scan use
    this same function, so they agree even then).
    """
    up, down = int(scale_up_messages), int(scale_down_messages)
    hold = (up + down) // 2
    if not down < hold < up:
        hold = down + 1
    return hold


def policy_logits(theta: jax.Array, features: jax.Array, hidden: int,
                  knob_head: bool = False) -> jax.Array:
    """MLP forward: ``features (F,) -> logits (3,)`` (or ``(6,)`` with
    the knob head — replica actions first, knob actions after);
    ``theta`` flat.

    The matvecs are written as broadcast-multiply + ``jnp.sum`` — the
    exact reduction pattern :func:`~..forecast.forecasters.lstsq_forecast`
    already proves bit-stable between the live jitted path and the
    vmapped compiled scan — rather than ``jnp.dot``, whose lowering may
    differ between those contexts.  With ``knob_head`` the input/hidden
    layer layout is unchanged — only the output layer widens, replica
    rows first — so splicing a headless theta's output rows into a
    knob-headed layout computes IDENTICAL replica logits (pinned by
    test): growing the action space never silently changes what the
    replica head decides.
    """
    f = N_FEATURES
    outputs = n_outputs(knob_head)
    o = 0
    w1 = theta[o : o + hidden * f].reshape(hidden, f)
    o += hidden * f
    b1 = theta[o : o + hidden]
    o += hidden
    w2 = theta[o : o + outputs * hidden].reshape(outputs, hidden)
    o += outputs * hidden
    b2 = theta[o : o + outputs]
    h = jnp.tanh(jnp.sum(w1 * features[None, :], axis=1) + b1)
    return jnp.sum(w2 * h[None, :], axis=1) + b2


def policy_features(
    times32: jax.Array,
    depths32: jax.Array,
    n: jax.Array,
    observed: jax.Array,
    replicas: jax.Array,
    frac_up32: jax.Array,
    frac_down32: jax.Array,
    scale_up_messages: jax.Array,
    max_pods: jax.Array,
    poll32: jax.Array,
    alpha32: jax.Array,
    window: jax.Array,
) -> jax.Array:
    """The fixed ``(8,)`` float32 feature vector, in declaration order:

    0. observed depth / up threshold (how far through the gate band);
    1. EWMA depth level / up threshold (:func:`ewma_level`, the shared
       forecaster smoothing — history's recency-weighted baseline);
    2. fitted depth trend × poll interval / up threshold
       (:func:`lstsq_slope`: depth change per tick, sign carries
       ramp-vs-drain);
    3. replicas / max pods (how much actuation headroom remains);
    4. remaining up-cooldown fraction (1 = just fired, 0 = armed);
    5. remaining down-cooldown fraction;
    6. ``log1p(observed)/10`` (scale-free backlog magnitude — the
       normalized features saturate above the up threshold);
    7. constant 1 (lets ES shape pure biases through the input layer).

    ``times32`` must be centered on the newest sample
    (:func:`~..forecast.forecasters._center_times` semantics), exactly as
    the forecasters require.
    """
    obs32 = observed.astype(jnp.float32)
    up_scale = jnp.maximum(scale_up_messages, 1).astype(jnp.float32)
    pods_scale = jnp.maximum(max_pods, 1).astype(jnp.float32)
    level = ewma_level(depths32, n, alpha32)
    slope = lstsq_slope(times32, depths32, n, window)
    return jnp.stack(
        [
            obs32 / up_scale,
            level / up_scale,
            slope * poll32 / up_scale,
            replicas.astype(jnp.float32) / pods_scale,
            frac_up32,
            frac_down32,
            jnp.log1p(obs32) * jnp.float32(0.1),
            jnp.asarray(1.0, jnp.float32),
        ]
    )


def learned_decision(
    theta: jax.Array,
    times32: jax.Array,
    depths32: jax.Array,
    n: jax.Array,
    observed: jax.Array,
    replicas: jax.Array,
    frac_up32: jax.Array,
    frac_down32: jax.Array,
    scale_up_messages: jax.Array,
    scale_down_messages: jax.Array,
    hold: jax.Array,
    min_samples: jax.Array,
    max_pods: jax.Array,
    poll32: jax.Array,
    alpha32: jax.Array,
    window: jax.Array,
    *,
    hidden: int,
    knob_head: bool = False,
) -> jax.Array:
    """One tick's effective depth (int32) from history + state features.

    Below ``min_samples`` history observations the policy passes the
    observed depth through unchanged — the same reactive warm-up contract
    as :class:`~..forecast.predictive.PredictivePolicy`, so a fresh
    controller behaves exactly like the reference until it has signal.
    The result is clamped to ``>= 0`` (the loop clamps its side too, so
    the compiled scan must match).
    """
    features = policy_features(
        times32, depths32, n, observed, replicas, frac_up32, frac_down32,
        scale_up_messages, max_pods, poll32, alpha32, window,
    )
    logits = policy_logits(theta, features, hidden, knob_head)
    action = jnp.argmax(logits[:N_ACTIONS])
    decision = jnp.where(
        action == ACTION_UP,
        scale_up_messages,
        jnp.where(action == ACTION_DOWN, scale_down_messages, hold),
    )
    warmed = n >= min_samples
    return jnp.maximum(0, jnp.where(warmed, decision, observed)).astype(
        jnp.int32
    )


def knob_delta_decision(
    theta: jax.Array,
    times32: jax.Array,
    depths32: jax.Array,
    n: jax.Array,
    observed: jax.Array,
    replicas: jax.Array,
    frac_up32: jax.Array,
    frac_down32: jax.Array,
    scale_up_messages: jax.Array,
    min_samples: jax.Array,
    max_pods: jax.Array,
    poll32: jax.Array,
    alpha32: jax.Array,
    window: jax.Array,
    *,
    hidden: int,
) -> jax.Array:
    """The knob head's tick decision: a ladder DELTA in {-1, 0, +1}
    (int32) — step the armed engine knob down / hold / up.  Same
    feature vector, same warm-up contract as :func:`learned_decision`
    (below ``min_samples`` the knob holds — a fresh controller must
    not thrash the engine before it has signal).  Requires a
    ``knob_head=True`` theta layout."""
    features = policy_features(
        times32, depths32, n, observed, replicas, frac_up32, frac_down32,
        scale_up_messages, max_pods, poll32, alpha32, window,
    )
    logits = policy_logits(theta, features, hidden, knob_head=True)
    delta = (
        jnp.argmax(logits[N_ACTIONS : N_ACTIONS + N_KNOB_ACTIONS])
        .astype(jnp.int32) - 1
    )
    warmed = n >= min_samples
    return jnp.where(warmed, delta, 0).astype(jnp.int32)


def cooldown_fraction(last: float, cooldown: float, now: float) -> float:
    """Remaining-cooldown fraction in [0, 1], computed in float64.

    ``((last + cooldown) - now) / cooldown`` with the zero floors — the
    *host-side* twin of the expression the compiled scan evaluates in
    float64 under ``enable_x64`` (plain adds and one divide: IEEE-exact
    in both, so the float32 feature cast downstream sees identical
    values).  Kept outside the jitted decision function on purpose: the
    live forecasters jit at float32, where an epoch-sized ``now`` would
    lose the seconds that matter.
    """
    if cooldown <= 0:
        return 0.0
    remaining = (last + cooldown) - now
    if remaining <= 0:
        return 0.0
    return remaining / cooldown
