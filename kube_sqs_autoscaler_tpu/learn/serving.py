"""Antithetic ES inside the SERVING twin — reward in serving units.

The fluid trainer (:mod:`.train`) optimizes queue-depth cost because
that is all a fluid world can score.  The fleet is scored in tokens/s,
time-over-TTFT-SLO, and shard churn, so this trainer evaluates its
population inside the token-level serving twin
(:mod:`..sim.twin.compiled`) and rewards exactly those axes —
KIS-S's sim-trains-policy loop with the simulator finally speaking the
plant's units (ROADMAP item 2).

Estimator, seeding, and rank shaping are the fluid trainer's verbatim
(the landscape argument in :mod:`.train`'s docstring applies with the
same force: integer completions through threshold gates and argmax
actions have no usable gradients).  Only the world and the reward
changed.  The checkpoint artifact is stamped ``twin: "serving"`` with
its reward units, and every fluid deployment seam rejects it at load
time (:func:`~.checkpoint.require_twin`).
"""

from __future__ import annotations

from dataclasses import asdict, dataclass
from typing import Any, Callable, Sequence

import numpy as np

from .checkpoint import TWIN_SERVING, PolicyCheckpoint
from .network import DEFAULT_HIDDEN, init_params, param_count
from .rollout import evaluate_population_serving
from .train import TrainResult, _rank_utilities

#: Serving feature-history capacity: 16 control ticks covers the
#: EWMA/trend features at the twin's default 48-tick episodes; stamped
#: into checkpoint meta like the fluid DEFAULT_HISTORY.
SERVING_HISTORY = 16

REWARD_UNITS = "tokens/s - time-over-TTFT-SLO - shard-churn - shard-seconds"


@dataclass(frozen=True)
class ServingESConfig:
    """One serving training run's knobs."""

    population: int = 24
    generations: int = 30
    sigma: float = 0.1
    lr: float = 0.2
    seed: int = 0
    hidden: int = DEFAULT_HIDDEN
    history: int = SERVING_HISTORY
    min_samples: int = 2
    # reward weights over reference-normalized serving axes
    tokens_weight: float = 1.0
    slo_weight: float = 0.6
    churn_weight: float = 0.1
    shard_weight: float = 0.1

    def __post_init__(self):
        if self.population < 2 or self.population % 2:
            raise ValueError(
                f"population must be an even number >= 2, got"
                f" {self.population}"
            )
        if self.generations < 1:
            raise ValueError("generations must be >= 1")
        if self.sigma <= 0 or self.lr <= 0:
            raise ValueError("sigma and lr must be > 0")
        if self.history < 2:
            raise ValueError("history must be >= 2")


@dataclass(frozen=True)
class ServingScales:
    """Per-scenario normalizers from the reactive reference plane."""

    tokens: np.ndarray  # [E] reactive tokens (>= 1)
    duration: np.ndarray  # [E] episode seconds
    ticks: np.ndarray  # [E] control ticks
    shard_budget: np.ndarray  # [E] max_shards * duration


def serving_reference_scales(scenarios: Sequence[Any]) -> ServingScales:
    """Reactive-baseline scales (one grouped compiled batch)."""
    from ..sim.twin.compiled import TwinConfig, run_twin_grouped

    episodes = run_twin_grouped(
        [TwinConfig(scenario=s) for s in scenarios], trajectory=False
    )
    return ServingScales(
        tokens=np.maximum(
            np.asarray([e.summary["tokens"] for e in episodes], np.float64),
            1.0,
        ),
        duration=np.asarray(
            [s.duration_s for s in scenarios], np.float64
        ),
        ticks=np.asarray(
            [max(1, s.cycles // s.control_every) for s in scenarios],
            np.float64,
        ),
        shard_budget=np.asarray(
            [max(1.0, s.max_active * s.duration_s) for s in scenarios],
            np.float64,
        ),
    )


def serving_reward_vector(
    summaries: dict[str, np.ndarray],
    scales: ServingScales,
    config: ServingESConfig,
) -> np.ndarray:
    """``[P, E]`` serving summaries → ``[P]`` mean rewards (higher =
    better): normalized tokens minus SLO debt minus churn minus
    shard-seconds — the twin bench's lexicographic axes, scalarized for
    the estimator with cost terms keeping over-provisioning honest."""
    reward = (
        config.tokens_weight * summaries["tokens"] / scales.tokens
        - config.slo_weight * summaries["time_over_slo_s"] / scales.duration
        - config.churn_weight * summaries["shard_changes"] / scales.ticks
        - config.shard_weight
        * summaries["shard_seconds"]
        / scales.shard_budget
    )
    return np.mean(reward, axis=1)


def train_serving(
    scenarios: Sequence[Any],
    config: ServingESConfig = ServingESConfig(),
    progress: Callable[[dict], None] | None = None,
) -> TrainResult:
    """Train the policy network inside the serving twin; best center.

    Identical loop discipline to the fluid :func:`~.train.train`:
    antithetic pairs plus the current center per generation, centered-
    rank shaping, best-center-by-training-reward checkpointing, held-out
    worlds never consulted.
    """
    scenarios = list(scenarios)
    scales = serving_reference_scales(scenarios)
    dim = param_count(config.hidden)
    half = config.population // 2
    rng = np.random.default_rng(config.seed)
    center = init_params(config.seed, config.hidden).astype(np.float64)
    best_theta = center.copy()
    best_reward = -np.inf
    stats: list[dict] = []
    for generation in range(config.generations):
        eps = rng.standard_normal((half, dim))
        thetas = np.concatenate(
            [
                center[None, :] + config.sigma * eps,
                center[None, :] - config.sigma * eps,
                center[None, :],
            ]
        ).astype(np.float32)
        summaries = evaluate_population_serving(
            thetas,
            scenarios,
            hidden=config.hidden,
            history=config.history,
            min_samples=config.min_samples,
        )
        rewards = serving_reward_vector(summaries, scales, config)
        pop_rewards, center_reward = rewards[:-1], float(rewards[-1])
        utilities = _rank_utilities(pop_rewards)
        grad = (utilities[:half] - utilities[half:]) @ eps
        center = center + (
            config.lr / (config.population * config.sigma)
        ) * grad
        if center_reward > best_reward:
            best_reward = center_reward
            best_theta = np.asarray(thetas[-1], np.float64)
        row = {
            "generation": generation,
            "center_reward": center_reward,
            "population_mean": float(np.mean(pop_rewards)),
            "population_best": float(np.max(pop_rewards)),
            "best_so_far": best_reward,
        }
        stats.append(row)
        if progress is not None:
            progress(row)
    checkpoint = PolicyCheckpoint(
        theta=np.asarray(best_theta, np.float32),
        hidden=config.hidden,
        meta={
            "trainer": "antithetic-es-serving",
            "twin": TWIN_SERVING,
            "reward_units": REWARD_UNITS,
            "config": asdict(config),
            "forecast_history": config.history,
            "min_samples": config.min_samples,
            "scenarios": [s.name for s in scenarios],
            "best_train_reward": best_reward,
            "reward_curve": [
                round(row["center_reward"], 6) for row in stats
            ],
        },
    )
    return TrainResult(checkpoint=checkpoint, stats=stats)


__all__ = [
    "REWARD_UNITS",
    "SERVING_HISTORY",
    "ServingESConfig",
    "ServingScales",
    "serving_reference_scales",
    "serving_reward_vector",
    "train_serving",
]
