"""The learned policy on the real control loop.

:class:`LearnedPolicy` is a :class:`~..core.types.DepthPolicy` — exactly
the seam :class:`~..forecast.predictive.PredictivePolicy` rides — so the
loop code does not know the decision came from a network: the policy
returns an *effective queue depth* and the untouched reference gates
(inclusive thresholds, strictly-After cooldowns, the up-cooling
``continue``, success-only timestamp advancement) do the rest.  Whatever
the weights say, a learned episode can never violate a bound or a
cooldown the reactive episode respects.

The feature vector needs state the ``DepthPolicy`` call does not carry —
the replica count and the two cooldown stamps — so the policy also
implements :class:`~..core.events.TickObserver` and mirrors that state
from the per-tick record, the same arithmetic the gates and
``PodAutoScaler`` apply (``record.scaled``: gate FIRE + no actuation
error, boundary no-ops included).  Against the simulator this mirror is
exact, which is what lets :func:`~..sim.compiled.verify_fidelity` hold
the live policy to the compiled scan tick-for-tick.  On a live cluster
the replica count is the same *relative* trajectory replay reports for
live journals (the controller never reads the deployment's size; it
starts from ``initial_replicas`` and folds in its own actuations).

Decision arithmetic lives in :func:`~.network.learned_decision` — one
pure function shared verbatim with the compiled scan — wrapped here in
the same ``jax.jit``-at-float32 convention as the live forecasters.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax

from ..core.events import TickRecord
from ..core.policy import PolicyConfig
from ..forecast.forecasters import _center_times
from ..forecast.history import DepthHistory
from .checkpoint import PolicyCheckpoint
from .network import (
    FEATURE_ALPHA,
    FEATURE_WINDOW,
    cooldown_fraction,
    hold_depth,
    knob_delta_decision,
    learned_decision,
)

_learned_decision = partial(
    jax.jit, static_argnames=("hidden", "knob_head")
)(learned_decision)

_knob_delta_decision = partial(jax.jit, static_argnames=("hidden",))(
    knob_delta_decision
)


class LearnedPolicy:
    """Threshold the gates on a trained network's up/hold/down decision.

    One instance drives one episode (like ``PredictivePolicy``'s history,
    the mirrored cooldown/replica state is episode-local).  Wire it into
    ``ControlLoop(depth_policy=policy, observer=policy)`` — the observer
    hook feeds both the depth history and the replica/cooldown mirror.
    """

    def __init__(
        self,
        checkpoint: PolicyCheckpoint,
        *,
        policy: PolicyConfig,
        poll_interval: float,
        max_pods: int,
        min_pods: int = 1,
        scale_up_pods: int = 1,
        scale_down_pods: int = 1,
        initial_replicas: int = 1,
        history: DepthHistory | None = None,
        min_samples: int = 3,
    ) -> None:
        from .checkpoint import TWIN_FLUID, require_twin

        # the deployment seam check: a serving-twin checkpoint's weights
        # mean shard counts and serving-plane features — thresholding
        # the fluid replica gates on them is silent garbage, so it must
        # be a load-time error here, not a bad episode later
        require_twin(checkpoint, TWIN_FLUID, "LearnedPolicy (ControlLoop)")
        self.checkpoint = checkpoint
        self.policy = policy
        self.poll_interval = float(poll_interval)
        self.max_pods = int(max_pods)
        self.min_pods = int(min_pods)
        self.scale_up_pods = int(scale_up_pods)
        self.scale_down_pods = int(scale_down_pods)
        self.history = history if history is not None else DepthHistory()
        # reactive warm-up below min_samples, same floor as PredictivePolicy
        self.min_samples = max(2, int(min_samples))
        self.name = f"learned@{checkpoint.hash}"
        self._theta = checkpoint.theta
        self._hidden = int(checkpoint.hidden)
        # the grown action space (ISSUE 15): a knob-headed checkpoint's
        # replica decision is computed the same way (first three
        # logits); its knob head additionally emits a ladder delta per
        # tick, read by sched.knobs.LearnedKnobPolicy off
        # `last_knob_delta` and actuated through the KnobActuator
        self._knob_head = bool(getattr(checkpoint, "knob_head", False))
        self.last_knob_delta: int | None = None
        self._hold = hold_depth(
            policy.scale_up_messages, policy.scale_down_messages
        )
        self.replicas = int(initial_replicas)
        # Cooldown mirror: the loop's initial_state(now) sets both stamps
        # at run() start, one poll interval BEFORE the first tick (sleep
        # first, then poll) — lazily initialized at the first call since
        # the policy cannot see the loop's start instant.
        self._last_up: float | None = None
        self._last_down: float | None = None
        #: scoreboard for the observability layer (same field the
        #: predictive policy exports: the depth the gates thresholded)
        self.last_prediction: int | None = None

    def effective_messages(self, now: float, num_messages: int) -> int:
        if self._last_up is None:
            self._last_up = now - self.poll_interval
            self._last_down = now - self.poll_interval
        times, depths, n = self.history.with_sample(now, float(num_messages))
        frac_up = cooldown_fraction(
            self._last_up, self.policy.scale_up_cooldown, now
        )
        frac_down = cooldown_fraction(
            self._last_down, self.policy.scale_down_cooldown, now
        )
        # f64 centering before the float32 jit boundary, exactly
        # the forecasters' convention (_center_times docstring)
        times32 = np.asarray(_center_times(times, n))
        depths32 = np.asarray(depths)
        decision = int(
            _learned_decision(
                self._theta,
                times32,
                depths32,
                n,
                int(num_messages),
                self.replicas,
                np.float32(frac_up),
                np.float32(frac_down),
                self.policy.scale_up_messages,
                self.policy.scale_down_messages,
                self._hold,
                self.min_samples,
                self.max_pods,
                np.float32(self.poll_interval),
                np.float32(FEATURE_ALPHA),
                FEATURE_WINDOW,
                hidden=self._hidden,
                knob_head=self._knob_head,
            )
        )
        if self._knob_head:
            # same features, the other head: one extra tiny jitted call
            # per tick, paid only by knob-headed checkpoints
            self.last_knob_delta = int(
                _knob_delta_decision(
                    self._theta,
                    times32,
                    depths32,
                    n,
                    int(num_messages),
                    self.replicas,
                    np.float32(frac_up),
                    np.float32(frac_down),
                    self.policy.scale_up_messages,
                    self.min_samples,
                    self.max_pods,
                    np.float32(self.poll_interval),
                    np.float32(FEATURE_ALPHA),
                    FEATURE_WINDOW,
                    hidden=self._hidden,
                )
            )
        self.last_prediction = decision
        return decision

    def take_knob_delta(self) -> int | None:
        """Consume this tick's knob-head delta (None once taken, and on
        ticks where no decision ran).  Consumption semantics on
        purpose: the knob adapter evaluates every tick, including
        metric-failure ticks where :meth:`effective_messages` never
        runs — re-applying a stale delta would walk the ladder
        repeatedly on ONE decision."""
        delta, self.last_knob_delta = self.last_knob_delta, None
        return delta

    # ------------------------------------------------------------------
    # Durable-state surface (core/durable.py StateProvider): the mirror
    # IS control state — a restart used to reset it to initial_replicas
    # and lazy cooldown stamps, feeding the network replica/cooldown
    # features from a world that no longer exists.
    # ------------------------------------------------------------------

    def export_state(self) -> dict:
        state: dict = {
            "records": 1,
            "replicas": self.replicas,
            "last_up": self._last_up,
            "last_down": self._last_down,
            "checkpoint_hash": self.checkpoint.hash,
            "history": self.history.export_state(),
        }
        state["records"] += state["history"].get("records", 0)
        return state

    def import_state(
        self, state: dict, *, rebase: float = 0.0,
        now: float | None = None, max_age_s: float = 0.0,
    ) -> int:
        """Restore mirror + feature history.  A snapshot written under
        DIFFERENT weights is refused whole: the mirror's meaning (and
        the feature window) belongs to the checkpoint that ran."""
        if state.get("checkpoint_hash") not in (None, self.checkpoint.hash):
            return 0
        recovered = 0
        replicas = state.get("replicas")
        if replicas is not None:
            self.replicas = max(
                self.min_pods, min(self.max_pods, int(replicas))
            )
            recovered += 1
        for attr, key in (("_last_up", "last_up"), ("_last_down", "last_down")):
            stamp = state.get(key)
            if stamp is not None:
                setattr(self, attr, float(stamp) + rebase)
        history = state.get("history")
        if isinstance(history, dict):
            recovered += self.history.import_state(
                history, rebase=rebase, now=now, max_age_s=max_age_s
            )
        return recovered

    def reconcile_observed(self, replicas: int) -> None:
        """kube-controller style: the OBSERVED replica count outranks the
        remembered trajectory (the world may have scaled, crashed, or
        been edited while this controller was down)."""
        self.replicas = max(self.min_pods, min(self.max_pods, int(replicas)))

    def on_tick(self, record: TickRecord) -> None:
        """Mirror the world the features describe, from the tick record.

        History: successful fresh observations only (stale-held depths
        are an old observation at a new timestamp — same exclusion as
        ``DepthHistory.on_tick``).  Replicas/cooldowns: every successful
        actuation, stale ticks included (the gates really fired there),
        with ``PodAutoScaler``'s exact clamp arithmetic and the
        reference's success-only stamp advancement.
        """
        self.history.on_tick(record)
        if record.scaled("up"):
            self.replicas = min(
                self.max_pods, self.replicas + self.scale_up_pods
            )
            self._last_up = record.start
        if record.scaled("down"):
            self.replicas = max(
                self.min_pods, self.replicas - self.scale_down_pods
            )
            self._last_down = record.start
