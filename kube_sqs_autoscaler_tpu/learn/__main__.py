"""``make learn-demo``: the learned-policy lifecycle on a FakeClock.

A deterministic walk through the whole subsystem in a few seconds:
a tiny-population ES training run in the compiled twin, checkpoint
save → load with bitwise round-trip, the compiled-vs-Python fidelity
gate on the trained network, and a real ``ControlLoop`` episode on a
``FakeClock`` driven by the loaded checkpoint — exit 0 when every
milestone is observed, exit 2 on any missing one (the same contract as
``make chaos-demo`` / ``make fleet-demo``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from dataclasses import replace
from typing import Sequence

import numpy as np


def _demo_scenarios():
    """Two short worlds (60 ticks each): one ramp, one burst."""
    from ..sim.evaluate import default_battery

    base = {s.name: s for s in default_battery()}
    return [
        replace(base["ramp"], duration=300.0),
        replace(base["burst"], duration=300.0),
    ]


def _check_demo() -> tuple[dict, list[str]]:
    problems: list[str] = []

    def expect(condition: bool, message: str) -> None:
        if not condition:
            problems.append(message)

    from ..sim.compiled import verify_fidelity
    from ..sim.simulator import Simulation
    from .checkpoint import load_checkpoint, save_checkpoint
    from .rollout import learned_config
    from .train import ESConfig, train

    scenarios = _demo_scenarios()

    # 1. train: a tiny population for a few seeded generations
    result = train(
        scenarios, ESConfig(population=8, generations=6, seed=7)
    )
    curve = result.reward_curve
    expect(
        all(np.isfinite(curve)), f"non-finite training rewards: {curve}"
    )
    # a tiny population is noisy generation-to-generation, so the
    # milestones are the ones train() actually guarantees: some
    # generation beat the seed, and the returned checkpoint is the best
    # center seen (never worse than anything on the curve)
    expect(
        max(curve) > curve[0],
        f"no generation improved on the seed policy: {curve}",
    )
    best = float(result.checkpoint.meta["best_train_reward"])
    expect(
        best >= max(curve) - 1e-12,
        f"returned checkpoint ({best}) is not the best center on the"
        f" curve ({max(curve)})",
    )

    # 2. checkpoint round trip: save -> load is bitwise
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "learned.json")
        save_checkpoint(path, result.checkpoint)
        loaded = load_checkpoint(path)
    expect(
        np.array_equal(loaded.theta, result.checkpoint.theta),
        "checkpoint theta changed across save -> load",
    )
    expect(
        loaded.hash == result.checkpoint.hash,
        "checkpoint hash changed across save -> load",
    )

    # 3. fidelity: the compiled twin and the real ControlLoop agree
    # tick-for-tick on the trained network
    fidelity = verify_fidelity(
        scenarios=scenarios,
        forecasters=(),
        extra_episodes=[
            (f"{s.name}/learned", learned_config(s, loaded))
            for s in scenarios
        ],
    )
    expect(
        fidelity.ok,
        "compiled-vs-Python divergences: "
        + "; ".join(fidelity.format_divergences(3)),
    )

    # 4. deployment: a real ControlLoop episode on a FakeClock, decisions
    # bitwise identical between the trained and the reloaded weights
    decisions: list[list[int]] = []
    for checkpoint in (result.checkpoint, loaded):
        records: list = []

        class _Recorder:
            def on_tick(self, record):
                records.append(record)

        sim = Simulation(
            learned_config(scenarios[0], checkpoint),
            extra_observers=(_Recorder(),),
        )
        episode = sim.run()
        decisions.append([r.decision_messages for r in records])
    expect(
        decisions[0] == decisions[1],
        "reloaded checkpoint made different decisions than the"
        " freshly-trained one",
    )
    expect(
        episode.final_replicas > scenarios[0].min_pods,
        "the learned episode never scaled the fleet up",
    )

    summary = {
        "generations": len(curve),
        "reward_first": round(curve[0], 4),
        "reward_last": round(curve[-1], 4),
        "checkpoint_hash": loaded.hash,
        "fidelity_episodes": fidelity.episodes,
        "fidelity_ticks": fidelity.ticks,
        "divergences": len(fidelity.divergences),
        "episode_final_replicas": episode.final_replicas,
        "episode_max_depth": round(episode.max_depth, 1),
        "ok": not problems,
    }
    return summary, problems


def main(argv: Sequence[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="Deterministic learned-policy lifecycle: tiny ES train,"
        " checkpoint round trip, fidelity gate, FakeClock deployment —"
        " fails on any missing milestone."
    )
    parser.parse_args(argv)
    summary, problems = _check_demo()
    print(json.dumps(summary))
    for line in problems:
        print(f"missing milestone: {line}", file=sys.stderr)
    return 0 if not problems else 2


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
