"""Attribute-summing queue-depth metric source.

Reference counterpart: ``sqs/sqs.go``.  The "metric" is the sum of a
configured list of string-valued queue attributes fetched in one
``GetQueueAttributes`` call (``sqs/sqs.go:45-67``); with the default
attribute list the depth is visible + delayed + in-flight messages
(``sqs/sqs.go:28-33``).

Two deliberate behavior fixes over the reference (both documented in
SURVEY.md §2.2-C3 / §7.1 step 4):

- An attribute present in the request but missing from the response is an
  explicit :class:`MetricError` instead of the reference's nil-pointer
  dereference at ``sqs/sqs.go:58``.
- A non-integer attribute value raises :class:`MetricError` with the
  reference's context string ``"Failed to get '<attr>' number of messages
  in queue"`` (``sqs/sqs.go:60``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Protocol, Sequence

from ..core.types import MetricError

# sqs/sqs.go:28-33 — default depth = visible + delayed + not-visible.
DEFAULT_ATTRIBUTE_NAMES: tuple[str, ...] = (
    "ApproximateNumberOfMessages",
    "ApproximateNumberOfMessagesDelayed",
    "ApproximateNumberOfMessagesNotVisible",
)

# main.go:28 — the CSV form used as the --attribute-names flag default.
DEFAULT_ATTRIBUTE_NAMES_CSV = ",".join(DEFAULT_ATTRIBUTE_NAMES)


def parse_attribute_names(csv_text: str) -> tuple[str, ...]:
    """Parse the ``--attribute-names`` CSV override (``main.go:103-110``).

    Each item is whitespace-trimmed.  Passing the default CSV verbatim yields
    the canonical default tuple, matching the reference's string-compare fast
    path (behaviorally identical either way, SURVEY.md §2.2-C1).
    """
    if csv_text == DEFAULT_ATTRIBUTE_NAMES_CSV:
        return DEFAULT_ATTRIBUTE_NAMES
    return tuple(item.strip() for item in csv_text.split(","))


class QueueService(Protocol):
    """The provider seam (reference: interface ``SQS``, ``sqs/sqs.go:14-18``).

    One read method is all production needs; the write-side
    ``set_queue_attributes`` lives only on the fake (the reference's
    ``SetQueueAttributes`` is likewise a test-only seam, ``sqs/sqs.go:16``).
    """

    def get_queue_attributes(
        self, queue_url: str, attribute_names: Sequence[str]
    ) -> Mapping[str, str]:
        """Fetch the requested attributes as a name->string-value map."""
        ...


@dataclass
class QueueMetricSource:
    """Sums configured attributes into one integer depth (``sqs/sqs.go:20-24``)."""

    client: QueueService
    queue_url: str
    attribute_names: Sequence[str] = field(default=DEFAULT_ATTRIBUTE_NAMES)

    def num_messages(self) -> int:
        try:
            attributes = self.client.get_queue_attributes(
                self.queue_url, list(self.attribute_names)
            )
        except Exception as err:
            raise MetricError("Failed to get messages in SQS") from err

        messages = 0
        for name in self.attribute_names:
            if name not in attributes:
                # reference nil-derefs here (sqs/sqs.go:58); we error instead
                raise MetricError(
                    f"Failed to get '{name}' number of messages in queue"
                )
            try:
                messages += int(attributes[name])
            except (TypeError, ValueError) as err:
                raise MetricError(
                    f"Failed to get '{name}' number of messages in queue"
                ) from err
        return messages
