"""Real AWS SQS client, stdlib-only.

Reference counterpart: ``NewSqsClient`` + the AWS SDK (``sqs/sqs.go:35-43``).
The reference leans on aws-sdk-go for transport, signing, and credential
resolution; this rebuild implements the same three pieces directly:

- **Protocol**: the SQS JSON protocol (what current AWS SDKs speak) — one
  POST to the queue's endpoint with ``X-Amz-Target:
  AmazonSQS.GetQueueAttributes`` and a JSON body.  Production only ever
  needs ``GetQueueAttributes`` (``sqs/sqs.go:51``); the write-side
  ``SetQueueAttributes`` of the reference's ``SQS`` interface is a test-only
  seam (``sqs/sqs.go:16``) and lives on :class:`~.fake.FakeQueueService`.
- **Signing**: SigV4 via :mod:`..utils.sigv4`.
- **Credentials**: the standard AWS chain, same order the SDK uses
  (``sqs/sqs.go:36`` note in SURVEY §2.2-C3): env vars → shared credentials
  file (``~/.aws/credentials``, honoring ``AWS_PROFILE``) → EC2/ECS instance
  role (IMDSv2), matching how the reference runs under an instance role in
  the README deployment.

Region resolution: the ``--aws-region`` flag, else ``AWS_REGION`` /
``AWS_DEFAULT_REGION``, else parsed from the queue URL host
(``sqs.<region>.amazonaws.com``).
"""

from __future__ import annotations

import configparser
import json
import os
import time
import urllib.error
import urllib.parse
import urllib.request
from pathlib import Path
from typing import Mapping, Sequence

from ..utils.sigv4 import Credentials, SignableRequest, sign_request


class AwsError(RuntimeError):
    """Transport or service failure talking to SQS."""


class CredentialsError(AwsError):
    """No credentials found anywhere in the chain."""


# --- credential chain -------------------------------------------------------


def _credentials_from_env() -> Credentials | None:
    access_key = os.environ.get("AWS_ACCESS_KEY_ID")
    secret = os.environ.get("AWS_SECRET_ACCESS_KEY")
    if access_key and secret:
        return Credentials(access_key, secret, os.environ.get("AWS_SESSION_TOKEN"))
    return None


def _credentials_from_shared_file() -> Credentials | None:
    path = Path(
        os.environ.get("AWS_SHARED_CREDENTIALS_FILE", "~/.aws/credentials")
    ).expanduser()
    if not path.is_file():
        return None
    profile = os.environ.get("AWS_PROFILE", "default")
    parser = configparser.ConfigParser()
    try:
        parser.read(path)
    except configparser.Error:
        return None
    if profile not in parser:
        return None
    section = parser[profile]
    access_key = section.get("aws_access_key_id")
    secret = section.get("aws_secret_access_key")
    if access_key and secret:
        return Credentials(access_key, secret, section.get("aws_session_token"))
    return None


def _credentials_from_instance_role(timeout: float = 2.0) -> Credentials | None:
    """EC2 IMDSv2 instance-role credentials (how the README deployment runs)."""
    base = "http://169.254.169.254"
    try:
        token_req = urllib.request.Request(
            f"{base}/latest/api/token",
            method="PUT",
            headers={"X-aws-ec2-metadata-token-ttl-seconds": "21600"},
        )
        with urllib.request.urlopen(token_req, timeout=timeout) as resp:
            imds_token = resp.read().decode()
        headers = {"X-aws-ec2-metadata-token": imds_token}
        role_url = f"{base}/latest/meta-data/iam/security-credentials/"
        with urllib.request.urlopen(
            urllib.request.Request(role_url, headers=headers), timeout=timeout
        ) as resp:
            role = resp.read().decode().strip().splitlines()[0]
        with urllib.request.urlopen(
            urllib.request.Request(role_url + role, headers=headers), timeout=timeout
        ) as resp:
            data = json.loads(resp.read())
        expires_at = None
        if data.get("Expiration"):
            try:
                expires_at = time.mktime(
                    time.strptime(data["Expiration"], "%Y-%m-%dT%H:%M:%SZ")
                ) - time.timezone
            except ValueError:
                pass
        return Credentials(
            data["AccessKeyId"],
            data["SecretAccessKey"],
            data.get("Token"),
            expires_at=expires_at,
        )
    except Exception:
        return None


def resolve_credentials(allow_imds: bool = True) -> Credentials:
    """Standard chain: env -> shared file -> instance role."""
    for provider in (_credentials_from_env, _credentials_from_shared_file):
        creds = provider()
        if creds:
            return creds
    if allow_imds:
        creds = _credentials_from_instance_role()
        if creds:
            return creds
    raise CredentialsError(
        "No AWS credentials found (env, shared credentials file, instance role)"
    )


def region_from_queue_url(queue_url: str) -> str | None:
    """``https://sqs.us-east-1.amazonaws.com/123/q`` -> ``us-east-1``."""
    host = urllib.parse.urlsplit(queue_url).netloc
    parts = host.split(".")
    if len(parts) >= 3 and parts[0] == "sqs":
        return parts[1]
    return None


# --- the client -------------------------------------------------------------


class AwsSqsService:
    """``QueueService`` implementation against real AWS SQS."""

    # refresh temporary credentials this many seconds before they expire
    CREDENTIAL_REFRESH_WINDOW = 300.0

    def __init__(
        self,
        region: str = "",
        credentials: Credentials | None = None,
        timeout: float = 10.0,
        endpoint: str | None = None,
    ) -> None:
        self.region = region
        self._credentials = credentials
        # Explicitly injected credentials are the caller's responsibility;
        # chain-resolved ones are refreshed as they near expiry (the SDK the
        # reference uses does the same for instance-role credentials).
        self._credentials_injected = credentials is not None
        self.timeout = timeout
        self.endpoint = endpoint  # override for tests / localstack-style use

    def _current_credentials(self) -> Credentials:
        creds = self._credentials
        stale = (
            creds is None
            or (
                not self._credentials_injected
                and creds.expires_at is not None
                and time.time() > creds.expires_at - self.CREDENTIAL_REFRESH_WINDOW
            )
        )
        if stale:
            creds = self._credentials = resolve_credentials()
        return creds

    def _resolve_region(self, queue_url: str) -> str:
        if self.region:
            return self.region
        env_region = os.environ.get("AWS_REGION") or os.environ.get(
            "AWS_DEFAULT_REGION"
        )
        if env_region:
            return env_region
        from_url = region_from_queue_url(queue_url)
        if from_url:
            return from_url
        raise AwsError(
            "Cannot determine AWS region: pass --aws-region, set AWS_REGION, "
            "or use a regional queue URL"
        )

    def _call(self, action: str, queue_url: str, body: dict) -> dict:
        """One signed SQS JSON-protocol call (``X-Amz-Target`` dispatch)."""
        region = self._resolve_region(queue_url)
        credentials = self._current_credentials()

        parsed = urllib.parse.urlsplit(self.endpoint or queue_url)
        url = urllib.parse.urlunsplit((parsed.scheme, parsed.netloc, "/", "", ""))
        request = SignableRequest(
            method="POST",
            url=url,
            headers={
                "Content-Type": "application/x-amz-json-1.0",
                "X-Amz-Target": f"AmazonSQS.{action}",
            },
            body=json.dumps(body).encode("utf-8"),
        )
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        signed = sign_request(request, credentials, region, "sqs", amz_date)

        http_request = urllib.request.Request(
            signed.url, data=signed.body, headers=signed.headers, method="POST"
        )
        try:
            with urllib.request.urlopen(http_request, timeout=self.timeout) as resp:
                raw = resp.read()
                return json.loads(raw) if raw.strip() else {}
        except urllib.error.HTTPError as err:
            detail = err.read().decode("utf-8", "replace")[:512]
            raise AwsError(f"SQS returned HTTP {err.code}: {detail}") from err
        except urllib.error.URLError as err:
            raise AwsError(f"SQS request failed: {err.reason}") from err

    def get_queue_attributes(
        self, queue_url: str, attribute_names: Sequence[str]
    ) -> Mapping[str, str]:
        payload = self._call(
            "GetQueueAttributes",
            queue_url,
            {"QueueUrl": queue_url, "AttributeNames": list(attribute_names)},
        )
        return payload.get("Attributes", {})

    # --- message operations (used by the scaled workers, not the
    # controller; the reference's controller likewise only ever reads
    # attributes, sqs/sqs.go:51) ---------------------------------------

    def send_message(self, queue_url: str, body: str) -> str:
        payload = self._call(
            "SendMessage", queue_url, {"QueueUrl": queue_url, "MessageBody": body}
        )
        return payload.get("MessageId", "")

    def receive_messages(
        self, queue_url: str, max_messages: int = 1, wait_time_s: int = 0
    ) -> list[dict]:
        payload = self._call(
            "ReceiveMessage",
            queue_url,
            {
                "QueueUrl": queue_url,
                # SQS rejects MaxNumberOfMessages outside 1..10
                "MaxNumberOfMessages": max(1, min(max_messages, 10)),
                "WaitTimeSeconds": wait_time_s,
                # SentTimestamp feeds the workers' --request-ttl
                # admission deadline; without it messages never expire
                "AttributeNames": ["SentTimestamp"],
            },
        )
        out = []
        for m in payload.get("Messages", []):
            message = {"MessageId": m.get("MessageId", ""),
                       "ReceiptHandle": m["ReceiptHandle"],
                       "Body": m.get("Body", "")}
            if m.get("Attributes"):
                message["Attributes"] = m["Attributes"]
            out.append(message)
        return out

    def delete_message(self, queue_url: str, receipt_handle: str) -> None:
        self._call(
            "DeleteMessage",
            queue_url,
            {"QueueUrl": queue_url, "ReceiptHandle": receipt_handle},
        )

    def change_message_visibility(
        self, queue_url: str, receipt_handle: str, visibility_timeout: float
    ) -> None:
        """Reset an in-flight message's visibility window (0 = return it
        to the queue immediately — the fleet's drain-timeout and
        evacuation hand-back path)."""
        self._call(
            "ChangeMessageVisibility",
            queue_url,
            {"QueueUrl": queue_url, "ReceiptHandle": receipt_handle,
             "VisibilityTimeout": int(visibility_timeout)},
        )
