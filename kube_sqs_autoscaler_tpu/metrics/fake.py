"""In-memory fake queue service.

Equivalent of the reference's ``MockSQS`` (``main_test.go:273-286``,
``sqs/sqs_test.go:27-41``): holds one attribute map; ``get_queue_attributes``
returns it, and ``set_queue_attributes`` is the write-side seam tests use to
change queue depth mid-run (``main_test.go:46-49``).  Also supports error
injection for the metric-failure paths the reference never tests.
"""

from __future__ import annotations

import threading
from typing import Mapping, Sequence


class FakeQueueService:
    """Settable attribute map behind the ``QueueService`` seam."""

    def __init__(self, attributes: Mapping[str, str] | None = None):
        self._lock = threading.Lock()
        self._attributes: dict[str, str] = dict(attributes or {})
        self.fail_next_get: Exception | None = None
        self.get_calls = 0

    @classmethod
    def with_depths(
        cls, visible: int, delayed: int = 0, not_visible: int = 0
    ) -> "FakeQueueService":
        """Seed the three default attributes (cf. ``main_test.go:289-293``)."""
        return cls(
            {
                "ApproximateNumberOfMessages": str(visible),
                "ApproximateNumberOfMessagesDelayed": str(delayed),
                "ApproximateNumberOfMessagesNotVisible": str(not_visible),
            }
        )

    def get_queue_attributes(
        self, queue_url: str, attribute_names: Sequence[str]
    ) -> Mapping[str, str]:
        with self._lock:
            self.get_calls += 1
            if self.fail_next_get is not None:
                err, self.fail_next_get = self.fail_next_get, None
                raise err
            # Like the reference mock (main_test.go:277-279), returns the
            # whole stored map regardless of the requested names; the metric
            # source picks out what it asked for.
            return dict(self._attributes)

    def set_queue_attributes(self, attributes: Mapping[str, str]) -> None:
        """Test seam: replace the attribute map (``main_test.go:281-286``)."""
        with self._lock:
            self._attributes = dict(attributes)

    def set_depths(
        self, visible: int, delayed: int = 0, not_visible: int = 0
    ) -> None:
        """Convenience for the common three-attribute reseed."""
        self.set_queue_attributes(
            {
                "ApproximateNumberOfMessages": str(visible),
                "ApproximateNumberOfMessagesDelayed": str(delayed),
                "ApproximateNumberOfMessagesNotVisible": str(not_visible),
            }
        )
