"""In-memory fake queue service.

Equivalent of the reference's ``MockSQS`` (``main_test.go:273-286``,
``sqs/sqs_test.go:27-41``): holds one attribute map; ``get_queue_attributes``
returns it, and ``set_queue_attributes`` is the write-side seam tests use to
change queue depth mid-run (``main_test.go:46-49``).  Also supports error
injection for the metric-failure paths the reference never tests.
"""

from __future__ import annotations

import threading
import time
from typing import Mapping, Sequence


class FakeQueueService:
    """Settable attribute map behind the ``QueueService`` seam."""

    def __init__(self, attributes: Mapping[str, str] | None = None):
        self._lock = threading.Lock()
        self._attributes: dict[str, str] = dict(attributes or {})
        self.fail_next_get: Exception | None = None
        self.get_calls = 0

    @classmethod
    def with_depths(
        cls, visible: int, delayed: int = 0, not_visible: int = 0
    ) -> "FakeQueueService":
        """Seed the three default attributes (cf. ``main_test.go:289-293``)."""
        return cls(
            {
                "ApproximateNumberOfMessages": str(visible),
                "ApproximateNumberOfMessagesDelayed": str(delayed),
                "ApproximateNumberOfMessagesNotVisible": str(not_visible),
            }
        )

    def get_queue_attributes(
        self, queue_url: str, attribute_names: Sequence[str]
    ) -> Mapping[str, str]:
        with self._lock:
            self.get_calls += 1
            if self.fail_next_get is not None:
                err, self.fail_next_get = self.fail_next_get, None
                raise err
            # Like the reference mock (main_test.go:277-279), returns the
            # whole stored map regardless of the requested names; the metric
            # source picks out what it asked for.
            return dict(self._attributes)

    def set_queue_attributes(self, attributes: Mapping[str, str]) -> None:
        """Test seam: replace the attribute map (``main_test.go:281-286``)."""
        with self._lock:
            self._attributes = dict(attributes)

    def set_depths(
        self, visible: int, delayed: int = 0, not_visible: int = 0
    ) -> None:
        """Convenience for the common three-attribute reseed."""
        self.set_queue_attributes(
            {
                "ApproximateNumberOfMessages": str(visible),
                "ApproximateNumberOfMessagesDelayed": str(delayed),
                "ApproximateNumberOfMessagesNotVisible": str(not_visible),
            }
        )


class FakeMessageQueue:
    """In-memory queue with real message semantics (send/receive/delete).

    Where :class:`FakeQueueService` fakes only the *attributes* surface the
    controller reads (all the reference's mock does), this fake also models
    the messages themselves with SQS-like visibility: ``receive`` makes a
    message in-flight (counted in ``ApproximateNumberOfMessagesNotVisible``)
    until it is ``delete``d or its visibility timeout lapses.  Lets worker +
    autoscaler integration tests share one queue object end-to-end.

    Time is injectable (``now_fn``) so visibility timeouts are
    deterministic under a ``FakeClock``.
    """

    def __init__(self, visibility_timeout: float = 30.0, now_fn=None):
        self._lock = threading.Lock()
        self._now = now_fn or time.monotonic
        # SentTimestamp base: the injected clock when given (so request
        # ages are deterministic under a FakeClock), else epoch seconds
        # like real SQS — NOT the monotonic visibility clock, whose
        # origin is arbitrary and would not match any consumer's clock
        self._sent_now = now_fn or time.time
        self.visibility_timeout = visibility_timeout
        # (message_id, body, sent_ms) triples
        self._visible: list[tuple[str, str, str]] = []
        # receipt_handle -> (deadline, message_id, body, sent_ms); like
        # real SQS, a fresh receipt handle is issued per receive, so a
        # stale handle from a previous delivery cannot delete a
        # redelivered message
        self._inflight: dict[str, tuple[float, str, str, str]] = {}
        self._message_counter = 0
        self._receipt_counter = 0

    def _requeue_expired(self) -> None:
        now = self._now()
        expired = [
            h for h, (deadline, _, _, _) in self._inflight.items()
            if deadline <= now
        ]
        for handle in expired:
            _, message_id, body, sent = self._inflight.pop(handle)
            self._visible.append((message_id, body, sent))

    def send_message(self, queue_url: str, body: str) -> str:
        with self._lock:
            self._message_counter += 1
            message_id = f"msg-{self._message_counter}"
            # SQS stamps SentTimestamp in epoch milliseconds, as a string
            sent = str(int(self._sent_now() * 1000))
            self._visible.append((message_id, body, sent))
            return message_id

    def receive_messages(
        self, queue_url: str, max_messages: int = 1, wait_time_s: int = 0
    ) -> list[dict]:
        # long polling is a no-op for the in-memory fake: an empty receive
        # returns immediately rather than blocking virtual/real time
        with self._lock:
            self._requeue_expired()
            batch, self._visible = (
                self._visible[:max_messages],
                self._visible[max_messages:],
            )
            deadline = self._now() + self.visibility_timeout
            out = []
            for message_id, body, sent in batch:
                self._receipt_counter += 1
                handle = f"rh-{self._receipt_counter}"
                self._inflight[handle] = (deadline, message_id, body, sent)
                out.append({
                    "MessageId": message_id,
                    "ReceiptHandle": handle,
                    "Body": body,
                    # the attribute surface request-TTL shedding reads
                    "Attributes": {"SentTimestamp": sent},
                })
            return out

    def delete_message(self, queue_url: str, receipt_handle: str) -> None:
        with self._lock:
            self._inflight.pop(receipt_handle, None)

    def change_message_visibility(
        self, queue_url: str, receipt_handle: str, visibility_timeout: float
    ) -> None:
        """Re-deadline one in-flight message (SQS ChangeMessageVisibility).

        ``visibility_timeout=0`` returns the message to the visible queue
        immediately — how a draining worker hands un-finished requests
        back instead of making survivors wait out the full timeout.  A
        stale/unknown handle is a silent no-op, like ``delete_message``.
        """
        with self._lock:
            entry = self._inflight.pop(receipt_handle, None)
            if entry is None:
                return
            _, message_id, body, sent = entry
            if visibility_timeout <= 0:
                self._visible.append((message_id, body, sent))
            else:
                self._inflight[receipt_handle] = (
                    self._now() + visibility_timeout, message_id, body,
                    sent,
                )

    def get_queue_attributes(self, queue_url, attribute_names):
        with self._lock:
            self._requeue_expired()
            return {
                "ApproximateNumberOfMessages": str(len(self._visible)),
                "ApproximateNumberOfMessagesDelayed": "0",
                "ApproximateNumberOfMessagesNotVisible": str(len(self._inflight)),
            }
