"""Metric sources: queue-depth clients.

Reference counterpart: package ``sqs`` (``sqs/sqs.go``).
"""

from .fake import FakeQueueService
from .queue import (
    DEFAULT_ATTRIBUTE_NAMES,
    QueueMetricSource,
    parse_attribute_names,
)

__all__ = [
    "DEFAULT_ATTRIBUTE_NAMES",
    "QueueMetricSource",
    "parse_attribute_names",
    "FakeQueueService",
]
