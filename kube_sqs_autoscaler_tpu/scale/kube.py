"""Real Kubernetes Deployment API client (stdlib HTTP/TLS; PyYAML only for
kubeconfig files, with a JSON fallback when PyYAML is absent).

Reference counterpart: ``NewPodAutoScaler``'s client-go wiring
(``scale/scale.go:31-52``) plus the Get/Update calls
(``scale/scale.go:55,72,82,100``).  Same config resolution order:

- ``KUBE_CONFIG_PATH`` env var names a kubeconfig file
  (``scale/scale.go:32``); when unset/empty, fall back to in-cluster
  configuration (service-account token + CA at
  ``/var/run/secrets/kubernetes.io/serviceaccount``), exactly client-go's
  ``BuildConfigFromFlags("", path)`` behavior that the README deployment
  relies on.
- Config/client failure at construction raises :class:`KubeConfigError`
  with the reference's panic messages (``scale/scale.go:35,40``) — startup
  config errors are fatal, matching the reference's panic-not-error choice
  (documented in SURVEY §5 "failure detection").

API surface is the one the actuator needs (SURVEY §1 seam): typed GET and
full-object PUT of ``apps/v1`` Deployments in one namespace — deliberately
*not* the Scale subresource and with *no* conflict retry, preserving the
reference's read-modify-write shape (SURVEY §7.3).
"""

from __future__ import annotations

import json
import os
import ssl
import tempfile
import urllib.error
import urllib.parse
import urllib.request
from base64 import b64decode
from dataclasses import dataclass
from pathlib import Path

from .objects import Deployment

SERVICE_ACCOUNT_DIR = Path("/var/run/secrets/kubernetes.io/serviceaccount")


class KubeConfigError(RuntimeError):
    """Startup configuration failure (reference panics: ``scale/scale.go:35,40``)."""


class KubeApiError(RuntimeError):
    """A Deployment API call failed (non-2xx or transport error)."""

    def __init__(self, message: str, status: int | None = None):
        super().__init__(message)
        self.status = status


@dataclass
class ClusterConfig:
    """Resolved connection parameters for one apiserver."""

    server: str  # https://host:port
    token: str | None = None
    # In-cluster bound service-account tokens rotate on disk (~hourly on
    # modern clusters); when set, the token is re-read per request like
    # client-go does, instead of being frozen at startup.
    token_file: str | None = None
    ca_cert_path: str | None = None
    client_cert_path: str | None = None
    client_key_path: str | None = None
    skip_tls_verify: bool = False

    def bearer_token(self) -> str | None:
        if self.token_file:
            try:
                return Path(self.token_file).read_text().strip()
            except OSError:
                return self.token  # fall back to the startup token
        return self.token

    def ssl_context(self) -> ssl.SSLContext:
        context = ssl.create_default_context(
            cafile=self.ca_cert_path if self.ca_cert_path else None
        )
        if self.skip_tls_verify:
            context.check_hostname = False
            context.verify_mode = ssl.CERT_NONE
        if self.client_cert_path:
            context.load_cert_chain(self.client_cert_path, self.client_key_path)
        return context


def _materialize(data_b64: str, suffix: str) -> str:
    """Write base64 ``*-data`` kubeconfig fields to a temp file for ssl."""
    handle = tempfile.NamedTemporaryFile(
        mode="wb", suffix=suffix, delete=False, prefix="kubecfg-"
    )
    with handle:
        handle.write(b64decode(data_b64))
    return handle.name


def load_kubeconfig(path: str | Path) -> ClusterConfig:
    """Parse the current-context cluster/user from a kubeconfig file.

    Kubeconfigs are YAML; JSON is a YAML subset and kubectl accepts it too,
    so without PyYAML installed a JSON-format kubeconfig still works.
    """
    try:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
    except OSError as err:
        raise KubeConfigError("Failed to configure incluster or local config") from err
    try:
        import yaml

        doc = yaml.safe_load(text)
    except ImportError:
        try:
            doc = json.loads(text)
        except ValueError as err:
            raise KubeConfigError(
                "Failed to configure incluster or local config: PyYAML is not "
                "installed and the kubeconfig is not JSON-formatted"
            ) from err
    except Exception as err:
        raise KubeConfigError("Failed to configure incluster or local config") from err
    if not isinstance(doc, dict):
        raise KubeConfigError("Failed to configure incluster or local config")

    def by_name(section: str, name: str) -> dict:
        for entry in doc.get(section, []) or []:
            if entry.get("name") == name:
                return entry.get(section.rstrip("s"), {}) or {}
        return {}

    current = doc.get("current-context", "")
    context = by_name("contexts", current)
    cluster = by_name("clusters", context.get("cluster", ""))
    user = by_name("users", context.get("user", ""))
    server = cluster.get("server")
    if not server:
        raise KubeConfigError("Failed to configure incluster or local config")

    ca_path = cluster.get("certificate-authority")
    if not ca_path and cluster.get("certificate-authority-data"):
        ca_path = _materialize(cluster["certificate-authority-data"], ".crt")
    cert_path = user.get("client-certificate")
    if not cert_path and user.get("client-certificate-data"):
        cert_path = _materialize(user["client-certificate-data"], ".crt")
    key_path = user.get("client-key")
    if not key_path and user.get("client-key-data"):
        key_path = _materialize(user["client-key-data"], ".key")

    return ClusterConfig(
        server=server.rstrip("/"),
        token=user.get("token"),
        ca_cert_path=ca_path,
        client_cert_path=cert_path,
        client_key_path=key_path,
        skip_tls_verify=bool(cluster.get("insecure-skip-tls-verify", False)),
    )


def load_incluster_config() -> ClusterConfig:
    """Service-account config, as the README deployment runs the controller."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    token_path = SERVICE_ACCOUNT_DIR / "token"
    if not host or not token_path.is_file():
        raise KubeConfigError("Failed to configure incluster or local config")
    ca_path = SERVICE_ACCOUNT_DIR / "ca.crt"
    return ClusterConfig(
        server=f"https://{host}:{port}",
        token=token_path.read_text().strip(),
        token_file=str(token_path),  # re-read per request; tokens rotate
        ca_cert_path=str(ca_path) if ca_path.is_file() else None,
    )


def load_config() -> ClusterConfig:
    """``KUBE_CONFIG_PATH`` file if set, else in-cluster (``scale/scale.go:32-33``)."""
    path = os.environ.get("KUBE_CONFIG_PATH")
    if path:
        return load_kubeconfig(path)
    return load_incluster_config()


class KubeDeploymentAPI:
    """``DeploymentAPI`` over the real apiserver REST interface."""

    def __init__(
        self,
        namespace: str,
        config: ClusterConfig | None = None,
        timeout: float = 10.0,
    ) -> None:
        # Constructor failure is fatal, like the reference's panics
        # (scale/scale.go:35,40).
        self.config = config or load_config()
        self.namespace = namespace
        self.timeout = timeout
        try:
            self._ssl_context: ssl.SSLContext | None = (
                self.config.ssl_context()
                if self.config.server.startswith("https")
                else None
            )
        except Exception as err:
            raise KubeConfigError("Failed to configure client") from err

    def _request(self, method: str, url: str, body: bytes | None = None) -> dict:
        headers = {"Accept": "application/json"}
        if body is not None:
            headers["Content-Type"] = "application/json"
        token = self.config.bearer_token()
        if token:
            headers["Authorization"] = f"Bearer {token}"
        request = urllib.request.Request(url, data=body, headers=headers, method=method)
        try:
            with urllib.request.urlopen(
                request, timeout=self.timeout, context=self._ssl_context
            ) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as err:
            detail = err.read().decode("utf-8", "replace")
            message = detail[:512]
            try:  # apiserver Status objects carry the useful message
                message = json.loads(detail).get("message", message)
            except (ValueError, AttributeError):
                pass
            raise KubeApiError(
                f"{method} {url} -> HTTP {err.code}: {message}", status=err.code
            ) from err
        except urllib.error.URLError as err:
            raise KubeApiError(f"{method} {url} failed: {err.reason}") from err

    def _deployment_url(self, name: str) -> str:
        return (
            f"{self.config.server}/apis/apps/v1/namespaces/"
            f"{urllib.parse.quote(self.namespace)}/deployments/"
            f"{urllib.parse.quote(name)}"
        )

    def get(self, name: str) -> Deployment:
        return Deployment.from_raw(self._request("GET", self._deployment_url(name)))

    def update(self, deployment: Deployment) -> Deployment:
        # Full-object replace (PUT), not a patch and not the Scale
        # subresource — the reference's exact write shape (scale/scale.go:72).
        body = json.dumps(deployment.raw).encode("utf-8")
        return Deployment.from_raw(
            self._request("PUT", self._deployment_url(deployment.name), body)
        )
