"""Actuator layer: replica scalers over an orchestrator API.

Reference counterpart: package ``scale`` (``scale/scale.go``).

Two production actuators satisfy the :class:`~..core.types.Scaler` seam:
:class:`PodAutoScaler` (a Deployment's replica integer, the reference's
semantics) and the in-process serving fleet's
:class:`~..fleet.WorkerPool` (re-exported lazily here — real
ContinuousWorker replicas with failover and graceful drain; the contract
test pins that both behave identically through the ControlLoop).
"""

from .actuator import PodAutoScaler
from .fake import FakeDeploymentAPI, NotFoundError
from .objects import Deployment

__all__ = [
    "PodAutoScaler",
    "FakeDeploymentAPI",
    "NotFoundError",
    "Deployment",
    "WorkerPool",
]


def __getattr__(name):
    # Lazy: the fleet package is the actuator seam's other production
    # implementation, but importing it here eagerly would couple the
    # plain control plane to the serving stack's module graph.
    if name == "WorkerPool":
        from ..fleet import WorkerPool

        return WorkerPool
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
