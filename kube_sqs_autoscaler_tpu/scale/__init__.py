"""Actuator layer: replica scalers over an orchestrator API.

Reference counterpart: package ``scale`` (``scale/scale.go``).
"""

from .actuator import PodAutoScaler
from .fake import FakeDeploymentAPI, NotFoundError
from .objects import Deployment

__all__ = ["PodAutoScaler", "FakeDeploymentAPI", "NotFoundError", "Deployment"]
