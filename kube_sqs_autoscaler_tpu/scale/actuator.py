"""PodAutoScaler: clamped-step replica actuator.

Reference counterpart: ``scale/scale.go:21-107``.  Semantics reproduced:

- ``scale_up`` (``scale/scale.go:54-79``): Get the deployment; on API error
  raise :class:`ScaleError` with the reference's context string, no scale.
  If ``current >= max``: Info log, return successfully (boundary no-op is
  success — this matters to the policy, which refreshes its cooldown
  timestamp on success, SURVEY.md §2.2-C2 item 8).  Else step by
  ``scale_up_pods`` clamped to max and write back the *whole* object
  (read-modify-write, no conflict retry — preserved, see SURVEY.md §7.3).
- ``scale_down`` (``scale/scale.go:81-107``): mirror image with the min
  clamp.

The orchestrator is abstracted by :class:`DeploymentAPI` — satisfied by the
in-memory :class:`~.fake.FakeDeploymentAPI` (tests) and the real
:class:`~.kube.KubeDeploymentAPI` (production), exactly like the reference's
client-go interface seam (``scale/scale.go:22``).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Protocol

from ..core.types import ScaleError
from .objects import Deployment

log = logging.getLogger(__name__)


class DeploymentAPI(Protocol):
    """The slice of an orchestrator the actuator needs (one namespace)."""

    def get(self, name: str) -> Deployment:
        """Fetch a deployment by name; raises on API failure / not found."""
        ...

    def update(self, deployment: Deployment) -> Deployment:
        """Replace the deployment object; raises on API failure."""
        ...


@dataclass
class PodAutoScaler:
    """Bounded step scaler for one Deployment (``scale/scale.go:21-29``)."""

    client: DeploymentAPI
    max: int
    min: int
    scale_up_pods: int
    scale_down_pods: int
    deployment: str
    namespace: str

    def scale_up(self) -> None:
        try:
            deployment = self.client.get(self.deployment)
        except Exception as err:
            raise ScaleError(
                "Failed to get deployment from kube server, no scale up occurred"
            ) from err

        current = deployment.replicas
        if current >= self.max:
            log.info("More than max pods running. No scale up. Replicas: %d", current)
            return
        next_replicas = min(current + self.scale_up_pods, self.max)

        try:
            self.client.update(deployment.with_replicas(next_replicas))
        except Exception as err:
            raise ScaleError("Failed to scale up") from err
        log.info("Scale up successful. Replicas: %d", next_replicas)

    def scale_down(self) -> None:
        try:
            deployment = self.client.get(self.deployment)
        except Exception as err:
            raise ScaleError(
                "Failed to get deployment from kube server, no scale down occurred"
            ) from err

        current = deployment.replicas
        if current <= self.min:
            log.info("Less than min pods running. No scale down. Replicas: %d", current)
            return
        next_replicas = max(current - self.scale_down_pods, self.min)

        try:
            self.client.update(deployment.with_replicas(next_replicas))
        except Exception as err:
            raise ScaleError("Failed to scale down") from err
        log.info("Scale down successful. Replicas: %d", next_replicas)
