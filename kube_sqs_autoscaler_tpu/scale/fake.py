"""In-memory fake orchestrator.

Equivalent of client-go's ``fake.NewSimpleClientset`` as the reference's
tests use it (``scale/scale_test.go:85-105``, ``main_test.go:243-261``): a
namespace-scoped Deployment store implementing the full
:class:`~.actuator.DeploymentAPI` surface in memory, so the production
actuator runs unmodified against it.

Like the client-go fake, objects are copied on the way in and out — mutating
a returned ``Deployment`` does not change the store until ``update`` is
called.  Error injection hooks (``fail_next_get`` / ``fail_next_update``)
cover the error paths the reference never tests (SURVEY.md §4 gaps).
"""

from __future__ import annotations

import threading

from .objects import Deployment


class NotFoundError(KeyError):
    """Deployment does not exist (client-go would return a 404 StatusError)."""


class FakeDeploymentAPI:
    """In-memory, thread-safe Deployment store for one namespace."""

    def __init__(self, namespace: str, deployments: list[Deployment] | None = None):
        self.namespace = namespace
        self._lock = threading.Lock()
        self._store: dict[str, Deployment] = {}
        self.get_calls = 0
        self.update_calls = 0
        self.fail_next_get: Exception | None = None
        self.fail_next_update: Exception | None = None
        for deployment in deployments or []:
            self._store[deployment.name] = deployment.clone()

    @classmethod
    def with_deployments(
        cls, namespace: str, replicas: int, *names: str
    ) -> "FakeDeploymentAPI":
        """Pre-seeded store, like the reference's two-deployment fixture
        (``main_test.go:243-261`` seeds ``deploy`` and ``deploy-no-scale``)."""
        return cls(
            namespace,
            [Deployment(name=n, namespace=namespace, replicas=replicas) for n in names],
        )

    def get(self, name: str) -> Deployment:
        with self._lock:
            self.get_calls += 1
            if self.fail_next_get is not None:
                err, self.fail_next_get = self.fail_next_get, None
                raise err
            if name not in self._store:
                raise NotFoundError(f'deployments.apps "{name}" not found')
            return self._store[name].clone()

    def update(self, deployment: Deployment) -> Deployment:
        with self._lock:
            self.update_calls += 1
            if self.fail_next_update is not None:
                err, self.fail_next_update = self.fail_next_update, None
                raise err
            if deployment.name not in self._store:
                raise NotFoundError(f'deployments.apps "{deployment.name}" not found')
            self._store[deployment.name] = deployment.clone()
            return deployment.clone()

    def replicas(self, name: str) -> int:
        """Test convenience: current stored replica count."""
        with self._lock:
            if name not in self._store:
                raise NotFoundError(f'deployments.apps "{name}" not found')
            return self._store[name].replicas


class RecordingDeploymentAPI:
    """Recorder + persistent-failure proxy over a DeploymentAPI.

    The restart battery's shared evidence collector (``core/durable``'s
    demo and ``bench.py --suite restart``): timestamps every successful
    replica write on the injected clock — the cooldown-violation
    evidence — and counts/timestamps every attempt that reached the
    "apiserver" — the breaker's did-an-RPC-happen evidence.  ``fail``
    holds the apiserver down persistently (the one-shot
    ``fail_next_update`` hook cannot keep it down long enough to open a
    breaker)."""

    def __init__(self, inner, clock) -> None:
        self.inner = inner
        self.clock = clock
        self.fail = False
        self.update_attempts: list = []  # t of every RPC that reached us
        self.scale_times: list = []  # (t, replicas) successful writes

    def get(self, name):
        return self.inner.get(name)

    def update(self, deployment):
        self.update_attempts.append(self.clock.now())
        if self.fail:
            raise RuntimeError("apiserver down")
        self.scale_times.append((self.clock.now(), deployment.replicas))
        return self.inner.update(deployment)
