"""Minimal Deployment object model.

The actuator only reads and writes ``spec.replicas`` of one named Deployment
(``scale/scale.go:60-70``), but — like the reference, which round-trips the
*whole* typed Deployment object through ``Get``/``Update``
(``scale/scale.go:55,72``) — we carry the full raw object so a real
API-server write is a faithful read-modify-write of the entire resource, not
a patch.  (The reference deliberately does not use the Scale subresource or
conflict retries; SURVEY.md §7.3 says to preserve, not fix, that.)
"""

from __future__ import annotations

import copy
import dataclasses
from dataclasses import dataclass, field
from typing import Any


@dataclass
class Deployment:
    """A Deployment as the actuator sees it: identity + replicas + raw body."""

    name: str
    namespace: str
    replicas: int
    raw: dict[str, Any] = field(default_factory=dict)

    def clone(self) -> "Deployment":
        """Deep, independent copy.

        Equivalent to ``copy.deepcopy(self)`` but ~10x cheaper: only ``raw``
        is mutable and so needs the deep copy (and most objects in the
        fake-store hot path carry an empty one); ``dataclasses.replace``
        carries every other field — including any added later — verbatim.
        """
        return dataclasses.replace(
            self, raw=copy.deepcopy(self.raw) if self.raw else {}
        )

    def with_replicas(self, replicas: int) -> "Deployment":
        """Copy with a new replica count, keeping the raw body in sync."""
        raw = copy.deepcopy(self.raw)
        if raw:
            raw.setdefault("spec", {})["replicas"] = int(replicas)
        return Deployment(
            name=self.name,
            namespace=self.namespace,
            replicas=int(replicas),
            raw=raw,
        )

    @classmethod
    def from_raw(cls, raw: dict[str, Any]) -> "Deployment":
        """Build from a Kubernetes apps/v1 JSON object."""
        meta = raw.get("metadata", {})
        spec = raw.get("spec", {})
        return cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", ""),
            # apiserver semantics: spec.replicas defaults to 1 when unset
            replicas=int(spec.get("replicas", 1)),
            raw=copy.deepcopy(raw),
        )
