"""Pure scaling policy: threshold + cooldown + startup grace.

This is the reference's control-loop *policy* (``main.go:35-80``) factored
into a side-effect-free function, per SURVEY.md §7.1 step 2.  All eight
behavioral subtleties documented in SURVEY.md §2.2-C2 are reproduced:

1.  Both cooldown timestamps start at "now" (``main.go:37-38``) — no scaling
    during the first cooldown window after boot.  See :func:`initial_state`.
2.  The loop sleeps first, then polls (``main.go:41``) — that lives in
    :mod:`.loop`, not here.
3.  Metric errors skip the tick (loop concern).
4.  Observation logging (loop concern).
5.  Scale-up gate is inclusive: ``num_messages >= scale_up_messages``
    (``main.go:51``).  Cooldown is "still cooling" iff
    ``last + cooldown > now`` strictly (``main.go:52``:
    ``lastScaleUpTime.Add(cool).After(now)``), so a tick landing exactly on
    the cooldown boundary *fires*.  While cooling with a high queue, the
    scale-down branch must not even be evaluated that tick (the ``continue``
    at ``main.go:54``) — encoded as ``TickPlan.down is Gate.SKIPPED``.
6.  Scale-down gate is inclusive: ``num_messages <= scale_down_messages``
    (``main.go:65``), with its own cooldown, symmetric.
7.  The branches are ``if`` + ``if``, not ``else if`` (``main.go:51,65``):
    with overlapping thresholds one tick can scale up *and then* down.
8.  Timestamps advance only on *successful* actuation (``main.go:62,76``);
    a boundary no-op returns success and therefore *does* refresh the
    timestamp.  The plan cannot know success in advance, so execution-order
    rules are part of the plan contract (see :class:`TickPlan`).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, replace


class Gate(enum.Enum):
    """Outcome of one scaling gate for one tick."""

    IDLE = "idle"  # threshold not met
    FIRE = "fire"  # threshold met, cooldown elapsed: actuate
    COOLING = "cooling"  # threshold met but still in cooldown: log + end tick
    SKIPPED = "skipped"  # not evaluated (an earlier gate ended the tick)


# Integer gate codes: the scan-able twin of :class:`Gate`.  The compiled
# simulator (``sim/compiled.py``) evaluates whole episodes inside
# ``jax.lax.scan``, where enum values cannot flow; both the live gates
# below and the compiled gates share :func:`gate_code`, so the decision
# arithmetic exists exactly once.
GATE_IDLE, GATE_FIRE, GATE_COOLING, GATE_SKIPPED = 0, 1, 2, 3
GATE_BY_CODE: tuple[Gate, ...] = (Gate.IDLE, Gate.FIRE, Gate.COOLING, Gate.SKIPPED)


def gate_code(threshold_met, now, last, cooldown):
    """Branchless core of both gates; works elementwise on arrays.

    Encodes the two reference subtleties shared by ``main.go:51-52`` and
    ``main.go:65-66``: the threshold test is inclusive (callers pass the
    already-evaluated ``threshold_met``), and cooldown is "still cooling"
    iff ``last + cooldown > now`` *strictly* — a tick landing exactly on
    the boundary fires.  Returns ``GATE_IDLE``/``GATE_FIRE``/
    ``GATE_COOLING``; all inputs may be Python scalars or numpy/JAX
    arrays (the arithmetic form is what makes it ``lax.scan``-able).
    """
    cooling = last + cooldown > now
    return threshold_met * (GATE_FIRE + cooling)


@dataclass(frozen=True)
class PolicyConfig:
    """Thresholds and cooldowns (reference defaults, ``main.go:83-87``)."""

    scale_up_messages: int = 100  # --scale-up-messages
    scale_down_messages: int = 10  # --scale-down-messages
    scale_up_cooldown: float = 10.0  # --scale-up-cool-down (seconds)
    scale_down_cooldown: float = 30.0  # --scale-down-cool-down (seconds)


@dataclass(frozen=True)
class PolicyState:
    """The policy's entire memory: two cooldown timestamps (``main.go:37-38``)."""

    last_scale_up: float
    last_scale_down: float


@dataclass(frozen=True)
class TickPlan:
    """A whole tick's decisions as one pure value (both gates at one instant).

    Used for analysis and property tests.  The live loop instead calls
    :func:`gate_up` / :func:`gate_down` sequentially — the reference
    re-reads ``time.Now()`` when it reaches the down branch
    (``main.go:66``), after the scale-up RPCs, so under a real clock the
    down gate must be evaluated with a *fresh* timestamp, not the one the
    up gate saw.
    """

    up: Gate
    down: Gate


def initial_state(now: float) -> PolicyState:
    """Startup grace: both cooldowns start 'just scaled' (``main.go:37-38``)."""
    return PolicyState(last_scale_up=now, last_scale_down=now)


def gate_up(
    num_messages: int, now: float, config: PolicyConfig, state: PolicyState
) -> Gate:
    """The scale-up gate (``main.go:51-52``). Pure."""
    return GATE_BY_CODE[
        int(
            gate_code(
                num_messages >= config.scale_up_messages,
                now,
                state.last_scale_up,
                config.scale_up_cooldown,
            )
        )
    ]


def gate_down(
    num_messages: int, now: float, config: PolicyConfig, state: PolicyState
) -> Gate:
    """The scale-down gate (``main.go:65-66``). Pure."""
    return GATE_BY_CODE[
        int(
            gate_code(
                num_messages <= config.scale_down_messages,
                now,
                state.last_scale_down,
                config.scale_down_cooldown,
            )
        )
    ]


def plan_tick(
    num_messages: int,
    now: float,
    config: PolicyConfig,
    state: PolicyState,
) -> TickPlan:
    """Both gates at one instant. Pure; no clocks, no I/O, no mutation."""
    up = gate_up(num_messages, now, config, state)
    if up is Gate.COOLING:
        # the reference `continue`s: the down branch is never evaluated
        return TickPlan(up=up, down=Gate.SKIPPED)
    return TickPlan(up=up, down=gate_down(num_messages, now, config, state))


def mark_scaled_up(state: PolicyState, now: float) -> PolicyState:
    """State after a *successful* scale-up actuation (``main.go:62``)."""
    return replace(state, last_scale_up=now)


def mark_scaled_down(state: PolicyState, now: float) -> PolicyState:
    """State after a *successful* scale-down actuation (``main.go:76``)."""
    return replace(state, last_scale_down=now)
