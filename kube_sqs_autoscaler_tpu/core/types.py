"""Framework seams: the two leaf protocols and their error types.

The reference gets its testability from two interface seams — ``SQS``
(``sqs/sqs.go:14-18``) behind the metric source and client-go's
``DeploymentInterface`` (``scale/scale.go:22``) behind the actuator
(SURVEY.md §1).  These protocols are the same seams, idiomatically Python:
anything with ``num_messages()`` is a metric source, anything with
``scale_up()``/``scale_down()`` is a scaler.

Failures are exceptions rather than Go error returns; the control loop
catches :class:`MetricError`/:class:`ScaleError` and continues the loop,
matching ``main.go:43-47,57-60,71-74``.
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable


class MetricError(RuntimeError):
    """Metric source failure (reference: wrapped error at ``sqs/sqs.go:53,60``)."""


class ScaleError(RuntimeError):
    """Actuator failure (reference: wrapped error at ``scale/scale.go:57,74``)."""


@runtime_checkable
class MetricSource(Protocol):
    """Produces the scalar the policy thresholds on (queue depth)."""

    def num_messages(self) -> int:
        """Current queue depth. Raises :class:`MetricError` on failure."""
        ...


@runtime_checkable
class DepthPolicy(Protocol):
    """Maps the observed queue depth to the depth the gates threshold on.

    The plug-point for predictive scaling (``forecast.PredictivePolicy``):
    it sits *before* the pure gates, so threshold inclusivity, cooldown
    strictness, and the up-cooling ``continue`` are untouched whatever the
    policy returns.  The reactive/reference behavior is the identity map
    (``ControlLoop`` with no policy, or ``forecast.ReactivePolicy``).
    """

    def effective_messages(self, now: float, num_messages: int) -> int:
        """Depth for this tick's gates. Pure w.r.t. the loop; may keep
        internal forecast state. Exceptions fall back to the observed
        depth (the loop never dies)."""
        ...


@runtime_checkable
class Scaler(Protocol):
    """Actuates the replica count on an orchestrator."""

    def scale_up(self) -> None:
        """Step replicas up (clamped). No-op at max. Raises :class:`ScaleError`."""
        ...

    def scale_down(self) -> None:
        """Step replicas down (clamped). No-op at min. Raises :class:`ScaleError`."""
        ...
