"""The control loop: sleep → poll → plan → actuate.

Reference counterpart: ``Run()`` at ``main.go:35-80``.  The loop owns the
side effects; all decisions come from the pure policy (:mod:`.policy`).
Execution follows the :class:`~.policy.TickPlan` contract exactly:

- sleep *first*, then poll (``main.go:41``) — so the first observation
  happens one poll interval after start, and cooldown timestamps initialized
  at start (:func:`~.policy.initial_state`) give the startup grace window;
- a metric failure logs ``"Failed to get SQS messages: …"`` and skips the
  tick (``main.go:43-47``) — the loop never dies;
- every observation logs ``"Found %d messages in the queue"`` (``main.go:49``);
- an up-cooling tick logs and ends the tick (``main.go:52-55``, including the
  reference's trailing space in ``"… skipping scale up "``);
- an actuation failure logs and ends the tick without touching policy state
  (``main.go:57-60,71-74``);
- only successful actuation (including boundary no-ops) advances the
  matching cooldown timestamp (``main.go:62,76``).

Deviation from the reference (deliberate, SURVEY.md §7.1): the loop takes an
injectable :class:`~.clock.Clock` and supports bounded runs (``max_ticks``)
and cooperative stop, so behavior is testable without real time.  With
``SystemClock`` and defaults it blocks forever exactly like ``Run``.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field

from .clock import Clock, SystemClock
from .durable import ControllerCrash, DurableStateStore
from .events import TickObserver, TickRecord
from .policy import (
    Gate,
    PolicyConfig,
    PolicyState,
    gate_down,
    gate_up,
    initial_state,
    mark_scaled_down,
    mark_scaled_up,
)
from .resilience import ResilienceConfig, ResiliencePolicy
from .types import DepthPolicy, MetricSource, Scaler

log = logging.getLogger(__name__)


@dataclass
class LoopConfig:
    """Loop cadence + policy knobs (defaults: ``main.go:83-87``)."""

    poll_interval: float = 5.0  # --poll-period
    policy: PolicyConfig = field(default_factory=PolicyConfig)


class ControlLoop:
    """Drives one scaler from one metric source on one clock."""

    def __init__(
        self,
        scaler: Scaler,
        metric_source: MetricSource,
        config: LoopConfig | None = None,
        clock: Clock | None = None,
        observer: TickObserver | None = None,
        depth_policy: DepthPolicy | None = None,
        resilience: ResilienceConfig | None = None,
        durable: DurableStateStore | None = None,
    ) -> None:
        self.scaler = scaler
        self.metric_source = metric_source
        self.config = config or LoopConfig()
        self.clock = clock or SystemClock()
        self.observer = observer
        # None = reference behavior: gates threshold the observed depth.
        self.depth_policy = depth_policy
        # None / all-defaults = reference behavior: one attempt per RPC,
        # metric failures fail static, no breaker (core/resilience.py).
        self.resilience = (
            ResiliencePolicy(resilience, self.clock, self.config.poll_interval)
            if resilience is not None and resilience.enabled
            else None
        )
        # None = reference behavior: the controller's memory dies with
        # the process.  With a DurableStateStore the loop snapshots its
        # whole control state after every tick and REHYDRATES it (via
        # initial_policy_state) at episode start — core/durable.py.
        self.durable = durable
        self.ticks = 0  # completed ticks (observability; not used by policy)
        self._stop = threading.Event()

    def stop(self) -> None:
        """Ask the loop to exit after its current tick.

        Sticky: a stop requested even *before* :meth:`run` starts (e.g. a
        SIGTERM landing between handler installation and the run call) still
        takes effect — ``run`` never clears the flag itself.  Use
        :meth:`reset` to reuse a stopped loop.
        """
        self._stop.set()

    def reset(self) -> None:
        """Clear a previous :meth:`stop` so the loop can run again."""
        self._stop.clear()

    def initial_policy_state(self) -> PolicyState:
        """The episode's starting policy state.

        Reference behavior (no durable store): ``initial_state(now)`` —
        both cooldowns start "just scaled", the startup grace window.
        With a :class:`~.durable.DurableStateStore` the store rehydrates
        first (snapshot + journal tail + unresolved actuation intent,
        reconciled against the scaler's observed replica count) and the
        restored, rebased cooldown stamps stand in; any refusal —
        missing, torn, corrupt, or future-schema snapshot — falls back
        to the cold start above, never to a crash.
        """
        now = self.clock.now()
        if self.durable is None:
            return initial_state(now)
        self.durable.rehydrate(
            now, observed_replicas=getattr(self.scaler, "replicas", None)
        )
        # consumed, not read: only the FIRST episode after boot starts
        # from the restored stamps — a later run() on the same loop is
        # a fresh episode (reference grace), per run()'s contract
        restored = self.durable.take_restored_policy_state()
        return restored if restored is not None else initial_state(now)

    def run(self, max_ticks: int | None = None, *,
            scheduler=None) -> PolicyState:
        """Run the loop; blocks until ``max_ticks`` ticks or :meth:`stop`.

        ``max_ticks=None`` runs forever, like the reference.  Each call is a
        fresh episode (fresh startup-grace state and tick budget);
        ``self.ticks`` accumulates across episodes for observability.

        ``scheduler`` hands the sleep loop to the event scheduler seam
        (:mod:`..sched`): pass an
        :class:`~..sched.scheduler.EventScheduler` (or ``True`` to
        build one on this loop's clock) and the episode runs as a
        registered ``control-tick`` event instead — same cadence, same
        sticky-stop and ``max_ticks`` semantics, byte-identical tick
        records (pinned by test), but on a queue other events (knob
        timers, fleet cycles) can share.
        """
        if scheduler is not None and scheduler is not False:
            from ..sched.scheduler import drive_loop

            return drive_loop(
                self, max_ticks=max_ticks,
                scheduler=None if scheduler is True else scheduler,
            )
        state = self.initial_policy_state()
        ticks_this_run = 0
        while not self._stop.is_set():
            if max_ticks is not None and ticks_this_run >= max_ticks:
                break
            self.clock.sleep(self.config.poll_interval)
            if self._stop.is_set():  # stop requested mid-sleep: skip the tick
                break
            state = self.tick(state)
            ticks_this_run += 1
            self.ticks += 1
        return state

    def tick(self, state: PolicyState) -> PolicyState:
        """One loop body (post-sleep): observe, plan, actuate. Returns new state.

        Side-effect order and log lines are the reference's; the only
        addition is the :class:`~.events.TickRecord` handed to the optional
        observer after the tick completes.
        """
        record = TickRecord(start=self.clock.now())
        crashed = False
        new_state = state
        try:
            new_state = self._tick(state, record)
            return new_state
        except ControllerCrash:
            # simulated process death (sim/faults.CrashPlan): nothing
            # after this instant happens — no observer, no journal line,
            # no snapshot — exactly like the pod vanishing mid-tick
            crashed = True
            raise
        finally:
            if not crashed:
                if self.resilience is not None:
                    record.breaker_state = self.resilience.breaker_state
                record.duration = self.clock.now() - record.start
                # The decide span is the remainder once observation and
                # scaler time are accounted — defined only for ticks that
                # got past the observation (a metric failure ends the
                # tick inside observe).
                if record.metric_error is None and record.observe_s is not None:
                    record.decide_s = max(
                        0.0,
                        record.duration
                        - record.observe_s
                        - (record.actuate_s or 0.0),
                    )
                if self.observer is not None:
                    try:
                        self.observer.on_tick(record)
                    except Exception:  # instrumentation must never kill the loop
                        log.exception("Tick observer failed")
                # The snapshot is the LAST durable act of the tick — after
                # the journal observer, so the journal is never behind the
                # snapshot (rehydration replays the journal tail forward,
                # never backward).  A torn-journal crash (ControllerCrash
                # out of the observer, a BaseException the guard above
                # does not swallow) therefore skips the snapshot too.
                if self.durable is not None:
                    try:
                        self.durable.snapshot(
                            clock_now=self.clock.now(),
                            policy_state=new_state,
                            ticks=self.ticks + 1,
                            last_tick_start=record.start,
                        )
                    except Exception:  # durability must never kill the loop
                        log.exception("Control-plane snapshot failed")

    def _actuate(self, record: TickRecord, action, direction: str) -> str | None:
        """One scaler call with its clock time accumulated into the record's
        actuate span; returns the error string on failure (tick ends).
        With a resilience policy the call goes through the circuit breaker,
        per-call deadline, and retry budget (``core/resilience.py``) — an
        open breaker fails here without touching the scaler.  With a
        durable store, a write-ahead INTENT lands before the RPC: a crash
        between the actuation and the tick's snapshot must rehydrate as
        "may have scaled" (cooldown stamp advanced), never double-scale."""
        started = self.clock.now()
        if self.durable is not None:
            try:
                self.durable.note_intent(direction, started)
            except Exception:  # durability must never block an actuation
                log.exception("Actuation intent write failed")
        try:
            if self.resilience is not None:
                self.resilience.actuate(action, record)
            else:
                action()
        except Exception as err:
            return str(err)
        finally:
            record.actuate_s = (record.actuate_s or 0.0) + (
                self.clock.now() - started
            )
        return None

    def _tick(self, state: PolicyState, record: TickRecord) -> PolicyState:
        try:
            if self.resilience is not None:
                num_messages = self.resilience.observe(
                    self.metric_source.num_messages, record
                )
            else:
                num_messages = self.metric_source.num_messages()
        except Exception as err:  # the loop must never die (main.go:43-47)
            record.observe_s = self.clock.now() - record.start
            # Degraded mode: within the stale TTL the tick proceeds on the
            # last good depth (marked stale; the forecaster history skips
            # it); past the TTL the reference's fail-static skip applies.
            held = (
                self.resilience.stale_depth(self.clock.now())
                if self.resilience is not None
                else None
            )
            if held is None:
                log.error("Failed to get SQS messages: %s", err)
                record.metric_error = str(err)
                return state
            num_messages, age = held
            record.stale = True
            record.stale_age_s = age
            log.warning(
                "Metric poll failed (%s); holding last good depth %d"
                " (age %.1fs of %gs TTL)",
                err,
                num_messages,
                age,
                self.resilience.config.stale_depth_ttl,
            )
        else:
            record.observe_s = self.clock.now() - record.start
            log.info("Found %d messages in the queue", num_messages)
        record.num_messages = num_messages

        # Depth-policy seam: the gates threshold `decision` — the observed
        # depth under the reactive default, the forecasted depth at
        # now + horizon under a predictive policy.  A policy failure falls
        # back to the observed depth; the loop never dies.  A stale-held
        # depth bypasses the policy: forecasting forward from an
        # observation that is itself old double-counts the staleness.
        decision = num_messages
        if self.depth_policy is not None and not record.stale:
            try:
                decision = max(
                    0,
                    int(
                        self.depth_policy.effective_messages(
                            self.clock.now(), num_messages
                        )
                    ),
                )
            except Exception as err:
                log.error(
                    "Depth policy failed, using observed depth: %s", err
                )
                # no forecast fields on the record: a stale prediction from
                # an earlier tick must not be exported as this tick's
                decision = num_messages
            else:
                if decision != num_messages:
                    log.info(
                        "Forecast %d messages at horizon (observed %d)",
                        decision,
                        num_messages,
                    )
                record.predicted_messages = getattr(
                    self.depth_policy, "last_prediction", None
                )
                record.forecast_error = getattr(
                    self.depth_policy, "last_abs_error", None
                )
        record.decision_messages = decision

        # Gates are evaluated sequentially with a fresh clock read each, like
        # the reference's inline time.Now() calls (main.go:52,66): under a
        # real clock the down gate sees time that has advanced past the
        # scale-up RPCs.
        policy = self.config.policy
        record.up = up = gate_up(decision, self.clock.now(), policy, state)
        if up is Gate.COOLING:
            log.info("Waiting for cool down, skipping scale up ")
            return state
        if up is Gate.FIRE:
            error = self._actuate(record, self.scaler.scale_up, "up")
            if error is not None:
                log.error("Failed scaling up: %s", error)
                record.up_error = error
                return state
            state = mark_scaled_up(state, self.clock.now())

        record.down = down = gate_down(
            decision, self.clock.now(), policy, state
        )
        if down is Gate.COOLING:
            log.info("Waiting for cool down, skipping scale down")
            return state
        if down is Gate.FIRE:
            error = self._actuate(record, self.scaler.scale_down, "down")
            if error is not None:
                log.error("Failed scaling down: %s", error)
                record.down_error = error
                return state
            state = mark_scaled_down(state, self.clock.now())

        return state
