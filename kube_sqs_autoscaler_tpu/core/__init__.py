"""Core: clock, pure scaling policy, control loop, and resilience layer."""

from .clock import Clock, FakeClock, SystemClock
from .policy import (
    Gate,
    PolicyConfig,
    PolicyState,
    TickPlan,
    initial_state,
    plan_tick,
)
from .resilience import (
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExceeded,
    ResilienceConfig,
    ResiliencePolicy,
    RetryPolicy,
    call_with_deadline,
)

__all__ = [
    "Clock",
    "FakeClock",
    "SystemClock",
    "Gate",
    "PolicyConfig",
    "PolicyState",
    "TickPlan",
    "initial_state",
    "plan_tick",
    "CircuitBreaker",
    "CircuitOpenError",
    "DeadlineExceeded",
    "ResilienceConfig",
    "ResiliencePolicy",
    "RetryPolicy",
    "call_with_deadline",
]
