"""Core: clock abstraction, pure scaling policy, and the control loop."""

from .clock import Clock, FakeClock, SystemClock
from .policy import (
    Gate,
    PolicyConfig,
    PolicyState,
    TickPlan,
    initial_state,
    plan_tick,
)

__all__ = [
    "Clock",
    "FakeClock",
    "SystemClock",
    "Gate",
    "PolicyConfig",
    "PolicyState",
    "TickPlan",
    "initial_state",
    "plan_tick",
]
