"""Resilience layer: retries, deadlines, circuit breaker, stale-depth hold.

The reference's entire failure story is "log and skip the tick"
(``main.go:43-47,57-60,71-74``): a flaky metric source silently freezes
scaling for the whole poll interval, a dead API server eats the tick
budget on every gate fire, and nothing distinguishes "degraded for 20
minutes" from "one blip".  This module is the opt-in hardening around
those two RPC seams, composed from four small deterministic pieces:

- :class:`RetryPolicy` — jittered exponential backoff with a *seeded* RNG
  driven by the loop's injectable clock, budgeted within the poll
  interval (a retry storm must never push the next tick late by more
  than ``retry_budget_fraction`` of the period);
- :func:`call_with_deadline` — a per-call deadline measured on the same
  clock.  Python cannot safely cancel a blocking call, so the deadline
  is *post-hoc*: a call that returns after its deadline is treated as
  failed (``DeadlineExceeded``), which keeps the breaker/stale-hold
  accounting honest and is exactly measurable under a ``FakeClock``;
- :class:`CircuitBreaker` — three states (closed → open → half-open)
  around the scaler, so consecutive actuation failures stop paying the
  failing RPC's latency every tick; after ``reset_timeout`` one
  half-open probe decides re-close vs re-open;
- the stale-depth hold — on metric failure, the last good observation is
  reused within ``stale_depth_ttl`` (the tick proceeds, marked
  ``stale`` on the :class:`~.events.TickRecord`, never fed to forecaster
  history), then the loop falls back to the reference's fail-static
  skip.

Everything is configured by the frozen :class:`ResilienceConfig`; with
the defaults every feature is off and :class:`~.loop.ControlLoop`
behaves byte-for-byte like the reference (``ResilienceConfig().enabled``
is ``False`` and the loop keeps its original code path).

BaseException hygiene: only ``Exception`` is ever caught or retried —
``KeyboardInterrupt``/``SystemExit`` raised inside a wrapped call
propagate immediately, never consumed as "one more failure".
"""

from __future__ import annotations

import random
from dataclasses import dataclass

from .clock import Clock
from .types import ScaleError


class DeadlineExceeded(RuntimeError):
    """A wrapped call returned only after its per-call deadline."""


class CircuitOpenError(ScaleError):
    """The breaker rejected the call without attempting the RPC."""


#: Breaker states, in escalation order (the ints are the Prometheus
#: gauge encoding: closed=0, half_open=1, open=2).
BREAKER_CLOSED = "closed"
BREAKER_HALF_OPEN = "half_open"
BREAKER_OPEN = "open"
BREAKER_STATE_CODES = {BREAKER_CLOSED: 0, BREAKER_HALF_OPEN: 1, BREAKER_OPEN: 2}


@dataclass(frozen=True)
class ResilienceConfig:
    """The resilience knobs, one per CLI flag.  Defaults = reference.

    ``metric_retries``/``scaler_retries`` are *extra* attempts after the
    first try; 0 (default) keeps the reference's single attempt.
    ``metric_timeout``/``scaler_timeout`` are per-attempt deadlines in
    seconds (0 = none).  ``breaker_failures`` consecutive scaler
    failures open the breaker (0 = no breaker); ``breaker_reset``
    seconds later one half-open probe is allowed through.
    ``stale_depth_ttl`` seconds is how long a failed poll may reuse the
    last good observation before the loop falls back to the reference's
    skip (0 = never hold).
    """

    metric_retries: int = 0  # --metric-retries
    metric_timeout: float = 0.0  # --metric-timeout (seconds)
    scaler_retries: int = 0  # --scaler-retries
    scaler_timeout: float = 0.0  # --scaler-timeout (seconds)
    breaker_failures: int = 0  # --breaker-failures
    breaker_reset: float = 60.0  # --breaker-reset (seconds)
    stale_depth_ttl: float = 0.0  # --stale-depth-ttl (seconds)
    retry_base_delay: float = 0.2  # first backoff (seconds)
    retry_max_delay: float = 2.0  # backoff cap (seconds)
    retry_jitter: float = 0.5  # fraction of each delay randomized away
    retry_budget_fraction: float = 0.5  # of the poll interval, per tick
    retry_seed: int = 0  # backoff jitter RNG seed (determinism)

    @property
    def enabled(self) -> bool:
        """Is any opt-in feature on?  ``False`` = pure reference loop."""
        return bool(
            self.metric_retries
            or self.metric_timeout
            or self.scaler_retries
            or self.scaler_timeout
            or self.breaker_failures
            or self.stale_depth_ttl
        )


class RetryPolicy:
    """Seeded jittered exponential backoff on an injectable clock.

    ``delay(attempt)`` for attempt ``n`` (0-based) is
    ``min(max_delay, base_delay * 2**n)`` with up to ``jitter`` of it
    removed by the seeded RNG — deterministic for a given seed, decorrelated
    across controllers sharing a flaky dependency.
    """

    def __init__(
        self,
        retries: int,
        base_delay: float = 0.2,
        max_delay: float = 2.0,
        jitter: float = 0.5,
        seed: int = 0,
    ) -> None:
        if retries < 0:
            raise ValueError(f"retries must be >= 0, got {retries}")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        self.retries = retries
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.jitter = jitter
        self._rng = random.Random(seed)

    def delay(self, attempt: int) -> float:
        """Backoff before retry ``attempt`` (0-based). Consumes one RNG draw."""
        delay = min(self.max_delay, self.base_delay * (2.0**attempt))
        if self.jitter:
            delay *= 1.0 - self.jitter * self._rng.random()
        return delay

    def run(
        self,
        fn,
        clock: Clock,
        timeout: float = 0.0,
        deadline: float | None = None,
        on_attempts=None,
    ) -> tuple[object, int]:
        """``fn()`` with up to ``retries`` retried attempts.

        Returns ``(result, extra_attempts_used)``.  ``timeout`` is the
        per-attempt deadline (:func:`call_with_deadline`); ``deadline``
        is the *budget*: no backoff sleep may carry the clock past it —
        the last error re-raises instead (the next poll is never pushed
        late by a retry storm).  ``on_attempts`` (optional) is called
        with the running extra-attempt count before every attempt, so
        callers can ledger retries even when the final attempt raises.
        Only ``Exception`` is retried.
        """
        attempt = 0
        while True:
            if on_attempts is not None:
                on_attempts(attempt)
            try:
                return call_with_deadline(fn, clock, timeout), attempt
            except Exception:
                if attempt >= self.retries:
                    raise
                backoff = self.delay(attempt)
                if deadline is not None and clock.now() + backoff > deadline:
                    raise  # out of budget: surface the real error now
                clock.sleep(backoff)
                attempt += 1


def call_with_deadline(fn, clock: Clock, timeout: float = 0.0):
    """``fn()`` under a clock-measured deadline (0 = none).

    Post-hoc by design: a synchronous Python call cannot be safely
    cancelled, so a call that *returns* after ``timeout`` clock-seconds
    raises :class:`DeadlineExceeded` instead — the result is discarded
    and the failure feeds retries/breaker/stale-hold exactly like an
    RPC error would.  (A boundary-exact call — duration == timeout —
    still succeeds, matching the gates' boundary-fires convention.)
    """
    if not timeout:
        return fn()
    started = clock.now()
    result = fn()
    elapsed = clock.now() - started
    if elapsed > timeout:
        raise DeadlineExceeded(
            f"call took {elapsed:g}s, exceeding the {timeout:g}s deadline"
        )
    return result


class CircuitBreaker:
    """Three-state breaker: closed → open → half-open, loop-thread only.

    Closed counts *consecutive* failures; at ``failure_threshold`` it
    opens at that instant.  While open, :meth:`allow` rejects until
    ``reset_timeout`` has elapsed, then flips to half-open and admits
    one probe: a success closes (counter reset), a failure re-opens and
    restarts the full reset wait.  Timestamps come from the caller (the
    loop's clock) so every transition is deterministic under a
    ``FakeClock``.
    """

    def __init__(self, failure_threshold: int, reset_timeout: float) -> None:
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}"
            )
        if reset_timeout < 0:
            raise ValueError(
                f"reset_timeout must be >= 0, got {reset_timeout}"
            )
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self.state = BREAKER_CLOSED
        self.failures = 0  # consecutive, reset on any success
        self.opened_at: float | None = None

    def allow(self, now: float) -> bool:
        """May a call proceed at ``now``?  Open→half-open happens here."""
        if self.state == BREAKER_OPEN:
            assert self.opened_at is not None
            if now >= self.opened_at + self.reset_timeout:
                self.state = BREAKER_HALF_OPEN  # one probe goes through
                return True
            return False
        return True  # closed or half-open (the probe itself)

    def seconds_until_probe(self, now: float) -> float:
        """Time until the next half-open probe (0 when calls may proceed)."""
        if self.state != BREAKER_OPEN or self.opened_at is None:
            return 0.0
        return max(0.0, self.opened_at + self.reset_timeout - now)

    def record_success(self) -> None:
        self.state = BREAKER_CLOSED
        self.failures = 0
        self.opened_at = None

    def record_failure(self, now: float) -> None:
        self.failures += 1
        if self.state == BREAKER_HALF_OPEN or (
            self.failures >= self.failure_threshold
        ):
            self.state = BREAKER_OPEN
            self.opened_at = now


class ResiliencePolicy:
    """One config + one clock, bound into the loop's two RPC seams.

    Owns the per-seam :class:`RetryPolicy` instances (independent seeded
    RNG streams so metric retries never perturb scaler jitter), the
    optional :class:`CircuitBreaker`, and the last-good-observation
    state behind the stale-depth hold.  Single-threaded by contract —
    it lives inside the loop's tick.
    """

    def __init__(
        self, config: ResilienceConfig, clock: Clock, poll_interval: float
    ) -> None:
        self.config = config
        self.clock = clock
        self.poll_interval = poll_interval
        self._metric_retry = RetryPolicy(
            config.metric_retries,
            base_delay=config.retry_base_delay,
            max_delay=config.retry_max_delay,
            jitter=config.retry_jitter,
            seed=config.retry_seed,
        )
        self._scaler_retry = RetryPolicy(
            config.scaler_retries,
            base_delay=config.retry_base_delay,
            max_delay=config.retry_max_delay,
            jitter=config.retry_jitter,
            seed=config.retry_seed + 1,
        )
        self.breaker = (
            CircuitBreaker(config.breaker_failures, config.breaker_reset)
            if config.breaker_failures > 0
            else None
        )
        self._last_good: tuple[float, int] | None = None  # (t, depth)

    @property
    def breaker_state(self) -> str | None:
        """Current breaker state name (``None`` when no breaker)."""
        return self.breaker.state if self.breaker is not None else None

    def _budget_deadline(self, tick_start: float) -> float:
        return tick_start + self.config.retry_budget_fraction * self.poll_interval

    def observe(self, fn, record) -> int:
        """One metric poll with retries + deadline; remembers the last
        good depth for the stale hold.  Retry attempts used (success or
        not) land on ``record.metric_retries``."""

        def note(extra: int) -> None:
            if extra:
                record.metric_retries = extra

        value, _ = self._metric_retry.run(
            fn,
            self.clock,
            timeout=self.config.metric_timeout,
            deadline=self._budget_deadline(record.start),
            on_attempts=note,
        )
        depth = int(value)
        self._last_good = (self.clock.now(), depth)
        return depth

    def stale_depth(self, now: float) -> tuple[int, float] | None:
        """``(depth, age_s)`` of a last good observation still inside the
        TTL, else ``None`` (fail static, the reference behavior)."""
        if self.config.stale_depth_ttl <= 0 or self._last_good is None:
            return None
        t, depth = self._last_good
        age = now - t
        if age > self.config.stale_depth_ttl:
            return None
        return depth, age

    def actuate(self, action, record) -> None:
        """One scaler call through the breaker, deadline, and retries.

        An open breaker raises :class:`CircuitOpenError` without touching
        the scaler (the loop's failed-actuation path handles it: log,
        end tick, cooldown untouched).  The breaker records the *final*
        outcome — retries within one tick are one verdict.
        """
        now = self.clock.now()
        if self.breaker is not None and not self.breaker.allow(now):
            raise CircuitOpenError(
                f"circuit breaker open after {self.breaker.failures} "
                f"consecutive scaler failures; next probe in "
                f"{self.breaker.seconds_until_probe(now):.1f}s"
            )
        base = record.scaler_retries or 0  # up + down share one ledger

        def note(extra: int) -> None:
            if base + extra:
                record.scaler_retries = base + extra

        try:
            self._scaler_retry.run(
                action,
                self.clock,
                timeout=self.config.scaler_timeout,
                deadline=self._budget_deadline(record.start),
                on_attempts=note,
            )
        except Exception:
            if self.breaker is not None:
                self.breaker.record_failure(self.clock.now())
            raise
        else:
            if self.breaker is not None:
                self.breaker.record_success()

    # ------------------------------------------------------------------
    # Durable-state surface (core/durable.py StateProvider): the breaker
    # and the stale-hold are exactly the control state a restart used to
    # zero — a crashed controller came back with a CLOSED breaker and
    # hammered the still-dead apiserver, and with no last-good depth to
    # hold through the outage that killed it.
    # ------------------------------------------------------------------

    def export_state(self) -> dict:
        state: dict = {"records": 0}
        if self._last_good is not None:
            t, depth = self._last_good
            state["last_good"] = {"t": t, "depth": depth}
            state["records"] += 1
        if self.breaker is not None:
            state["breaker"] = {
                "state": self.breaker.state,
                "failures": self.breaker.failures,
                "opened_at": self.breaker.opened_at,
            }
            state["records"] += 1
        return state

    def import_state(
        self, state: dict, *, rebase: float = 0.0,
        now: float | None = None, max_age_s: float = 0.0,
    ) -> int:
        """Restore the stale-hold observation and the breaker, every
        instant shifted by ``rebase`` — a held depth that aged past its
        TTL during the downtime expires through the ordinary
        :meth:`stale_depth` age check, and an open breaker whose reset
        window elapsed while the pod was down re-probes immediately
        through the ordinary :meth:`~CircuitBreaker.allow` check."""
        recovered = 0
        last_good = state.get("last_good")
        if isinstance(last_good, dict):
            try:
                t = float(last_good["t"]) + rebase
                depth = int(last_good["depth"])
            except (KeyError, TypeError, ValueError):
                pass
            else:
                self._last_good = (t, depth)
                recovered += 1
        saved = state.get("breaker")
        if self.breaker is not None and isinstance(saved, dict):
            name = saved.get("state")
            opened = saved.get("opened_at")
            if name == BREAKER_OPEN and opened is None:
                # an open breaker with no timestamp could never probe
                # again — refuse the record, keep the fresh closed
                # breaker (cold is safe; wedged-open forever is not)
                name = None
            if name in BREAKER_STATE_CODES:
                self.breaker.state = name
                self.breaker.failures = int(saved.get("failures", 0) or 0)
                self.breaker.opened_at = (
                    float(opened) + rebase if opened is not None else None
                )
                recovered += 1
        return recovered
