"""Structured tick events: the loop's observability seam.

The reference's only observability is logrus text lines at fixed decision
points (SURVEY.md §5 "Metrics / logging / observability — Logging only").
Those log lines are preserved verbatim in :mod:`.loop`; this module adds the
structured counterpart as an *extension*: the loop fills one
:class:`TickRecord` per tick and hands it to an optional
:class:`TickObserver`.  Consumers (the Prometheus registry in
:mod:`..obs.prometheus`, tests, traces) read the record; the loop itself
never depends on what observers do — an observer exception is logged and
swallowed so the loop's never-dies guarantee (``main.go:43-47``) extends to
instrumentation.

Lives in ``core`` (not ``obs``) so the layering stays one-directional:
``obs`` imports ``core``, never the reverse.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, fields
from typing import Any, Protocol, runtime_checkable

from .policy import Gate

log = logging.getLogger(__name__)


@dataclass
class TickRecord:
    """Everything that happened in one loop tick, as one value.

    Field semantics mirror the tick flow (``main.go:41-79``):

    - ``metric_error`` set ⇒ the tick ended at the observation
      (``num_messages`` is ``None`` and both gates stay ``SKIPPED``);
    - ``up``/``down`` are the gate outcomes actually evaluated this tick —
      ``down`` remains ``SKIPPED`` when the up gate was ``COOLING`` (the
      reference's ``continue`` at ``main.go:54``);
    - ``up_error``/``down_error`` set ⇒ the gate fired but actuation failed
      (the cooldown timestamp was *not* advanced);
    - ``decision_messages`` is the depth the gates actually thresholded on:
      equal to ``num_messages`` under the reactive policy, the forecasted
      depth under a :class:`~..core.types.DepthPolicy`;
    - ``predicted_messages``/``forecast_error`` are the depth policy's
      forecast scoreboard for this tick (``None`` when reactive or not yet
      warmed up / scored);
    - ``duration`` is measured on the loop's own clock, so it is virtual
      under a ``FakeClock`` and wall-clock in production;
    - ``observe_s``/``decide_s``/``actuate_s`` split ``duration`` into the
      tick's three phases (metric fetch / depth policy + gates / scaler
      RPCs) for the flight recorder's trace export — ``actuate_s`` stays
      ``None`` when no gate fired, ``decide_s`` when the tick ended at the
      observation.  All zero under a ``FakeClock``.

    Resilience extension fields (``core/resilience.py``; all ``None`` —
    and therefore absent from journal lines — unless the opt-in layer
    produced them):

    - ``stale`` is ``True`` when the poll failed but the tick proceeded
      on the last good depth within the stale TTL (``num_messages`` is
      that *held* depth, ``metric_error`` stays ``None`` so gate
      accounting and replay treat the tick as a normal observation;
      ``stale_age_s`` is the held observation's age);
    - ``metric_retries``/``scaler_retries`` count *extra* attempts the
      retry policy spent this tick (absent when the first try sufficed);
    - ``breaker_state`` is the circuit breaker's state after the tick
      (``closed``/``half_open``/``open``), present only when a breaker
      is configured.
    """

    start: float
    duration: float = 0.0
    num_messages: int | None = None
    metric_error: str | None = None
    decision_messages: int | None = None
    predicted_messages: int | None = None
    forecast_error: float | None = None
    up: Gate = Gate.SKIPPED
    down: Gate = Gate.SKIPPED
    up_error: str | None = None
    down_error: str | None = None
    observe_s: float | None = None
    decide_s: float | None = None
    actuate_s: float | None = None
    stale: bool | None = None
    stale_age_s: float | None = None
    metric_retries: int | None = None
    scaler_retries: int | None = None
    breaker_state: str | None = None

    def scaled(self, direction: str) -> bool:
        """Did this tick successfully actuate in ``direction`` ("up"/"down")?

        Mirrors the reference's "success" notion (``main.go:62,76``):
        the gate fired and the actuation call returned — including
        boundary no-ops, which count as success.
        """
        if direction == "up":
            return self.up is Gate.FIRE and self.up_error is None
        if direction == "down":
            return self.down is Gate.FIRE and self.down_error is None
        raise ValueError(f"direction must be 'up' or 'down', got {direction!r}")

    def to_dict(self) -> dict[str, Any]:
        """The record as one flat JSON-ready dict (the journal line format).

        ``None`` fields are omitted (journal lines stay lean; the reader
        restores dataclass defaults); :class:`~.policy.Gate` s serialize as
        their string values.
        """
        out: dict[str, Any] = {}
        for f in fields(self):
            value = getattr(self, f.name)
            if value is None:
                continue
            out[f.name] = value.value if isinstance(value, Gate) else value
        return out

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "TickRecord":
        """Inverse of :meth:`to_dict`.  Unknown keys are ignored so a newer
        journal (same schema version, added fields) still loads."""
        known = {f.name for f in fields(cls)}
        kwargs = {k: v for k, v in data.items() if k in known}
        for gate_field in ("up", "down"):
            if gate_field in kwargs:
                kwargs[gate_field] = Gate(kwargs[gate_field])
        return cls(**kwargs)


@runtime_checkable
class TickObserver(Protocol):
    """Anything that wants the per-tick record."""

    def on_tick(self, record: TickRecord) -> None:
        """Called once per completed tick, after all tick side effects."""
        ...


class CompositeTickObserver:
    """Fans one tick record out to several observers.

    Lets the loop feed the Prometheus registry *and* a forecast history
    (and tests) from its single observer slot.  Failure isolation matches
    the loop's own observer contract: one observer raising is logged and
    must not starve the others, so each child is guarded individually.
    """

    def __init__(self, observers: list[TickObserver] | tuple[TickObserver, ...]):
        self.observers = tuple(observers)

    def on_tick(self, record: TickRecord) -> None:
        for observer in self.observers:
            try:
                observer.on_tick(record)
            except Exception:  # same never-dies guarantee as the loop's guard
                log.exception("Tick observer %r failed", observer)


# The fan-out under its observability name: the CLI wires Prometheus +
# flight-recorder ring + journal through one of these.
MultiObserver = CompositeTickObserver
