"""Injectable clock.

The reference couples its loop directly to ``time.Now()``/``time.Sleep``
(``main.go:37-41``), which forces its integration tests to burn ~56 s of real
wall time (SURVEY.md §4, §6).  Here every time-dependent component takes a
``Clock`` so the same behavioral scenarios run deterministically: the
production :class:`SystemClock` wraps the monotonic clock, and
:class:`FakeClock` advances virtual time on ``sleep`` and fires scheduled
callbacks — the deterministic analogue of the reference tests mutating the
mock queue from the test goroutine mid-run (``main_test.go:46-49``).
"""

from __future__ import annotations

import heapq
import itertools
import time
from typing import Callable, Protocol, runtime_checkable


@runtime_checkable
class Clock(Protocol):
    """Minimal clock surface the framework needs: read time, block for time."""

    def now(self) -> float:
        """Current time in seconds. Only differences are meaningful."""
        ...

    def sleep(self, seconds: float) -> None:
        """Block for ``seconds`` (virtual or real)."""
        ...


class SystemClock:
    """Real clock: monotonic ``now`` (immune to wall-clock steps), real sleep."""

    def now(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)


class FakeClock:
    """Deterministic virtual clock for tests and simulation.

    ``sleep`` advances virtual time instantly, firing any callbacks scheduled
    via :meth:`at` / :meth:`after` in timestamp order as the clock passes
    them.  Callbacks run with the clock set to their scheduled instant, so a
    scenario like "the queue drains at t=7s" is exact rather than racy.
    """

    def __init__(self, start: float = 0.0) -> None:
        self._now = float(start)
        self._events: list[tuple[float, int, Callable[[], None]]] = []
        self._counter = itertools.count()  # FIFO tie-break for equal times
        self.sleeps: list[float] = []  # record of requested sleeps (for tests)

    def now(self) -> float:
        return self._now

    def at(self, when: float, callback: Callable[[], None]) -> None:
        """Schedule ``callback`` to fire when virtual time reaches ``when``.

        Scheduling in the past fires on the next advance.
        """
        heapq.heappush(self._events, (float(when), next(self._counter), callback))

    def after(self, delay: float, callback: Callable[[], None]) -> None:
        """Schedule ``callback`` ``delay`` seconds from the current instant."""
        self.at(self._now + delay, callback)

    def sleep(self, seconds: float) -> None:
        self.sleeps.append(seconds)
        self.advance(max(0.0, seconds))

    def advance(self, seconds: float) -> None:
        """Move virtual time forward, firing due events in order."""
        deadline = self._now + float(seconds)
        while self._events and self._events[0][0] <= deadline:
            when, _, callback = heapq.heappop(self._events)
            self._now = max(self._now, when)
            callback()
        self._now = deadline
