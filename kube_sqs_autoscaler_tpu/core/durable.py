"""Durable control-plane state: the controller is a failure domain.

The reference deployment "relies on Kubernetes restarting a crashed
controller pod" (obs/server.py) — and a restart of *this* controller
used to silently lose every piece of accumulated control state: cooldown
stamps, circuit-breaker state, forecaster history, the learned policy's
replica/cooldown mirror, the fleet's exactly-once reply registry, the
DRR/EDF accounting and flood classifications that make fair queueing
work, and the overload-ladder tier.  This module makes all of it a
*snapshot*: one crash-safe, schema-versioned JSON file the loop rewrites
atomically each tick, plus a startup **rehydration** path that restores
what is still true and discards what is not.

Design rules (each one is load-bearing):

- **atomic write-rename** — the snapshot is written to ``<path>.tmp``,
  flushed, fsynced, then ``os.replace``d over the live file, so a crash
  mid-write can never tear the snapshot a restart will read (the tmp
  file tears instead, and is simply overwritten next tick).  The
  *reader* is still torn-write tolerant like the journal reader: a
  truncated, corrupt, wrong-kind, hash-mismatched, or future-schema
  file is a **cold start with a logged reason — never a crash loop**.
- **time is rebased, never trusted** — the loop's clock is monotonic
  and restarts with the process, so raw clock values in a snapshot are
  meaningless to the next boot.  Every saved instant is shifted by
  ``rebase = (now - downtime) - saved_clock``, where ``downtime`` is
  measured on the **wall clock** carried in the snapshot.  A cooldown
  that had 12 s left keeps exactly 12 s minus the downtime; a breaker
  opened 40 s ago stays open for the remainder of its reset window.
- **expire by wall-clock age** (kube-controller style) — each
  registered section carries a TTL; a snapshot older than a section's
  TTL expires that section (counted, surfaced as
  ``state_records_expired``), and a snapshot older than
  ``max_age_s`` cold-starts entirely.  Stale memory is worse than no
  memory.
- **trust the observed world over the remembered one** — after the
  sections import, providers exposing ``reconcile_observed`` are handed
  the *actual* replica count read through the Scaler seam; the learned
  policy's mirror adopts it instead of its remembered trajectory.
- **journal-tail rehydration** — the snapshot is written *after* the
  tick's journal line, so the journal can be one tick ahead (snapshot
  write failed, or the crash tore exactly between them).  Rehydration
  re-drives the tail records (rebased) through every provider's
  ``on_tick`` and advances the restored cooldown stamps for any
  actuation the tail proves happened.
- **write-ahead actuation intent** — the dangerous crash window is
  *after the scaler RPC, before anything durable recorded it*: a warm
  restart that restored the pre-actuation stamp would re-fire inside
  the cooldown (the double-scale the reference's cold restart is
  accidentally immune to, because startup grace over-cools).  The loop
  therefore journals an **intent** (direction + instant, its own tiny
  atomic file) *before* every scaler call; the snapshot that covers
  the completed tick clears it.  Rehydration treats an unresolved
  intent as "may have actuated": the matching cooldown stamp advances
  to the intent instant.  Pessimistic by design — a crash after a
  *failed* actuation costs one skipped window, never a double-scale.

Providers implement the :class:`StateProvider` protocol —
``export_state()`` returning a JSON-able dict with a ``"records"``
count, ``import_state(state, rebase=, now=, max_age_s=)`` returning how
many records were restored.  Wire-ups live with the subsystems
(``core/resilience.py``, ``forecast/history.py``, ``learn/policy.py``,
``fleet/pool.py``/``sharded.py``, ``workloads/tenancy.py``,
``sched/knobs.py``, ``planes/pool.py`` — the disaggregated pool's
section, :data:`~..planes.pool.DISAGG_SECTION`, carries the shared
reply registry plus the plane-mode bit a restart must not forget:
whether measured economics had speculative drafting on — and
``obs/lifecycle.py``, whose ``request_trace`` section rides open
request traces across the restart so the phase chain of an in-flight
request survives the controller dying mid-decode: the rehydrated
registry bumps its flow-id epoch, so re-stamped phases never collide
with the pre-crash Perfetto flow).

Runnable as ``python -m kube_sqs_autoscaler_tpu.core.durable`` — the
``make restart-demo`` gate: a JAX-free FakeClock kill→restart→reconcile
walkthrough asserting every rehydration milestone (snapshot-per-tick,
warm stamps, breaker survival, intent pessimism, corrupt/future-schema
fallback), exit 2 on any missing milestone.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Protocol, runtime_checkable

from .policy import PolicyState, initial_state

log = logging.getLogger(__name__)

#: Bump on any backward-incompatible change to the snapshot body.  The
#: reader refuses a mismatched snapshot by COLD-STARTING (never by
#: crashing): a rolled-back controller reading a newer build's state
#: must degrade to the reference behavior, not crash-loop the pod.
SNAPSHOT_SCHEMA_VERSION = 1

_SNAPSHOT_KIND = "control-plane-snapshot"
_INTENT_KIND = "actuation-intent"


class ControllerCrash(BaseException):
    """A simulated kill of the controller process (crash injection).

    Derives from ``BaseException`` on purpose: the loop's never-dies
    guards catch ``Exception`` only, so a crash injected at any seam
    propagates instantly — no retry, no stale hold, no observer, no
    snapshot — exactly like the process vanishing at that instant.
    """


@runtime_checkable
class StateProvider(Protocol):
    """One subsystem's durable-state surface."""

    def export_state(self) -> dict:
        """The subsystem's state as a JSON-able dict (``"records"``
        counts the restorable units inside, for recovery accounting)."""
        ...

    def import_state(
        self, state: dict, *, rebase: float = 0.0,
        now: float | None = None, max_age_s: float = 0.0,
    ) -> int:
        """Restore from an exported dict; every saved clock instant
        shifts by ``rebase``.  Returns records actually restored
        (a provider may drop internally-expired ones)."""
        ...


@dataclass(frozen=True)
class _StoreEvent:
    """Restart/rehydrate instant for the Chrome-trace timeline (shaped
    like a :class:`~..fleet.pool.FleetEvent`; ``restart-*`` names land
    in their own trace category)."""

    name: str
    t: float
    args: dict = field(default_factory=dict)


@dataclass
class RehydrationReport:
    """What one startup recovered, expired, and refused."""

    cold_start: bool
    reason: str | None = None
    downtime_s: float = 0.0
    snapshot_age_s: float = 0.0
    records_recovered: int = 0
    records_expired: int = 0
    sections_recovered: list[str] = field(default_factory=list)
    sections_expired: list[str] = field(default_factory=list)
    snapshot_hash: str | None = None
    restarts: int = 0
    journal_tail_ticks: int = 0
    intent_applied: str | None = None
    duration_s: float = 0.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _content_hash(body: dict) -> str:
    """sha256 of the canonical body (hash key excluded) — names exactly
    which state survived, for the journal restart header and the gates."""
    scrubbed = {k: v for k, v in body.items() if k != "hash"}
    canonical = json.dumps(scrubbed, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canonical.encode("utf-8")).hexdigest()[:16]


def _atomic_write(path: str, text: str) -> None:
    """write → flush → fsync → rename: the snapshot is either the old
    complete file or the new complete file, never a tear."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(text)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


class DurableStateStore:
    """The controller's crash-safe memory: one snapshot file, rewritten
    atomically each tick; one rehydration at boot.

    ``wall_clock`` measures downtime across restarts (``time.time`` in
    production; a ``FakeClock.now`` in deterministic tests — the two
    processes of a restart must share it, exactly like SentTimestamp).
    ``max_age_s`` > 0 cold-starts when the snapshot is older than that
    (a controller down for an hour should not resurrect hour-old
    cooldowns as if they were news).  Providers register with
    :meth:`register`; order is preserved (export and import run in
    registration order).
    """

    def __init__(
        self,
        path: str,
        *,
        wall_clock: Callable[[], float] | None = None,
        max_age_s: float = 0.0,
        journal_path: str | None = None,
    ) -> None:
        if not path:
            raise ValueError("the durable store needs a snapshot path")
        if max_age_s < 0:
            raise ValueError(f"max_age_s must be >= 0, got {max_age_s}")
        self.path = path
        self.wall_clock = wall_clock or time.time
        self.max_age_s = max_age_s
        self.journal_path = journal_path
        self._providers: dict[str, tuple[Any, float | None]] = {}
        self.snapshots_written = 0
        self.snapshot_hash: str | None = None
        self.restarts = 0  # restored from the snapshot chain at rehydrate
        self.last_report: RehydrationReport | None = None
        self._restored_policy: PolicyState | None = None
        self._rehydrated = False
        self.metrics = None  # optional ControllerMetrics sink
        self.events: deque[_StoreEvent] = deque(maxlen=256)

    # ------------------------------------------------------------------
    # Registration
    # ------------------------------------------------------------------

    def register(
        self, name: str, provider: Any, ttl_s: float | None = None
    ) -> None:
        """Register one named section.  ``ttl_s`` is the section's
        wall-clock expiry: a snapshot older than it restores nothing
        for this section (``None`` = never expires)."""
        if name in self._providers:
            raise ValueError(f"duplicate durable section {name!r}")
        if ttl_s is not None and ttl_s < 0:
            raise ValueError(f"ttl_s must be >= 0, got {ttl_s}")
        self._providers[name] = (provider, ttl_s)

    # ------------------------------------------------------------------
    # Snapshot (the per-tick write)
    # ------------------------------------------------------------------

    def snapshot(
        self,
        *,
        clock_now: float,
        policy_state: PolicyState,
        ticks: int = 0,
        last_tick_start: float | None = None,
    ) -> None:
        """Serialize the whole control plane and atomically replace the
        snapshot file.  Also clears any resolved actuation intent: the
        snapshot covers the tick the intent belonged to."""
        sections = {}
        for name, (provider, _ttl) in self._providers.items():
            try:
                sections[name] = provider.export_state()
            except Exception:
                # one broken exporter must not cost the others their
                # durability (and must never kill the loop)
                log.exception("durable section %r export failed", name)
        body = {
            "kind": _SNAPSHOT_KIND,
            "schema": SNAPSHOT_SCHEMA_VERSION,
            "saved_wall": self.wall_clock(),
            "saved_clock": clock_now,
            "ticks": ticks,
            "restarts": self.restarts,
            "policy": {
                "last_scale_up": policy_state.last_scale_up,
                "last_scale_down": policy_state.last_scale_down,
            },
            "last_tick_start": (
                clock_now if last_tick_start is None else last_tick_start
            ),
            "sections": sections,
        }
        body["hash"] = _content_hash(body)
        _atomic_write(self.path, json.dumps(body, separators=(",", ":")))
        self.snapshot_hash = body["hash"]
        self.snapshots_written += 1
        self._clear_intent()

    # ------------------------------------------------------------------
    # Write-ahead actuation intent
    # ------------------------------------------------------------------

    @property
    def intent_path(self) -> str:
        return self.path + ".intent"

    def note_intent(self, direction: str, clock_now: float) -> None:
        """Record "about to actuate ``direction``" durably, BEFORE the
        scaler RPC.  Rehydration treats an unresolved intent as "may
        have actuated" and advances the matching cooldown stamp — the
        pessimism that makes the after-actuate-before-journal crash
        window double-scale-proof."""
        if direction not in ("up", "down"):
            raise ValueError(f"direction must be 'up'/'down', got {direction!r}")
        body = {
            "kind": _INTENT_KIND,
            "direction": direction,
            "clock": clock_now,
            "wall": self.wall_clock(),
        }
        _atomic_write(self.intent_path, json.dumps(body))

    def _clear_intent(self) -> None:
        try:
            os.remove(self.intent_path)
        except FileNotFoundError:
            pass
        except OSError:
            # a stale intent is conservative (one skipped window), a
            # dead loop is not — never raise out of the snapshot path
            log.exception("could not clear actuation intent")

    def _load_intent(self, saved_wall: float) -> dict | None:
        """The unresolved intent, if one outlives the snapshot (a
        resolved intent is removed by :meth:`snapshot`; the wall-clock
        comparison is belt and braces for a failed removal)."""
        try:
            with open(self.intent_path, "r", encoding="utf-8") as fh:
                body = json.load(fh)
        except (OSError, ValueError):
            return None
        if not isinstance(body, dict) or body.get("kind") != _INTENT_KIND:
            return None
        if body.get("direction") not in ("up", "down"):
            return None
        try:
            wall, clock = float(body["wall"]), float(body["clock"])
        except (KeyError, TypeError, ValueError):
            return None
        if wall < saved_wall:
            return None  # older than the snapshot: already resolved
        return {"direction": body["direction"], "clock": clock, "wall": wall}

    # ------------------------------------------------------------------
    # Load + rehydrate
    # ------------------------------------------------------------------

    def _load(self) -> tuple[dict | None, str | None]:
        """``(body, refusal_reason)`` — a missing/torn/corrupt/foreign
        snapshot returns ``(None, reason)``; this method never raises."""
        try:
            with open(self.path, "r", encoding="utf-8") as fh:
                raw = fh.read()
        except FileNotFoundError:
            return None, None  # first boot: silent cold start
        except OSError as err:
            return None, f"snapshot unreadable: {err}"
        try:
            body = json.loads(raw)
        except ValueError:
            return None, "snapshot corrupt (not valid JSON — torn write?)"
        if not isinstance(body, dict) or body.get("kind") != _SNAPSHOT_KIND:
            return None, "snapshot is not a control-plane snapshot"
        schema = body.get("schema")
        if schema != SNAPSHOT_SCHEMA_VERSION:
            return None, (
                f"snapshot schema {schema!r} unsupported (this build "
                f"reads {SNAPSHOT_SCHEMA_VERSION}) — refusing a foreign "
                "build's state"
            )
        if body.get("hash") != _content_hash(body):
            return None, "snapshot content hash mismatch (corrupt)"
        return body, None

    def rehydrate(
        self,
        clock_now: float,
        *,
        observed_replicas: int | None = None,
    ) -> RehydrationReport:
        """Restore the control plane from snapshot + journal tail.

        Idempotent per store instance (one boot rehydrates once).  On
        ANY refusal the report says why and the loop cold-starts with
        the reference's ``initial_state`` grace — rehydration must
        never be able to crash-loop the controller.
        """
        if self._rehydrated:
            assert self.last_report is not None
            return self.last_report
        self._rehydrated = True
        if self.metrics is not None:
            begin = getattr(self.metrics, "begin_rehydration", None)
            if begin is not None:
                begin()
        started = time.perf_counter()
        self._event("restart-detected", clock_now)
        body, reason = self._load()
        restart_seen = body is not None or reason is not None
        if body is None:
            # a refused file is still a restart (the pod DID come back):
            # the chain stays monotone in the snapshots this boot writes,
            # even though the corrupt predecessor's count is unreadable
            self.restarts = 1 if restart_seen else 0
            report = RehydrationReport(
                cold_start=True, reason=reason, restarts=self.restarts,
            )
            if reason is not None:
                log.warning("Cold start: %s", reason)
            return self._finish(report, clock_now, started)
        downtime = max(0.0, self.wall_clock() - float(body["saved_wall"]))
        self.restarts = int(body.get("restarts", 0)) + 1
        if self.max_age_s and downtime > self.max_age_s:
            report = RehydrationReport(
                cold_start=True,
                reason=(
                    f"snapshot is {downtime:.0f}s old, past the "
                    f"{self.max_age_s:g}s limit — stale memory is worse "
                    "than no memory"
                ),
                downtime_s=downtime, snapshot_age_s=downtime,
                snapshot_hash=body.get("hash"), restarts=self.restarts,
            )
            log.warning("Cold start: %s", report.reason)
            return self._finish(report, clock_now, started)

        rebase = (clock_now - downtime) - float(body["saved_clock"])
        report = RehydrationReport(
            cold_start=False, downtime_s=downtime,
            snapshot_age_s=downtime, snapshot_hash=body.get("hash"),
            restarts=self.restarts,
        )
        sections = body.get("sections") or {}
        for name, (provider, ttl) in self._providers.items():
            section = sections.get(name)
            if not isinstance(section, dict):
                continue
            declared = int(section.get("records", 0) or 0)
            if ttl is not None and downtime > ttl:
                report.records_expired += declared
                report.sections_expired.append(name)
                continue
            try:
                recovered = int(provider.import_state(
                    section, rebase=rebase, now=clock_now,
                    max_age_s=ttl or 0.0,
                ))
            except Exception:
                log.exception("durable section %r import failed", name)
                report.records_expired += declared
                report.sections_expired.append(name)
                continue
            report.records_recovered += recovered
            report.records_expired += max(0, declared - recovered)
            report.sections_recovered.append(name)

        # cooldown stamps, rebased onto this boot's clock
        policy = body.get("policy") or {}
        try:
            state = PolicyState(
                last_scale_up=float(policy["last_scale_up"]) + rebase,
                last_scale_down=float(policy["last_scale_down"]) + rebase,
            )
        except (KeyError, TypeError, ValueError):
            state = initial_state(clock_now)

        # journal tail: ticks the journal recorded after the snapshot's
        # last covered tick (the crash windows between journal line and
        # snapshot write) — re-driven through every provider's on_tick
        last_covered = float(body.get("last_tick_start", body["saved_clock"]))
        state, tail = self._replay_journal_tail(state, last_covered, rebase)
        report.journal_tail_ticks = tail

        # unresolved write-ahead intent: assume the RPC landed
        intent = self._load_intent(float(body["saved_wall"]))
        if intent is not None:
            stamp = intent["clock"] + rebase
            if intent["direction"] == "up":
                state = dataclasses.replace(
                    state, last_scale_up=max(state.last_scale_up, stamp)
                )
            else:
                state = dataclasses.replace(
                    state, last_scale_down=max(state.last_scale_down, stamp)
                )
            report.intent_applied = intent["direction"]
            log.warning(
                "Unresolved scale-%s intent from the crashed boot: "
                "assuming it actuated (cooldown stamp advanced — "
                "pessimistic, never double-scales)", intent["direction"],
            )
        # The intent is NOT cleared here: the advanced stamp only
        # becomes durable at this boot's first snapshot, and a second
        # crash before that tick must find the intent again (clearing
        # now would re-open the exact double-scale window it closes).
        # snapshot() clears it once a covering snapshot exists, and the
        # wall-clock guard in _load_intent makes any leftover a no-op.

        # the observed world outranks the remembered one
        if observed_replicas is not None:
            for name, (provider, _ttl) in self._providers.items():
                reconcile = getattr(provider, "reconcile_observed", None)
                if reconcile is not None:
                    try:
                        reconcile(int(observed_replicas))
                    except Exception:
                        log.exception("durable section %r reconcile failed",
                                      name)

        self._restored_policy = state
        log.info(
            "Warm restart: recovered %d record(s) across %s, expired %d, "
            "downtime %.1fs, %d journal-tail tick(s)",
            report.records_recovered, report.sections_recovered or "nothing",
            report.records_expired, downtime, tail,
        )
        return self._finish(report, clock_now, started)

    def _replay_journal_tail(
        self, state: PolicyState, last_covered: float, rebase: float
    ) -> tuple[PolicyState, int]:
        """Re-drive post-snapshot journal records (rebased) through the
        providers and the cooldown stamps.  Only the crashed boot's
        episode is in the snapshot's clock domain, so the tail is the
        journal's newest non-empty boot (rotation continuations
        included, restart headers excluded)."""
        if not self.journal_path:
            return state, 0
        # Deferred, optional use of the obs layer: the reader is only
        # needed when a journal is actually configured, and importing it
        # lazily keeps the core package import-free of obs at module
        # load (obs imports core at module level; this must not cycle).
        try:
            from ..obs.journal import read_journal_episodes

            episodes = read_journal_episodes(self.journal_path)
        except Exception:
            return state, 0  # no journal / unreadable: nothing to stitch
        # newest boot = trailing continuation episodes plus the first
        # non-continuation episode under them, skipping empty trailers
        boot: list = []
        for meta, records in reversed(episodes):
            if not records and not boot:
                continue
            boot = list(records) + boot
            if not meta.get("_continuation"):
                break
        applied = 0
        for record in boot:
            if record.start <= last_covered + 1e-9:
                continue
            rebased = dataclasses.replace(
                record, start=record.start + rebase
            )
            applied += 1
            if rebased.scaled("up"):
                state = dataclasses.replace(
                    state,
                    last_scale_up=max(state.last_scale_up, rebased.start),
                )
            if rebased.scaled("down"):
                state = dataclasses.replace(
                    state,
                    last_scale_down=max(state.last_scale_down, rebased.start),
                )
            for _name, (provider, _ttl) in self._providers.items():
                on_tick = getattr(provider, "on_tick", None)
                if on_tick is not None:
                    try:
                        on_tick(rebased)
                    except Exception:
                        log.exception("journal-tail replay failed for %r",
                                      _name)
        return state, applied

    def _finish(
        self, report: RehydrationReport, clock_now: float, started: float
    ) -> RehydrationReport:
        report.duration_s = time.perf_counter() - started
        self.last_report = report
        self._event(
            "restart-rehydrated", clock_now,
            cold_start=report.cold_start,
            recovered=report.records_recovered,
            expired=report.records_expired,
            snapshot_hash=report.snapshot_hash,
        )
        if self.metrics is not None:
            sink = getattr(self.metrics, "set_rehydration", None)
            if sink is not None:
                try:
                    sink(report)
                except Exception:
                    log.exception("rehydration metrics export failed")
        return report

    def restored_policy_state(self) -> PolicyState | None:
        """The rebased cooldown stamps (``None`` = cold start)."""
        return self._restored_policy

    def take_restored_policy_state(self) -> PolicyState | None:
        """Consume the restored stamps (one episode gets them).  A
        SECOND ``run()`` on the same loop is a fresh episode by the
        loop's contract — it must get the reference startup grace, not
        the boot-time stamps re-applied over whatever the first episode
        actuated."""
        state, self._restored_policy = self._restored_policy, None
        return state

    def restart_journal_meta(self) -> dict:
        """The restart block for a freshly-reopened journal's header:
        which snapshot the new boot rose from and how much state
        actually survived — ``sim.replay.stitch_restart_episodes``
        pairs it with the pre-crash episode."""
        report = self.last_report
        if report is None:
            return {}
        return {
            "snapshot_hash": report.snapshot_hash,
            "records_recovered": report.records_recovered,
            "records_expired": report.records_expired,
            "cold_start": report.cold_start,
            "restarts": report.restarts,
            "downtime_s": round(report.downtime_s, 3),
        }

    def journal_meta_after_rehydrate(
        self,
        clock_now: float,
        meta: dict,
        *,
        observed_replicas: int | None = None,
    ) -> dict:
        """Rehydrate (idempotent), then return ``meta`` with the
        restart block stamped in — the ONE correct ordering for a boot
        that records a journal: rehydration must run BEFORE the journal
        reopens on ``journal_path`` (the tail replay reads the
        pre-crash file state, and the fresh header must carry the
        restart block), so this helper makes the ordering uninvertible
        at every call site."""
        self.rehydrate(clock_now, observed_replicas=observed_replicas)
        restart = self.restart_journal_meta()
        return {**meta, "restart": restart} if restart else dict(meta)

    def _event(self, name: str, t: float, **args) -> None:
        self.events.append(_StoreEvent(name, t, args))


# ---------------------------------------------------------------------------
# make restart-demo: a JAX-free FakeClock kill → restart → reconcile
# walkthrough (the chaos-demo / fleet-demo contract: exit 2 on any
# missing milestone).
# ---------------------------------------------------------------------------


def _demo() -> tuple[dict, list[str]]:
    import tempfile

    # `python -m ...core.durable` runs this module as __main__, so the
    # module-level ControllerCrash here is a DIFFERENT class object from
    # the canonical one the loop raises — catch the canonical one.
    from ..core.durable import ControllerCrash as CanonicalCrash
    from ..core.clock import FakeClock
    from ..core.loop import ControlLoop, LoopConfig
    from ..core.policy import PolicyConfig
    from ..core.resilience import ResilienceConfig
    from ..forecast.history import DepthHistory
    from ..metrics.fake import FakeQueueService
    from ..metrics.queue import QueueMetricSource
    from ..scale.actuator import PodAutoScaler
    from ..scale.fake import FakeDeploymentAPI, RecordingDeploymentAPI

    problems: list[str] = []

    def expect(condition: bool, message: str) -> None:
        if not condition:
            problems.append(message)

    tmp = tempfile.mkdtemp(prefix="restart-demo-")
    path = os.path.join(tmp, "controller.state")
    # ONE FakeClock plays both the loop clock and the restart wall clock
    # (like SentTimestamp, the two boots of a restart must share the
    # wall-clock base); since virtual time never resets across the
    # demo's "boots", the rebase is zero and stamps stay absolute —
    # the monotonic-reset arithmetic is pinned by tests/test_durable.py.
    clock = FakeClock()
    queue = FakeQueueService.with_depths(5000)  # permanent overload
    api = RecordingDeploymentAPI(
        FakeDeploymentAPI.with_deployments("default", 1, "workers"), clock
    )
    scale_times = api.scale_times
    policy = PolicyConfig(
        scale_up_messages=100, scale_down_messages=1,
        scale_up_cooldown=30.0, scale_down_cooldown=60.0,
    )

    def build():
        store = DurableStateStore(path, wall_clock=clock.now)
        history = DepthHistory(capacity=32)
        store.register("forecast-history", history, ttl_s=600.0)
        scaler = PodAutoScaler(
            client=api, max=10, min=1, scale_up_pods=1,
            scale_down_pods=1, deployment="workers", namespace="default",
        )
        loop = ControlLoop(
            scaler,
            QueueMetricSource(queue, "demo://queue",
                              ("ApproximateNumberOfMessages",)),
            LoopConfig(poll_interval=5.0, policy=policy),
            clock=clock,
            observer=history,
            resilience=ResilienceConfig(
                breaker_failures=2, breaker_reset=40.0,
            ),
            durable=store,
        )
        store.register("resilience", loop.resilience, ttl_s=600.0)
        return loop, store, history

    # --- boot 1: run to the first scale-up, snapshotting every tick ---
    loop, store, history = build()
    state = loop.initial_policy_state()
    expect(store.last_report is not None and store.last_report.cold_start,
           "first boot did not report a (silent) cold start")
    first_fire = None
    for _ in range(8):  # ticks at t=5..40; startup grace ends at 30
        clock.advance(5.0)
        state = loop.tick(state)
        if first_fire is None and scale_times:
            first_fire = scale_times[-1][0]
    boot1_snapshots = store.snapshots_written
    expect(boot1_snapshots >= 8, "the loop did not snapshot every tick")
    expect(first_fire == 30.0,
           f"expected the startup-grace fire at t=30, got {first_fire}")
    pre_crash_len = len(history)

    # --- crash 1: after-actuate-before-journal at the next fire ------
    # t=60 is the next eligible fire (30 + 30s cooldown, boundary fires).
    from ..sim.faults import CRASH_AFTER_ACTUATE, CrashingScaler, CrashPlan

    plan = CrashPlan(crashes=((0, CRASH_AFTER_ACTUATE),))
    loop.scaler = CrashingScaler(loop.scaler, plan, lambda: 0)
    crashed = False
    while clock.now() < 60.0 and not crashed:
        clock.advance(5.0)
        try:
            state = loop.tick(state)
        except CanonicalCrash:
            crashed = True
    expect(crashed, "the after-actuate crash never fired")
    expect(bool(scale_times) and scale_times[-1] == (60.0, 3),
           f"expected the crash tick to actuate to 3 replicas at t=60, "
           f"got {scale_times[-1] if scale_times else None}")
    expect(os.path.exists(path + ".intent"),
           "no write-ahead intent survived the crash")

    # --- boot 2: warm restart after 15s of downtime ------------------
    clock.advance(15.0)
    loop, store, history = build()
    state = loop.initial_policy_state()
    report = store.last_report
    expect(report is not None and not report.cold_start,
           "boot 2 cold-started despite a healthy snapshot")
    expect(report.records_recovered >= pre_crash_len,
           f"recovered {report.records_recovered} record(s), expected "
           f">= {pre_crash_len} (the forecaster ring)")
    expect(report.intent_applied == "up",
           "the unresolved scale-up intent was not applied")
    expect(len(history) >= pre_crash_len,
           "the forecaster history did not survive the restart")
    # cooldown honored ACROSS the gap: the crashed boot actuated at
    # t=60 (recorded nowhere but the intent), so no fire before t=90 —
    # and warm restart fires exactly there, not at restart + cooldown
    # (the cold restart's over-cooling).
    fires_before = len(scale_times)
    while clock.now() < 110.0:
        clock.advance(5.0)
        state = loop.tick(state)
    new_fires = scale_times[fires_before:]
    expect(bool(new_fires), "no post-restart scale-up at all")
    if new_fires:
        expect(new_fires[0][0] == 90.0,
               f"expected the first post-restart fire at t=90 "
               f"(crash-tick stamp 60 + 30s cooldown), got "
               f"{new_fires[0][0]}")
    ups = [t for t, _ in scale_times]
    gaps = [b - a for a, b in zip(ups, ups[1:])]
    expect(all(g >= 30.0 - 1e-9 for g in gaps),
           f"a scale-up fired inside the cooldown across the restart "
           f"(gaps {gaps})")

    # --- crash 2: an OPEN breaker must survive a restart -------------
    api.fail = True
    for _ in range(4):  # t=115 cooling, 120 fail, 125 fail→open, 130 fast
        clock.advance(5.0)
        state = loop.tick(state)
    expect(loop.resilience.breaker_state == "open",
           "the breaker never opened under scaler failures")
    opened_at = loop.resilience.breaker.opened_at
    clock.advance(5.0)
    state = loop.tick(state)  # t=135: snapshot the open breaker
    attempts_before = len(api.update_attempts)
    clock.advance(10.0)  # downtime, inside the 40s reset window
    loop, store, history = build()
    state = loop.initial_policy_state()
    expect(loop.resilience.breaker_state == "open",
           "the restarted breaker forgot it was open")
    restored_open = loop.resilience.breaker.opened_at
    expect(restored_open is not None and opened_at is not None
           and abs(restored_open - opened_at) < 1e-6,
           "the breaker's opened_at did not survive the restart")
    # while open, gate fires must not reach the apiserver
    for _ in range(2):  # t=150, 155 — probe not due until 165
        clock.advance(5.0)
        state = loop.tick(state)
    expect(len(api.update_attempts) == attempts_before,
           "an open breaker let a scaler RPC through after restart")
    api.fail = False

    # --- corrupt + future-schema snapshots must cold-start -----------
    with open(path, "w", encoding="utf-8") as fh:
        fh.write('{"kind": "control-plane-snapshot", "schema": 1, "torn')
    loop, store, _ = build()
    loop.initial_policy_state()
    expect(store.last_report.cold_start
           and "corrupt" in (store.last_report.reason or ""),
           "a torn snapshot did not fall back to cold start")
    future = {"kind": _SNAPSHOT_KIND, "schema": SNAPSHOT_SCHEMA_VERSION + 7,
              "saved_wall": clock.now(), "saved_clock": clock.now(),
              "policy": {}, "sections": {}}
    future["hash"] = _content_hash(future)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(future, fh)
    loop, store, _ = build()
    loop.initial_policy_state()
    expect(store.last_report.cold_start
           and "schema" in (store.last_report.reason or ""),
           "a future-schema snapshot did not fall back to cold start")
    expect(bool(store.events), "the store produced no restart trace instants")

    summary = {
        "scale_times": scale_times,
        "boot1_snapshots_written": boot1_snapshots,
        "cooldown_gaps": gaps,
        "ok": not problems,
    }
    return summary, problems


def main(argv=None) -> int:
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        description="Deterministic restart episode: snapshot-per-tick, "
        "crash, warm rehydration, cooldown/breaker honored across the "
        "gap, corrupt/future-schema fallback — fails on any missing "
        "milestone."
    )
    parser.parse_args(argv)
    summary, problems = _demo()
    print(json.dumps(summary))
    for line in problems:
        print(f"unexpected trajectory: {line}", file=sys.stderr)
    return 0 if not problems else 2


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
