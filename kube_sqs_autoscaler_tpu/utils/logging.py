"""Logging setup: logrus-like leveled text output.

The reference logs through logrus's default text formatter
(``main.go:13``, ``scale/scale.go:9``), e.g.::

    time="2016-01-02T15:04:05Z" level=info msg="Found 30 messages in the queue"

This configures stdlib logging to emit the same shape so operators migrating
from the reference can keep their log scrapers.
"""

from __future__ import annotations

import logging
import time


class LogrusTextFormatter(logging.Formatter):
    """``time="…" level=… msg="…"`` text format (logrus TextFormatter)."""

    def format(self, record: logging.LogRecord) -> str:
        stamp = time.strftime("%Y-%m-%dT%H:%M:%S%z", time.localtime(record.created))
        message = record.getMessage().replace('"', '\\"')
        line = f'time="{stamp}" level={record.levelname.lower()} msg="{message}"'
        if record.exc_info:
            line += f' error="{self.formatException(record.exc_info)}"'
        return line


def configure_logging(level: int = logging.INFO) -> None:
    """Install the logrus-style formatter on the root logger (idempotent)."""
    root = logging.getLogger()
    root.setLevel(level)
    for handler in root.handlers:
        if isinstance(handler.formatter, LogrusTextFormatter):
            return
    handler = logging.StreamHandler()
    handler.setFormatter(LogrusTextFormatter())
    root.addHandler(handler)
