"""Shared utilities: Go-style durations, logging setup."""

from .duration import format_duration, parse_duration

__all__ = ["parse_duration", "format_duration"]
