"""AWS Signature Version 4 request signing, stdlib-only.

The reference gets signing for free from aws-sdk-go (``sqs/sqs.go:36``);
this rebuild has a no-third-party-dependency constraint, so SigV4 is
implemented directly per the public specification
(docs.aws.amazon.com/IAM/latest/UserGuide/create-signed-request.html).

Pure functions over explicit inputs (timestamp included) so signatures are
deterministic and testable against golden vectors.
"""

from __future__ import annotations

import hashlib
import hmac
import urllib.parse
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Credentials:
    """A resolved AWS credential set (static or temporary).

    ``expires_at`` (epoch seconds) is set for temporary credentials from the
    instance-metadata service so callers can refresh before expiry; static
    env/file credentials leave it ``None``.
    """

    access_key_id: str
    secret_access_key: str
    session_token: str | None = None
    expires_at: float | None = None


@dataclass
class SignableRequest:
    """The parts of an HTTP request SigV4 covers."""

    method: str
    url: str  # absolute URL; query string (if any) must be RFC3986-encoded
    headers: dict[str, str] = field(default_factory=dict)
    body: bytes = b""


def _hmac_sha256(key: bytes, message: str) -> bytes:
    return hmac.new(key, message.encode("utf-8"), hashlib.sha256).digest()


def _sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _canonical_uri(path: str) -> str:
    # single URI-encode of each path segment, preserving slashes; empty -> "/"
    if not path:
        return "/"
    return urllib.parse.quote(path, safe="/-_.~")


def _canonical_query(query: str) -> str:
    # Decode percent-escapes then strictly re-encode per SigV4. Split
    # manually rather than via parse_qsl: in an RFC3986 query "+" is a
    # literal plus, and parse_qsl would corrupt it to a space.
    if not query:
        return ""
    encoded = []
    for pair in query.split("&"):
        key, _, value = pair.partition("=")
        encoded.append(
            (
                urllib.parse.quote(urllib.parse.unquote(key), safe="-_.~"),
                urllib.parse.quote(urllib.parse.unquote(value), safe="-_.~"),
            )
        )
    return "&".join(f"{k}={v}" for k, v in sorted(encoded))


def sign_request(
    request: SignableRequest,
    credentials: Credentials,
    region: str,
    service: str,
    amz_date: str,
) -> SignableRequest:
    """Return ``request`` with SigV4 ``Authorization`` (and aux) headers added.

    ``amz_date`` is the ISO-basic UTC timestamp, e.g. ``"20260729T120000Z"``;
    callers pass ``time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())``.
    """
    parsed = urllib.parse.urlsplit(request.url)
    date_stamp = amz_date[:8]
    payload_hash = _sha256_hex(request.body)

    headers = dict(request.headers)
    headers["host"] = parsed.netloc
    headers["x-amz-date"] = amz_date
    if credentials.session_token:
        headers["x-amz-security-token"] = credentials.session_token

    lower = {k.lower(): " ".join(str(v).split()) for k, v in headers.items()}
    signed_header_names = ";".join(sorted(lower))
    canonical_headers = "".join(f"{k}:{lower[k]}\n" for k in sorted(lower))

    canonical_request = "\n".join(
        [
            request.method.upper(),
            _canonical_uri(parsed.path),
            _canonical_query(parsed.query),
            canonical_headers,
            signed_header_names,
            payload_hash,
        ]
    )

    scope = f"{date_stamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            _sha256_hex(canonical_request.encode("utf-8")),
        ]
    )

    key = _hmac_sha256(
        _hmac_sha256(
            _hmac_sha256(
                _hmac_sha256(
                    ("AWS4" + credentials.secret_access_key).encode("utf-8"),
                    date_stamp,
                ),
                region,
            ),
            service,
        ),
        "aws4_request",
    )
    signature = hmac.new(
        key, string_to_sign.encode("utf-8"), hashlib.sha256
    ).hexdigest()

    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={credentials.access_key_id}/{scope}, "
        f"SignedHeaders={signed_header_names}, Signature={signature}"
    )
    return SignableRequest(
        method=request.method, url=request.url, headers=headers, body=request.body
    )
