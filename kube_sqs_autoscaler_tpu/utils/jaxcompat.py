"""JAX version compatibility shims.

The workload code targets the current ``jax.shard_map`` API (top-level
export, ``check_vma=`` keyword).  Older jaxlibs — including the 0.4.x this
image may ship — only have ``jax.experimental.shard_map.shard_map`` with
the ``check_rep=`` spelling.  :func:`install` bridges the gap in one place
so the ~20 call sites across flash/ring/zigzag/pipeline stay written
against the modern API.

Import-guarded: the control plane never imports JAX, and this module keeps
that true when jax is absent entirely.
"""

from __future__ import annotations

import importlib.util


def install() -> None:
    """Make ``jax.shard_map`` exist with the modern signature. Idempotent."""
    if importlib.util.find_spec("jax") is None:
        return
    import jax

    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kwargs):
        # check_rep is always disabled on the legacy API: the old
        # replication checker false-positives on these manual-collective
        # programs (e.g. "branches of cond produced mismatched replication
        # types" for the zig-zag kernel-vs-einsum cond), which is why it
        # was redesigned as check_vma.  The modern checker still runs
        # wherever jax.shard_map exists natively.
        del check_vma
        return _legacy_shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_rep=False,
            **kwargs,
        )

    jax.shard_map = shard_map
