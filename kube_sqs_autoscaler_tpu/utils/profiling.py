"""Opt-in profiling: JAX device traces and wall-clock span timing.

The reference has no tracing/profiling of any kind (SURVEY.md §5 — no
pprof, no OpenTelemetry, no timing instrumentation), so none is on by
default here either.  But a TPU workload fleet without profilers is
undiagnosable, so the framework ships two small opt-in tools:

- :func:`maybe_trace` — a context manager that wraps a region in
  ``jax.profiler.trace`` (XLA/TensorBoard trace of device + host
  activity) when given a directory, and is a free no-op when not.
  Workers enable it with ``ServiceConfig(profile_dir=...)``.
- :class:`SpanTimer` — a dependency-free wall-clock span recorder for
  control-plane code (which deliberately imports no JAX): named spans,
  monotonic clock, summary percentiles.  The observability layer
  (:mod:`..obs`) exposes per-tick latencies built on the same idea.

Layering: ``maybe_trace`` imports JAX lazily inside the context manager,
so importing this module from controller code keeps the no-JAX rule.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field


@contextlib.contextmanager
def maybe_trace(profile_dir: str | None):
    """``with maybe_trace(dir):`` — JAX device trace when ``dir`` is set.

    The trace is viewable with TensorBoard (or ``xprof``) pointed at the
    directory.  ``None``/empty disables tracing with zero overhead.
    """
    if not profile_dir:
        yield
        return
    import jax

    with jax.profiler.trace(profile_dir):
        yield


@dataclass
class SpanTimer:
    """Thread-safe wall-clock span aggregation, dependency-free
    (controller-safe).  :class:`~..workloads.service.QueueWorker` records
    each serve cycle under ``"cycle"``; reusable for any span.

    >>> timer = SpanTimer()
    >>> with timer.span("tick"):
    ...     pass
    >>> timer.summary()["tick"]["count"]
    1
    """

    clock: object = time  # injectable: needs .monotonic()
    _durations: dict = field(default_factory=lambda: defaultdict(list))
    _lock: threading.Lock = field(default_factory=threading.Lock)

    @contextlib.contextmanager
    def span(self, name: str):
        start = self.clock.monotonic()
        try:
            yield
        finally:
            elapsed = self.clock.monotonic() - start
            with self._lock:
                self._durations[name].append(elapsed)

    def summary(self) -> dict:
        """Per-span ``{count, total_s, mean_s, p50_s, p99_s, max_s}``."""
        with self._lock:
            snapshot = {k: list(v) for k, v in self._durations.items()}
        out = {}
        for name, durations in snapshot.items():
            ordered = sorted(durations)
            n = len(ordered)
            out[name] = {
                "count": n,
                "total_s": sum(ordered),
                "mean_s": sum(ordered) / n,
                "p50_s": ordered[n // 2],
                "p99_s": ordered[min(n - 1, (n * 99) // 100)],
                "max_s": ordered[-1],
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self._durations.clear()
