"""JAX platform-selection helper shared by every entry point.

This image's sitecustomize registers a TPU-tunnel PJRT plugin in each
Python process and calls ``jax.config.update("jax_platforms",
"axon,cpu")``, which OVERRIDES the ``JAX_PLATFORMS`` env var (config
beats env once set).  Any binary that must honor the env var — the graft
dryrun, the trainer, the worker, the workbench — calls
:func:`honor_env_platforms` before touching devices.

Lives in ``utils`` (not ``workloads``) because importing it must not pull
jax into controller-side processes; jax is imported lazily, only when the
env var is actually set.
"""

from __future__ import annotations

import os


def honor_env_platforms() -> None:
    """Make ``JAX_PLATFORMS`` authoritative over sitecustomize's config."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax

        jax.config.update("jax_platforms", platforms)
