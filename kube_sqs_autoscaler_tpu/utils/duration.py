"""Go-compatible duration strings.

The reference configures every time knob through Go's ``flag.DurationVar``
(``main.go:83-85``), whose accepted syntax is defined by Go's
``time.ParseDuration``: a signed sequence of decimal numbers with optional
fraction, each with a mandatory unit suffix — ``ns``, ``us``/``µs``, ``ms``,
``s``, ``m``, ``h`` — e.g. ``"5s"``, ``"300ms"``, ``"-1.5h"``, ``"2h45m"``.
To keep the CLI surface identical (``--poll-period=5s`` must work verbatim),
this module implements the same grammar.  Durations are represented as float
seconds throughout the framework.
"""

from __future__ import annotations

# Unit suffix -> seconds. Ordering matters only for formatting (largest first).
_UNITS = {
    "h": 3600.0,
    "m": 60.0,
    "s": 1.0,
    "ms": 1e-3,
    "us": 1e-6,
    "µs": 1e-6,  # µs (micro sign)
    "μs": 1e-6,  # μs (greek mu)
    "ns": 1e-9,
}


class DurationError(ValueError):
    """Raised for strings ``time.ParseDuration`` would reject."""


def parse_duration(text: str) -> float:
    """Parse a Go duration string into seconds.

    Mirrors ``time.ParseDuration``: requires a unit on every component
    (``"10"`` is invalid), accepts ``"0"`` bare, accepts a leading sign,
    and sums components left to right.
    """
    if not isinstance(text, str):
        raise DurationError(f"invalid duration: {text!r}")
    s = text.strip()
    original = text
    sign = 1.0
    if s.startswith(("+", "-")):
        if s[0] == "-":
            sign = -1.0
        s = s[1:]
    if s == "0":
        return 0.0
    if not s:
        raise DurationError(f"invalid duration: {original!r}")

    total = 0.0
    i = 0
    n = len(s)
    while i < n:
        # number: integer part and/or fraction
        start = i
        while i < n and (s[i].isdigit() or s[i] == "."):
            i += 1
        num_text = s[start:i]
        if not num_text or num_text == "." or num_text.count(".") > 1:
            raise DurationError(f"invalid duration: {original!r}")
        value = float(num_text)
        # unit: longest match first so "ms" wins over "m"
        unit = None
        for candidate in ("ms", "us", "µs", "μs", "ns", "h", "m", "s"):
            if s.startswith(candidate, i):
                unit = candidate
                break
        if unit is None:
            raise DurationError(
                f"missing or unknown unit in duration: {original!r}"
            )
        i += len(unit)
        total += value * _UNITS[unit]
    return sign * total


def format_duration(seconds: float) -> str:
    """Format seconds as a compact Go-style duration (e.g. ``90.0 -> "1m30s"``).

    Used only for logging/round-tripping; sub-second values print as
    ``ms``/``us``/``ns`` like Go's ``Duration.String``.
    """
    if seconds == 0:
        return "0s"
    sign = "-" if seconds < 0 else ""
    rem = abs(seconds)
    if rem < 1.0:
        for unit, mul in (("ms", 1e-3), ("us", 1e-6), ("ns", 1e-9)):
            if rem >= mul:
                value = rem / mul
                text = f"{value:.6g}"
                return f"{sign}{text}{unit}"
        return f"{sign}{rem / 1e-9:.6g}ns"
    parts = []
    for unit, mul in (("h", 3600.0), ("m", 60.0)):
        if rem >= mul:
            count = int(rem // mul)
            parts.append(f"{count}{unit}")
            rem -= count * mul
    if rem > 0 or not parts:
        text = f"{rem:.6g}"
        parts.append(f"{text}s")
    return sign + "".join(parts)
