"""Time-varying arrival processes for the closed-loop simulator.

The seed simulator modeled one world: a constant arrival rate.  Real
queue-fed fleets see steps (a product launch), ramps (organic growth,
cache warm-up), diurnal cycles (user traffic), and bursts (retry storms,
cron fan-out) — the scenarios the predictive-vs-reactive evaluation in
:mod:`.evaluate` runs head-to-head.

Each process exposes the instantaneous ``rate_at(t)`` and the *exact*
integral ``arrivals_between(t0, t1)``: the simulator integrates arrivals
analytically over each poll interval, so no quadrature error enters the
dynamics at any poll cadence.  One caveat the constant-rate seed world
does not share: the empty-queue floor is applied once per observation
interval, so if the queue empties mid-interval *and* the rate then rises
within that same interval, drain capacity idled while empty is credited
against the later arrivals — depth can be understated by at most one
interval's drain.  (With a constant rate the net rate cannot change sign
inside an interval, so the seed's lump-sum floor is genuinely exact.)
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Protocol, runtime_checkable


@runtime_checkable
class ArrivalProcess(Protocol):
    """A deterministic message-arrival intensity over simulated time."""

    def rate_at(self, t: float) -> float:
        """Instantaneous arrival rate (msg/s) at time ``t``."""
        ...

    def arrivals_between(self, t0: float, t1: float) -> float:
        """Exact ``∫ rate dt`` over ``[t0, t1]`` (``t1 >= t0``)."""
        ...


@dataclass(frozen=True)
class ConstantArrival:
    """The seed's world: a flat rate."""

    rate: float

    def rate_at(self, t: float) -> float:
        del t
        return self.rate

    def arrivals_between(self, t0: float, t1: float) -> float:
        return self.rate * (t1 - t0)


@dataclass(frozen=True)
class StepArrival:
    """``before`` msg/s until ``at``, ``after`` msg/s from then on."""

    before: float
    after: float
    at: float

    def rate_at(self, t: float) -> float:
        return self.after if t >= self.at else self.before

    def arrivals_between(self, t0: float, t1: float) -> float:
        if t1 <= self.at:
            return self.before * (t1 - t0)
        if t0 >= self.at:
            return self.after * (t1 - t0)
        return self.before * (self.at - t0) + self.after * (t1 - self.at)


@dataclass(frozen=True)
class RampArrival:
    """Linear ramp from ``start_rate`` at ``t_start`` to ``end_rate`` at
    ``t_end``; clamped flat outside the ramp."""

    start_rate: float
    end_rate: float
    t_start: float
    t_end: float

    def __post_init__(self):
        if self.t_end <= self.t_start:
            raise ValueError("t_end must be > t_start")

    def rate_at(self, t: float) -> float:
        if t <= self.t_start:
            return self.start_rate
        if t >= self.t_end:
            return self.end_rate
        frac = (t - self.t_start) / (self.t_end - self.t_start)
        return self.start_rate + frac * (self.end_rate - self.start_rate)

    def arrivals_between(self, t0: float, t1: float) -> float:
        # Piecewise: flat | linear | flat.  The linear segment integrates
        # exactly as the trapezoid of its endpoint rates.
        total = 0.0
        if t0 < self.t_start:
            flat_end = min(t1, self.t_start)
            total += self.start_rate * (flat_end - t0)
            t0 = flat_end
        if t0 < min(t1, self.t_end):
            seg_end = min(t1, self.t_end)
            total += 0.5 * (self.rate_at(t0) + self.rate_at(seg_end)) * (
                seg_end - t0
            )
            t0 = seg_end
        if t0 < t1:
            total += self.end_rate * (t1 - t0)
        return total


@dataclass(frozen=True)
class DiurnalArrival:
    """Sinusoidal daily cycle: ``base + amplitude·sin(2π(t−phase)/period)``.

    Requires ``amplitude <= base`` so the rate never clips at zero and the
    closed-form integral is exact everywhere.
    """

    base: float
    amplitude: float
    period: float
    phase: float = 0.0

    def __post_init__(self):
        if self.period <= 0:
            raise ValueError("period must be positive")
        if self.amplitude > self.base:
            raise ValueError(
                "amplitude must be <= base (rate would clip at zero and the"
                " analytic integral would be wrong)"
            )

    def _omega(self) -> float:
        return 2.0 * math.pi / self.period

    def rate_at(self, t: float) -> float:
        return self.base + self.amplitude * math.sin(self._omega() * (t - self.phase))

    def arrivals_between(self, t0: float, t1: float) -> float:
        w = self._omega()
        return self.base * (t1 - t0) + (self.amplitude / w) * (
            math.cos(w * (t0 - self.phase)) - math.cos(w * (t1 - self.phase))
        )


@dataclass(frozen=True)
class BurstArrival:
    """Rectangular bursts: ``burst_rate`` for ``burst_len`` seconds at the
    start of every ``period``, ``base`` in between."""

    base: float
    burst_rate: float
    period: float
    burst_len: float
    first_burst: float = 0.0

    def __post_init__(self):
        if not 0 < self.burst_len <= self.period:
            raise ValueError("need 0 < burst_len <= period")

    def _in_burst(self, t: float) -> bool:
        if t < self.first_burst:
            return False
        return (t - self.first_burst) % self.period < self.burst_len

    def rate_at(self, t: float) -> float:
        return self.burst_rate if self._in_burst(t) else self.base

    def arrivals_between(self, t0: float, t1: float) -> float:
        # base everywhere + the burst surplus over every overlapped window.
        total = self.base * (t1 - t0)
        surplus = self.burst_rate - self.base
        k = max(0, math.floor((t0 - self.first_burst) / self.period))
        burst_start = self.first_burst + k * self.period
        while burst_start < t1:
            overlap = min(t1, burst_start + self.burst_len) - max(t0, burst_start)
            if overlap > 0:
                total += surplus * overlap
            burst_start += self.period
        return total


def as_process(arrival: "float | int | ArrivalProcess") -> ArrivalProcess:
    """Coerce a plain number (the seed's config style) to a process."""
    if isinstance(arrival, (int, float)):
        return ConstantArrival(float(arrival))
    return arrival
