"""Time-varying arrival processes for the closed-loop simulator.

The seed simulator modeled one world: a constant arrival rate.  Real
queue-fed fleets see steps (a product launch), ramps (organic growth,
cache warm-up), diurnal cycles (user traffic), and bursts (retry storms,
cron fan-out) — the scenarios the predictive-vs-reactive evaluation in
:mod:`.evaluate` runs head-to-head.

Each process exposes the instantaneous ``rate_at(t)`` and the *exact*
integral ``arrivals_between(t0, t1)``: the simulator integrates arrivals
analytically over each poll interval, so no quadrature error enters the
dynamics at any poll cadence.  One caveat the constant-rate seed world
does not share: the empty-queue floor is applied once per observation
interval, so if the queue empties mid-interval *and* the rate then rises
within that same interval, drain capacity idled while empty is credited
against the later arrivals — depth can be understated by at most one
interval's drain.  (With a constant rate the net rate cannot change sign
inside an interval, so the seed's lump-sum floor is genuinely exact.)
"""

from __future__ import annotations

import hashlib
import math
import random
from dataclasses import dataclass
from typing import Any, Protocol, Sequence, runtime_checkable


@runtime_checkable
class ArrivalProcess(Protocol):
    """A deterministic message-arrival intensity over simulated time."""

    def rate_at(self, t: float) -> float:
        """Instantaneous arrival rate (msg/s) at time ``t``."""
        ...

    def arrivals_between(self, t0: float, t1: float) -> float:
        """Exact ``∫ rate dt`` over ``[t0, t1]`` (``t1 >= t0``)."""
        ...


@dataclass(frozen=True)
class ConstantArrival:
    """The seed's world: a flat rate."""

    rate: float

    def rate_at(self, t: float) -> float:
        del t
        return self.rate

    def arrivals_between(self, t0: float, t1: float) -> float:
        return self.rate * (t1 - t0)


@dataclass(frozen=True)
class StepArrival:
    """``before`` msg/s until ``at``, ``after`` msg/s from then on."""

    before: float
    after: float
    at: float

    def rate_at(self, t: float) -> float:
        return self.after if t >= self.at else self.before

    def arrivals_between(self, t0: float, t1: float) -> float:
        if t1 <= self.at:
            return self.before * (t1 - t0)
        if t0 >= self.at:
            return self.after * (t1 - t0)
        return self.before * (self.at - t0) + self.after * (t1 - self.at)


@dataclass(frozen=True)
class RampArrival:
    """Linear ramp from ``start_rate`` at ``t_start`` to ``end_rate`` at
    ``t_end``; clamped flat outside the ramp."""

    start_rate: float
    end_rate: float
    t_start: float
    t_end: float

    def __post_init__(self):
        if self.t_end <= self.t_start:
            raise ValueError("t_end must be > t_start")

    def rate_at(self, t: float) -> float:
        if t <= self.t_start:
            return self.start_rate
        if t >= self.t_end:
            return self.end_rate
        frac = (t - self.t_start) / (self.t_end - self.t_start)
        return self.start_rate + frac * (self.end_rate - self.start_rate)

    def arrivals_between(self, t0: float, t1: float) -> float:
        # Piecewise: flat | linear | flat.  The linear segment integrates
        # exactly as the trapezoid of its endpoint rates.
        total = 0.0
        if t0 < self.t_start:
            flat_end = min(t1, self.t_start)
            total += self.start_rate * (flat_end - t0)
            t0 = flat_end
        if t0 < min(t1, self.t_end):
            seg_end = min(t1, self.t_end)
            total += 0.5 * (self.rate_at(t0) + self.rate_at(seg_end)) * (
                seg_end - t0
            )
            t0 = seg_end
        if t0 < t1:
            total += self.end_rate * (t1 - t0)
        return total


@dataclass(frozen=True)
class DiurnalArrival:
    """Sinusoidal daily cycle: ``base + amplitude·sin(2π(t−phase)/period)``.

    Requires ``amplitude <= base`` so the rate never clips at zero and the
    closed-form integral is exact everywhere.
    """

    base: float
    amplitude: float
    period: float
    phase: float = 0.0

    def __post_init__(self):
        if self.period <= 0:
            raise ValueError("period must be positive")
        if self.amplitude > self.base:
            raise ValueError(
                "amplitude must be <= base (rate would clip at zero and the"
                " analytic integral would be wrong)"
            )

    def _omega(self) -> float:
        return 2.0 * math.pi / self.period

    def rate_at(self, t: float) -> float:
        return self.base + self.amplitude * math.sin(self._omega() * (t - self.phase))

    def arrivals_between(self, t0: float, t1: float) -> float:
        w = self._omega()
        return self.base * (t1 - t0) + (self.amplitude / w) * (
            math.cos(w * (t0 - self.phase)) - math.cos(w * (t1 - self.phase))
        )


@dataclass(frozen=True)
class BurstArrival:
    """Rectangular bursts: ``burst_rate`` for ``burst_len`` seconds at the
    start of every ``period``, ``base`` in between."""

    base: float
    burst_rate: float
    period: float
    burst_len: float
    first_burst: float = 0.0

    def __post_init__(self):
        if not 0 < self.burst_len <= self.period:
            raise ValueError("need 0 < burst_len <= period")

    def _in_burst(self, t: float) -> bool:
        if t < self.first_burst:
            return False
        return (t - self.first_burst) % self.period < self.burst_len

    def rate_at(self, t: float) -> float:
        return self.burst_rate if self._in_burst(t) else self.base

    def arrivals_between(self, t0: float, t1: float) -> float:
        # base everywhere + the burst surplus over every overlapped window.
        total = self.base * (t1 - t0)
        surplus = self.burst_rate - self.base
        k = max(0, math.floor((t0 - self.first_burst) / self.period))
        burst_start = self.first_burst + k * self.period
        while burst_start < t1:
            overlap = min(t1, burst_start + self.burst_len) - max(t0, burst_start)
            if overlap > 0:
                total += surplus * overlap
            burst_start += self.period
        return total


def as_process(arrival: "float | int | ArrivalProcess") -> ArrivalProcess:
    """Coerce a plain number (the seed's config style) to a process."""
    if isinstance(arrival, (int, float)):
        return ConstantArrival(float(arrival))
    return arrival


# ---------------------------------------------------------------------------
# Seeded scenario variants: principled train-vs-held-out splits.
# ---------------------------------------------------------------------------
#
# A policy tuned against the exact battery worlds (sweep winners, learned
# policies) must be scored on worlds it did NOT see, or the score is just
# memorization.  Variants jitter each arrival shape's parameters —
# rates, step instants, ramp slopes, diurnal phases, burst timings —
# within declared multiplicative bounds, seeded so a (seed, name, index)
# triple always produces the same world on any host/process (the seed is
# hashed with sha256, never Python's per-process ``hash``).  Every
# variant is an instance of the same analytic process class, so
# ``arrivals_between`` stays the *exact* integral of ``rate_at`` by
# construction — the property the simulators lean on.


def _variant_rng(seed: int, name: str, index: int) -> random.Random:
    """Process-stable RNG for one variant (sha256, not ``hash``)."""
    digest = hashlib.sha256(f"{seed}:{name}:{index}".encode()).digest()
    return random.Random(int.from_bytes(digest[:8], "big"))


def variant_bounds(
    process: ArrivalProcess, jitter: float = 0.2
) -> dict[str, tuple[float, float]]:
    """Declared per-parameter bounds a variant of ``process`` must obey.

    Multiplicative ``×(1 ± jitter)`` on every rate and timing parameter,
    except the diurnal ``phase`` which redraws uniformly over one (jittered)
    period — a phase shift is the whole point of a diurnal variant.  The
    generator additionally enforces each class's own validity invariants
    (``amplitude <= base``, ``0 < burst_len <= period``) by clamping
    *within* these bounds, so ``variant_bounds`` is the complete contract
    the property tests check.
    """
    lo, hi = 1.0 - jitter, 1.0 + jitter

    def band(value: float) -> tuple[float, float]:
        return (value * lo, value * hi)

    if isinstance(process, ConstantArrival):
        return {"rate": band(process.rate)}
    if isinstance(process, StepArrival):
        return {
            "before": band(process.before),
            "after": band(process.after),
            "at": band(process.at),
        }
    if isinstance(process, RampArrival):
        ramp_len = process.t_end - process.t_start
        return {
            "start_rate": band(process.start_rate),
            "end_rate": band(process.end_rate),
            "t_start": band(process.t_start),
            # the *slope* jitters through the ramp duration: t_end moves
            # with t_start plus a jittered length
            "ramp_len": band(ramp_len),
        }
    if isinstance(process, DiurnalArrival):
        return {
            "base": band(process.base),
            "amplitude": band(process.amplitude),
            "period": band(process.period),
            "phase": (0.0, process.period * hi),
        }
    if isinstance(process, BurstArrival):
        return {
            "base": band(process.base),
            "burst_rate": band(process.burst_rate),
            "period": band(process.period),
            "burst_len": band(process.burst_len),
            "first_burst": band(process.first_burst),
        }
    raise TypeError(
        f"no variant rule for arrival process {type(process).__name__}"
    )


def arrival_variant(
    process: "float | int | ArrivalProcess",
    seed: int,
    name: str,
    index: int,
    jitter: float = 0.2,
) -> ArrivalProcess:
    """One seeded variant of ``process`` within :func:`variant_bounds`."""
    process = as_process(process)
    rng = _variant_rng(seed, name, index)
    bounds = variant_bounds(process, jitter)

    def draw(key: str) -> float:
        lo, hi = bounds[key]
        return rng.uniform(lo, hi)

    if isinstance(process, ConstantArrival):
        return ConstantArrival(rate=draw("rate"))
    if isinstance(process, StepArrival):
        return StepArrival(
            before=draw("before"), after=draw("after"), at=draw("at")
        )
    if isinstance(process, RampArrival):
        t_start = draw("t_start")
        return RampArrival(
            start_rate=draw("start_rate"),
            end_rate=draw("end_rate"),
            t_start=t_start,
            t_end=t_start + max(draw("ramp_len"), 1e-6),
        )
    if isinstance(process, DiurnalArrival):
        base = draw("base")
        period = draw("period")
        return DiurnalArrival(
            base=base,
            # amplitude <= base keeps the closed-form integral exact
            # (class invariant); the clamp stays inside the declared band
            # because amplitude's lower bound is below base's
            amplitude=min(draw("amplitude"), base),
            period=period,
            phase=rng.uniform(0.0, period),
        )
    if isinstance(process, BurstArrival):
        period = draw("period")
        return BurstArrival(
            base=draw("base"),
            burst_rate=draw("burst_rate"),
            period=period,
            burst_len=min(draw("burst_len"), period),
            first_burst=draw("first_burst"),
        )
    raise TypeError(  # pragma: no cover — variant_bounds rejects first
        f"no variant rule for arrival process {type(process).__name__}"
    )


def scenario_variants(
    scenarios: "Sequence[Any]",
    n_variants: int,
    seed: int,
    jitter: float = 0.2,
) -> "list[Any]":
    """``n_variants`` seeded world-variants of each scenario.

    ``scenarios`` are :class:`~.evaluate.Scenario`-shaped frozen
    dataclasses (anything with ``name`` + ``arrival`` fields); each
    variant keeps every non-arrival field and appends ``~v{i}s{seed}`` to
    the name, so train (one seed) and held-out (another) splits are
    disjoint, reproducible, and self-describing in score rows.
    """
    import dataclasses

    out = []
    for scenario in scenarios:
        for index in range(n_variants):
            out.append(
                dataclasses.replace(
                    scenario,
                    name=f"{scenario.name}~v{index}s{seed}",
                    arrival=arrival_variant(
                        scenario.arrival, seed, scenario.name, index, jitter
                    ),
                )
            )
    return out
