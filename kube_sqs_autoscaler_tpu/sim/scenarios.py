"""Time-varying arrival processes for the closed-loop simulator.

The seed simulator modeled one world: a constant arrival rate.  Real
queue-fed fleets see steps (a product launch), ramps (organic growth,
cache warm-up), diurnal cycles (user traffic), and bursts (retry storms,
cron fan-out) — the scenarios the predictive-vs-reactive evaluation in
:mod:`.evaluate` runs head-to-head.

Each process exposes the instantaneous ``rate_at(t)`` and the *exact*
integral ``arrivals_between(t0, t1)``: the simulator integrates arrivals
analytically over each poll interval, so no quadrature error enters the
dynamics at any poll cadence.  One caveat the constant-rate seed world
does not share: the empty-queue floor is applied once per observation
interval, so if the queue empties mid-interval *and* the rate then rises
within that same interval, drain capacity idled while empty is credited
against the later arrivals — depth can be understated by at most one
interval's drain.  (With a constant rate the net rate cannot change sign
inside an interval, so the seed's lump-sum floor is genuinely exact.)
"""

from __future__ import annotations

import hashlib
import math
import random
from dataclasses import dataclass
from typing import Any, Protocol, Sequence, runtime_checkable


@runtime_checkable
class ArrivalProcess(Protocol):
    """A deterministic message-arrival intensity over simulated time."""

    def rate_at(self, t: float) -> float:
        """Instantaneous arrival rate (msg/s) at time ``t``."""
        ...

    def arrivals_between(self, t0: float, t1: float) -> float:
        """Exact ``∫ rate dt`` over ``[t0, t1]`` (``t1 >= t0``)."""
        ...


@dataclass(frozen=True)
class ConstantArrival:
    """The seed's world: a flat rate."""

    rate: float

    def rate_at(self, t: float) -> float:
        del t
        return self.rate

    def arrivals_between(self, t0: float, t1: float) -> float:
        return self.rate * (t1 - t0)


@dataclass(frozen=True)
class StepArrival:
    """``before`` msg/s until ``at``, ``after`` msg/s from then on."""

    before: float
    after: float
    at: float

    def rate_at(self, t: float) -> float:
        return self.after if t >= self.at else self.before

    def arrivals_between(self, t0: float, t1: float) -> float:
        if t1 <= self.at:
            return self.before * (t1 - t0)
        if t0 >= self.at:
            return self.after * (t1 - t0)
        return self.before * (self.at - t0) + self.after * (t1 - self.at)


@dataclass(frozen=True)
class RampArrival:
    """Linear ramp from ``start_rate`` at ``t_start`` to ``end_rate`` at
    ``t_end``; clamped flat outside the ramp."""

    start_rate: float
    end_rate: float
    t_start: float
    t_end: float

    def __post_init__(self):
        if self.t_end <= self.t_start:
            raise ValueError("t_end must be > t_start")

    def rate_at(self, t: float) -> float:
        if t <= self.t_start:
            return self.start_rate
        if t >= self.t_end:
            return self.end_rate
        frac = (t - self.t_start) / (self.t_end - self.t_start)
        return self.start_rate + frac * (self.end_rate - self.start_rate)

    def arrivals_between(self, t0: float, t1: float) -> float:
        # Piecewise: flat | linear | flat.  The linear segment integrates
        # exactly as the trapezoid of its endpoint rates.
        total = 0.0
        if t0 < self.t_start:
            flat_end = min(t1, self.t_start)
            total += self.start_rate * (flat_end - t0)
            t0 = flat_end
        if t0 < min(t1, self.t_end):
            seg_end = min(t1, self.t_end)
            total += 0.5 * (self.rate_at(t0) + self.rate_at(seg_end)) * (
                seg_end - t0
            )
            t0 = seg_end
        if t0 < t1:
            total += self.end_rate * (t1 - t0)
        return total


@dataclass(frozen=True)
class DiurnalArrival:
    """Sinusoidal daily cycle: ``base + amplitude·sin(2π(t−phase)/period)``.

    Requires ``amplitude <= base`` so the rate never clips at zero and the
    closed-form integral is exact everywhere.
    """

    base: float
    amplitude: float
    period: float
    phase: float = 0.0

    def __post_init__(self):
        if self.period <= 0:
            raise ValueError("period must be positive")
        if self.amplitude > self.base:
            raise ValueError(
                "amplitude must be <= base (rate would clip at zero and the"
                " analytic integral would be wrong)"
            )

    def _omega(self) -> float:
        return 2.0 * math.pi / self.period

    def rate_at(self, t: float) -> float:
        return self.base + self.amplitude * math.sin(self._omega() * (t - self.phase))

    def arrivals_between(self, t0: float, t1: float) -> float:
        w = self._omega()
        return self.base * (t1 - t0) + (self.amplitude / w) * (
            math.cos(w * (t0 - self.phase)) - math.cos(w * (t1 - self.phase))
        )


@dataclass(frozen=True)
class BurstArrival:
    """Rectangular bursts: ``burst_rate`` for ``burst_len`` seconds at the
    start of every ``period``, ``base`` in between."""

    base: float
    burst_rate: float
    period: float
    burst_len: float
    first_burst: float = 0.0

    def __post_init__(self):
        if not 0 < self.burst_len <= self.period:
            raise ValueError("need 0 < burst_len <= period")

    def _in_burst(self, t: float) -> bool:
        if t < self.first_burst:
            return False
        return (t - self.first_burst) % self.period < self.burst_len

    def rate_at(self, t: float) -> float:
        return self.burst_rate if self._in_burst(t) else self.base

    def arrivals_between(self, t0: float, t1: float) -> float:
        # base everywhere + the burst surplus over every overlapped window.
        total = self.base * (t1 - t0)
        surplus = self.burst_rate - self.base
        k = max(0, math.floor((t0 - self.first_burst) / self.period))
        burst_start = self.first_burst + k * self.period
        while burst_start < t1:
            overlap = min(t1, burst_start + self.burst_len) - max(t0, burst_start)
            if overlap > 0:
                total += surplus * overlap
            burst_start += self.period
        return total


@dataclass(frozen=True)
class PulseArrival:
    """A one-shot rectangular surge: ``rate`` msg/s on ``[start, start +
    width)``, zero outside — the flash-crowd primitive.  On its own it is
    a degenerate world (nothing before or after the pulse); composed over
    a base process via :class:`ComposedArrival` it is a product launch /
    retry storm landing on organic traffic."""

    rate: float
    start: float
    width: float

    def __post_init__(self):
        if self.width <= 0:
            raise ValueError("width must be positive")
        if self.rate < 0:
            raise ValueError("rate must be >= 0")

    def rate_at(self, t: float) -> float:
        return self.rate if self.start <= t < self.start + self.width else 0.0

    def arrivals_between(self, t0: float, t1: float) -> float:
        overlap = min(t1, self.start + self.width) - max(t0, self.start)
        return self.rate * max(0.0, overlap)


@dataclass(frozen=True)
class ComposedArrival:
    """The sum of component processes — arbitrary shapes stack (base +
    pulse, diurnal + bursts, ...).  Exact by construction: the integral
    of a sum is the sum of the component integrals, each of which is
    already exact."""

    parts: "tuple[ArrivalProcess, ...]"

    def __post_init__(self):
        if not self.parts:
            raise ValueError("ComposedArrival needs at least one part")

    def rate_at(self, t: float) -> float:
        return sum(p.rate_at(t) for p in self.parts)

    def arrivals_between(self, t0: float, t1: float) -> float:
        return sum(p.arrivals_between(t0, t1) for p in self.parts)


@dataclass(frozen=True)
class RegimeSwitchArrival:
    """Piecewise regimes: ``regimes[i] = (start_i, process_i)`` with
    ``process_i`` active on ``[start_i, start_{i+1})`` (the last regime
    runs forever).  Each regime's process is evaluated on its LOCAL
    clock ``t - start_i`` — a burst regime restarts its burst phase at
    the switch instant, which is what "the workload changed character"
    means.  The integral splits exactly at the boundaries, so the shape
    stays quadrature-free like every other process here."""

    regimes: "tuple[tuple[float, ArrivalProcess], ...]"

    def __post_init__(self):
        if not self.regimes:
            raise ValueError("RegimeSwitchArrival needs at least one regime")
        starts = [s for s, _ in self.regimes]
        if starts[0] != 0.0:
            raise ValueError("the first regime must start at t=0")
        if any(b <= a for a, b in zip(starts, starts[1:])):
            raise ValueError("regime starts must be strictly increasing")

    def _spans(self) -> "list[tuple[float, float, ArrivalProcess]]":
        starts = [s for s, _ in self.regimes]
        ends = starts[1:] + [math.inf]
        return [
            (s, e, p) for (s, p), e in zip(self.regimes, ends)
        ]

    def rate_at(self, t: float) -> float:
        for start, end, process in self._spans():
            if start <= t < end:
                return process.rate_at(t - start)
        # t before 0: the first regime's local clock extends backwards
        start, _, process = self._spans()[0]
        return process.rate_at(t - start)

    def arrivals_between(self, t0: float, t1: float) -> float:
        total = 0.0
        for start, end, process in self._spans():
            a, b = max(t0, start), min(t1, end)
            if b > a:
                total += process.arrivals_between(a - start, b - start)
        return total


def heavy_tail_lengths(
    tag: str, n: int, lo: int, hi: int, alpha: float = 1.2
) -> "list[int]":
    """``n`` integer lengths from a bounded-Pareto tail on ``[lo, hi]``.

    ``P(L >= k) ∝ k^-alpha``: most draws sit near ``lo``, a deterministic
    rare few reach toward ``hi`` — the prompt/output-length shape real
    serving traffic has and uniform budgets hide.  Seeded with sha256 of
    ``tag`` (the :func:`seeded_token_ids` convention), so a (tag, n, lo,
    hi, alpha) tuple always draws the identical sequence on any host —
    the serving twin and the real plane consume the SAME concrete
    integers, never "the same distribution"."""
    if not 1 <= lo <= hi:
        raise ValueError(f"need 1 <= lo <= hi, got lo={lo} hi={hi}")
    if alpha <= 0:
        raise ValueError(f"alpha={alpha} must be > 0")
    digest = hashlib.sha256(f"lengths:{tag}".encode()).digest()
    rng = random.Random(int.from_bytes(digest[:8], "big"))
    ratio = (lo / hi) ** alpha
    out = []
    for _ in range(n):
        u = rng.random()
        # inverse CDF of the bounded Pareto(alpha) on [lo, hi]
        x = lo / (1.0 - u * (1.0 - ratio)) ** (1.0 / alpha)
        out.append(max(lo, min(hi, int(x))))
    return out


def as_process(arrival: "float | int | ArrivalProcess") -> ArrivalProcess:
    """Coerce a plain number (the seed's config style) to a process."""
    if isinstance(arrival, (int, float)):
        return ConstantArrival(float(arrival))
    return arrival


# ---------------------------------------------------------------------------
# Seeded scenario variants: principled train-vs-held-out splits.
# ---------------------------------------------------------------------------
#
# A policy tuned against the exact battery worlds (sweep winners, learned
# policies) must be scored on worlds it did NOT see, or the score is just
# memorization.  Variants jitter each arrival shape's parameters —
# rates, step instants, ramp slopes, diurnal phases, burst timings —
# within declared multiplicative bounds, seeded so a (seed, name, index)
# triple always produces the same world on any host/process (the seed is
# hashed with sha256, never Python's per-process ``hash``).  Every
# variant is an instance of the same analytic process class, so
# ``arrivals_between`` stays the *exact* integral of ``rate_at`` by
# construction — the property the simulators lean on.


def _variant_rng(seed: int, name: str, index: int) -> random.Random:
    """Process-stable RNG for one variant (sha256, not ``hash``)."""
    digest = hashlib.sha256(f"{seed}:{name}:{index}".encode()).digest()
    return random.Random(int.from_bytes(digest[:8], "big"))


def variant_bounds(
    process: ArrivalProcess, jitter: float = 0.2
) -> dict[str, tuple[float, float]]:
    """Declared per-parameter bounds a variant of ``process`` must obey.

    Multiplicative ``×(1 ± jitter)`` on every rate and timing parameter,
    except the diurnal ``phase`` which redraws uniformly over one (jittered)
    period — a phase shift is the whole point of a diurnal variant.  The
    generator additionally enforces each class's own validity invariants
    (``amplitude <= base``, ``0 < burst_len <= period``) by clamping
    *within* these bounds, so ``variant_bounds`` is the complete contract
    the property tests check.
    """
    lo, hi = 1.0 - jitter, 1.0 + jitter

    def band(value: float) -> tuple[float, float]:
        return (value * lo, value * hi)

    if isinstance(process, ConstantArrival):
        return {"rate": band(process.rate)}
    if isinstance(process, StepArrival):
        return {
            "before": band(process.before),
            "after": band(process.after),
            "at": band(process.at),
        }
    if isinstance(process, RampArrival):
        ramp_len = process.t_end - process.t_start
        return {
            "start_rate": band(process.start_rate),
            "end_rate": band(process.end_rate),
            "t_start": band(process.t_start),
            # the *slope* jitters through the ramp duration: t_end moves
            # with t_start plus a jittered length
            "ramp_len": band(ramp_len),
        }
    if isinstance(process, DiurnalArrival):
        return {
            "base": band(process.base),
            "amplitude": band(process.amplitude),
            "period": band(process.period),
            "phase": (0.0, process.period * hi),
        }
    if isinstance(process, BurstArrival):
        return {
            "base": band(process.base),
            "burst_rate": band(process.burst_rate),
            "period": band(process.period),
            "burst_len": band(process.burst_len),
            "first_burst": band(process.first_burst),
        }
    if isinstance(process, PulseArrival):
        return {
            "rate": band(process.rate),
            "start": band(process.start),
            "width": band(process.width),
        }
    if isinstance(process, ComposedArrival):
        # composite shapes declare bounds per part; the generator
        # recurses with a per-part name so sibling parts draw
        # independent jitters
        bounds: dict[str, tuple[float, float]] = {}
        for i, part in enumerate(process.parts):
            for key, value in variant_bounds(part, jitter).items():
                bounds[f"part{i}.{key}"] = value
        return bounds
    if isinstance(process, RegimeSwitchArrival):
        bounds = {}
        for i, (start, part) in enumerate(process.regimes):
            if i > 0:  # the first regime's start is pinned at 0
                bounds[f"regime{i}.start"] = band(start)
            for key, value in variant_bounds(part, jitter).items():
                bounds[f"regime{i}.{key}"] = value
        return bounds
    raise TypeError(
        f"no variant rule for arrival process {type(process).__name__}"
    )


def arrival_variant(
    process: "float | int | ArrivalProcess",
    seed: int,
    name: str,
    index: int,
    jitter: float = 0.2,
) -> ArrivalProcess:
    """One seeded variant of ``process`` within :func:`variant_bounds`."""
    process = as_process(process)
    rng = _variant_rng(seed, name, index)
    bounds = variant_bounds(process, jitter)

    def draw(key: str) -> float:
        lo, hi = bounds[key]
        return rng.uniform(lo, hi)

    if isinstance(process, ConstantArrival):
        return ConstantArrival(rate=draw("rate"))
    if isinstance(process, StepArrival):
        return StepArrival(
            before=draw("before"), after=draw("after"), at=draw("at")
        )
    if isinstance(process, RampArrival):
        t_start = draw("t_start")
        return RampArrival(
            start_rate=draw("start_rate"),
            end_rate=draw("end_rate"),
            t_start=t_start,
            t_end=t_start + max(draw("ramp_len"), 1e-6),
        )
    if isinstance(process, DiurnalArrival):
        base = draw("base")
        period = draw("period")
        return DiurnalArrival(
            base=base,
            # amplitude <= base keeps the closed-form integral exact
            # (class invariant); the clamp stays inside the declared band
            # because amplitude's lower bound is below base's
            amplitude=min(draw("amplitude"), base),
            period=period,
            phase=rng.uniform(0.0, period),
        )
    if isinstance(process, BurstArrival):
        period = draw("period")
        return BurstArrival(
            base=draw("base"),
            burst_rate=draw("burst_rate"),
            period=period,
            burst_len=min(draw("burst_len"), period),
            first_burst=draw("first_burst"),
        )
    if isinstance(process, PulseArrival):
        return PulseArrival(
            rate=draw("rate"),
            start=draw("start"),
            width=max(draw("width"), 1e-6),
        )
    if isinstance(process, ComposedArrival):
        return ComposedArrival(
            parts=tuple(
                arrival_variant(part, seed, f"{name}#p{i}", index, jitter)
                for i, part in enumerate(process.parts)
            )
        )
    if isinstance(process, RegimeSwitchArrival):
        regimes = []
        prev = -math.inf
        for i, (start, part) in enumerate(process.regimes):
            # the start jitter draws from its OWN key — sharing the
            # part's key would consume the part's first draw and
            # perfectly correlate "when the regime switches" with its
            # first parameter, collapsing the variant space
            rng_i = _variant_rng(seed, f"{name}#r{i}.start", index)
            lo_hi = bounds.get(f"regime{i}.start")
            new_start = 0.0 if i == 0 else rng_i.uniform(*lo_hi)
            # boundaries must stay strictly increasing; clamp within the
            # declared band like the diurnal amplitude clamp
            new_start = max(new_start, prev + 1e-6)
            prev = new_start
            regimes.append(
                (
                    new_start,
                    arrival_variant(part, seed, f"{name}#r{i}", index,
                                    jitter),
                )
            )
        return RegimeSwitchArrival(regimes=tuple(regimes))
    raise TypeError(  # pragma: no cover — variant_bounds rejects first
        f"no variant rule for arrival process {type(process).__name__}"
    )


def scenario_variants(
    scenarios: "Sequence[Any]",
    n_variants: int,
    seed: int,
    jitter: float = 0.2,
) -> "list[Any]":
    """``n_variants`` seeded world-variants of each scenario.

    ``scenarios`` are :class:`~.evaluate.Scenario`-shaped frozen
    dataclasses (anything with ``name`` + ``arrival`` fields); each
    variant keeps every non-arrival field and appends ``~v{i}s{seed}`` to
    the name, so train (one seed) and held-out (another) splits are
    disjoint, reproducible, and self-describing in score rows.
    """
    import dataclasses

    out = []
    for scenario in scenarios:
        for index in range(n_variants):
            out.append(
                dataclasses.replace(
                    scenario,
                    name=f"{scenario.name}~v{index}s{seed}",
                    arrival=arrival_variant(
                        scenario.arrival, seed, scenario.name, index, jitter
                    ),
                )
            )
    return out


# ---------------------------------------------------------------------------
# Multi-tenant serving scenarios: deterministic per-cycle request schedules
# ---------------------------------------------------------------------------
#
# The arrival processes above drive the FLUID autoscaling world (messages
# per second into a depth integral).  The tenant battery instead drives
# the REAL serving engine cycle by cycle, so its schedules are integer
# send counts at exact engine cycles — adversarial shapes (one tenant
# floods, victims must keep their TTFT) stay bit-reproducible without
# any arrival quadrature.


@dataclass(frozen=True)
class TenantTraffic:
    """One tenant's deterministic send schedule within a scenario.

    The tenant sends ``per_cycle`` requests at every cycle ``c`` with
    ``start_cycle <= c < end_cycle`` and ``(c - start_cycle) % every ==
    0`` (``end_cycle=None`` = the scenario's full span).  ``weight`` is
    the DRR share the episode configures for it; ``ttft_slo_s`` its
    TTFT SLO (0 = none); ``flood=True`` marks the adversary the
    isolation gates exclude from the victim set."""

    tenant: str
    weight: float = 1.0
    per_cycle: int = 1
    every: int = 1
    start_cycle: int = 0
    end_cycle: "int | None" = None
    ttft_slo_s: float = 0.0
    flood: bool = False

    def __post_init__(self) -> None:
        if self.per_cycle < 0:
            raise ValueError("per_cycle must be >= 0")
        if self.every < 1:
            raise ValueError("every must be >= 1")
        if self.start_cycle < 0:
            raise ValueError("start_cycle must be >= 0")
        if self.end_cycle is not None and self.end_cycle < self.start_cycle:
            raise ValueError("end_cycle must be >= start_cycle")

    def sends_at(self, cycle: int, span: int) -> int:
        """Requests this tenant sends at engine cycle ``cycle`` of a
        ``span``-cycle schedule."""
        end = span if self.end_cycle is None else min(self.end_cycle, span)
        if not self.start_cycle <= cycle < end:
            return 0
        if (cycle - self.start_cycle) % self.every:
            return 0
        return self.per_cycle


@dataclass(frozen=True)
class TenantScenario:
    """A named multi-tenant traffic shape over ``cycles`` engine cycles."""

    name: str
    cycles: int
    traffics: "tuple[TenantTraffic, ...]"
    description: str = ""

    def __post_init__(self) -> None:
        if self.cycles < 1:
            raise ValueError("cycles must be >= 1")
        names = [t.tenant for t in self.traffics]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tenants in scenario {self.name}")

    @property
    def tenants(self) -> "tuple[str, ...]":
        return tuple(t.tenant for t in self.traffics)

    @property
    def victims(self) -> "tuple[str, ...]":
        return tuple(t.tenant for t in self.traffics if not t.flood)

    def total_requests(self) -> int:
        return sum(
            t.sends_at(c, self.cycles)
            for t in self.traffics
            for c in range(self.cycles)
        )

    def schedule(self) -> "list[list[tuple[str, int]]]":
        """``schedule()[c]`` = this cycle's ``(tenant, send_count)``
        pairs in declared tenant order — the bench interleaves these
        sends with real engine cycles."""
        return [
            [
                (t.tenant, t.sends_at(c, self.cycles))
                for t in self.traffics
                if t.sends_at(c, self.cycles)
            ]
            for c in range(self.cycles)
        ]


def seeded_token_ids(tag: str, n: int, vocab: int) -> "list[int]":
    """``n`` token ids drawn from a sha256-of-``tag``-seeded stream —
    the one seeding convention every deterministic token stream in the
    tenant battery uses (prefixes here, per-request suffixes in the
    bench), so the two can never silently desynchronize."""
    digest = hashlib.sha256(tag.encode()).digest()
    rng = random.Random(int.from_bytes(digest[:8], "big"))
    return [rng.randrange(1, max(2, vocab)) for _ in range(n)]


def tenant_prefix_ids(
    tenant: str, prefix_len: int, vocab: int, seed: int = 0
) -> "list[int]":
    """The tenant's shared prompt prefix: ``prefix_len`` token ids drawn
    from a hash-seeded stream, so every (tenant, seed) pair gets a
    distinct, reproducible prefix without any shared RNG state."""
    return seeded_token_ids(
        f"tenant-prefix:{tenant}:{seed}", prefix_len, vocab
    )


def flood_scenario(
    *, victims: int = 2, cycles: int = 40, flood_start: int = 4,
    flood_cycles: int = 8, flood_per_cycle: int = 8,
) -> TenantScenario:
    """One adversary floods a burst while victims trickle steadily —
    the isolation shape: with FIFO admission every victim request
    arriving during (or after) the burst waits behind the whole flood
    backlog; with DRR each refill still hands the victims their share."""
    traffics = [
        TenantTraffic(
            tenant="flood", weight=1.0, per_cycle=flood_per_cycle,
            start_cycle=flood_start,
            end_cycle=flood_start + flood_cycles, flood=True,
        )
    ]
    for v in range(victims):
        traffics.append(
            TenantTraffic(tenant=f"victim{v}", weight=1.0, per_cycle=1,
                          every=4, start_cycle=v)
        )
    return TenantScenario(
        name="flood-isolation", cycles=cycles, traffics=tuple(traffics),
        description=(
            "one tenant bursts %d req/cycle for %d cycles; %d victims "
            "send 1 req every 4 cycles throughout"
            % (flood_per_cycle, flood_cycles, victims)
        ),
    )


def prefix_share_scenario(
    *, tenants: int = 6, cycles: int = 48, every: int = 2,
) -> TenantScenario:
    """Many tenants, each reusing its own shared prefix — the locality
    shape the sticky-vs-freest routing comparison runs on: more tenants
    than one shard's pool entries, so scattered routing re-installs and
    LRU-thrashes prefixes that sticky routing keeps resident."""
    return TenantScenario(
        name="prefix-share", cycles=cycles,
        traffics=tuple(
            TenantTraffic(tenant=f"tenant{i}", per_cycle=1, every=every,
                          start_cycle=i % every)
            for i in range(tenants)
        ),
        description=(
            "%d prefix-sharing tenants, 1 req each every %d cycles"
            % (tenants, every)
        ),
    )


def default_tenant_battery() -> "list[TenantScenario]":
    """The adversarial-tenant battery ``bench.py --suite tenants``
    scores: flood isolation plus the prefix-sharing locality shape
    (the no-flood control is derived from the flood scenario by
    dropping its flood traffic — see the bench)."""
    return [flood_scenario(), prefix_share_scenario()]


def coordinated_flood_scenario(
    *, floods: int = 4, victims: int = 2, flood_per_cycle: int = 3,
    flood_start: int = 4, flood_cycles: int = 10,
    victim_every: int = 3, slo_s: float = 0.35,
    cycles: "int | None" = None,
) -> TenantScenario:
    """``floods`` distinct adversaries burst the SAME window — the
    shape pure DRR handles worst: fairness splits capacity evenly over
    the whole flood coalition, so each victim's share shrinks to
    ``1/(floods+victims)`` while every flooder individually looks
    legitimate.  Victims carry TTFT SLOs; the deadline-aware plane must
    keep their p99/time-over-SLO strictly better than pure DRR."""
    if cycles is None:
        cycles = flood_start + flood_cycles + 14
    traffics = [
        TenantTraffic(
            tenant=f"flood{f}", per_cycle=flood_per_cycle,
            start_cycle=flood_start,
            end_cycle=flood_start + flood_cycles, flood=True,
        )
        for f in range(floods)
    ]
    traffics += [
        TenantTraffic(tenant=f"victim{v}", per_cycle=1,
                      every=victim_every, start_cycle=v,
                      ttft_slo_s=slo_s)
        for v in range(victims)
    ]
    return TenantScenario(
        name="coordinated-flood", cycles=cycles,
        traffics=tuple(traffics),
        description=(
            "%d tenants flood %d req/cycle each for %d cycles in the "
            "same window; %d SLO victims send 1 req every %d cycles"
            % (floods, flood_per_cycle, flood_cycles, victims,
               victim_every)
        ),
    )


def zipf_scenario(
    *, tenants: int = 2000, heads: int = 2, head_per_cycle: int = 3,
    victims: int = 2, victim_every: int = 3, slo_s: float = 0.4,
    s: float = 1.0, cycles: int = 40, tail_keep: int = 5,
) -> TenantScenario:
    """Zipf-distributed traffic over a large open tenant population.

    Rank-``k`` of the ``tenants`` background tenants sends one request
    every ``ceil((k+1)**s)`` cycles — the classic 1/k rate curve, so a
    handful of head tenants dominate volume while a long tail of
    mostly-one-shot tenants churns the scheduler's registration state
    (they arrive unregistered, weight 1.0, and are pruned when
    drained).  The ``heads`` heaviest ranks send ``head_per_cycle``
    every cycle and are marked as the flood (the attack IS the zipf
    head); ``victims`` registered SLO tenants trickle throughout.
    ``tail_keep`` thins the one-shot deep tail to a deterministic
    1-in-``tail_keep`` (5 = the historical default; the admission-
    scale battery raises it so a 100k–1M population keeps a few
    thousand actual senders instead of tens of thousands)."""
    if tenants < heads:
        raise ValueError("tenants must be >= heads")
    if tail_keep < 1:
        raise ValueError(f"tail_keep={tail_keep} must be >= 1")
    traffics = []
    for k in range(tenants):
        if k < heads:
            traffics.append(TenantTraffic(
                tenant=f"z{k}", per_cycle=head_per_cycle, flood=True,
            ))
            continue
        every = min(cycles, max(1, math.ceil((k + 1) ** s)))
        if every >= cycles and k % tail_keep:
            # deep-tail thinning: keep a deterministic 1-in-tail_keep
            # of the one-shot tail so a huge tenant population does
            # not mean that many requests all landing at once
            continue
        traffics.append(TenantTraffic(
            tenant=f"z{k}", per_cycle=1, every=every,
            start_cycle=k % max(1, min(cycles, every)),
        ))
    traffics += [
        TenantTraffic(tenant=f"victim{v}", per_cycle=1,
                      every=victim_every, start_cycle=v,
                      ttft_slo_s=slo_s)
        for v in range(victims)
    ]
    return TenantScenario(
        name="zipf", cycles=cycles, traffics=tuple(traffics),
        description=(
            "%d-tenant zipf(s=%g) population, %d flooding head(s) at "
            "%d req/cycle, %d SLO victims"
            % (tenants, s, heads, head_per_cycle, victims)
        ),
    )


def flash_crowd_scenario(
    *, crowd: int = 1600, crowd_start: int = 6, crowd_span: int = 4,
    victims: int = 2, victim_every: int = 3, slo_s: float = 0.4,
    cycles: "int | None" = None,
) -> TenantScenario:
    """Tenant-population churn at its sharpest: ``crowd`` NEVER-seen
    tenants each send exactly one request inside a ``crowd_span``-cycle
    window (a product launch / retry storm), then vanish.  Stresses
    the open-population paths — unregistered staging, DRR registration
    churn and pruning, label-cardinality bounds — while the registered
    SLO victims must keep their TTFT through the stampede."""
    if cycles is None:
        cycles = crowd_start + crowd_span + 18
    traffics = [
        TenantTraffic(
            tenant=f"crowd{i}", per_cycle=1,
            start_cycle=crowd_start + (i % crowd_span),
            end_cycle=crowd_start + (i % crowd_span) + 1,
            flood=True,
        )
        for i in range(crowd)
    ]
    traffics += [
        TenantTraffic(tenant=f"victim{v}", per_cycle=1,
                      every=victim_every, start_cycle=v,
                      ttft_slo_s=slo_s)
        for v in range(victims)
    ]
    return TenantScenario(
        name="flash-crowd", cycles=cycles, traffics=tuple(traffics),
        description=(
            "%d one-shot tenants stampede over %d cycles from cycle "
            "%d; %d SLO victims trickle throughout"
            % (crowd, crowd_span, crowd_start, victims)
        ),
    )


def overload_battery(
    *, scale: float = 1.0,
) -> "list[TenantScenario]":
    """The adversarial overload battery ``bench.py --suite overload``
    scores (ROADMAP item 5): a coordinated multi-tenant flood, a
    zipf-population attack with thousands of distinct tenants, and a
    flash crowd.  ``scale`` shrinks the tenant POPULATIONS for the
    tier-1 smoke (1.0 = the full battery); the per-cycle attack
    intensity is deliberately NOT scaled — a smoke whose "flood" fits
    the engine's capacity would never engage the ladder and the
    battery would gate nothing."""
    def pop(value: int, floor: int) -> int:
        return max(floor, int(round(value * scale)))

    return [
        coordinated_flood_scenario(floods=pop(4, 4)),
        zipf_scenario(tenants=pop(2000, 40)),
        flash_crowd_scenario(crowd=pop(1600, 30)),
    ]


def admission_scale_scenario(
    *, tenants: int = 100_000, heads: int = 4, head_per_cycle: int = 4,
    victims: int = 2, victim_every: int = 2, slo_s: float = 0.4,
    cycles: int = 32,
) -> TenantScenario:
    """The sharded-admission stress shape: a 100k+-tenant zipf
    population whose COORDINATED head flood hammers the staging
    plane's O(active tenants) host work while SLO victims trickle —
    the regime where N admission shards beat one (each shard pays only
    its slice of the classifier/decay work, and they run
    concurrently).  The deep tail is thinned to ~``tenants/500``
    actual one-shot senders (deterministically), so the POPULATION
    scales to a million without the request count following it."""
    import dataclasses

    return dataclasses.replace(
        zipf_scenario(
            tenants=tenants, heads=heads,
            head_per_cycle=head_per_cycle, victims=victims,
            victim_every=victim_every, slo_s=slo_s, cycles=cycles,
            tail_keep=max(5, tenants // 500),
        ),
        name=f"admission-zipf-{tenants // 1000}k",
    )


def admission_scale_battery(
    *, scale: float = 1.0,
) -> "list[TenantScenario]":
    """The 100k–1M zipf battery ``bench.py --suite admission-scale``
    scores (ROADMAP item 4).  ``scale`` shrinks the tenant POPULATIONS
    for the tier-1 smoke (1.0 = the full battery); the coordinated
    head flood's per-cycle intensity is deliberately NOT scaled — a
    smoke whose flood never pressures the staging plane would gate
    nothing."""
    def pop(value: int, floor: int) -> int:
        return max(floor, int(round(value * scale)))

    return [
        admission_scale_scenario(tenants=pop(100_000, 1_000)),
        admission_scale_scenario(tenants=pop(1_000_000, 4_000)),
    ]


def disagg_scenario(
    *, tenants: int = 2, cycles: int = 36, every: int = 2,
    wave_start: int = 8, wave_cycles: int = 6, wave_per_cycle: int = 4,
) -> TenantScenario:
    """The two-plane shape: steady decode-bound tenants plus a mid-run
    prefill WAVE of fresh arrivals.  On the fused engine every wave
    arrival's ``[M,P]`` prefill serializes with the resident decode
    steps, so steady tenants' tokens stall and the wave's own TTFT
    queues behind decode work; with the planes split, the wave lands on
    prefill replicas while the decode plane gang-steps undisturbed —
    the separation the disagg TTFT gate measures at fixed total
    hardware."""
    traffics = [
        TenantTraffic(tenant=f"steady{i}", per_cycle=1, every=every,
                      start_cycle=i % every)
        for i in range(tenants)
    ]
    traffics.append(TenantTraffic(
        tenant="wave", per_cycle=wave_per_cycle, start_cycle=wave_start,
        end_cycle=wave_start + wave_cycles,
    ))
    return TenantScenario(
        name="disagg-wave", cycles=cycles, traffics=tuple(traffics),
        description=(
            "%d steady tenants send 1 req every %d cycles; a prefill "
            "wave of %d req/cycle runs cycles %d..%d"
            % (tenants, every, wave_per_cycle, wave_start,
               wave_start + wave_cycles)
        ),
    )


def draft_probe_prompts(
    count: int, prompt_len: int, vocab: int, seed: int = 0,
) -> "list[list[int]]":
    """``count`` deterministic candidate prompts for the speculative
    accept-rate probe.  Whether a draft model (the full model's first
    ``k`` layers) agrees with the full model is a property of the
    weights, not the prompt tag — so the bench MEASURES each candidate's
    accept rate on the real seeded model and partitions the pool into
    draft-friendly and draft-hostile halves; this helper only pins the
    candidate stream so the partition is reproducible."""
    return [
        seeded_token_ids(f"draft-probe:{seed}:{i}", prompt_len, vocab)
        for i in range(count)
    ]


def without_flood(scenario: TenantScenario) -> TenantScenario:
    """The scenario's no-flood control: identical victim schedules,
    adversary removed — the baseline the isolation gate compares
    victim TTFT against."""
    import dataclasses

    return dataclasses.replace(
        scenario,
        name=f"{scenario.name}~control",
        traffics=tuple(t for t in scenario.traffics if not t.flood),
    )
