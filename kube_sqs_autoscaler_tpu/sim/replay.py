"""Counterfactual replay: re-drive the control loop from a flight journal.

Closes the loop between the live controller and the scenario battery
(BLITZSCALE's fast-postmortem motivation, arxiv 2412.17246; KIS-S's
trace-driven policy evaluation, arxiv 2507.07932).  Two modes:

- :func:`replay` — **deterministic re-drive**: feed the journal's recorded
  observations (and recorded actuation failures) back through the *real*
  ``ControlLoop`` on a ``FakeClock`` pinned to the recorded tick times,
  and assert the loop reproduces the recorded gate decisions and replica
  trajectory tick-for-tick.  Any divergence means the build no longer
  makes the decisions the journal documents — the regression gate behind
  ``make replay-demo``.
- :func:`counterfactual` — **re-score under another policy**: infer the
  episode's arrival process from the recorded depths and replica
  trajectory (piecewise-constant rates, exact at observation points),
  rebuild the closed-loop world, and run any policy/forecaster through it
  (``bench.py --suite replay``), scored on the same
  :func:`~.evaluate.score_result` numbers as the synthetic battery.

Journals record what the loop *saw*; the world inference needs what the
world *was* (service rate, scaler bounds), which sim-recorded journals
carry in their header meta (:func:`sim_journal_meta`).  Live journals can
replay mode 1 with just the controller config; mode 2 additionally needs
the ``world`` meta block.
"""

from __future__ import annotations

import argparse
import bisect
import json
import sys
import tempfile
from dataclasses import dataclass, field
from typing import Any, Sequence

from ..core.clock import FakeClock
from ..core.events import MultiObserver, TickObserver, TickRecord
from ..core.loop import ControlLoop, LoopConfig
from ..core.policy import PolicyConfig, initial_state
from ..core.resilience import ResilienceConfig
from ..core.types import MetricError, ScaleError
from .simulator import SimConfig, Simulation

#: Record fields whose recorded/replayed values must match tick-for-tick.
#: ``stale`` is a decision: a held-depth tick proceeds to the gates while
#: a fail-static tick ends at the observation, and the two must replay as
#: what they were.
DECISION_FIELDS = (
    "metric_error",
    "num_messages",
    "decision_messages",
    "stale",
    "up",
    "down",
    "up_error",
    "down_error",
)


@dataclass(frozen=True)
class Divergence:
    """One recorded-vs-replayed mismatch."""

    tick: int
    tick_field: str
    recorded: Any
    replayed: Any


@dataclass
class ReplayResult:
    """Outcome of one deterministic re-drive."""

    ticks: int
    divergences: list[Divergence]
    #: replicas entering each tick (same alignment as the sim timeline)
    start_replicas: list[int]
    final_replicas: int
    #: True when the journal's world meta had no initial_replicas (live
    #: journals: the controller cannot know the deployment's size without
    #: an extra RPC) — the replica trajectory then starts from an ASSUMED
    #: 1 and is relative, not absolute; gate decisions are unaffected
    #: (they threshold depth only).
    assumed_initial_replicas: bool = False
    records: list[TickRecord] = field(repr=False, default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.divergences

    def format_divergences(self, limit: int = 10) -> list[str]:
        """Human-readable divergence lines (shared by the replay CLI and
        ``bench.py --suite replay`` so the report format cannot drift)."""
        return [
            f"tick {d.tick}: {d.tick_field} recorded={d.recorded!r}"
            f" replayed={d.replayed!r}"
            for d in self.divergences[:limit]
        ]


class _ScriptedSource:
    """MetricSource replaying the journal's observations, one per tick.

    With ``raise_for_stale`` (journals recorded under a stale-depth
    hold), a recorded-stale tick replays as the *poll failure* it was:
    the replayed loop's own stale hold then regenerates the held depth
    from its last fresh observation — the same mechanism, not a
    transcript of its output.  Without it (reference journals), stale
    records never appear and the flag is moot.
    """

    def __init__(self, raise_for_stale: bool = False) -> None:
        self.record: TickRecord | None = None
        self.raise_for_stale = raise_for_stale

    def num_messages(self) -> int:
        record = self.record
        assert record is not None, "arm() must run before each tick"
        if record.metric_error is not None:
            raise MetricError(record.metric_error)
        if record.stale and self.raise_for_stale:
            raise MetricError("replayed stale-held poll failure")
        assert record.num_messages is not None
        return record.num_messages


class _ScriptedScaler:
    """Bounded step scaler with per-tick scripted failures.

    Mirrors ``PodAutoScaler``'s clamp semantics (boundary no-op = success)
    but holds replicas in memory and raises the journal's recorded error
    strings, so a replayed actuation failure reproduces the recorded
    record byte-for-byte and leaves policy state unadvanced, exactly as
    the live episode did.
    """

    def __init__(
        self,
        initial: int,
        min_pods: int,
        max_pods: int,
        scale_up_pods: int,
        scale_down_pods: int,
    ) -> None:
        self.replicas = initial
        self.min_pods = min_pods
        self.max_pods = max_pods
        self.scale_up_pods = scale_up_pods
        self.scale_down_pods = scale_down_pods
        self._up_error: str | None = None
        self._down_error: str | None = None

    def arm(self, up_error: str | None, down_error: str | None) -> None:
        self._up_error = up_error
        self._down_error = down_error

    def scale_up(self) -> None:
        if self._up_error is not None:
            raise ScaleError(self._up_error)
        self.replicas = min(self.max_pods, self.replicas + self.scale_up_pods)

    def scale_down(self) -> None:
        if self._down_error is not None:
            raise ScaleError(self._down_error)
        self.replicas = max(
            self.min_pods, self.replicas - self.scale_down_pods
        )


class _Recorder:
    def __init__(self) -> None:
        self.records: list[TickRecord] = []

    def on_tick(self, record: TickRecord) -> None:
        self.records.append(record)


def sim_journal_meta(config: SimConfig) -> dict[str, Any]:
    """Journal header meta for a simulated episode: everything replay and
    counterfactual re-scoring need to re-drive it."""
    policy = config.loop.policy
    meta: dict[str, Any] = {
        "source": "sim",
        "t0": 0.0,
        "poll_interval": config.loop.poll_interval,
        "policy_config": {
            "scale_up_messages": policy.scale_up_messages,
            "scale_down_messages": policy.scale_down_messages,
            "scale_up_cooldown": policy.scale_up_cooldown,
            "scale_down_cooldown": policy.scale_down_cooldown,
        },
        "policy": config.policy,
        "world": {
            "service_rate_per_replica": config.service_rate_per_replica,
            "initial_depth": config.initial_depth,
            "initial_replicas": config.initial_replicas,
            "min_pods": config.min_pods,
            "max_pods": config.max_pods,
            "scale_up_pods": config.scale_up_pods,
            "scale_down_pods": config.scale_down_pods,
            "duration": config.duration,
        },
    }
    if config.policy == "predictive":
        meta["forecast"] = {
            "forecaster": config.forecaster,
            "horizon": config.forecast_horizon,
            "history": config.forecast_history,
            "min_samples": config.forecast_min_samples,
            "conservative": config.forecast_conservative,
        }
    if config.policy == "learned" and config.learned_checkpoint is not None:
        # the hash names which weights ran; replay demands a checkpoint
        # matching it (weights are an artifact, not journal content)
        meta["learn"] = {
            "checkpoint_hash": config.learned_checkpoint.hash,
            "hidden": int(config.learned_checkpoint.hidden),
            "history": config.forecast_history,
            "min_samples": config.forecast_min_samples,
        }
    if config.resilience is not None and config.resilience.enabled:
        # replay needs the stale TTL to re-derive held-depth decisions;
        # the rest documents what could appear in the tick lines
        meta["resilience"] = {
            "metric_retries": config.resilience.metric_retries,
            "metric_timeout": config.resilience.metric_timeout,
            "scaler_retries": config.resilience.scaler_retries,
            "scaler_timeout": config.resilience.scaler_timeout,
            "breaker_failures": config.resilience.breaker_failures,
            "breaker_reset": config.resilience.breaker_reset,
            "stale_depth_ttl": config.resilience.stale_depth_ttl,
        }
    return meta


def loop_config_from_meta(meta: dict[str, Any]) -> LoopConfig:
    policy = meta.get("policy_config") or {}
    return LoopConfig(
        poll_interval=float(meta.get("poll_interval", 5.0)),
        policy=PolicyConfig(
            scale_up_messages=int(policy.get("scale_up_messages", 100)),
            scale_down_messages=int(policy.get("scale_down_messages", 10)),
            scale_up_cooldown=float(policy.get("scale_up_cooldown", 10.0)),
            scale_down_cooldown=float(policy.get("scale_down_cooldown", 30.0)),
        ),
    )


def _depth_policy_from_meta(
    meta: dict[str, Any],
    checkpoint: Any = None,
) -> tuple[Any, TickObserver | None]:
    """(depth policy, its history observer) for a predictive or learned
    journal; (None, None) for reactive."""
    if meta.get("policy") == "learned":
        # Weights are a deployment artifact, not journal content — the
        # journal records only their content hash, so re-driving a
        # learned episode needs the caller to supply the checkpoint and
        # we verify it is THE one that ran.
        learn = meta.get("learn") or {}
        recorded_hash = learn.get("checkpoint_hash")
        if checkpoint is None:
            raise ValueError(
                f"this journal was recorded under a learned policy"
                f" (checkpoint hash {recorded_hash!r}); pass the matching"
                f" checkpoint via checkpoint= to replay it"
            )
        if recorded_hash is not None and checkpoint.hash != recorded_hash:
            raise ValueError(
                f"checkpoint hash {checkpoint.hash!r} does not match the"
                f" journal's recorded weights {recorded_hash!r} — replaying"
                f" different weights would silently re-score a different"
                f" policy"
            )
        from ..forecast import DepthHistory
        from ..learn import LearnedPolicy
        from ..learn.checkpoint import checkpoint_history

        default_history, default_min = checkpoint_history(checkpoint)
        config = loop_config_from_meta(meta)
        world = meta.get("world") or {}
        policy = LearnedPolicy(
            checkpoint,
            policy=config.policy,
            poll_interval=config.poll_interval,
            max_pods=int(world.get("max_pods", 5)),
            min_pods=int(world.get("min_pods", 1)),
            scale_up_pods=int(world.get("scale_up_pods", 1)),
            scale_down_pods=int(world.get("scale_down_pods", 1)),
            # Live journals omit initial_replicas (the controller never
            # knows the deployment's size; see cli._journal_meta) and the
            # live mirror starts at min_pods — start the replay mirror at
            # the same place or decisions diverge on a faithful journal.
            initial_replicas=int(
                world.get("initial_replicas", world.get("min_pods", 1))
            ),
            history=DepthHistory(
                capacity=int(learn.get("history", default_history))
            ),
            min_samples=int(learn.get("min_samples", default_min)),
        )
        # the policy is its own observer (history + replica/cooldown mirror)
        return policy, policy
    if meta.get("policy") != "predictive":
        return None, None
    # Lazy import: reactive replays stay JAX-free, like the live CLI.
    from ..forecast import DepthHistory, PredictivePolicy, make_forecaster

    forecast = meta.get("forecast") or {}
    history = DepthHistory(capacity=int(forecast.get("history", 128)))
    policy = PredictivePolicy(
        make_forecaster(forecast.get("forecaster", "holt")),
        history,
        horizon=float(forecast.get("horizon", 60.0)),
        min_samples=int(forecast.get("min_samples", 3)),
        conservative=bool(forecast.get("conservative", True)),
    )
    return policy, history


def replay(
    records: Sequence[TickRecord],
    meta: dict[str, Any],
    checkpoint: Any = None,
) -> ReplayResult:
    """Deterministically re-drive ``ControlLoop`` over a recorded episode.

    The clock is pinned to each record's recorded start before its tick
    runs, so cooldown arithmetic sees exactly the recorded instants —
    journals from the simulator replay bit-exactly; wall-clock journals
    replay to within the (sub-tick) drift of their in-tick clock reads.

    Journals recorded under a stale-depth hold (``meta["resilience"]``
    carries ``stale_depth_ttl``) replay the hold through the real
    mechanism: recorded-stale ticks re-raise as poll failures and the
    replayed loop's own hold regenerates the held depth, its TTL-expiry
    decisions, and — critically — the forecaster-history *skip* the live
    loop applied (feeding held depths to the history would forecast from
    data the live policy never saw).  Retries/timeouts/breaker are
    deliberately NOT re-driven (their backoff sleeps would need the live
    RNG stream replayed draw-for-draw); their in-tick clock consumption
    falls under the same sub-tick-drift caveat as wall-clock reads.
    """
    records = list(records)
    if not records:
        raise ValueError("cannot replay an empty journal")
    config = loop_config_from_meta(meta)
    t0 = float(meta.get("t0", records[0].start - config.poll_interval))
    world = meta.get("world") or {}
    scaler = _ScriptedScaler(
        initial=int(world.get("initial_replicas", 1)),
        min_pods=int(world.get("min_pods", 1)),
        max_pods=int(world.get("max_pods", 5)),
        scale_up_pods=int(world.get("scale_up_pods", 1)),
        scale_down_pods=int(world.get("scale_down_pods", 1)),
    )
    stale_ttl = float(
        (meta.get("resilience") or {}).get("stale_depth_ttl", 0.0) or 0.0
    )
    source = _ScriptedSource(raise_for_stale=stale_ttl > 0)
    depth_policy, history = _depth_policy_from_meta(meta, checkpoint)
    recorder = _Recorder()
    observers: list[TickObserver] = [recorder]
    if history is not None:
        observers.insert(0, history)
    clock = FakeClock(start=t0)
    loop = ControlLoop(
        scaler,
        source,
        config,
        clock=clock,
        observer=MultiObserver(observers),
        depth_policy=depth_policy,
        resilience=(
            ResilienceConfig(stale_depth_ttl=stale_ttl)
            if stale_ttl > 0
            else None
        ),
    )
    state = initial_state(clock.now())
    start_replicas: list[int] = []
    for record in records:
        clock.advance(max(0.0, record.start - clock.now()))
        source.record = record
        scaler.arm(record.up_error, record.down_error)
        start_replicas.append(scaler.replicas)
        state = loop.tick(state)

    divergences: list[Divergence] = []
    for index, (recorded, replayed) in enumerate(
        zip(records, recorder.records)
    ):
        for name in DECISION_FIELDS:
            a, b = getattr(recorded, name), getattr(replayed, name)
            if a != b:
                divergences.append(Divergence(index, name, a, b))
    return ReplayResult(
        ticks=len(recorder.records),
        divergences=divergences,
        start_replicas=start_replicas,
        final_replicas=scaler.replicas,
        assumed_initial_replicas="initial_replicas" not in world,
        records=recorder.records,
    )


def replay_journal(path: str, checkpoint: Any = None) -> ReplayResult:
    """:func:`replay` straight from a journal file.

    A journal accumulates one episode per controller restart (each restart
    appends a fresh header); episodes are separate loop runs with their own
    startup-grace state and clock epoch, so they cannot be replayed as one.
    This replays the journal's **last** episode — the natural postmortem
    target; use :func:`~..obs.journal.read_journal_episodes` + :func:`replay`
    to examine earlier ones.

    Size rotation splits one episode across files: the live file then opens
    with a *continuation* header, and the episode's head lives in
    ``<path>.1``.  That head is rejoined automatically; if it was itself
    rotated away (more than one rotation per episode with one kept
    generation), replay refuses rather than re-applying a bogus
    startup-grace window mid-episode.
    """
    import os

    from ..obs.journal import read_journal_episodes

    non_empty = [(m, r) for m, r in read_journal_episodes(path) if r]
    if not non_empty:
        raise ValueError(f"journal {path!r} holds no tick records")
    meta, records = non_empty[-1]
    if meta.get("_continuation"):
        rotated = path + ".1"
        # the head is the rotated file's LAST episode, empty or not: a
        # restart header that was rotated out before its first tick landed
        # is still the episode boundary — filtering empties here would
        # graft the previous run's records onto this episode
        previous = (
            read_journal_episodes(rotated) if os.path.exists(rotated) else []
        )
        if not previous or previous[-1][0].get("_continuation"):
            raise ValueError(
                f"journal {path!r} starts mid-episode (rotation"
                " continuation) and the episode's head is no longer"
                " available — record with a larger --journal-max-bytes or"
                " replay the .1 generation"
            )
        head_meta, head_records = previous[-1]
        meta, records = head_meta, head_records + records
    return replay(records, meta, checkpoint)


def stitch_restart_episodes(path: str) -> list[dict[str, Any]]:
    """Pair every post-crash episode with its pre-crash predecessor.

    A controller rehydrating from a :class:`~..core.durable`
    snapshot stamps its fresh journal header with a ``restart`` meta
    block (snapshot content hash, recovered/expired record counts,
    downtime) — see ``DurableStateStore.restart_journal_meta``.  This
    walks the journal's episodes and returns one stitch per restart
    header: which snapshot the new boot rose from, how much state
    actually survived, and what the pre-crash episode looked like at
    the moment it died (tick count, last successful actuations) — the
    postmortem view of "did the state that mattered make it across".

    Rotation continuations are not restarts and are skipped; episodes
    without a ``restart`` block (pre-durability runs, cold starts onto
    a fresh path) contribute nothing.
    """
    from ..obs.journal import read_journal_episodes

    episodes = read_journal_episodes(path)
    stitches: list[dict[str, Any]] = []
    for index, (meta, records) in enumerate(episodes):
        restart = meta.get("restart")
        if not isinstance(restart, dict) or meta.get("_continuation"):
            continue
        # the pre-crash episode: the newest earlier non-continuation
        # boot plus its trailing continuations
        prior_records: list[TickRecord] = []
        for prior_meta, prior in reversed(episodes[:index]):
            prior_records = list(prior) + prior_records
            if not prior_meta.get("_continuation"):
                break
        stitches.append({
            "episode": index,
            "snapshot_hash": restart.get("snapshot_hash"),
            "records_recovered": restart.get("records_recovered"),
            "records_expired": restart.get("records_expired"),
            "cold_start": restart.get("cold_start"),
            "downtime_s": restart.get("downtime_s"),
            "prior_ticks": len(prior_records),
            "prior_scaled_up": sum(
                1 for r in prior_records if r.scaled("up")
            ),
            "prior_scaled_down": sum(
                1 for r in prior_records if r.scaled("down")
            ),
            "post_ticks": len(records),
        })
    return stitches


@dataclass(frozen=True)
class RecordedArrival:
    """Piecewise-constant arrival process inferred from a journal.

    Segment ``i`` carries ``rates[i]`` msg/s over ``[times[i],
    times[i+1])``; the last segment extends indefinitely (and the first
    extends backwards before ``times[0]``).  Satisfies the
    :class:`~.scenarios.ArrivalProcess` protocol, so the simulator
    integrates it exactly at observation points like any synthetic shape.

    One segment per recorded tick and one ``arrivals_between`` call per
    simulated tick would make a naive per-call scan O(n²) over an episode
    — a day-long journal is ~17k ticks — so the cumulative integral is
    precomputed once and each call is two O(log n) lookups.
    """

    times: tuple[float, ...]
    rates: tuple[float, ...]

    def __post_init__(self):
        if len(self.times) != len(self.rates):
            raise ValueError("times and rates must have equal length")
        cumulative = [0.0]
        for i in range(1, len(self.times)):
            cumulative.append(
                cumulative[-1]
                + self.rates[i - 1] * (self.times[i] - self.times[i - 1])
            )
        # frozen dataclass: the cache is set once here, never mutated
        object.__setattr__(self, "_cumulative", tuple(cumulative))

    def _segment(self, t: float) -> int:
        return max(0, bisect.bisect_right(self.times, t) - 1)

    def rate_at(self, t: float) -> float:
        if not self.times:
            return 0.0
        return self.rates[self._segment(t)]

    def _integral_to(self, t: float) -> float:
        """``∫ rate`` from ``times[0]`` to ``t`` (negative before it)."""
        if t <= self.times[0]:
            return self.rates[0] * (t - self.times[0])
        i = self._segment(t)
        return self._cumulative[i] + self.rates[i] * (t - self.times[i])

    def arrivals_between(self, t0: float, t1: float) -> float:
        if not self.times:
            return 0.0
        return self._integral_to(t1) - self._integral_to(t0)


def infer_arrivals(
    records: Sequence[TickRecord], meta: dict[str, Any]
) -> RecordedArrival:
    """Reconstruct the arrival process a recorded episode experienced.

    Between consecutive observations the queue gained ``Δdepth`` while
    ``replicas × service_rate`` drained it, so the interval's arrival rate
    is ``max(0, Δdepth + drained) / Δt`` — exact unless the queue emptied
    mid-interval (then a lower bound, same caveat as the simulator's own
    per-interval floor).  The replica count per interval is reconstructed
    from the journal's successful actuations and the world's bounds.

    Segment times are **episode-relative** (the first interval starts at
    0): the counterfactual simulator's clock starts at 0, so a live
    journal's wall-clock epochs must not leak into the process — with a
    sim journal's ``t0: 0`` the shift is a no-op.
    """
    world = meta.get("world") or {}
    if "service_rate_per_replica" not in world:
        raise ValueError(
            "journal meta lacks world.service_rate_per_replica — cannot"
            " infer arrivals (counterfactual needs a sim-recorded journal"
            " or a live journal with a world block)"
        )
    if not records:
        raise ValueError("journal holds no tick records")
    service_rate = float(world["service_rate_per_replica"])
    replicas = int(world.get("initial_replicas", 1))
    min_pods = int(world.get("min_pods", 1))
    max_pods = int(world.get("max_pods", 5))
    up_step = int(world.get("scale_up_pods", 1))
    down_step = int(world.get("scale_down_pods", 1))
    poll = float(meta.get("poll_interval", 5.0))
    t0 = float(meta.get("t0", records[0].start - poll))
    t_prev = t0
    depth_prev = float(world.get("initial_depth", 0.0))
    times: list[float] = []
    rates: list[float] = []
    for record in records:
        if record.num_messages is None:
            continue  # metric failure: no observation, interval extends
        dt = record.start - t_prev
        if dt > 0:
            drained = replicas * service_rate * dt
            arrived = max(0.0, record.num_messages - depth_prev + drained)
            times.append(t_prev - t0)
            rates.append(arrived / dt)
        if record.scaled("up"):
            replicas = min(max_pods, replicas + up_step)
        if record.scaled("down"):
            replicas = max(min_pods, replicas - down_step)
        t_prev = record.start
        depth_prev = float(record.num_messages)
    if not times:
        raise ValueError("journal holds no usable observation intervals")
    return RecordedArrival(tuple(times), tuple(rates))


def counterfactual(
    records: Sequence[TickRecord],
    meta: dict[str, Any],
    policy: str = "reactive",
    forecaster: str = "holt",
    horizon: float | None = None,
    slo_depth: float = 300.0,
    checkpoint: Any = None,
) -> dict:
    """Re-score a recorded episode under any policy/forecaster.

    Rebuilds the recorded world (inferred arrivals + the journal's world
    parameters), runs the requested policy through the full closed-loop
    simulator, and scores it with the battery's
    :func:`~.evaluate.score_result` — so "what would the holt forecaster
    have done during yesterday's incident?" is one function call.

    ``policy="learned"`` re-scores a trained network
    (:mod:`..learn`): pass its ``checkpoint``; the row is labeled with
    the checkpoint's content hash so an incident review names exactly
    which weights the what-if ran.
    """
    from .evaluate import score_result

    records = list(records)
    world = meta.get("world") or {}
    arrival = infer_arrivals(records, meta)
    loop_config = loop_config_from_meta(meta)
    forecast = meta.get("forecast") or {}
    if horizon is None:
        horizon = float(forecast.get("horizon", 60.0))
    history = int(forecast.get("history", 128))
    min_samples = int(forecast.get("min_samples", 3))
    if policy == "learned":
        if checkpoint is None:
            raise ValueError(
                "counterfactual(policy='learned') needs the trained"
                " weights: pass checkpoint=load_checkpoint(path)"
            )
        # the feature window is part of what the weights mean — it comes
        # from the checkpoint, not from the journal's forecast block
        from ..learn.checkpoint import checkpoint_history

        history, min_samples = checkpoint_history(checkpoint)
    # duration spans ALL recorded ticks — metric-failure ticks consumed a
    # poll interval too, so filtering them out here would truncate the
    # rebuilt episode and score a shorter world than the recorded row
    duration = len(records) * loop_config.poll_interval
    sim = Simulation(
        SimConfig(
            arrival_rate=arrival,
            service_rate_per_replica=float(world["service_rate_per_replica"]),
            duration=duration,
            initial_depth=float(world.get("initial_depth", 0.0)),
            initial_replicas=int(world.get("initial_replicas", 1)),
            min_pods=int(world.get("min_pods", 1)),
            max_pods=int(world.get("max_pods", 5)),
            scale_up_pods=int(world.get("scale_up_pods", 1)),
            scale_down_pods=int(world.get("scale_down_pods", 1)),
            loop=loop_config,
            policy=policy,
            forecaster=forecaster,
            forecast_horizon=horizon,
            # honor the recorded forecast configuration like replay() does:
            # re-scoring "the recorded policy" with default warm-up/gating
            # would silently score a different policy
            forecast_history=history,
            forecast_min_samples=min_samples,
            forecast_conservative=bool(forecast.get("conservative", True)),
            learned_checkpoint=checkpoint if policy == "learned" else None,
        )
    )
    result = sim.run()
    row = score_result(result, slo_depth)
    if policy == "reactive":
        row["policy"] = "reactive"
    elif policy == "learned":
        row["policy"] = f"learned@{checkpoint.hash}"
    else:
        row["policy"] = f"{policy}:{forecaster}"
    return row


def record_episode(
    config: SimConfig, journal_path: str
) -> "tuple[dict[str, Any], Any]":
    """Run one simulated episode with a flight journal attached.

    Returns ``(meta, SimResult)``; the journal lands on disk at
    ``journal_path`` ready for :func:`replay_journal`.
    """
    from ..obs.journal import TickJournal

    meta = sim_journal_meta(config)
    with TickJournal(journal_path, meta=meta) as journal:
        sim = Simulation(config, extra_observers=(journal,))
        result = sim.run()
    return meta, result


def _demo_config() -> SimConfig:
    """A short, scaling-active episode for ``make replay-demo``: a burst
    world that exercises both gates, cooldown skips, and bound clamps —
    sized so the fleet is *not* saturated, leaving the counterfactual
    forecasters real headroom to beat the recorded reactive run."""
    from .scenarios import BurstArrival

    return SimConfig(
        arrival_rate=BurstArrival(
            base=20.0, burst_rate=140.0, period=200.0,
            burst_len=60.0, first_burst=60.0,
        ),
        service_rate_per_replica=10.0,
        duration=400.0,
        initial_replicas=2,
        max_pods=20,
    )


def main(argv: Sequence[str] | None = None) -> int:
    """Record (or load) a journal, verify replay fidelity, print a verdict.

    Exit status 0 = tick-for-tick reproduction; 2 = divergence (the
    ``make replay-demo`` contract: any decision drift fails the build).
    """
    parser = argparse.ArgumentParser(
        description="Replay a controller flight journal and verify the "
        "recorded decisions reproduce tick-for-tick."
    )
    parser.add_argument(
        "--journal", default="",
        help="journal to replay (default: record a fresh demo episode)",
    )
    parser.add_argument(
        "--record-to", default="",
        help="where the demo episode's journal is written (default: a"
        " temporary directory)",
    )
    parser.add_argument(
        "--checkpoint", default="",
        help="learned-policy checkpoint (JSON) for journals recorded under"
        " --policy=learned; must match the journal's recorded weights hash",
    )
    args = parser.parse_args(argv)
    checkpoint = None
    if args.checkpoint:
        from ..learn.checkpoint import CheckpointError, load_checkpoint

        try:
            checkpoint = load_checkpoint(args.checkpoint)
        except CheckpointError as err:
            parser.error(str(err))
    path = args.journal
    if not path:
        path = args.record_to or (
            tempfile.mkdtemp(prefix="replay-demo-") + "/journal.jsonl"
        )
        record_episode(_demo_config(), path)
    try:
        result = replay_journal(path, checkpoint=checkpoint)
    except ValueError as err:
        # e.g. a learned journal without (or with mismatched) weights:
        # an actionable message and the tool's exit-2 verdict, not a
        # traceback
        print(f"cannot replay {path}: {err}", file=sys.stderr)
        return 2
    print(
        json.dumps(
            {
                "journal": path,
                "ticks": result.ticks,
                "divergences": len(result.divergences),
                "final_replicas": result.final_replicas,
                "trajectory_assumed_start": result.assumed_initial_replicas,
                "ok": result.ok,
            }
        )
    )
    for line in result.format_divergences():
        print(line, file=sys.stderr)
    return 0 if result.ok else 2


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
