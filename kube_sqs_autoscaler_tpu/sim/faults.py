"""Deterministic failure processes for chaos-testing the control loop.

The arrival processes in :mod:`.scenarios` made *demand* a first-class,
exactly-integrable input to the simulator; this module does the same for
*failure*: outages and latency spikes are values injected into the
closed-loop simulator (``SimConfig.faults``), not monkeypatches — so the
chaos battery in :mod:`.evaluate` scores recovery behavior with the
same determinism the forecast battery scores prediction.

A :class:`FailureProcess` answers, for each controller RPC at virtual
time ``t``, one :class:`Fault`: optional extra latency the call consumes
(the clock advances — tick budget is real) and an optional error the
call then raises (``MetricError``/``ScaleError``, exactly the failure
types the production clients throw).  Concrete processes:

- :class:`Blackout`      — one dead window (metric, scaler, or both —
  "both" is the correlated outage: the AZ is gone, not one endpoint);
- :class:`BurstyOutage`  — rectangular outage windows at the start of
  every period (the failure-shaped twin of ``BurstArrival``);
- :class:`FlakyCalls`    — per-call random failures, derandomized by
  hashing ``(seed, t)`` so any two controller configs polling at the
  same instants face the *same* fault draw (fair A/B scoring), while
  retried attempts — which happen after a backoff, at a different
  ``t`` — get fresh draws;
- :class:`LatencySpikes` — calls succeed but consume extra virtual
  seconds inside windows (a slow dependency, not a dead one);
- :func:`compose`        — overlay several processes (latencies add,
  first error wins).

Runnable as ``python -m kube_sqs_autoscaler_tpu.sim.faults`` — the
``make chaos-demo`` gate: a JAX-free deterministic episode through a
correlated outage, asserting the resilience layer's expected trajectory
(retries burn, stale hold engages then expires to fail-static, the
breaker opens and re-closes via a half-open probe, the fleet recovers).
Exit 0 = every milestone seen; exit 2 = unexpected trajectory.
"""

from __future__ import annotations

import argparse
import json
import random
import sys
from dataclasses import dataclass
from typing import Protocol, Sequence, runtime_checkable

from ..core.clock import Clock
from ..core.types import MetricError, ScaleError


@dataclass(frozen=True)
class Fault:
    """What one call experiences: added latency, then (optionally) an error."""

    error: str | None = None
    latency: float = 0.0


#: The no-fault outcome (shared instance; Fault is frozen).
OK = Fault()


@runtime_checkable
class FailureProcess(Protocol):
    """Deterministic per-call fault decisions over simulated time."""

    def metric_fault(self, t: float) -> Fault:
        """Fault for a metric poll issued at time ``t``."""
        ...

    def scale_fault(self, t: float) -> Fault:
        """Fault for a scaler call issued at time ``t``."""
        ...


@dataclass(frozen=True)
class Blackout:
    """One outage window ``[start, start + duration)``.

    ``metric``/``scale`` choose the failing surface; both True is the
    *correlated* outage.  ``latency`` is what each failing call still
    costs before erroring (a timing-out RPC is slow, not instant).
    """

    start: float
    duration: float
    metric: bool = True
    scale: bool = False
    latency: float = 0.0

    def _fault(self, t: float, affected: bool, what: str) -> Fault:
        if affected and self.start <= t < self.start + self.duration:
            return Fault(
                error=f"{what} outage (blackout t={self.start:g}"
                f"+{self.duration:g})",
                latency=self.latency,
            )
        return OK

    def metric_fault(self, t: float) -> Fault:
        return self._fault(t, self.metric, "metric")

    def scale_fault(self, t: float) -> Fault:
        return self._fault(t, self.scale, "scaler")


@dataclass(frozen=True)
class BurstyOutage:
    """Rectangular outages: dead for ``outage_len`` s at the start of every
    ``period``, healthy in between (mirrors ``scenarios.BurstArrival``)."""

    period: float
    outage_len: float
    first: float = 0.0
    metric: bool = True
    scale: bool = False
    latency: float = 0.0

    def __post_init__(self):
        if not 0 < self.outage_len <= self.period:
            raise ValueError("need 0 < outage_len <= period")

    def _down(self, t: float) -> bool:
        if t < self.first:
            return False
        return (t - self.first) % self.period < self.outage_len

    def _fault(self, t: float, affected: bool, what: str) -> Fault:
        if affected and self._down(t):
            return Fault(
                error=f"{what} outage (bursty period={self.period:g})",
                latency=self.latency,
            )
        return OK

    def metric_fault(self, t: float) -> Fault:
        return self._fault(t, self.metric, "metric")

    def scale_fault(self, t: float) -> Fault:
        return self._fault(t, self.scale, "scaler")


@dataclass(frozen=True)
class FlakyCalls:
    """Memoryless per-call failures at ``failure_rate``, derandomized.

    The draw for a call at time ``t`` is ``Random(f"{seed}:{surface}:
    {round(t, 6)}").random()`` (string seeds hash via SHA-512 — stable
    across processes, unlike ``hash()``) — a pure function of the call
    instant, so
    (a) two episodes over the same process are identical, (b) reference
    and resilient controllers polling on the same cadence face the same
    faults, and (c) a retry after backoff (different ``t``) is a fresh
    independent draw, which is the whole point of retrying.
    """

    failure_rate: float
    seed: int = 0
    metric: bool = True
    scale: bool = False
    latency: float = 0.0

    def __post_init__(self):
        if not 0.0 <= self.failure_rate <= 1.0:
            raise ValueError(
                f"failure_rate must be in [0, 1], got {self.failure_rate}"
            )

    def _fault(self, t: float, affected: bool, what: str) -> Fault:
        if not affected:
            return OK
        draw = random.Random(f"{self.seed}:{what}:{round(t, 6)}").random()
        if draw < self.failure_rate:
            return Fault(
                error=f"{what} call failed (flaky p={self.failure_rate:g},"
                f" t={t:g})",
                latency=self.latency,
            )
        return OK

    def metric_fault(self, t: float) -> Fault:
        return self._fault(t, self.metric, "metric")

    def scale_fault(self, t: float) -> Fault:
        return self._fault(t, self.scale, "scaler")


@dataclass(frozen=True)
class LatencySpikes:
    """Calls *succeed* but consume ``delay`` extra virtual seconds inside
    periodic windows — a slow dependency eating the tick budget."""

    period: float
    spike_len: float
    delay: float
    first: float = 0.0
    metric: bool = True
    scale: bool = False

    def __post_init__(self):
        if not 0 < self.spike_len <= self.period:
            raise ValueError("need 0 < spike_len <= period")

    def _slow(self, t: float) -> bool:
        if t < self.first:
            return False
        return (t - self.first) % self.period < self.spike_len

    def _fault(self, t: float, affected: bool) -> Fault:
        if affected and self._slow(t):
            return Fault(latency=self.delay)
        return OK

    def metric_fault(self, t: float) -> Fault:
        return self._fault(t, self.metric)

    def scale_fault(self, t: float) -> Fault:
        return self._fault(t, self.scale)


@dataclass(frozen=True)
class ComposedFaults:
    """Overlay: latencies add, the first process with an error names it."""

    processes: tuple[FailureProcess, ...]

    def _merge(self, faults: Sequence[Fault]) -> Fault:
        latency = sum(f.latency for f in faults)
        error = next((f.error for f in faults if f.error is not None), None)
        if latency == 0.0 and error is None:
            return OK
        return Fault(error=error, latency=latency)

    def metric_fault(self, t: float) -> Fault:
        return self._merge([p.metric_fault(t) for p in self.processes])

    def scale_fault(self, t: float) -> Fault:
        return self._merge([p.scale_fault(t) for p in self.processes])


def compose(*processes: FailureProcess) -> ComposedFaults:
    """Overlay several failure processes into one."""
    return ComposedFaults(tuple(processes))


@dataclass(frozen=True)
class FleetFaultPlan:
    """Deterministic replica- and shard-fault schedule for the fleet.

    The fleet's analogue of the RPC :class:`FailureProcess`es above:
    faults are values applied at known *pool cycles* (flag flips via
    :meth:`~..fleet.WorkerPool.kill_worker` /
    :meth:`~..fleet.WorkerPool.hang_worker`), not process murder — so
    the fleet chaos battery's zero-lost / zero-duplicate gates replay
    identically every run.  ``kills``/``hangs`` are ``(cycle,
    replica_index)`` pairs; the driver calls :meth:`apply` once per
    cycle BEFORE the cycle runs.  Unknown replica indices fail loudly
    (a plan that kills nobody would gate nothing).

    Shard-granularity faults (the sharded plane's failure domain,
    actuated through :class:`~..fleet.ShardedWorkerPool`'s chaos
    seams): ``shard_poisons``/``shard_wedges`` are ``(start_cycle,
    end_cycle, shard)`` windows — the fault is injected at ``start``
    and healed at ``end`` (end-exclusive, like every window here) —
    and ``shard_mask_corruptions`` are one-shot ``(cycle, shard)``
    device-mask bit flips (the quarantine path's mask re-assert is
    what heals those).

    Admission-plane faults (ISSUE 19 — the sharded admission front is
    its own failure domain): ``admission_kills`` are one-shot
    ``(cycle, admission_shard)`` kills — the shard's staged requests
    hand back via ``change_message_visibility(0)`` and the shard
    rehydrates from its tombstone + gossip on the next cycle — and
    ``admission_partitions`` are ``(start_cycle, end_cycle, shard)``
    gossip-partition windows validated like ``shard_poisons``: the
    shard keeps admitting but neither sends nor receives flood
    classifications until the window heals.
    """

    kills: tuple[tuple[int, int], ...] = ()
    hangs: tuple[tuple[int, int], ...] = ()
    shard_poisons: tuple[tuple[int, int, int], ...] = ()
    shard_wedges: tuple[tuple[int, int, int], ...] = ()
    shard_mask_corruptions: tuple[tuple[int, int], ...] = ()
    admission_kills: tuple[tuple[int, int], ...] = ()
    admission_partitions: tuple[tuple[int, int, int], ...] = ()

    def __post_init__(self):
        for name in ("shard_poisons", "shard_wedges", "admission_partitions"):
            for start, end, _ in getattr(self, name):
                if not start < end:
                    raise ValueError(
                        f"{name} window needs start < end, got "
                        f"[{start}, {end})"
                    )

    def apply(self, cycle: int, pool) -> None:
        for at, index in self.kills:
            if at == cycle:
                pool.kill_worker(index)
        for at, index in self.hangs:
            if at == cycle:
                pool.hang_worker(index)
        for start, end, shard in self.shard_poisons:
            if cycle == start:
                pool.poison_shard(shard, True)
            elif cycle == end:
                pool.poison_shard(shard, False)
        for start, end, shard in self.shard_wedges:
            if cycle == start:
                pool.wedge_shard(shard, True)
            elif cycle == end:
                pool.wedge_shard(shard, False)
        for at, shard in self.shard_mask_corruptions:
            if at == cycle:
                pool.corrupt_shard_mask(shard)
        for at, shard in self.admission_kills:
            if at == cycle:
                pool.kill_admission_shard(shard)
        for start, end, shard in self.admission_partitions:
            if cycle == start:
                pool.partition_admission_shard(shard, True)
            elif cycle == end:
                pool.partition_admission_shard(shard, False)

    def indices(self) -> set[int]:
        """Every replica index the plan touches (for pre-validation)."""
        return {i for _, i in self.kills} | {i for _, i in self.hangs}

    def shards(self) -> set[int]:
        """Every shard index the plan touches (for pre-validation)."""
        return (
            {s for _, _, s in self.shard_poisons}
            | {s for _, _, s in self.shard_wedges}
            | {s for _, s in self.shard_mask_corruptions}
        )

    def admission_shards(self) -> set[int]:
        """Every admission shard the plan touches (for pre-validation)."""
        return {s for _, s in self.admission_kills} | {
            s for _, _, s in self.admission_partitions
        }


# ---------------------------------------------------------------------------
# Controller crash injection (ISSUE 14): the controller itself is a
# failure domain.  A CrashPlan kills the whole controller process — loop
# AND in-process serving pool — at NAMED crash points inside a tick, so
# the restart battery can prove the durable snapshot + rehydration path
# (core/durable.py) at every window a real kill -9 could hit:
#
#   after-observe                the world was polled; nothing actuated,
#                                nothing journaled, nothing snapshotted
#   after-decide                 the gate fired; the crash lands BEFORE
#                                the scaler RPC (the write-ahead intent
#                                is already durable)
#   after-actuate-before-journal the scaler RPC landed; no journal line,
#                                no snapshot — the classic double-scale
#                                window, closed by the intent
#   torn-mid-journal-line        the tick ran fully; the journal write
#                                tore mid-line; the snapshot (which
#                                follows the journal) never happened
#   tick-boundary                everything durable landed; the kill
#                                falls between ticks (the seamless case)
#
# Crashes raise ControllerCrash (a BaseException) so no never-dies guard
# can swallow them — exactly like the process vanishing at that instant.
# ---------------------------------------------------------------------------

CRASH_AFTER_OBSERVE = "after-observe"
CRASH_AFTER_DECIDE = "after-decide"
CRASH_AFTER_ACTUATE = "after-actuate-before-journal"
CRASH_TORN_JOURNAL = "torn-mid-journal-line"
CRASH_TICK_BOUNDARY = "tick-boundary"
CRASH_POINTS = (
    CRASH_AFTER_OBSERVE,
    CRASH_AFTER_DECIDE,
    CRASH_AFTER_ACTUATE,
    CRASH_TORN_JOURNAL,
    CRASH_TICK_BOUNDARY,
)


@dataclass(frozen=True)
class CrashPlan:
    """Deterministic controller-kill schedule: ``(tick_index, point)``
    pairs, tick indices counted across restarts (the driver's tick
    *attempt* counter, 0-based).  Unknown points fail loudly — a plan
    that kills nowhere gates nothing.

    The mid-tick points are actuated by the wrappers below
    (:class:`CrashingMetricSource` / :class:`CrashingScaler` /
    :class:`CrashingJournal`); ``tick-boundary`` is the
    :class:`~..fleet.pool.FleetDriver`'s own post-tick check.  Note the
    actuation points only fire on ticks where a gate actually reaches
    the scaler — schedule them on ticks the episode's backlog makes
    fire, and assert the observed crash count.
    """

    crashes: tuple[tuple[int, str], ...]

    def __post_init__(self):
        for tick, point in self.crashes:
            if tick < 0:
                raise ValueError(f"crash tick must be >= 0, got {tick}")
            if point not in CRASH_POINTS:
                raise ValueError(
                    f"unknown crash point {point!r} (valid: "
                    f"{', '.join(CRASH_POINTS)})"
                )

    def point_at(self, tick: int) -> "str | None":
        """The crash point scheduled for tick ``tick`` (None = none)."""
        for at, point in self.crashes:
            if at == tick:
                return point
        return None

    def boundary_crash(self, tick: int) -> bool:
        return self.point_at(tick) == CRASH_TICK_BOUNDARY


class CrashingMetricSource:
    """MetricSource proxy that kills the controller right AFTER a
    successful observation on the scheduled tick (``tick_fn`` supplies
    the driver's current tick-attempt index)."""

    def __init__(self, inner, plan: CrashPlan, tick_fn) -> None:
        self.inner = inner
        self.plan = plan
        self.tick_fn = tick_fn

    def num_messages(self) -> int:
        value = self.inner.num_messages()
        if self.plan.point_at(self.tick_fn()) == CRASH_AFTER_OBSERVE:
            from ..core.durable import ControllerCrash

            raise ControllerCrash(
                f"injected kill after observe (tick {self.tick_fn()})"
            )
        return value


class CrashingScaler:
    """Scaler proxy for the two actuation-adjacent crash points:
    ``after-decide`` dies BEFORE the wrapped RPC (decision made, intent
    durable, world untouched); ``after-actuate-before-journal`` dies
    right after the RPC returns (world changed, nothing durable knows)."""

    def __init__(self, inner, plan: CrashPlan, tick_fn) -> None:
        self.inner = inner
        self.plan = plan
        self.tick_fn = tick_fn

    @property
    def replicas(self):
        # pass through the observed-world surface (rehydration
        # reconciles against it; stubs without one stay without one)
        return getattr(self.inner, "replicas")

    def _call(self, action, direction: str) -> None:
        from ..core.durable import ControllerCrash

        point = self.plan.point_at(self.tick_fn())
        if point == CRASH_AFTER_DECIDE:
            raise ControllerCrash(
                f"injected kill after decide, before scale_{direction} "
                f"(tick {self.tick_fn()})"
            )
        action()
        if point == CRASH_AFTER_ACTUATE:
            raise ControllerCrash(
                f"injected kill after scale_{direction}, before journal "
                f"(tick {self.tick_fn()})"
            )

    def scale_up(self) -> None:
        self._call(self.inner.scale_up, "up")

    def scale_down(self) -> None:
        self._call(self.inner.scale_down, "down")


class CrashingJournal:
    """TickObserver proxy that TEARS the journal mid-line on the
    scheduled tick — half the record's bytes, no newline — then kills
    the controller.  The loop's observer guard catches ``Exception``
    only, so the ControllerCrash propagates and the tick's snapshot
    (which follows the journal observer) never happens: the restart
    must heal the torn tail (the journal reader already tolerates it)
    and recover the tick from nothing but the previous snapshot."""

    def __init__(self, journal, plan: CrashPlan, tick_fn) -> None:
        self.journal = journal
        self.plan = plan
        self.tick_fn = tick_fn

    def on_tick(self, record) -> None:
        if self.plan.point_at(self.tick_fn()) == CRASH_TORN_JOURNAL:
            from ..core.durable import ControllerCrash

            self.journal.tear(record)
            raise ControllerCrash(
                f"injected kill mid-journal-line (tick {self.tick_fn()})"
            )
        self.journal.on_tick(record)


# ---------------------------------------------------------------------------
# Injection wrappers: the simulator wires these around the REAL metric
# source and scaler, so the system under test stays the production stack.
# ---------------------------------------------------------------------------


class FaultyMetricSource:
    """MetricSource proxy consulting a :class:`FailureProcess` per poll.

    ``on_failure`` (optional) runs before a fault raises — the simulator
    passes its world-advance hook so the queue's true depth is sampled
    (and ``max_depth`` stays honest) even on ticks the controller never
    saw.
    """

    def __init__(
        self,
        inner,
        faults: FailureProcess,
        clock: Clock,
        on_failure=None,
    ) -> None:
        self.inner = inner
        self.faults = faults
        self.clock = clock
        self.on_failure = on_failure

    def num_messages(self) -> int:
        fault = self.faults.metric_fault(self.clock.now())
        if fault.latency > 0:
            self.clock.sleep(fault.latency)
        if fault.error is not None:
            if self.on_failure is not None:
                self.on_failure()
            raise MetricError(fault.error)
        return self.inner.num_messages()


class FaultyScaler:
    """Scaler proxy consulting a :class:`FailureProcess` per actuation."""

    def __init__(self, inner, faults: FailureProcess, clock: Clock) -> None:
        self.inner = inner
        self.faults = faults
        self.clock = clock

    def _call(self, action) -> None:
        fault = self.faults.scale_fault(self.clock.now())
        if fault.latency > 0:
            self.clock.sleep(fault.latency)
        if fault.error is not None:
            raise ScaleError(fault.error)
        action()

    def scale_up(self) -> None:
        self._call(self.inner.scale_up)

    def scale_down(self) -> None:
        self._call(self.inner.scale_down)


# ---------------------------------------------------------------------------
# make chaos-demo: one deterministic episode through a correlated outage.
# ---------------------------------------------------------------------------


def _demo_episode():
    """One FakeClock episode exercising every resilience mechanism.

    World: overload (arrivals far above one replica's capacity) so the
    up gate wants to fire every cooldown.  The metric poll blacks out at
    t=[60, 180); the scaler follows at t=[80, 180) (correlated outage,
    staggered so the stale hold demonstrably *actuates* first).
    Resilience: 2 metric retries, stale TTL 60 s (expires mid-outage →
    fail-static ticks), breaker opens after 2 scaler failures, reset
    25 s (one half-open probe fails inside the outage and re-opens; the
    post-recovery probe succeeds and re-closes).
    """
    from ..core.resilience import ResilienceConfig
    from .simulator import SimConfig, Simulation

    faults = compose(
        Blackout(start=60.0, duration=120.0, metric=True, scale=False),
        Blackout(start=80.0, duration=100.0, metric=False, scale=True),
    )
    resilience = ResilienceConfig(
        metric_retries=2,
        scaler_retries=0,
        breaker_failures=2,
        breaker_reset=25.0,
        stale_depth_ttl=60.0,
    )
    config = SimConfig(
        arrival_rate=80.0,
        service_rate_per_replica=10.0,
        duration=400.0,
        initial_replicas=1,
        max_pods=10,
        faults=faults,
        resilience=resilience,
    )
    from ..obs.journal import TickRing

    ring = TickRing(capacity=512)
    sim = Simulation(config, extra_observers=(ring,))
    result = sim.run()
    return sim, result, ring.snapshot()


def _check_demo(records, result) -> list[str]:
    """The expected trajectory, as individually reportable milestones."""
    problems: list[str] = []

    def expect(condition: bool, message: str) -> None:
        if not condition:
            problems.append(message)

    stale = [r for r in records if r.stale]
    static = [r for r in records if r.metric_error is not None]
    retried = [r for r in records if (r.metric_retries or 0) > 0]
    states = [r.breaker_state for r in records if r.breaker_state]
    expect(bool(retried), "no tick recorded metric retries during the outage")
    expect(bool(stale), "the stale-depth hold never engaged")
    expect(
        bool(static),
        "the stale TTL never expired into fail-static (reference) ticks",
    )
    if stale and static:
        expect(
            min(r.start for r in static) > min(r.start for r in stale),
            "fail-static ticks started before the stale hold did",
        )
    expect("open" in states, "the circuit breaker never opened")
    if "open" in states:
        after_open = states[states.index("open"):]
        expect(
            "closed" in after_open,
            "the breaker never re-closed after the outage",
        )
    # Stale holds must actuate: the held depth sits far above the up
    # threshold, so scale-ups continue until the breaker interferes.
    expect(
        any(r.scaled("up") for r in stale),
        "no stale-held tick successfully scaled up",
    )
    # Recovery: fresh observations resume, the outage backlog pushes the
    # fleet to max_pods, and by episode end the backlog is drained (the
    # fleet may already be scaling back down — that, too, is recovery).
    tail = records[-5:]
    expect(
        all(r.metric_error is None and not r.stale for r in tail),
        "the last ticks are not fresh observations (no recovery)",
    )
    peak_replicas = max((r for _, _, r in result.timeline), default=0)
    expect(
        peak_replicas == 10,
        f"expected the outage backlog to drive the fleet to max_pods=10,"
        f" peaked at {peak_replicas}",
    )
    expect(
        result.final_depth < 300.0,
        f"expected the backlog drained below the SLO depth by episode end,"
        f" got {result.final_depth:.0f}",
    )
    return problems


def main(argv: Sequence[str] | None = None) -> int:
    """Run the chaos demo episode and verify its trajectory.

    Exit 0 = every resilience milestone observed; 2 = unexpected
    trajectory (the ``make chaos-demo`` contract, mirroring
    ``make replay-demo``).
    """
    parser = argparse.ArgumentParser(
        description="Deterministic chaos episode: outage, degraded mode,"
        " breaker trip, recovery — fails on any missing milestone."
    )
    parser.parse_args(argv)
    sim, result, records = _demo_episode()
    problems = _check_demo(records, result)
    states = [r.breaker_state for r in records if r.breaker_state]
    transitions = [s for i, s in enumerate(states) if i == 0 or states[i - 1] != s]
    print(
        json.dumps(
            {
                "ticks": result.ticks,
                "stale_ticks": sum(1 for r in records if r.stale),
                "fail_static_ticks": sum(
                    1 for r in records if r.metric_error is not None
                ),
                "metric_retries": sum(r.metric_retries or 0 for r in records),
                "breaker_transitions": transitions,
                "max_depth": round(result.max_depth, 1),
                "final_replicas": result.final_replicas,
                "ok": not problems,
            }
        )
    )
    for line in problems:
        print(f"unexpected trajectory: {line}", file=sys.stderr)
    return 0 if not problems else 2


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
