"""Autotuning sweeps: grid/random policy search on the compiled simulator.

KIS-S (arxiv 2507.07932) frames autoscaler tuning as simulator-driven
policy search — thousands of candidate configurations scored against the
same deterministic worlds.  PR 1's scenario battery explored 4 policies;
this driver explores the (gate × policy × forecast) parameter space —
thresholds, cooldowns, scale step, forecaster, horizon, history — by
batching every (scenario × configuration) point through the compiled
``lax.scan`` simulator (:mod:`.compiled`), so a few hundred episodes cost
one device call.

Scoring reuses the battery's :func:`~.evaluate.score_result` verbatim:
the compiled episodes come back as ordinary
:class:`~.simulator.SimResult` objects, so sweep rows, battery rows, and
counterfactual replay rows are judged on identical numbers.  The summary
reports, per scenario, the best configuration (lexicographic: max depth,
then churn, then time-over-SLO) and the max-depth-vs-churn Pareto front —
the two-axis tradeoff a fleet operator actually tunes.

``bench.py --suite sweep`` (``make bench-sweep``) runs
:func:`~.compiled.verify_fidelity` first, then a default grid, and writes
``BENCH_r08.json``.
"""

from __future__ import annotations

import itertools
import random
from dataclasses import asdict, dataclass, field, replace
from typing import Iterable, Sequence

from ..core.loop import LoopConfig
from ..core.policy import PolicyConfig
from .evaluate import Scenario, default_battery, score_result
from .simulator import SimConfig


@dataclass(frozen=True)
class SweepPoint:
    """One candidate configuration: gate knobs + depth policy knobs.

    ``policy`` is ``"reactive"`` or a forecaster name (``ewma``/``holt``/
    ``lstsq``); ``horizon``/``history`` only apply to forecaster points.
    """

    scale_up_messages: int = 100
    scale_down_messages: int = 10
    scale_up_cooldown: float = 10.0
    scale_down_cooldown: float = 30.0
    scale_up_pods: int = 1
    policy: str = "reactive"
    horizon: float = 30.0
    history: int = 128

    def label(self) -> str:
        gates = (
            f"up{self.scale_up_messages}/down{self.scale_down_messages}"
            f"/cu{self.scale_up_cooldown:g}/cd{self.scale_down_cooldown:g}"
            f"/step{self.scale_up_pods}"
        )
        if self.policy == "reactive":
            return f"{gates}/reactive"
        return f"{gates}/{self.policy}@{self.horizon:g}s/h{self.history}"

    def to_config(self, scenario: Scenario) -> SimConfig:
        """This point applied to one scenario's world."""
        loop = LoopConfig(
            poll_interval=scenario.loop.poll_interval,
            policy=PolicyConfig(
                scale_up_messages=self.scale_up_messages,
                scale_down_messages=self.scale_down_messages,
                scale_up_cooldown=self.scale_up_cooldown,
                scale_down_cooldown=self.scale_down_cooldown,
            ),
        )
        config = SimConfig(
            arrival_rate=scenario.arrival,
            service_rate_per_replica=scenario.service_rate_per_replica,
            duration=scenario.duration,
            initial_replicas=scenario.initial_replicas,
            min_pods=scenario.min_pods,
            max_pods=scenario.max_pods,
            scale_up_pods=self.scale_up_pods,
            loop=loop,
        )
        if self.policy != "reactive":
            config = replace(
                config,
                policy="predictive",
                forecaster=self.policy,
                forecast_horizon=self.horizon,
                forecast_history=self.history,
            )
        return config


@dataclass(frozen=True)
class SweepSpec:
    """The search space as one axis-per-field grid.

    :meth:`grid` is the full cross product; :meth:`sample` draws a random
    subset of it (seeded — sweeps are reproducible).  Reactive points
    collapse the forecaster-only axes (horizon/history) to a single
    canonical value, so the grid never counts the same reactive
    configuration twice.
    """

    scale_up_messages: tuple[int, ...] = (50, 100, 200)
    scale_down_messages: tuple[int, ...] = (10,)
    scale_up_cooldown: tuple[float, ...] = (10.0, 20.0)
    scale_down_cooldown: tuple[float, ...] = (30.0,)
    scale_up_pods: tuple[int, ...] = (1, 2)
    policies: tuple[str, ...] = ("reactive", "ewma", "holt", "lstsq")
    horizons: tuple[float, ...] = (15.0, 45.0)
    histories: tuple[int, ...] = (128,)

    def _gate_axes(self):
        return itertools.product(
            self.scale_up_messages,
            self.scale_down_messages,
            self.scale_up_cooldown,
            self.scale_down_cooldown,
            self.scale_up_pods,
        )

    def _policy_axes(self) -> list[tuple[str, float, int]]:
        points: list[tuple[str, float, int]] = []
        for policy in self.policies:
            if policy == "reactive":
                points.append(("reactive", self.horizons[0], self.histories[0]))
            else:
                points.extend(
                    (policy, horizon, history)
                    for horizon in self.horizons
                    for history in self.histories
                )
        return points

    def grid(self) -> list[SweepPoint]:
        """The full cross product, reactive deduplicated."""
        return [
            SweepPoint(
                scale_up_messages=up,
                scale_down_messages=down,
                scale_up_cooldown=cu,
                scale_down_cooldown=cd,
                scale_up_pods=step,
                policy=policy,
                horizon=horizon,
                history=history,
            )
            for up, down, cu, cd, step in self._gate_axes()
            for policy, horizon, history in self._policy_axes()
        ]

    def sample(self, n: int, seed: int = 0) -> list[SweepPoint]:
        """``n`` distinct points drawn uniformly from :meth:`grid`."""
        grid = self.grid()
        if n >= len(grid):
            return grid
        rng = random.Random(seed)
        return rng.sample(grid, n)


def pareto_front(points: Sequence[tuple[float, float]]) -> list[int]:
    """Indices of the non-dominated points (both axes minimized).

    A point is dominated when another is at least as good on both axes
    and strictly better on one.  O(n²) on purpose: sweep fronts are a few
    hundred points and the quadratic form is obviously correct.
    """
    front = []
    for i, (xi, yi) in enumerate(points):
        dominated = any(
            (xj <= xi and yj <= yi) and (xj < xi or yj < yi)
            for j, (xj, yj) in enumerate(points)
            if j != i
        )
        if not dominated:
            front.append(i)
    return front


#: score-row ordering for "best": worst backlog first, then churn, then
#: SLO time — the battery's priorities (evaluate module docstring).
#: Serving-twin rows rank in SERVING units instead: most tokens/s,
#: then least time-over-TTFT-SLO, then least shard churn — the twin
#: bench's lexicographic axes.
def _rank(row: dict) -> tuple:
    if "tokens_per_second" in row:
        return (
            -row["tokens_per_second"],
            row["time_over_slo_s"],
            row["shard_changes"],
        )
    return (
        row["max_depth"],
        row["replica_changes"],
        row["time_over_slo_s"],
    )


@dataclass
class SweepReport:
    """All scored (scenario × point) rows + the tuning summaries."""

    rows: list[dict] = field(default_factory=list)

    @property
    def points(self) -> int:
        return len(self.rows)

    def _by_scenario(self) -> dict[str, list[dict]]:
        grouped: dict[str, list[dict]] = {}
        for row in self.rows:
            grouped.setdefault(row["scenario"], []).append(row)
        return grouped

    def best_per_scenario(self) -> dict[str, dict]:
        """The winning configuration for each scenario (see ``_rank``)."""
        return {
            name: min(rows, key=lambda r: _rank(r["score"]))
            for name, rows in self._by_scenario().items()
        }

    def best_points_per_scenario(self) -> dict[str, SweepPoint]:
        """The winning configurations as re-runnable :class:`SweepPoint`\\ s.

        Rebuilt from the rows' recorded ``point`` dicts, so a tuned
        winner can be re-evaluated on *other* worlds than the one it was
        tuned on.  (The learn bench aggregates winners per scenario
        *family* across variants rather than per scenario, so it picks
        its baseline from the raw rows directly — this per-scenario form
        is the API for everything else.)
        """
        return {
            name: SweepPoint(**row["point"])
            for name, row in self.best_per_scenario().items()
        }

    def pareto_per_scenario(self) -> dict[str, list[dict]]:
        """Backlog-vs-churn Pareto front per scenario, best-first.

        Fluid rows minimize (max depth, replica churn); serving rows
        minimize (-tokens/s, shard churn) — the same two-axis
        throughput-vs-actuation tradeoff in each world's units."""
        fronts: dict[str, list[dict]] = {}
        for name, rows in self._by_scenario().items():
            axes = [
                (
                    (-r["score"]["tokens_per_second"],
                     r["score"]["shard_changes"])
                    if "tokens_per_second" in r["score"]
                    else (r["score"]["max_depth"],
                          r["score"]["replica_changes"])
                )
                for r in rows
            ]
            front = [rows[i] for i in pareto_front(axes)]
            fronts[name] = sorted(front, key=lambda r: _rank(r["score"]))
        return fronts

    def summary(self) -> dict:
        """The artifact block ``bench.py --suite sweep`` records."""
        return {
            "points": self.points,
            "best": {
                name: {"config": row["label"], "score": row["score"]}
                for name, row in self.best_per_scenario().items()
            },
            "pareto": {
                name: [
                    {"config": row["label"], "score": row["score"]}
                    for row in front
                ]
                for name, front in self.pareto_per_scenario().items()
            },
        }


def run_sweep(
    points: "SweepSpec | Iterable[SweepPoint]",
    scenarios: Sequence[Scenario] | None = None,
) -> SweepReport:
    """Score every (scenario × point) through the compiled simulator.

    Episodes are batched into as few device calls as the compiled shapes
    allow: one batch per (tick count, history capacity) group — with the
    default battery and spec, exactly one call for the entire sweep.
    """
    # Lazy import: this module's spec/Pareto half stays importable without
    # JAX (bench.py's default suite imports nothing from sim.compiled).
    if isinstance(points, SweepSpec):
        points = points.grid()
    points = list(points)
    if not points:
        raise ValueError("sweep needs at least one point")
    scenarios = tuple(scenarios if scenarios is not None else default_battery())
    from .twin.scenario import ServingScenario

    serving = [isinstance(s, ServingScenario) for s in scenarios]
    if any(serving):
        if not all(serving):
            raise ValueError(
                "one sweep takes fluid scenarios OR serving scenarios,"
                " not a mix (their score units are incomparable)"
            )
        return _run_serving_sweep(points, scenarios)
    from .compiled import run_episodes_grouped

    jobs = [
        (scenario, point) for scenario in scenarios for point in points
    ]
    episodes = run_episodes_grouped(
        [point.to_config(scenario) for scenario, point in jobs]
    )
    report = SweepReport()
    for (scenario, point), episode in zip(jobs, episodes):
        report.rows.append(
            {
                "scenario": scenario.name,
                "label": point.label(),
                "point": asdict(point),
                "score": score_result(episode.result, scenario.slo_depth),
            }
        )
    return report


def _run_serving_sweep(points, scenarios) -> SweepReport:
    """Tuned-threshold baselines on SERVING worlds: each reactive gate
    point re-runs through the token-level twin and is scored in serving
    units (:func:`~.twin.compiled.score_twin_summary`), so
    ``best_per_scenario``/``best_points_per_scenario`` pick winners on
    the same lexicographic axes the twin bench gates.  Forecaster
    points are skipped — the serving twin's policy seam is reactive
    thresholds or the learned network, and a sweep must not silently
    score a forecaster point as something else."""
    from .twin.compiled import (
        run_twin_grouped,
        score_twin_summary,
        twin_config_for_point,
    )

    reactive_points = [p for p in points if p.policy == "reactive"]
    if not reactive_points:
        raise ValueError(
            "a serving sweep needs at least one reactive point"
            " (forecaster points have no serving-twin analogue)"
        )
    jobs = [
        (scenario, point)
        for scenario in scenarios
        for point in reactive_points
    ]
    episodes = run_twin_grouped(
        [twin_config_for_point(point, scenario)
         for scenario, point in jobs],
        trajectory=False,
    )
    report = SweepReport()
    for (scenario, point), episode in zip(jobs, episodes):
        report.rows.append(
            {
                "scenario": scenario.name,
                "label": point.label(),
                "point": asdict(point),
                "score": score_twin_summary(episode.summary, scenario),
            }
        )
    return report
