"""The reference serving episode: the REAL plane on the twin's script.

This driver runs a :class:`~.scenario.ServingScenario` through the real
:class:`~...workloads.shard_plane.ShardedBatcher` — the actual jitted
gang engine, insert programs, freest-first/sticky routers, and
:class:`~...workloads.tenancy.PrefixPool` — under the exact cycle
contract the compiled twin's scan encodes (see
:mod:`.compiled`'s module docstring for the per-cycle order).  The gate
decisions go through the reference :func:`~...core.policy.gate_code`
and a learned policy through the same jitted
:func:`~...learn.network.learned_decision` the live ``LearnedPolicy``
wraps; shard scale actuation replicates the
:class:`~...fleet.sharded.ShardedWorkerPool` state machine's exact
ordering (resurrect newest-draining / activate lowest-inactive /
drain newest-serving, drain-retire after the engine cycle) — pinned
against the real pool class by a tier-1 test.

Two claims are verified against the ENGINE itself each cycle, not
against this driver's bookkeeping: first tokens settle at the
admission cycle's combined transfer (``ttft_count`` must grow by
exactly the admitted count), and completions/tokens come from
``step()``'s returns and the emitted-token counters.  What the driver
owns is the queue, the clock, and the scale state — the parts the real
deployment splits across the worker poll loop and the fleet pool.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from functools import lru_cache

import numpy as np

from ...core.policy import GATE_COOLING, GATE_FIRE, GATE_SKIPPED, gate_code
from ...forecast.forecasters import _center_times
from ...forecast.history import DepthHistory
from ...learn.network import FEATURE_ALPHA, FEATURE_WINDOW, cooldown_fraction, hold_depth
from ..scenarios import seeded_token_ids, tenant_prefix_ids
from .compiled import SERVING_SUMMARY_KEYS, TRAJECTORY_KEYS, TwinConfig
from .scenario import SHARD_DRAINING, SHARD_INACTIVE, SHARD_SERVING

#: The pool's static prefix bucket for prefixed episodes (twin worlds
#: are cycle-accounted, so the content length only needs to be legal).
HOST_PREFIX_LEN = 4


@lru_cache(maxsize=4)
def tiny_twin_model(seed: int = 0, max_seq_len: int = 24):
    """The fidelity battery's tiny real model (CPU-friendly).  Token
    CONTENT is irrelevant to the twin's cycle observables — the model
    exists so the real engine runs its actual compiled programs."""
    import jax
    import jax.numpy as jnp

    from ...workloads.model import ModelConfig, init_params

    config = ModelConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=max_seq_len, dtype=jnp.float32,
    )
    params = init_params(jax.random.key(seed), config)
    return params, config


@dataclass
class HostEpisode:
    """The reference run's per-cycle trail + serving-unit summary, in
    the twin's exact shapes so the fidelity gate compares field for
    field."""

    config: TwinConfig
    summary: dict
    trajectory: dict


def _scale_up(state: list[int]) -> "int | None":
    """ShardedWorkerPool.scale_up's pick: resurrect the newest draining
    shard first, else activate the lowest inactive one."""
    draining = [s for s in reversed(range(len(state)))
                if state[s] == SHARD_DRAINING]
    if draining:
        return draining[0]
    inactive = [s for s in range(len(state))
                if state[s] == SHARD_INACTIVE]
    return inactive[0] if inactive else None


def _scale_down(state: list[int]) -> "int | None":
    """ShardedWorkerPool.scale_down's pick: drain the newest serving
    shard."""
    serving = [s for s in reversed(range(len(state)))
               if state[s] == SHARD_SERVING]
    return serving[0] if serving else None


def run_host_episode(
    config: TwinConfig, params=None, model_config=None
) -> HostEpisode:
    """One scripted episode through the real sharded plane."""
    import jax.numpy as jnp  # noqa: F401  (engine path needs jax anyway)

    from ...learn.policy import _learned_decision
    from ...workloads.shard_plane import ShardedBatcher

    scenario = config.scenario
    if params is None or model_config is None:
        params, model_config = tiny_twin_model(
            max_seq_len=max(
                24,
                HOST_PREFIX_LEN + scenario.prompt_len
                + scenario.generate_tokens,
            )
        )
    tenancy = None
    prefix_ids = {}
    if scenario.pool_entries > 0:
        from ...workloads.tenancy import TenancyConfig

        names = tuple(f"t{i}" for i in range(scenario.tenants))
        tenancy = TenancyConfig(
            tenants=names,
            prefix_pool=scenario.pool_entries,
            prefix_len=HOST_PREFIX_LEN,
            sticky=True,
        )
        prefix_ids = {
            i: np.asarray(
                tenant_prefix_ids(
                    names[i], HOST_PREFIX_LEN, model_config.vocab_size
                ),
                np.int32,
            )
            for i in range(scenario.tenants)
        }
    engine = ShardedBatcher(
        params, model_config,
        shards=scenario.shards, shard_slots=scenario.shard_slots,
        prompt_len=scenario.prompt_len,
        generate_tokens=scenario.generate_tokens,
        decode_block=scenario.decode_block,
        tenancy=tenancy,
    )
    state = [
        SHARD_SERVING if s < scenario.initial_shards else SHARD_INACTIVE
        for s in range(scenario.shards)
    ]
    for s in range(scenario.initial_shards, scenario.shards):
        engine.set_shard_active(s, False)

    sends = scenario.sends()
    total = int(sends.sum())
    arr_cycle = scenario.arrival_cycles()
    budgets = scenario.request_budgets(total)
    tenants = scenario.request_tenants(total)
    prompts = [
        np.asarray(
            seeded_token_ids(
                f"{scenario.name}:prompt:{i}", 3, model_config.vocab_size
            ),
            np.int32,
        )
        for i in range(total)
    ]

    learned = config.policy == "learned"
    if learned:
        from ...learn.checkpoint import checkpoint_history

        capacity, min_samples = checkpoint_history(config.checkpoint)
        min_samples = max(2, min_samples)
        history = DepthHistory(capacity)
        theta = config.checkpoint.theta
        hidden = int(config.checkpoint.hidden)
    hold = hold_depth(config.up_q, config.down_q)
    last_up = last_down = 0.0  # startup grace at t=0, reference style
    changes = 0
    queue: deque[int] = deque()
    next_arrival = 0
    prev_tokens = prev_hits = prev_misses = 0
    done_budget_ok = True
    completed_once: set[int] = set()
    over_slo = 0.0
    ttft_cycles_sum = 0
    max_queue = 0
    traj: dict[str, list] = {key: [] for key in TRAJECTORY_KEYS}

    for c in range(scenario.cycles):
        # arrivals land before everything else this cycle
        for _ in range(int(sends[c])):
            queue.append(next_arrival)
            next_arrival += 1

        if c % scenario.control_every == 0:
            t = c * scenario.cycle_dt
            observed = len(queue)
            serving_before = sum(1 for s in state if s == SHARD_SERVING)
            decision = observed
            if learned:
                times, depths, n = history.with_sample(t, float(observed))
                decision = int(
                    _learned_decision(
                        theta,
                        np.asarray(_center_times(times, n)),
                        np.asarray(depths),
                        n,
                        observed,
                        serving_before,
                        np.float32(cooldown_fraction(
                            last_up, config.up_cd, t
                        )),
                        np.float32(cooldown_fraction(
                            last_down, config.down_cd, t
                        )),
                        config.up_q,
                        config.down_q,
                        hold,
                        min_samples,
                        scenario.max_active,
                        np.float32(scenario.tick_dt),
                        np.float32(FEATURE_ALPHA),
                        FEATURE_WINDOW,
                        hidden=hidden,
                    )
                )
                history.observe(t, float(observed))
            up_code = gate_code(
                decision >= config.up_q, t, last_up, config.up_cd
            )
            if up_code == GATE_FIRE:
                if serving_before < scenario.max_active:
                    pick = _scale_up(state)
                    state[pick] = SHARD_SERVING
                    engine.set_shard_active(pick, True)
                last_up = t  # FIRE refreshes the stamp, clamps included
            down_code = (
                GATE_SKIPPED
                if up_code == GATE_COOLING
                else gate_code(
                    decision <= config.down_q, t, last_down,
                    config.down_cd,
                )
            )
            if down_code == GATE_FIRE:
                serving_mid = sum(1 for s in state if s == SHARD_SERVING)
                if serving_mid > scenario.min_shards:
                    pick = _scale_down(state)
                    state[pick] = SHARD_DRAINING
                    engine.set_shard_active(pick, False)
                last_down = t
            serving_after = sum(1 for s in state if s == SHARD_SERVING)
            changes += serving_after != serving_before

        # refill: FIFO over the queue through the REAL router's capacity
        free = engine.free_slots
        k = min(len(queue), len(free))
        batch = [queue.popleft() for _ in range(k)]
        ttft_c = 0
        for i in batch:
            wait = c - int(arr_cycle[i])
            ttft_c += wait
            over_slo += max(
                0.0, wait * scenario.cycle_dt - scenario.ttft_slo_s
            )
        ttft_cycles_sum += ttft_c
        if batch:
            if scenario.pool_entries > 0:
                engine.submit_many_prefixed([
                    (
                        f"t{int(tenants[i])}",
                        prefix_ids[int(tenants[i])],
                        prompts[i],
                        i,
                    )
                    for i in batch
                ])
            elif scenario.heavy_tail is not None:
                # per-request budgets ride the real per-row-budget
                # resume insert (produced=[] = a fresh admission)
                engine.submit_resume([
                    (prompts[i], i, [], int(budgets[i]), 0.0)
                    for i in batch
                ])
            else:
                engine.submit_many([(prompts[i], i) for i in batch])
        max_queue = max(max_queue, len(queue))

        ttft_before = engine.ttft_count
        finished = engine.step()
        # the same-cycle first-token-settle claim, checked against the
        # ENGINE's own TTFT counter, not this driver's bookkeeping
        if engine.ttft_count - ttft_before != k:
            raise AssertionError(
                f"cycle {c}: {k} admissions but"
                f" {engine.ttft_count - ttft_before} first tokens"
                f" settled — the twin's TTFT model no longer matches"
                f" the engine"
            )
        for payload, tokens in finished:
            if payload in completed_once:
                done_budget_ok = False
            completed_once.add(payload)
            if len(tokens) != int(budgets[payload]):
                done_budget_ok = False
        tokens_c = engine.tokens_emitted - prev_tokens
        prev_tokens = engine.tokens_emitted

        # drain-retire: the pool's end-of-cycle check
        for s in range(scenario.shards):
            if state[s] == SHARD_DRAINING and engine.shard_busy(s) == 0:
                state[s] = SHARD_INACTIVE
        serving_end = sum(1 for s in state if s == SHARD_SERVING)

        pool = engine.prefix_pool
        hits_c = (pool.hits - prev_hits) if pool is not None else 0
        misses_c = (pool.misses - prev_misses) if pool is not None else 0
        if pool is not None:
            prev_hits, prev_misses = pool.hits, pool.misses

        traj["admitted"].append(k)
        traj["completed"].append(len(finished))
        traj["tokens"].append(tokens_c)
        traj["ttft_cycles"].append(ttft_c)
        traj["queue"].append(len(queue))
        traj["serving"].append(serving_end)
        traj["pool_hits"].append(hits_c)
        traj["pool_misses"].append(misses_c)

    if not done_budget_ok:
        raise AssertionError(
            "the real plane completed a request twice or off-budget —"
            " episode is not a valid fidelity reference"
        )
    admitted = total - len(queue)
    # unserved lower-bound SLO debt, the twin's exact formula
    for i in list(queue):
        over_slo += max(
            0.0,
            (scenario.cycles - int(arr_cycle[i])) * scenario.cycle_dt
            - scenario.ttft_slo_s,
        )
    summary = {
        "tokens": int(sum(traj["tokens"])),
        "time_over_slo_s": float(over_slo),
        "shard_changes": int(changes),
        "shard_seconds": float(
            sum(traj["serving"]) * scenario.cycle_dt
        ),
        "completions": int(sum(traj["completed"])),
        "admitted": int(admitted),
        "final_queue": int(len(queue)),
        "max_queue": int(max_queue),
        "ttft_cycles_sum": int(ttft_cycles_sum),
        "pool_hits": int(sum(traj["pool_hits"])),
        "pool_misses": int(sum(traj["pool_misses"])),
    }
    assert set(summary) == set(SERVING_SUMMARY_KEYS)
    return HostEpisode(
        config=config,
        summary=summary,
        trajectory={k: np.asarray(v) for k, v in traj.items()},
    )
