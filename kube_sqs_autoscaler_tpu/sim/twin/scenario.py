"""Serving scenarios: deterministic request scripts on the cycle clock.

A :class:`ServingScenario` describes one world for the serving twin and
the real sharded plane alike: an analytic arrival process sampled into
an *exact* integer per-cycle send schedule (floor-of-cumulative-integral
differences — no quadrature, no RNG), per-request output budgets (fixed
or a seeded bounded-Pareto heavy tail), an optional tenant population
with a per-shard prefix pool, and the autoscaler's gate/cooldown knobs
in queue-depth units.  Both simulators consume the SAME concrete
integers, which is what lets the fidelity gate demand equality rather
than statistics.

The widened arrival shapes (:class:`~..scenarios.ComposedArrival`,
:class:`~..scenarios.RegimeSwitchArrival`,
:class:`~..scenarios.PulseArrival`) plug in here unchanged — the
schedule derivation only needs ``arrivals_between`` to be the exact
integral of ``rate_at``, the property every process in
:mod:`..scenarios` carries by construction.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass
from typing import Sequence

import numpy as np

from ..scenarios import (
    ArrivalProcess,
    BurstArrival,
    ComposedArrival,
    ConstantArrival,
    PulseArrival,
    RampArrival,
    RegimeSwitchArrival,
    arrival_variant,
    as_process,
    heavy_tail_lengths,
)

#: Shard lifecycle codes inside the twin scan — the
#: :mod:`...fleet.sharded` state machine's scan-able integers
#: (INACTIVE/SERVING/DRAINING; QUARANTINED/PROBING are chaos states the
#: twin deliberately does not model).
SHARD_INACTIVE, SHARD_SERVING, SHARD_DRAINING = 0, 1, 2


@dataclass(frozen=True)
class ServingScenario:
    """One serving world: traffic script + plane geometry + gate knobs.

    ``arrival`` is requests/second on the episode's wall clock
    (``cycles × cycle_dt`` seconds long).  ``heavy_tail = (lo, hi,
    alpha)`` switches per-request output budgets from the uniform
    ``generate_tokens`` to a seeded bounded-Pareto draw (admitted
    through the real plane's per-row-budget resume insert).  ``tenants
    > 0`` routes requests round-robin over a tenant population through
    the prefix pool (``pool_entries`` per shard) with sticky routing —
    the locality shape of PR 10.
    """

    name: str
    arrival: ArrivalProcess
    cycles: int = 240
    cycle_dt: float = 0.05
    shards: int = 4
    shard_slots: int = 2
    decode_block: int = 2
    min_shards: int = 1
    max_shards: int = 0  # 0 = all shards
    initial_shards: int = 1
    control_every: int = 5  # engine cycles per autoscaler tick
    scale_up_queue: int = 6
    scale_down_queue: int = 1
    up_cooldown_s: float = 0.5
    down_cooldown_s: float = 1.5
    ttft_slo_s: float = 0.25
    generate_tokens: int = 6
    heavy_tail: "tuple[int, int, float] | None" = None
    budget_seed: int = 0
    tenants: int = 0
    pool_entries: int = 0
    prompt_len: int = 4
    description: str = ""

    def __post_init__(self) -> None:
        if self.cycles < 1:
            raise ValueError("cycles must be >= 1")
        if self.cycle_dt <= 0:
            raise ValueError("cycle_dt must be > 0")
        if self.shards < 1 or self.shard_slots < 1:
            raise ValueError("shards and shard_slots must be >= 1")
        if not 1 <= self.min_shards <= self.max_active <= self.shards:
            raise ValueError(
                f"need 1 <= min_shards <= max_shards <= shards, got "
                f"{self.min_shards}/{self.max_active}/{self.shards}"
            )
        if not self.min_shards <= self.initial_shards <= self.max_active:
            raise ValueError("initial_shards out of [min, max] range")
        if self.control_every < 1:
            raise ValueError("control_every must be >= 1")
        if self.decode_block < 1:
            raise ValueError("decode_block must be >= 1")
        if self.generate_tokens < 1:
            raise ValueError("generate_tokens must be >= 1")
        if self.heavy_tail is not None:
            lo, hi, alpha = self.heavy_tail
            if not 1 <= lo <= hi <= self.generate_tokens:
                raise ValueError(
                    "heavy_tail budgets must satisfy 1 <= lo <= hi <= "
                    "generate_tokens (the engine's per-row budget cap)"
                )
            if alpha <= 0:
                raise ValueError("heavy_tail alpha must be > 0")
        if self.tenants < 0 or self.pool_entries < 0:
            raise ValueError("tenants and pool_entries must be >= 0")
        if self.pool_entries and not self.tenants:
            raise ValueError("pool_entries needs tenants > 0")
        if self.pool_entries and self.heavy_tail is not None:
            # the real plane's pooled admission path carries a uniform
            # budget (per-request budgets ride the resume insert, which
            # has no pooled variant) — a world combining them would be
            # one the reference driver cannot realize, surfacing as
            # cryptic fidelity divergences instead of this error
            raise ValueError(
                "heavy_tail budgets and a prefix pool cannot combine:"
                " the plane's pooled insert admits at the uniform"
                " generate_tokens budget"
            )
        if self.pool_entries and self.pool_entries < self.shard_slots:
            # the real PrefixPool enforces entries >= per-shard slots
            # (same-batch LRU-eviction corruption guard); the twin
            # mirrors the constraint so its worlds stay realizable
            raise ValueError(
                f"pool_entries={self.pool_entries} must be >= "
                f"shard_slots={self.shard_slots}"
            )

    @property
    def max_active(self) -> int:
        return self.max_shards if self.max_shards else self.shards

    @property
    def slots(self) -> int:
        return self.shards * self.shard_slots

    @property
    def tick_dt(self) -> float:
        """Seconds per autoscaler tick."""
        return self.control_every * self.cycle_dt

    @property
    def duration_s(self) -> float:
        return self.cycles * self.cycle_dt

    def sends(self) -> np.ndarray:
        """Integer requests arriving at each cycle, from the EXACT
        arrival integral: ``sends[c] = floor(F((c+1)·dt)) - floor(F(c·
        dt))`` with ``F(t) = arrivals_between(0, t)``.  Deterministic,
        quadrature-free, and identical however either simulator is
        batched."""
        process = as_process(self.arrival)
        out = np.zeros(self.cycles, np.int32)
        prev = 0
        for c in range(self.cycles):
            cum = math.floor(
                process.arrivals_between(0.0, (c + 1) * self.cycle_dt)
            )
            out[c] = cum - prev
            prev = cum
        return out

    def total_requests(self) -> int:
        return int(self.sends().sum())

    def request_budgets(self, total: "int | None" = None) -> np.ndarray:
        """Per-request output budgets, in arrival (FIFO) order."""
        total = self.total_requests() if total is None else total
        if self.heavy_tail is None:
            return np.full(total, self.generate_tokens, np.int32)
        lo, hi, alpha = self.heavy_tail
        return np.asarray(
            heavy_tail_lengths(
                f"{self.name}:budgets:{self.budget_seed}", total, lo, hi,
                alpha,
            ),
            np.int32,
        )

    def request_tenants(self, total: "int | None" = None) -> np.ndarray:
        """Tenant index per request (round-robin; zeros with tenancy
        off)."""
        total = self.total_requests() if total is None else total
        if self.tenants <= 0:
            return np.zeros(total, np.int32)
        return (np.arange(total, dtype=np.int32)) % np.int32(self.tenants)

    def arrival_cycles(self) -> np.ndarray:
        """Arrival cycle per request, expanded from :meth:`sends`."""
        sends = self.sends()
        return np.repeat(
            np.arange(self.cycles, dtype=np.int32), sends
        ).astype(np.int32)


def twin_variants(
    scenarios: Sequence[ServingScenario],
    n_variants: int,
    seed: int,
    jitter: float = 0.2,
) -> "list[ServingScenario]":
    """Seeded held-out variants: the arrival shape re-drawn inside
    :func:`~..scenarios.variant_bounds` (the new composite shapes
    recurse), the heavy-tail budget stream re-seeded.  Plane geometry
    and gate knobs stay fixed — a variant is the same fleet facing a
    world it never trained on, the same split discipline the fluid
    learn bench uses."""
    out = []
    for scenario in scenarios:
        for index in range(n_variants):
            out.append(
                dataclasses.replace(
                    scenario,
                    name=f"{scenario.name}~v{index}s{seed}",
                    arrival=arrival_variant(
                        scenario.arrival, seed, scenario.name, index,
                        jitter,
                    ),
                    budget_seed=scenario.budget_seed + 1000 * seed + index,
                )
            )
    return out


def default_twin_battery(
    *, cycles: int = 240, cycle_dt: float = 0.05
) -> "list[ServingScenario]":
    """The serving-twin battery: six worlds over one plane geometry.

    Rates are sized against the plane's real capacity (≈0.55 req/cycle
    per serving shard at the default geometry: 2 slots, block 2, budget
    6) so the gates are genuinely exercised — under-provisioned starts,
    overload windows that leave backlog for slow scalers, and calm
    stretches where holding shards down matters.
    """
    common = dict(cycles=cycles, cycle_dt=cycle_dt)
    return [
        ServingScenario(
            name="twin-steady",
            arrival=ConstantArrival(rate=24.0),  # ~1.2 req/cycle
            description="steady load needing ~2-3 shards",
            **common,
        ),
        ServingScenario(
            name="twin-ramp",
            arrival=RampArrival(
                start_rate=6.0, end_rate=44.0,
                t_start=0.1 * cycles * cycle_dt,
                t_end=0.7 * cycles * cycle_dt,
            ),
            description="organic growth from idle to full fleet",
            **common,
        ),
        ServingScenario(
            name="twin-flash-crowd",
            arrival=ComposedArrival(
                parts=(
                    ConstantArrival(rate=9.0),
                    PulseArrival(
                        rate=60.0,
                        start=0.25 * cycles * cycle_dt,
                        width=0.12 * cycles * cycle_dt,
                    ),
                )
            ),
            description="one-shot stampede on organic traffic",
            **common,
        ),
        ServingScenario(
            name="twin-regime-switch",
            arrival=RegimeSwitchArrival(
                regimes=(
                    (0.0, ConstantArrival(rate=8.0)),
                    (
                        0.35 * cycles * cycle_dt,
                        BurstArrival(
                            base=16.0, burst_rate=56.0,
                            period=0.2 * cycles * cycle_dt,
                            burst_len=0.07 * cycles * cycle_dt,
                        ),
                    ),
                    (0.8 * cycles * cycle_dt, ConstantArrival(rate=6.0)),
                )
            ),
            description="calm -> retry-storm regime -> calm",
            **common,
        ),
        ServingScenario(
            name="twin-heavy-tail",
            arrival=ConstantArrival(rate=26.0),
            heavy_tail=(1, 6, 1.1),
            description="bounded-Pareto output lengths, per-row budgets",
            **common,
        ),
        ServingScenario(
            name="twin-prefix-tenants",
            arrival=ConstantArrival(rate=22.0),
            tenants=5,
            pool_entries=2,
            description=(
                "5 tenants round-robin through a 2-entry/shard prefix "
                "pool with sticky routing"
            ),
            **common,
        ),
    ]
