"""The serving plane as one ``lax.scan`` per episode.

One scan iteration is one ENGINE CYCLE of the real sharded plane
(:class:`~...workloads.shard_plane.ShardedBatcher` driven by the
:mod:`.host` reference driver), reproduced integer-for-integer:

- **arrivals** land on the queue from the scenario's exact-integral
  send schedule;
- every ``control_every`` cycles an **autoscaler tick** runs: the
  observed queue depth (or the learned MLP's decision over it — the
  same :func:`~...learn.network.learned_decision` the fluid twin and
  the live ``LearnedPolicy`` call) goes through the reference
  :func:`~...core.policy.gate_code` gates with cooldowns, actuating the
  :mod:`...fleet.sharded` shard state machine (scale-up resurrects the
  newest draining shard else activates the lowest inactive one;
  scale-down drains the newest serving shard; both stamps refresh on
  FIRE, boundary no-ops included);
- **refill** admits ``min(queue, eligible slots)`` requests FIFO,
  routed one at a time to the freest serving shard (deterministic
  lowest-index tie-break — the real router's exact order), sticky to a
  tenant's home shard when tenancy is on, each admission touching the
  per-shard prefix-pool LRU (hit/miss/install counters);
- **step** mirrors the gang block engine's dispatch-ahead mechanics
  exactly: a dispatched block spends ``min(decode_block, remaining)``
  device budget immediately but its tokens settle one cycle later;
  admission first-tokens settle the same cycle (the one combined
  transfer); a slot frees the cycle its produced count reaches budget;
- **drain-retire** flips an emptied draining shard inactive, end of
  cycle — the pool's ``run_cycle`` order.

TTFT is cycle-counted at admission (first tokens settle at the
admission cycle's combined transfer), so time-over-TTFT-SLO is exact —
plus a lower-bound penalty for requests still queued at episode end,
so refusing admission can never launder SLO debt.

What the twin deliberately does NOT model (see ARCHITECTURE.md): KV
bytes, host/queue-poll jitter and backoff, DRR fair admission, chaos
states, speculative decode.  Within that boundary,
:func:`~.fidelity.verify_twin_fidelity` holds it to ZERO divergences
against the real plane.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import enable_x64

from ...core.policy import GATE_COOLING, GATE_FIRE, GATE_SKIPPED, gate_code
from ...learn.network import FEATURE_ALPHA, FEATURE_WINDOW, hold_depth, learned_decision
from .scenario import SHARD_DRAINING, SHARD_INACTIVE, SHARD_SERVING, ServingScenario

#: Policy kinds inside the twin scan — reactive thresholds, or the
#: learned MLP (the fluid twin's code for it, for symmetry).
REACTIVE_KIND, LEARNED_KIND = 0, 4

#: Summary keys every twin episode returns (the serving-unit
#: accumulators the ES trainer and the sweep scorer consume).
SERVING_SUMMARY_KEYS = (
    "tokens",
    "time_over_slo_s",
    "shard_changes",
    "shard_seconds",
    "completions",
    "admitted",
    "final_queue",
    "max_queue",
    "ttft_cycles_sum",
    "pool_hits",
    "pool_misses",
)

#: Trajectory keys (per-cycle arrays) the fidelity gate compares.
TRAJECTORY_KEYS = (
    "admitted",
    "completed",
    "tokens",
    "ttft_cycles",
    "queue",
    "serving",
    "pool_hits",
    "pool_misses",
)


@dataclass(frozen=True)
class TwinConfig:
    """One twin episode: a scenario + the policy that autoscales it.

    ``policy`` is ``"reactive"`` (threshold the scenario's queue gates
    on the observed depth) or ``"learned"`` (a serving-twin-trained
    checkpoint; fluid-twin checkpoints are rejected unless
    ``allow_twin_mismatch`` — the bench's explicit baseline escape
    hatch, never the deployment default).  Gate knobs default to the
    scenario's; the serving sweep overrides them per point.
    """

    scenario: ServingScenario
    policy: str = "reactive"
    checkpoint: Any = None
    allow_twin_mismatch: bool = False
    scale_up_queue: "int | None" = None
    scale_down_queue: "int | None" = None
    up_cooldown_s: "float | None" = None
    down_cooldown_s: "float | None" = None

    def __post_init__(self):
        if self.policy not in ("reactive", "learned"):
            raise ValueError(
                f"twin policy must be 'reactive' or 'learned', got"
                f" {self.policy!r}"
            )
        if self.policy == "learned":
            if self.checkpoint is None:
                raise ValueError("policy='learned' needs a checkpoint")
            from ...learn.checkpoint import TWIN_SERVING, checkpoint_twin

            kind = checkpoint_twin(self.checkpoint)
            if kind != TWIN_SERVING and not self.allow_twin_mismatch:
                raise ValueError(
                    f"checkpoint was trained in the {kind!r} twin; the"
                    f" serving twin evaluates serving-twin checkpoints"
                    f" (pass allow_twin_mismatch=True to score a"
                    f" foreign checkpoint as an explicit baseline)"
                )

    @property
    def up_q(self) -> int:
        return (
            self.scale_up_queue
            if self.scale_up_queue is not None
            else self.scenario.scale_up_queue
        )

    @property
    def down_q(self) -> int:
        return (
            self.scale_down_queue
            if self.scale_down_queue is not None
            else self.scenario.scale_down_queue
        )

    @property
    def up_cd(self) -> float:
        return (
            self.up_cooldown_s
            if self.up_cooldown_s is not None
            else self.scenario.up_cooldown_s
        )

    @property
    def down_cd(self) -> float:
        return (
            self.down_cooldown_s
            if self.down_cooldown_s is not None
            else self.scenario.down_cooldown_s
        )


def encode_twin_config(
    config: TwinConfig, r_max: int, t_max: int
) -> dict[str, Any]:
    """One :class:`TwinConfig` as the scan's parameter row (request
    arrays padded to the batch group's ``r_max``/``t_max``)."""
    s = config.scenario
    sends = s.sends()
    total = int(sends.sum())
    if total > r_max:
        raise ValueError(f"{total} requests exceed the group pad {r_max}")
    arr = np.full(r_max, s.cycles + 1, np.int32)
    arr[:total] = s.arrival_cycles()
    budgets = np.ones(r_max, np.int32)
    budgets[:total] = s.request_budgets(total)
    tenants = np.zeros(r_max, np.int32)
    tenants[:total] = s.request_tenants(total)
    row: dict[str, Any] = {
        "arrived": sends,
        "arr_cycle": arr,
        "budgets": budgets,
        "tenant": tenants,
        "n_requests": np.int32(total),
        "block": np.int32(s.decode_block),
        "min_shards": np.int32(s.min_shards),
        "max_shards": np.int32(s.max_active),
        "initial_shards": np.int32(s.initial_shards),
        "control_every": np.int32(s.control_every),
        "cycle_dt": np.float64(s.cycle_dt),
        "slo_s": np.float64(s.ttft_slo_s),
        "up_q": np.int32(config.up_q),
        "down_q": np.int32(config.down_q),
        "up_cd": np.float64(config.up_cd),
        "down_cd": np.float64(config.down_cd),
        "policy_kind": np.int32(REACTIVE_KIND),
        "theta": np.zeros(1, np.float32),
        "hold": np.int32(hold_depth(config.up_q, config.down_q)),
        "alpha": np.float32(FEATURE_ALPHA),
        "window": np.int32(FEATURE_WINDOW),
        "min_samples": np.int32(2),
        "poll32": np.float32(s.tick_dt),
        "sticky": np.bool_(s.tenants > 0 and s.pool_entries > 0),
        "sticky_threshold": np.int32(s.shard_slots),
        "use_pool": np.bool_(s.pool_entries > 0),
    }
    if config.policy == "learned":
        from ...learn.checkpoint import (
            checkpoint_history,
            require_no_knob_head,
        )

        # the serving twin's scan slices the headless theta layout
        require_no_knob_head(config.checkpoint, "the serving twin")
        _, min_samples = checkpoint_history(config.checkpoint)
        row["policy_kind"] = np.int32(LEARNED_KIND)
        row["theta"] = np.asarray(config.checkpoint.theta, np.float32)
        row["min_samples"] = np.int32(max(2, min_samples))
    return row


def _twin_episode(
    p: dict[str, Any],
    *,
    cycles: int,
    shards: int,
    shard_slots: int,
    r_max: int,
    t_max: int,
    entries: int,
    capacity: int,
    hidden: int,
    trajectory: bool,
):
    """One serving episode as a single scan over engine cycles."""
    slots = shards * shard_slots
    shard_of = jnp.arange(slots, dtype=jnp.int32) // shard_slots
    s_idx = jnp.arange(shards, dtype=jnp.int32)
    cap_idx = jnp.arange(capacity)
    learned = hidden > 0

    def cycle_fn(carry, xs):
        c, arrived = xs
        (
            queue, d, busy, dev_rem, fly, prod, budget_row,
            state, last_up, last_down, h_t, h_d, h_n, home,
            pool_key, pool_stamp, pool_ctr,
            tokens, over_slo, ttft_sum, changes, shard_s,
            completions, max_q, hits, misses,
        ) = carry

        # -- arrivals land before everything else this cycle
        queue = queue + arrived

        # -- autoscaler tick (every control_every cycles) ---------------
        is_tick = (c % p["control_every"]) == 0
        t = c.astype(jnp.float64) * p["cycle_dt"]
        serving_mask = state == SHARD_SERVING
        serving_before = jnp.sum(serving_mask).astype(jnp.int32)
        observed = queue

        decision = observed
        snap_t, snap_d, n = h_t, h_d, h_n
        if learned:
            # history snapshot including this tick's observation —
            # DepthHistory.with_sample's exact semantics, shared
            # verbatim with the fluid twin's scan
            obs_f = observed.astype(jnp.float64)
            full = h_n >= capacity
            snap_t = jnp.where(
                full,
                jnp.roll(h_t, -1).at[-1].set(t),
                jnp.where(cap_idx < h_n, h_t, t),
            )
            snap_d = jnp.where(
                full,
                jnp.roll(h_d, -1).at[-1].set(obs_f),
                jnp.where(cap_idx < h_n, h_d, obs_f),
            )
            n = jnp.minimum(h_n + 1, capacity)
            times32 = (snap_t - snap_t[-1]).astype(jnp.float32)
            depths32 = snap_d.astype(jnp.float32)
            rem_up = (last_up + p["up_cd"]) - t
            rem_down = (last_down + p["down_cd"]) - t
            frac_up32 = jnp.where(
                (p["up_cd"] > 0) & (rem_up > 0),
                rem_up / jnp.where(p["up_cd"] > 0, p["up_cd"], 1.0),
                0.0,
            ).astype(jnp.float32)
            frac_down32 = jnp.where(
                (p["down_cd"] > 0) & (rem_down > 0),
                rem_down / jnp.where(p["down_cd"] > 0, p["down_cd"], 1.0),
                0.0,
            ).astype(jnp.float32)
            learned_dec = learned_decision(
                p["theta"], times32, depths32, n, observed,
                serving_before, frac_up32, frac_down32,
                p["up_q"], p["down_q"], p["hold"], p["min_samples"],
                p["max_shards"], p["poll32"], p["alpha"], p["window"],
                hidden=hidden,
            )
            decision = jnp.where(
                p["policy_kind"] == LEARNED_KIND, learned_dec, decision
            )

        # -- the reference gates (inclusive thresholds, strictly-After
        # cooldowns, up-cooling skips the down gate, FIRE refreshes the
        # stamp even on a clamped boundary no-op)
        up_code = gate_code(
            decision >= p["up_q"], t, last_up, p["up_cd"]
        )
        up_fire = is_tick & (up_code == GATE_FIRE)
        down_code = jnp.where(
            up_code == GATE_COOLING,
            GATE_SKIPPED,
            gate_code(decision <= p["down_q"], t, last_down, p["down_cd"]),
        )
        down_fire = is_tick & (down_code == GATE_FIRE)

        # scale-up: resurrect the newest draining shard, else activate
        # the lowest inactive one (ShardedWorkerPool.scale_up's order)
        can_up = up_fire & (serving_before < p["max_shards"])
        drain_mask = state == SHARD_DRAINING
        has_drain = jnp.any(drain_mask)
        pick_drain = jnp.argmax(jnp.where(drain_mask, s_idx + 1, 0))
        pick_inact = jnp.argmax(
            jnp.where(state == SHARD_INACTIVE, shards - s_idx, 0)
        )
        pick_up = jnp.where(has_drain, pick_drain, pick_inact)
        state = jnp.where(
            can_up & (s_idx == pick_up), SHARD_SERVING, state
        )
        last_up = jnp.where(up_fire, t, last_up)

        # scale-down: drain the newest serving shard
        serving_mid = jnp.sum(state == SHARD_SERVING).astype(jnp.int32)
        can_down = down_fire & (serving_mid > p["min_shards"])
        pick_down = jnp.argmax(
            jnp.where(state == SHARD_SERVING, s_idx + 1, 0)
        )
        state = jnp.where(
            can_down & (s_idx == pick_down), SHARD_DRAINING, state
        )
        last_down = jnp.where(down_fire, t, last_down)

        serving_after = jnp.sum(state == SHARD_SERVING).astype(jnp.int32)
        changes = changes + (
            is_tick & (serving_after != serving_before)
        ).astype(jnp.int32)
        if learned:
            h_t = jnp.where(is_tick, snap_t, h_t)
            h_d = jnp.where(is_tick, snap_d, h_d)
            h_n = jnp.where(is_tick, n, h_n)

        # -- refill: FIFO over the queue, freest-serving-shard-first ----
        eligible = (~busy) & (state[shard_of] == SHARD_SERVING)
        k = jnp.minimum(queue, jnp.sum(eligible).astype(jnp.int32))
        first_flag = jnp.zeros(slots, jnp.int32)

        def admit(j, st):
            (eligible, busy, dev_rem, prod, budget_row, first_flag,
             home, pool_key, pool_stamp, pool_ctr,
             ttft_sum, over_slo, hits, misses) = st
            take = j < k
            req = jnp.minimum(d + j, r_max - 1)
            avail = jnp.sum(
                eligible.reshape(shards, shard_slots), axis=1
            ).astype(jnp.int32)
            freest = jnp.argmax(avail).astype(jnp.int32)
            tn = p["tenant"][req]
            hm = home[jnp.minimum(tn, t_max - 1)]
            safe_hm = jnp.maximum(hm, 0)
            stick = (
                p["sticky"] & (hm >= 0) & (avail[safe_hm] > 0)
                & ((avail[freest] - avail[safe_hm])
                   < p["sticky_threshold"])
            )
            pick = jnp.where(stick, safe_hm, freest)
            # first admission under sticky routing sets the home shard
            set_home = take & p["sticky"] & (hm < 0)
            home = home.at[jnp.minimum(tn, t_max - 1)].set(
                jnp.where(set_home, freest, hm)
            )
            row = jnp.argmax(eligible & (shard_of == pick))
            g = p["budgets"][req]
            busy = busy.at[row].set(jnp.where(take, True, busy[row]))
            dev_rem = dev_rem.at[row].set(
                jnp.where(take, g - 1, dev_rem[row])
            )
            prod = prod.at[row].set(jnp.where(take, 0, prod[row]))
            budget_row = budget_row.at[row].set(
                jnp.where(take, g, budget_row[row])
            )
            first_flag = first_flag.at[row].set(
                jnp.where(take, 1, first_flag[row])
            )
            eligible = eligible.at[row].set(eligible[row] & ~take)
            # prefix-pool acquire: LRU hit touches, miss installs into
            # the first empty slot else evicts the least recently used
            pooled = take & p["use_pool"]
            keys_row = pool_key[pick]
            is_hit = jnp.any(keys_row == tn)
            hit_idx = jnp.argmax(keys_row == tn)
            empty = keys_row < 0
            install_idx = jnp.where(
                jnp.any(empty),
                jnp.argmax(empty),
                jnp.argmin(
                    jnp.where(empty, jnp.iinfo(jnp.int32).max,
                              pool_stamp[pick])
                ),
            )
            idx = jnp.where(is_hit, hit_idx, install_idx)
            pool_ctr = pool_ctr + pooled.astype(jnp.int32)
            pool_key = pool_key.at[pick, idx].set(
                jnp.where(pooled, tn, pool_key[pick, idx])
            )
            pool_stamp = pool_stamp.at[pick, idx].set(
                jnp.where(pooled, pool_ctr, pool_stamp[pick, idx])
            )
            hits = hits + (pooled & is_hit).astype(jnp.int32)
            misses = misses + (pooled & ~is_hit).astype(jnp.int32)
            # TTFT: first tokens settle at this cycle's combined
            # transfer, so the wait is admission cycle - arrival cycle
            wait = (c - p["arr_cycle"][req]).astype(jnp.int32)
            ttft_sum = ttft_sum + jnp.where(take, wait, 0)
            over_slo = over_slo + jnp.where(
                take,
                jnp.maximum(
                    0.0,
                    wait.astype(jnp.float64) * p["cycle_dt"] - p["slo_s"],
                ),
                0.0,
            )
            return (eligible, busy, dev_rem, prod, budget_row,
                    first_flag, home, pool_key, pool_stamp, pool_ctr,
                    ttft_sum, over_slo, hits, misses)

        hits0, misses0, ttft0 = hits, misses, ttft_sum
        (eligible, busy, dev_rem, prod, budget_row, first_flag, home,
         pool_key, pool_stamp, pool_ctr, ttft_sum, over_slo, hits,
         misses) = lax.fori_loop(
            0, slots, admit,
            (eligible, busy, dev_rem, prod, budget_row, first_flag,
             home, pool_key, pool_stamp, pool_ctr, ttft_sum, over_slo,
             hits, misses),
        )
        queue = queue - k
        d = d + k
        max_q = jnp.maximum(max_q, queue)

        # -- step: the gang block engine's dispatch-ahead mechanics -----
        # dispatch block N+1 (spends device budget now), settle the
        # first tokens admitted this cycle AND block N's tokens (they
        # ride the one combined transfer), then free completed slots
        live = busy & (dev_rem > 0)
        n_disp = jnp.where(live, jnp.minimum(p["block"], dev_rem), 0)
        dev_rem = dev_rem - n_disp
        settled = fly
        fly = n_disp
        tokens_c = k + jnp.sum(settled).astype(jnp.int32)
        prod = prod + first_flag + settled
        done_rows = busy & (prod >= budget_row)
        busy = busy & ~done_rows
        completed_c = jnp.sum(done_rows).astype(jnp.int32)
        tokens = tokens + tokens_c
        completions = completions + completed_c

        # -- drain-retire: an emptied draining shard goes inactive
        shard_busy = jnp.sum(
            busy.reshape(shards, shard_slots), axis=1
        )
        state = jnp.where(
            (state == SHARD_DRAINING) & (shard_busy == 0),
            SHARD_INACTIVE, state,
        )
        serving_end = jnp.sum(state == SHARD_SERVING).astype(jnp.int32)
        # integer serving-cycles; seconds = count * dt once at the end,
        # so the accumulator is exact (the host scorer's sum * dt form)
        shard_s = shard_s + serving_end

        out = (
            (
                k, completed_c, tokens_c, ttft_sum - ttft0, queue,
                serving_end, hits - hits0, misses - misses0,
            )
            if trajectory
            else ()
        )
        carry = (
            queue, d, busy, dev_rem, fly, prod, budget_row,
            state, last_up, last_down, h_t, h_d, h_n, home,
            pool_key, pool_stamp, pool_ctr,
            tokens, over_slo, ttft_sum, changes, shard_s,
            completions, max_q, hits, misses,
        )
        return carry, out

    init = (
        jnp.asarray(0, jnp.int32),  # queue
        jnp.asarray(0, jnp.int32),  # admitted (FIFO cursor)
        jnp.zeros(slots, bool),  # busy
        jnp.zeros(slots, jnp.int32),  # device remaining
        jnp.zeros(slots, jnp.int32),  # in-flight block tokens
        jnp.zeros(slots, jnp.int32),  # produced
        jnp.ones(slots, jnp.int32),  # budget
        jnp.where(  # shard states: initial prefix serving
            jnp.arange(shards) < p["initial_shards"],
            SHARD_SERVING, SHARD_INACTIVE,
        ).astype(jnp.int32),
        jnp.asarray(0.0, jnp.float64),  # last_up (startup grace at t=0)
        jnp.asarray(0.0, jnp.float64),  # last_down
        jnp.zeros(capacity, jnp.float64),
        jnp.zeros(capacity, jnp.float64),
        jnp.asarray(0, jnp.int32),
        jnp.full(t_max, -1, jnp.int32),  # tenant home shards
        jnp.full((shards, entries), -1, jnp.int32),  # pool keys
        jnp.zeros((shards, entries), jnp.int32),  # pool LRU stamps
        jnp.asarray(0, jnp.int32),  # pool recency counter
        jnp.asarray(0, jnp.int32),  # tokens
        jnp.asarray(0.0, jnp.float64),  # time over TTFT SLO
        jnp.asarray(0, jnp.int32),  # ttft cycle sum
        jnp.asarray(0, jnp.int32),  # shard-count changes
        jnp.asarray(0, jnp.int32),  # serving shard-cycles
        jnp.asarray(0, jnp.int32),  # completions
        jnp.asarray(0, jnp.int32),  # max queue
        jnp.asarray(0, jnp.int32),  # pool hits
        jnp.asarray(0, jnp.int32),  # pool misses
    )
    xs = (jnp.arange(cycles, dtype=jnp.int32), p["arrived"])
    carry, outs = lax.scan(cycle_fn, init, xs, length=cycles)
    d_final = carry[1]
    # requests still queued at episode end: their TTFT is already at
    # least (cycles - arrival), so the SLO debt below is a LOWER bound —
    # a policy cannot improve its score by refusing admission
    req_idx = jnp.arange(r_max, dtype=jnp.int32)
    unserved = (req_idx >= d_final) & (req_idx < p["n_requests"])
    pending_wait = (
        (cycles - p["arr_cycle"]).astype(jnp.float64) * p["cycle_dt"]
        - p["slo_s"]
    )
    over_slo = carry[18] + jnp.sum(
        jnp.where(unserved, jnp.maximum(0.0, pending_wait), 0.0)
    )
    summary = {
        "tokens": carry[17],
        "time_over_slo_s": over_slo,
        "shard_changes": carry[20],
        "shard_seconds": carry[21].astype(jnp.float64) * p["cycle_dt"],
        "completions": carry[22],
        "admitted": d_final,
        "final_queue": carry[0],
        "max_queue": carry[23],
        "ttft_cycles_sum": carry[19],
        "pool_hits": carry[24],
        "pool_misses": carry[25],
    }
    if not trajectory:
        return summary
    names = TRAJECTORY_KEYS
    return {**summary, "trajectory": dict(zip(names, outs))}


@partial(
    jax.jit,
    static_argnames=(
        "cycles", "shards", "shard_slots", "r_max", "t_max", "entries",
        "capacity", "hidden", "trajectory",
    ),
)
def _run_twin_batch(
    params, cycles, shards, shard_slots, r_max, t_max, entries,
    capacity, hidden, trajectory=True,
):
    return jax.vmap(
        lambda row: _twin_episode(
            row, cycles=cycles, shards=shards, shard_slots=shard_slots,
            r_max=r_max, t_max=t_max, entries=entries, capacity=capacity,
            hidden=hidden, trajectory=trajectory,
        )
    )(params)


@partial(
    jax.jit,
    static_argnames=(
        "cycles", "shards", "shard_slots", "r_max", "t_max", "entries",
        "capacity", "hidden",
    ),
)
def _run_twin_population(
    params, thetas, cycles, shards, shard_slots, r_max, t_max, entries,
    capacity, hidden,
):
    """``[P, D]`` thetas × ``[E, …]`` scenario rows → ``[P, E]``
    serving summaries (trajectory off: a training generation transfers
    :data:`SERVING_SUMMARY_KEYS` scalars per episode, nothing else)."""

    def one(theta, row):
        return _twin_episode(
            dict(row, theta=theta), cycles=cycles, shards=shards,
            shard_slots=shard_slots, r_max=r_max, t_max=t_max,
            entries=entries, capacity=capacity, hidden=hidden,
            trajectory=False,
        )

    return jax.vmap(
        lambda theta: jax.vmap(lambda row: one(theta, row))(params)
    )(thetas)


@dataclass
class TwinEpisode:
    """One compiled serving episode: summary + per-cycle trail."""

    config: TwinConfig
    summary: dict[str, Any]
    trajectory: dict[str, np.ndarray] = field(default_factory=dict)

    @property
    def tokens_per_second(self) -> float:
        return float(self.summary["tokens"]) / self.config.scenario.duration_s


def _group_key(config: TwinConfig) -> tuple:
    s = config.scenario
    hidden = (
        int(config.checkpoint.hidden) if config.policy == "learned" else 0
    )
    capacity = 2
    if config.policy == "learned":
        from ...learn.checkpoint import checkpoint_history

        capacity, _ = checkpoint_history(config.checkpoint)
    return (s.cycles, s.shards, s.shard_slots, capacity, hidden)


def run_twin_episodes(
    configs: Sequence[TwinConfig], trajectory: bool = True
) -> list[TwinEpisode]:
    """One device call for a batch of configs sharing compiled shapes
    (cycles, plane geometry, history capacity, hidden width).  Request
    counts, tenant populations, and pool sizes pad to the batch max."""
    configs = list(configs)
    if not configs:
        return []
    keys = {_group_key(c) for c in configs}
    if len(keys) > 1:
        raise ValueError(
            f"one twin batch must share (cycles, shards, shard_slots,"
            f" history, hidden); got {sorted(keys)} — use"
            f" run_twin_grouped"
        )
    cycles, shards, shard_slots, capacity, hidden = keys.pop()
    r_max = max(1, max(c.scenario.total_requests() for c in configs))
    t_max = max(1, max(c.scenario.tenants for c in configs))
    entries = max(1, max(c.scenario.pool_entries for c in configs))
    rows = [encode_twin_config(c, r_max, t_max) for c in configs]
    theta_len = max(row["theta"].shape[0] for row in rows)
    for row in rows:
        if row["theta"].shape[0] < theta_len:
            row["theta"] = np.zeros(theta_len, np.float32)
    batch = {key: np.stack([row[key] for row in rows]) for key in rows[0]}
    with enable_x64():
        out = _run_twin_batch(
            {k: jnp.asarray(v) for k, v in batch.items()},
            cycles=cycles, shards=shards, shard_slots=shard_slots,
            r_max=r_max, t_max=t_max, entries=entries, capacity=capacity,
            hidden=hidden, trajectory=trajectory,
        )
        out = jax.tree_util.tree_map(np.asarray, out)
    episodes = []
    for i, config in enumerate(configs):
        summary = {
            key: out[key][i].item() for key in SERVING_SUMMARY_KEYS
        }
        traj = (
            {
                key: np.asarray(out["trajectory"][key][i])
                for key in TRAJECTORY_KEYS
            }
            if trajectory
            else {}
        )
        episodes.append(
            TwinEpisode(config=config, summary=summary, trajectory=traj)
        )
    return episodes


def run_twin_grouped(
    configs: Sequence[TwinConfig], trajectory: bool = True
) -> list[TwinEpisode]:
    """:func:`run_twin_episodes` over mixed compiled shapes — groups,
    runs one batch per group, scatters back into input order."""
    configs = list(configs)
    groups: dict[tuple, list[int]] = {}
    for index, config in enumerate(configs):
        groups.setdefault(_group_key(config), []).append(index)
    episodes: list[TwinEpisode | None] = [None] * len(configs)
    for indices in groups.values():
        for index, episode in zip(
            indices,
            run_twin_episodes([configs[i] for i in indices], trajectory),
        ):
            episodes[index] = episode
    return episodes  # type: ignore[return-value]


def score_twin_summary(
    summary: dict[str, Any], scenario: ServingScenario
) -> dict:
    """A twin summary as a battery-style scorecard row in SERVING
    units — the lexicographic axes the twin bench gates on (tokens/s,
    then time-over-TTFT-SLO, then shard churn), plus the context a
    reviewer needs to read the row."""
    duration = scenario.duration_s
    return {
        "tokens_per_second": round(float(summary["tokens"]) / duration, 1),
        "time_over_slo_s": round(float(summary["time_over_slo_s"]), 3),
        "shard_changes": int(summary["shard_changes"]),
        "shard_seconds": round(float(summary["shard_seconds"]), 2),
        "completions": int(summary["completions"]),
        "admitted": int(summary["admitted"]),
        "final_queue": int(summary["final_queue"]),
        "max_queue": int(summary["max_queue"]),
        "pool_hits": int(summary["pool_hits"]),
        "pool_misses": int(summary["pool_misses"]),
        "cycles": scenario.cycles,
    }


def serving_lex_key(rows: Sequence[dict]) -> tuple:
    """Aggregate lexicographic ordering over serving score rows:
    MORE tokens/s first (negated), then LESS time-over-SLO, then LESS
    churn — smaller tuple wins, like the fluid ``_lex_score``."""
    return (
        -round(sum(r["tokens_per_second"] for r in rows), 1),
        round(sum(r["time_over_slo_s"] for r in rows), 3),
        sum(r["shard_changes"] for r in rows),
    )


def twin_config_for_point(point, scenario: ServingScenario) -> TwinConfig:
    """A sweep point's gate knobs applied to one serving scenario —
    how tuned-threshold reactive baselines re-run on serving worlds
    (:func:`~..sweep.run_sweep` routes ServingScenario jobs here).
    Forecaster points have no serving-twin analogue; callers filter to
    reactive points."""
    if point.policy != "reactive":
        raise ValueError(
            f"the serving twin sweeps reactive gate points only, got"
            f" policy={point.policy!r}"
        )
    return TwinConfig(
        scenario=scenario,
        scale_up_queue=point.scale_up_messages,
        scale_down_queue=point.scale_down_messages,
        up_cooldown_s=point.scale_up_cooldown,
        down_cooldown_s=point.scale_down_cooldown,
    )


__all__ = [
    "LEARNED_KIND",
    "REACTIVE_KIND",
    "SERVING_SUMMARY_KEYS",
    "TRAJECTORY_KEYS",
    "TwinConfig",
    "TwinEpisode",
    "encode_twin_config",
    "run_twin_episodes",
    "run_twin_grouped",
    "score_twin_summary",
    "serving_lex_key",
    "twin_config_for_point",
]
