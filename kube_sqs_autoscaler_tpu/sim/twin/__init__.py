"""The token-level compiled serving twin (ROADMAP item 2).

PR 9's compiled twin simulates a *fluid queue* — the right world for the
reference autoscaler, the wrong one for the sharded serving fleet the
controller has actuated since PR 6: the fleet is scored in tokens/s,
TTFT, and time-over-TTFT-SLO, and its spin-up is a near-free mask flip
(BLITZSCALE), which a fluid replica-rate world cannot express at all.

This package simulates the serving plane itself at token granularity —
slots, decode blocks, refill/admission, freest-first + sticky routing,
prefix-cache hits/misses, shard counts behind the drain/retire state
machine — as ONE ``jax.lax.scan`` per episode, vmapped over config ×
scenario batches, exactly the architecture ``sim/compiled.py`` proved
for the fluid loop.  Fidelity is mechanical, not assumed:
:func:`~.fidelity.verify_twin_fidelity` replays the identical scripted
request streams through the REAL :class:`~...workloads.shard_plane.
ShardedBatcher` and compares cycle-for-cycle completions, tokens,
TTFT, queue depths, shard counts, and prefix hits/misses — 0
divergences, reported through replay's ``Divergence`` machinery.

The learned autoscaling policy (``learn/``) retrains inside this twin
with reward in serving units (tokens/s, time-over-TTFT-SLO, churn);
``bench.py --suite twin`` gates the result.
"""

from .compiled import (  # noqa: F401
    SERVING_SUMMARY_KEYS,
    TwinConfig,
    TwinEpisode,
    run_twin_episodes,
    run_twin_grouped,
    score_twin_summary,
)
from .fidelity import TwinFidelityReport, verify_twin_fidelity  # noqa: F401
from .host import run_host_episode, tiny_twin_model  # noqa: F401
from .scenario import (  # noqa: F401
    ServingScenario,
    default_twin_battery,
    twin_variants,
)
