"""The serving twin's mechanical fidelity gate.

Same contract as the fluid twin's :func:`~..compiled.verify_fidelity`:
run identical scripted worlds through the compiled scan AND the real
plane (:mod:`.host`), compare cycle-for-cycle, and report every
mismatch through the flight recorder's :class:`~..replay.Divergence`
machinery.  ``bench.py --suite twin`` exits 2 on any divergence before
trusting a single training or comparison number.

Compared per cycle: admitted count, completions, tokens emitted, TTFT
cycle sums, queue depth, serving shard count, prefix-pool hits and
misses.  Compared per episode: every serving summary accumulator
(time-over-SLO to float64 noise, everything else exactly).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Sequence

from ..replay import Divergence
from .compiled import (
    SERVING_SUMMARY_KEYS,
    TRAJECTORY_KEYS,
    TwinConfig,
    run_twin_grouped,
)
from .host import run_host_episode
from .scenario import ServingScenario


@dataclass
class TwinFidelityReport:
    """Outcome of one serving-twin fidelity pass."""

    episodes: int
    cycles: int
    divergences: list[tuple[str, Divergence]]

    @property
    def ok(self) -> bool:
        return not self.divergences

    def format_divergences(self, limit: int = 10) -> list[str]:
        return [
            f"{label}: cycle {d.tick}: {d.tick_field}"
            f" recorded={d.recorded!r} replayed={d.replayed!r}"
            for label, d in self.divergences[:limit]
        ]


def _label(config: TwinConfig) -> str:
    return f"{config.scenario.name}/{config.policy}"


def verify_twin_fidelity(
    configs: "Sequence[TwinConfig | ServingScenario]",
) -> TwinFidelityReport:
    """Compiled-vs-real over every config; 0 divergences or the list.

    Bare :class:`ServingScenario`\\ s run under the reactive policy;
    pass :class:`TwinConfig` rows to cover learned checkpoints and
    swept gate knobs (the twin bench covers both).  Compiled episodes
    batch by shape group in as few device calls as the shapes allow;
    each real episode runs the actual jitted plane cycle by cycle.
    """
    rows = [
        c if isinstance(c, TwinConfig) else TwinConfig(scenario=c)
        for c in configs
    ]
    compiled = run_twin_grouped(rows, trajectory=True)
    divergences: list[tuple[str, Divergence]] = []
    total_cycles = 0
    for config, twin in zip(rows, compiled):
        host = run_host_episode(config)
        label = _label(config)
        total_cycles += config.scenario.cycles
        for key in TRAJECTORY_KEYS:
            a, b = host.trajectory[key], twin.trajectory[key]
            for cycle in range(config.scenario.cycles):
                if int(a[cycle]) != int(b[cycle]):
                    divergences.append(
                        (
                            label,
                            Divergence(
                                cycle, key, int(a[cycle]), int(b[cycle])
                            ),
                        )
                    )
                    break  # first mismatch per field tells the story
        for key in SERVING_SUMMARY_KEYS:
            recorded, replayed = host.summary[key], twin.summary[key]
            if key == "time_over_slo_s":
                same = math.isclose(
                    recorded, replayed, rel_tol=1e-9, abs_tol=1e-9
                )
            else:
                same = int(recorded) == int(replayed)
            if not same:
                divergences.append(
                    (
                        label,
                        Divergence(
                            config.scenario.cycles,
                            f"summary.{key}",
                            recorded,
                            replayed,
                        ),
                    )
                )
    return TwinFidelityReport(
        episodes=len(rows), cycles=total_cycles, divergences=divergences
    )
