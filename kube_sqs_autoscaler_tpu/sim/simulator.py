"""Deterministic queue/worker-pool world driven by the production loop.

World model (fluid approximation of an SQS-fed worker Deployment):

- messages arrive at ``arrival_rate`` msg/s;
- each of the current ``replicas`` drains ``service_rate_per_replica`` msg/s;
- queue depth integrates the net rate, floored at zero, and is updated
  lazily whenever the controller observes it (each poll), so dynamics are
  exact at observation points regardless of poll cadence.

The controller under simulation is the real production stack —
``ControlLoop`` + ``PodAutoScaler`` + ``QueueMetricSource`` — wired to the
in-memory fakes on a ``FakeClock``; nothing is mocked *inside* the system
under test.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import cached_property
from typing import TYPE_CHECKING, Sequence

if TYPE_CHECKING:  # annotation-only: the reactive path stays lean
    from ..core.resilience import ResilienceConfig
    from ..learn.checkpoint import PolicyCheckpoint
    from .faults import FailureProcess

from ..core.clock import FakeClock
from ..core.events import MultiObserver, TickObserver
from ..core.loop import ControlLoop, LoopConfig
from ..metrics.fake import FakeQueueService
from ..metrics.queue import QueueMetricSource
from ..scale.actuator import PodAutoScaler
from ..scale.fake import FakeDeploymentAPI
from .scenarios import ArrivalProcess


@dataclass(frozen=True)
class SimConfig:
    """World + policy parameters (policy defaults = reference defaults).

    ``arrival_rate`` accepts the seed's plain msg/s number *or* any
    :class:`~.scenarios.ArrivalProcess` (step/ramp/diurnal/burst); a plain
    number keeps the exact constant-rate arithmetic of the seed.

    ``policy`` selects the depth policy the gates threshold through:
    ``"reactive"`` (the reference), ``"predictive"`` (forecasted depth at
    ``now + forecast_horizon`` via the named ``forecaster``), or
    ``"learned"`` (a trained network's up/hold/down decision expressed as
    an effective depth; requires ``learned_checkpoint``, reuses
    ``forecast_history``/``forecast_min_samples`` for its feature
    ring buffer and reactive warm-up).

    ``faults`` injects a deterministic :class:`~.faults.FailureProcess`
    around the metric source and scaler (``None`` = healthy world);
    ``resilience`` hands the loop an opt-in
    :class:`~..core.resilience.ResilienceConfig` (``None`` = reference
    failure handling) — the chaos battery (:mod:`.evaluate`) scores the
    two against each other.
    """

    arrival_rate: float | ArrivalProcess = 50.0  # msg/s into the queue
    service_rate_per_replica: float = 10.0  # msg/s drained per replica
    duration: float = 600.0  # simulated seconds
    initial_depth: float = 0.0
    initial_replicas: int = 1
    min_pods: int = 1
    max_pods: int = 20
    scale_up_pods: int = 1
    scale_down_pods: int = 1
    loop: LoopConfig = field(default_factory=LoopConfig)
    policy: str = "reactive"  # "reactive" | "predictive"
    forecaster: str = "holt"  # ewma | holt | lstsq (policy="predictive")
    forecast_horizon: float = 30.0  # seconds ahead the gates look
    forecast_history: int = 128  # ring-buffer capacity (samples)
    forecast_min_samples: int = 3  # reactive warm-up before forecasting
    forecast_conservative: bool = True  # gates see max(observed, forecast)
    faults: "FailureProcess | None" = None  # sim.faults injection
    resilience: "ResilienceConfig | None" = None  # core.resilience opt-in
    learned_checkpoint: "PolicyCheckpoint | None" = None  # policy="learned"


@dataclass
class SimResult:
    """Timeline of (t, observed_depth, replicas) at each poll + summary."""

    timeline: list[tuple[float, int, int]]
    final_replicas: int
    final_depth: float
    max_depth: float
    ticks: int

    @cached_property
    def replica_changes(self) -> int:
        """Scaling churn: ticks whose entering replica count changed.

        Cached: the recount is O(timeline) and sweep scoring
        (:mod:`.sweep`) reads it once per scored configuration — results
        are effectively frozen once built, so the first read's answer is
        the answer.
        """
        changes = 0
        for (_, _, a), (_, _, b) in zip(self.timeline, self.timeline[1:]):
            if a != b:
                changes += 1
        return changes

    def time_over(self, depth_threshold: float) -> float:
        """Simulated seconds the *observed* depth sat above ``depth_threshold``
        (left-rule over the observation timeline — the SLO metric the
        scenario battery reports)."""
        over = 0.0
        for (t0, d0, _), (t1, _, _) in zip(self.timeline, self.timeline[1:]):
            if d0 > depth_threshold:
                over += t1 - t0
        return over


class _WorldQueue(FakeQueueService):
    """Queue whose depth integrates arrivals/drains up to observation time."""

    def __init__(self, sim: "Simulation"):
        super().__init__()
        self._sim = sim

    def get_queue_attributes(self, queue_url, attribute_names):
        self._sim.advance_world()
        depth = int(self._sim.depth)
        self.set_queue_attributes({"ApproximateNumberOfMessages": str(depth)})
        return super().get_queue_attributes(queue_url, attribute_names)


class Simulation:
    """One closed-loop episode.

    ``extra_observers`` (e.g. a flight-recorder :class:`~..obs.journal.
    TickJournal`/``TickRing``) are fanned out on the loop's observer slot
    alongside any forecast history the policy needs — recording a
    simulated episode uses exactly the production observer seam.
    """

    def __init__(
        self,
        config: SimConfig | None = None,
        extra_observers: Sequence[TickObserver] = (),
    ):
        self.config = config or SimConfig()
        self.clock = FakeClock()
        self.depth = float(self.config.initial_depth)
        self._last_world_update = 0.0
        self.deployments = FakeDeploymentAPI.with_deployments(
            "sim", self.config.initial_replicas, "workers"
        )
        self.scaler = PodAutoScaler(
            client=self.deployments,
            max=self.config.max_pods,
            min=self.config.min_pods,
            scale_up_pods=self.config.scale_up_pods,
            scale_down_pods=self.config.scale_down_pods,
            deployment="workers",
            namespace="sim",
        )
        self.queue = _WorldQueue(self)
        self.metric_source = QueueMetricSource(
            client=self.queue,
            queue_url="sim://queue",
            attribute_names=("ApproximateNumberOfMessages",),
        )
        # Fault injection wraps the REAL source/scaler (the system under
        # test is unchanged); a failing poll still advances the world so
        # the timeline — and max_depth — track the backlog the controller
        # could not see.
        loop_metric_source = self.metric_source
        loop_scaler = self.scaler
        if self.config.faults is not None:
            from .faults import FaultyMetricSource, FaultyScaler

            loop_metric_source = FaultyMetricSource(
                self.metric_source,
                self.config.faults,
                self.clock,
                on_failure=self.advance_world,
            )
            loop_scaler = FaultyScaler(
                self.scaler, self.config.faults, self.clock
            )
        depth_policy = None
        observers: list[TickObserver] = list(extra_observers)
        if self.config.policy == "predictive":
            # Lazy import: the reactive path (and bench.py's default suite)
            # stays JAX-free; only a predictive episode pays the import.
            from ..forecast import DepthHistory, PredictivePolicy, make_forecaster

            history = DepthHistory(capacity=self.config.forecast_history)
            depth_policy = PredictivePolicy(
                make_forecaster(self.config.forecaster),
                history,
                horizon=self.config.forecast_horizon,
                min_samples=self.config.forecast_min_samples,
                conservative=self.config.forecast_conservative,
            )
            observers.insert(0, history)
        elif self.config.policy == "learned":
            # Lazy import like the predictive path: only a learned episode
            # pays the learn-package (and JAX) import.
            from ..forecast import DepthHistory
            from ..learn import LearnedPolicy

            if self.config.learned_checkpoint is None:
                raise ValueError(
                    "policy='learned' requires SimConfig.learned_checkpoint"
                )
            depth_policy = LearnedPolicy(
                self.config.learned_checkpoint,
                policy=self.config.loop.policy,
                poll_interval=self.config.loop.poll_interval,
                max_pods=self.config.max_pods,
                min_pods=self.config.min_pods,
                scale_up_pods=self.config.scale_up_pods,
                scale_down_pods=self.config.scale_down_pods,
                initial_replicas=self.config.initial_replicas,
                history=DepthHistory(capacity=self.config.forecast_history),
                min_samples=self.config.forecast_min_samples,
            )
            # the policy IS its own observer: the tick-record hook feeds
            # both the depth history and the replica/cooldown mirror
            observers.insert(0, depth_policy)
        elif self.config.policy != "reactive":
            raise ValueError(
                f"policy must be 'reactive', 'predictive' or 'learned',"
                f" got {self.config.policy!r}"
            )
        if not observers:
            observer: TickObserver | None = None
        elif len(observers) == 1:
            observer = observers[0]
        else:
            observer = MultiObserver(observers)
        self.depth_policy = depth_policy
        self.loop = ControlLoop(
            loop_scaler,
            loop_metric_source,
            self.config.loop,
            clock=self.clock,
            observer=observer,
            depth_policy=depth_policy,
            resilience=self.config.resilience,
        )
        self.timeline: list[tuple[float, int, int]] = []
        self._max_depth = self.depth

    def advance_world(self) -> None:
        """Integrate queue dynamics from the last update to clock.now()."""
        now = self.clock.now()
        dt = now - self._last_world_update
        if dt <= 0:
            return
        replicas = self.deployments.replicas("workers")
        arrival = self.config.arrival_rate
        if isinstance(arrival, (int, float)):
            # The seed's constant-rate arithmetic, expression-for-expression:
            # time-varying worlds must not perturb existing sim results.
            net_rate = arrival - replicas * self.config.service_rate_per_replica
            self.depth = max(0.0, self.depth + net_rate * dt)
        else:
            # Arrivals integrate analytically; the empty-queue floor is
            # per-interval, so a mid-interval empty + rate rise understates
            # depth by at most that interval's drain (see scenarios.py).
            arrived = arrival.arrivals_between(self._last_world_update, now)
            drained = replicas * self.config.service_rate_per_replica * dt
            self.depth = max(0.0, self.depth + arrived - drained)
        self._max_depth = max(self._max_depth, self.depth)
        self._last_world_update = now
        self.timeline.append((now, int(self.depth), replicas))

    def run(self) -> SimResult:
        ticks = max(1, int(self.config.duration / self.config.loop.poll_interval))
        self.loop.run(max_ticks=ticks)
        self.advance_world()
        return SimResult(
            timeline=self.timeline,
            final_replicas=self.deployments.replicas("workers"),
            final_depth=self.depth,
            max_depth=self._max_depth,
            ticks=self.loop.ticks,
        )
