"""Virtual-time cost model: dispatches AND data movement, priced.

The comms benches price a serving episode as ``decode_cost_s`` per gang
dispatch + ``insert_cost_s`` per admission + ``transfer_cost_s`` per
transfer dispatch — honest about WHEN work happens but blind to WHERE
bytes go: a one-hop settle pull and a cross-torus evacuation cost the
same flat fee.  :class:`CostModel` closes that gap with the routing
layer's topology: transfer cost becomes the MODELED COMPLETION TIME of
the episode's transfer ops scheduled over the link graph
(:func:`~..comms.routing.simulate_schedule`), so contended links, hop
counts, and chunked disjoint-path routing all price in.

This is the honesty ROADMAP item 3 needs: a knob-head trained against
virtual-time rewards can only learn to avoid a contended link if the
cost model charges for it.  Topology-free construction degrades to the
flat per-dispatch fee, byte-identical to the comms-bench arithmetic.
"""

from __future__ import annotations

from typing import Any, Iterable

#: The comms-suite virtual-time fees (bench.py pins these numbers —
#: they are modeling constants, not measurements).
DECODE_COST_S = 0.002
INSERT_COST_S = 0.006
TRANSFER_COST_S = 0.001


class CostModel:
    """Price an episode's dispatches + transfers in virtual seconds."""

    def __init__(
        self,
        *,
        topology: Any = None,
        decode_cost_s: float = DECODE_COST_S,
        insert_cost_s: float = INSERT_COST_S,
        transfer_cost_s: float = TRANSFER_COST_S,
        routed: bool = True,
    ) -> None:
        self.topology = topology
        self.decode_cost_s = decode_cost_s
        self.insert_cost_s = insert_cost_s
        self.transfer_cost_s = transfer_cost_s
        self.routed = routed

    def compute_cost_s(
        self, *, decode_dispatches: int = 0, insert_dispatches: int = 0
    ) -> float:
        """The dispatch side of the bill (unchanged arithmetic)."""
        return (
            decode_dispatches * self.decode_cost_s
            + insert_dispatches * self.insert_cost_s
        )

    def transfer_cost(self, ops: Iterable[Any]) -> dict:
        """The data-movement side: with a topology, the modeled
        completion time of ``ops`` (TransferOps or dicts with
        kind/source/destination/nbytes) scheduled over the link graph,
        plus the per-link utilization the schedule implies; without
        one, the flat per-op fee the comms bench charges."""
        ops = list(ops)
        if self.topology is None:
            return {
                "model": "flat",
                "transfer_cost_s": len(ops) * self.transfer_cost_s,
                "ops": len(ops),
            }
        from ..comms.routing import simulate_schedule

        result = simulate_schedule(
            ops, self.topology, routed=self.routed,
        )
        return {
            "model": "routed" if self.routed else "when-only",
            "transfer_cost_s": result.makespan,
            "ops": len(ops),
            "link_utilization": dict(result.link_utilization),
            "link_bytes": dict(result.link_bytes),
        }

    def episode_cost_s(
        self,
        *,
        decode_dispatches: int = 0,
        insert_dispatches: int = 0,
        transfer_ops: Iterable[Any] = (),
    ) -> float:
        """Total virtual seconds: dispatches + the transfer model.
        Transfers overlap compute on the real engine, so this is the
        PESSIMAL serial bound — a stable reward denominator, not a
        latency claim."""
        return self.compute_cost_s(
            decode_dispatches=decode_dispatches,
            insert_dispatches=insert_dispatches,
        ) + float(self.transfer_cost(transfer_ops)["transfer_cost_s"])
