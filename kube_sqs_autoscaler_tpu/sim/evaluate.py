"""Head-to-head scenario battery: reactive vs. each forecaster.

Simulator-driven policy evaluation (the KIS-S harness shape,
arxiv 2507.07932): every candidate policy runs the *same* deterministic
world — identical arrival process, service rates, bounds, cadence — and
is scored on the three numbers a queue-serving fleet cares about:

- ``max_depth``      — worst backlog (latency proxy; BLITZSCALE's point
  that scale-up lateness is the dominant serving cost, arxiv 2412.17246);
- ``time_over_slo``  — seconds the observed depth sat above the
  scenario's SLO depth;
- ``replica_changes``— churn (each change is a pod start/stop: image
  pulls, TPU grab/release, cache warm-up).

Used by ``bench.py --suite forecast`` (the ``BENCH_r06`` artifact) and the
acceptance tests; later policies (RL, multi-queue) plug into the same
battery.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..core.loop import LoopConfig
from ..core.policy import PolicyConfig
from .scenarios import (
    ArrivalProcess,
    BurstArrival,
    DiurnalArrival,
    RampArrival,
    StepArrival,
)
from .simulator import SimConfig, Simulation


@dataclass(frozen=True)
class Scenario:
    """One world the battery replays under every candidate policy."""

    name: str
    arrival: ArrivalProcess
    duration: float = 900.0
    service_rate_per_replica: float = 10.0
    min_pods: int = 1
    max_pods: int = 30
    initial_replicas: int = 1
    slo_depth: float = 300.0
    # Forecast horizon (s) predictive policies use on this scenario — a
    # deployment knob matched to the traffic's timescale: ~1 cooldown past
    # the poll period for fast transients, longer for slow cycles (a long
    # horizon on a fast ramp extrapolates the trend past its end and
    # overshoots; a short one on a slow cycle sees the peak too late).
    horizon: float = 60.0
    loop: LoopConfig = field(
        default_factory=lambda: LoopConfig(
            poll_interval=5.0,
            policy=PolicyConfig(
                scale_up_messages=100,
                scale_down_messages=10,
                scale_up_cooldown=10.0,
                scale_down_cooldown=30.0,
            ),
        )
    )


def default_battery() -> tuple[Scenario, ...]:
    """Step, ramp, diurnal, burst — the four arrival shapes from ISSUE/KIS-S.

    Magnitudes are sized so the default thresholds are genuinely exercised:
    steady-state demand crosses several replicas' capacity and the backlog
    moves through both gates' thresholds within each episode.
    """
    return (
        Scenario(
            name="step",
            # launch day: 20 msg/s overnight, 120 msg/s from t=120 on
            arrival=StepArrival(before=20.0, after=120.0, at=120.0),
        ),
        Scenario(
            name="ramp",
            # organic growth: 10 -> 150 msg/s over 10 minutes, then flat
            arrival=RampArrival(
                start_rate=10.0, end_rate=150.0, t_start=60.0, t_end=660.0
            ),
            horizon=30.0,
        ),
        Scenario(
            name="diurnal",
            # user traffic: 80 +/- 60 msg/s, two full cycles per episode.
            # The fleet starts at steady state for the base load (8 pods at
            # 10 msg/s each): a cold 1-pod start makes every policy's max
            # depth the same cold-start backlog (actuation-rate-limited,
            # one pod per cooldown), hiding the cyclic behavior the
            # scenario exists to score.
            arrival=DiurnalArrival(base=80.0, amplitude=60.0, period=450.0),
            initial_replicas=8,
        ),
        Scenario(
            name="burst",
            # retry storms: 250 msg/s for 45 s every 5 minutes over 25 base
            arrival=BurstArrival(
                base=25.0, burst_rate=250.0, period=300.0,
                burst_len=45.0, first_burst=120.0,
            ),
        ),
    )


def run_episode(
    scenario: Scenario,
    policy: str = "reactive",
    forecaster: str = "holt",
    horizon: float | None = None,
) -> dict:
    """One policy through one scenario; returns the scorecard row.

    ``horizon=None`` uses the scenario's own tuned horizon.
    """
    horizon = scenario.horizon if horizon is None else horizon
    sim = Simulation(
        SimConfig(
            arrival_rate=scenario.arrival,
            service_rate_per_replica=scenario.service_rate_per_replica,
            duration=scenario.duration,
            initial_replicas=scenario.initial_replicas,
            min_pods=scenario.min_pods,
            max_pods=scenario.max_pods,
            loop=scenario.loop,
            policy=policy,
            forecaster=forecaster,
            forecast_horizon=horizon,
        )
    )
    result = sim.run()
    return score_result(result, scenario.slo_depth)


def score_result(result, slo_depth: float) -> dict:
    """One :class:`~.simulator.SimResult` as the battery's scorecard row.

    Shared by the live scenario battery and the journal counterfactual
    re-scoring (:mod:`.replay`), so recorded episodes and synthetic
    scenarios are judged on identical numbers.
    """
    return {
        "max_depth": round(result.max_depth, 1),
        "time_over_slo_s": round(result.time_over(slo_depth), 1),
        "replica_changes": result.replica_changes,
        "final_replicas": result.final_replicas,
        "final_depth": round(result.final_depth, 1),
        "ticks": result.ticks,
    }


def evaluate_battery(
    scenarios: tuple[Scenario, ...] | None = None,
    forecasters: tuple[str, ...] = ("ewma", "holt", "lstsq"),
    horizon: float | None = None,
) -> dict:
    """Every scenario × (reactive + each forecaster) → nested scorecard."""
    scenarios = scenarios if scenarios is not None else default_battery()
    report: dict = {}
    for scenario in scenarios:
        row: dict = {"reactive": run_episode(scenario, policy="reactive")}
        for name in forecasters:
            row[f"predictive:{name}"] = run_episode(
                scenario, policy="predictive", forecaster=name, horizon=horizon
            )
        report[scenario.name] = row
    return report


def summarize(
    report: dict,
    target_scenarios: tuple[str, ...] = ("ramp", "diurnal"),
    churn_budget: float = 1.25,
) -> dict:
    """Pick the winning forecaster and spell out the acceptance deltas.

    The winner is the forecaster with the lowest summed ``max_depth`` over
    ``target_scenarios`` among those whose churn stays within
    ``churn_budget`` × reactive on every target scenario; ties break to
    the lower total churn.
    """
    candidates: dict[str, dict] = {}
    names = [k for k in next(iter(report.values())) if k != "reactive"]
    for name in names:
        depth_total = 0.0
        churn_ok = True
        churn_total = 0
        deltas = {}
        for scen in target_scenarios:
            reactive = report[scen]["reactive"]
            predictive = report[scen][name]
            depth_total += predictive["max_depth"]
            churn_total += predictive["replica_changes"]
            # a churn-free reactive baseline leaves any churn over budget
            allowed = churn_budget * max(reactive["replica_changes"], 1)
            if predictive["replica_changes"] > allowed:
                churn_ok = False
            deltas[scen] = {
                "max_depth_reduction": round(
                    reactive["max_depth"] - predictive["max_depth"], 1
                ),
                "churn_delta": (
                    predictive["replica_changes"] - reactive["replica_changes"]
                ),
            }
        candidates[name] = {
            "depth_total": depth_total,
            "churn_total": churn_total,
            "within_churn_budget": churn_ok,
            "deltas": deltas,
        }
    eligible = {n: c for n, c in candidates.items() if c["within_churn_budget"]}
    pool = eligible or candidates
    winner = min(pool, key=lambda n: (pool[n]["depth_total"], pool[n]["churn_total"]))
    return {
        "winner": winner,
        "target_scenarios": list(target_scenarios),
        "churn_budget": churn_budget,
        "candidates": candidates,
    }
