"""Head-to-head scenario battery: reactive vs. each forecaster.

Simulator-driven policy evaluation (the KIS-S harness shape,
arxiv 2507.07932): every candidate policy runs the *same* deterministic
world — identical arrival process, service rates, bounds, cadence — and
is scored on the three numbers a queue-serving fleet cares about:

- ``max_depth``      — worst backlog (latency proxy; BLITZSCALE's point
  that scale-up lateness is the dominant serving cost, arxiv 2412.17246);
- ``time_over_slo``  — seconds the observed depth sat above the
  scenario's SLO depth;
- ``replica_changes``— churn (each change is a pod start/stop: image
  pulls, TPU grab/release, cache warm-up).

Used by ``bench.py --suite forecast`` (the ``BENCH_r06`` artifact) and the
acceptance tests; later policies (RL, multi-queue) plug into the same
battery.

The CHAOS battery (:func:`chaos_battery` / :func:`evaluate_chaos`,
``bench.py --suite chaos``) reuses the same machinery with a fourth
input dimension: a deterministic :class:`~.faults.FailureProcess` per
scenario, scoring the resilience layer (``core/resilience.py``) against
the reference's log-and-skip failure handling on identical worlds under
identical faults.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..core.loop import LoopConfig
from ..core.policy import PolicyConfig
from ..core.resilience import ResilienceConfig
from .faults import Blackout, FailureProcess, FlakyCalls, LatencySpikes
from .scenarios import (
    ArrivalProcess,
    BurstArrival,
    DiurnalArrival,
    RampArrival,
    StepArrival,
)
from .simulator import SimConfig, Simulation


@dataclass(frozen=True)
class Scenario:
    """One world the battery replays under every candidate policy."""

    name: str
    arrival: ArrivalProcess
    duration: float = 900.0
    service_rate_per_replica: float = 10.0
    min_pods: int = 1
    max_pods: int = 30
    initial_replicas: int = 1
    slo_depth: float = 300.0
    # Forecast horizon (s) predictive policies use on this scenario — a
    # deployment knob matched to the traffic's timescale: ~1 cooldown past
    # the poll period for fast transients, longer for slow cycles (a long
    # horizon on a fast ramp extrapolates the trend past its end and
    # overshoots; a short one on a slow cycle sees the peak too late).
    horizon: float = 60.0
    loop: LoopConfig = field(
        default_factory=lambda: LoopConfig(
            poll_interval=5.0,
            policy=PolicyConfig(
                scale_up_messages=100,
                scale_down_messages=10,
                scale_up_cooldown=10.0,
                scale_down_cooldown=30.0,
            ),
        )
    )
    # Chaos dimension: deterministic fault process injected around the
    # metric source and scaler (None = healthy world, the forecast
    # battery's scenarios).
    faults: FailureProcess | None = None


def default_battery() -> tuple[Scenario, ...]:
    """Step, ramp, diurnal, burst — the four arrival shapes from ISSUE/KIS-S.

    Magnitudes are sized so the default thresholds are genuinely exercised:
    steady-state demand crosses several replicas' capacity and the backlog
    moves through both gates' thresholds within each episode.
    """
    return (
        Scenario(
            name="step",
            # launch day: 20 msg/s overnight, 120 msg/s from t=120 on
            arrival=StepArrival(before=20.0, after=120.0, at=120.0),
        ),
        Scenario(
            name="ramp",
            # organic growth: 10 -> 150 msg/s over 10 minutes, then flat
            arrival=RampArrival(
                start_rate=10.0, end_rate=150.0, t_start=60.0, t_end=660.0
            ),
            horizon=30.0,
        ),
        Scenario(
            name="diurnal",
            # user traffic: 80 +/- 60 msg/s, two full cycles per episode.
            # The fleet starts at steady state for the base load (8 pods at
            # 10 msg/s each): a cold 1-pod start makes every policy's max
            # depth the same cold-start backlog (actuation-rate-limited,
            # one pod per cooldown), hiding the cyclic behavior the
            # scenario exists to score.
            arrival=DiurnalArrival(base=80.0, amplitude=60.0, period=450.0),
            initial_replicas=8,
        ),
        Scenario(
            name="burst",
            # retry storms: 250 msg/s for 45 s every 5 minutes over 25 base
            arrival=BurstArrival(
                base=25.0, burst_rate=250.0, period=300.0,
                burst_len=45.0, first_burst=120.0,
            ),
        ),
    )


def run_episode(
    scenario: Scenario,
    policy: str = "reactive",
    forecaster: str = "holt",
    horizon: float | None = None,
) -> dict:
    """One policy through one scenario; returns the scorecard row.

    ``horizon=None`` uses the scenario's own tuned horizon.
    """
    horizon = scenario.horizon if horizon is None else horizon
    sim = Simulation(
        SimConfig(
            arrival_rate=scenario.arrival,
            service_rate_per_replica=scenario.service_rate_per_replica,
            duration=scenario.duration,
            initial_replicas=scenario.initial_replicas,
            min_pods=scenario.min_pods,
            max_pods=scenario.max_pods,
            loop=scenario.loop,
            policy=policy,
            forecaster=forecaster,
            forecast_horizon=horizon,
        )
    )
    result = sim.run()
    return score_result(result, scenario.slo_depth)


def score_result(result, slo_depth: float) -> dict:
    """One :class:`~.simulator.SimResult` as the battery's scorecard row.

    Shared by the live scenario battery and the journal counterfactual
    re-scoring (:mod:`.replay`), so recorded episodes and synthetic
    scenarios are judged on identical numbers.
    """
    return {
        "max_depth": round(result.max_depth, 1),
        "time_over_slo_s": round(result.time_over(slo_depth), 1),
        "replica_changes": result.replica_changes,
        "final_replicas": result.final_replicas,
        "final_depth": round(result.final_depth, 1),
        "ticks": result.ticks,
    }


def evaluate_battery(
    scenarios: tuple[Scenario, ...] | None = None,
    forecasters: tuple[str, ...] = ("ewma", "holt", "lstsq"),
    horizon: float | None = None,
) -> dict:
    """Every scenario × (reactive + each forecaster) → nested scorecard."""
    scenarios = scenarios if scenarios is not None else default_battery()
    report: dict = {}
    for scenario in scenarios:
        row: dict = {"reactive": run_episode(scenario, policy="reactive")}
        for name in forecasters:
            row[f"predictive:{name}"] = run_episode(
                scenario, policy="predictive", forecaster=name, horizon=horizon
            )
        report[scenario.name] = row
    return report


def summarize(
    report: dict,
    target_scenarios: tuple[str, ...] = ("ramp", "diurnal"),
    churn_budget: float = 1.25,
) -> dict:
    """Pick the winning forecaster and spell out the acceptance deltas.

    The winner is the forecaster with the lowest summed ``max_depth`` over
    ``target_scenarios`` among those whose churn stays within
    ``churn_budget`` × reactive on every target scenario; ties break to
    the lower total churn.
    """
    candidates: dict[str, dict] = {}
    names = [k for k in next(iter(report.values())) if k != "reactive"]
    for name in names:
        depth_total = 0.0
        churn_ok = True
        churn_total = 0
        deltas = {}
        for scen in target_scenarios:
            reactive = report[scen]["reactive"]
            predictive = report[scen][name]
            depth_total += predictive["max_depth"]
            churn_total += predictive["replica_changes"]
            # a churn-free reactive baseline leaves any churn over budget
            allowed = churn_budget * max(reactive["replica_changes"], 1)
            if predictive["replica_changes"] > allowed:
                churn_ok = False
            deltas[scen] = {
                "max_depth_reduction": round(
                    reactive["max_depth"] - predictive["max_depth"], 1
                ),
                "churn_delta": (
                    predictive["replica_changes"] - reactive["replica_changes"]
                ),
            }
        candidates[name] = {
            "depth_total": depth_total,
            "churn_total": churn_total,
            "within_churn_budget": churn_ok,
            "deltas": deltas,
        }
    eligible = {n: c for n, c in candidates.items() if c["within_churn_budget"]}
    pool = eligible or candidates
    winner = min(pool, key=lambda n: (pool[n]["depth_total"], pool[n]["churn_total"]))
    return {
        "winner": winner,
        "target_scenarios": list(target_scenarios),
        "churn_budget": churn_budget,
        "candidates": candidates,
    }


# ---------------------------------------------------------------------------
# Chaos battery: the resilience layer vs. reference failure handling.
# ---------------------------------------------------------------------------


def default_resilience() -> ResilienceConfig:
    """The battery's resilient configuration.

    Retries absorb per-call flakiness, the stale hold bridges metric
    blackouts (TTL sized to the battery's longest outage), the breaker
    stops paying a dead API server's latency after 3 straight failures.
    Timeouts stay off: the post-hoc deadline would convert the latency
    scenario's *slow successes* into failures — strictly worse than
    using the data (the deadline knob is for real RPC stacks where slow
    usually means doomed, and is covered by unit tests).
    """
    return ResilienceConfig(
        metric_retries=2,
        scaler_retries=1,
        breaker_failures=3,
        breaker_reset=30.0,
        stale_depth_ttl=300.0,
    )


def chaos_battery() -> tuple[Scenario, ...]:
    """Five worlds: one healthy control + four fault shapes.

    Every fault window opens *after* the demand shift has pushed the
    observed depth through the scale-up threshold, so the stale hold has
    a meaningful observation to bridge with — the incident shape that
    matters (an outage during quiet hours strands nothing).
    """
    return (
        Scenario(
            name="calm",
            # the no-fault control: any resilient-vs-reference difference
            # here is a regression by definition
            arrival=StepArrival(before=20.0, after=120.0, at=120.0),
        ),
        Scenario(
            name="metric-blackout",
            # monitoring dies for 5 minutes in the middle of a launch
            # ramp: reference freezes scaling; the stale hold keeps
            # climbing toward the last observed backlog
            arrival=StepArrival(before=20.0, after=120.0, at=120.0),
            faults=Blackout(start=150.0, duration=300.0, metric=True),
        ),
        Scenario(
            name="flaky-metric",
            # 35% of polls fail all episode long during organic growth:
            # reference loses a third of its decisions, retries recover
            # nearly all of them
            arrival=RampArrival(
                start_rate=10.0, end_rate=150.0, t_start=60.0, t_end=660.0
            ),
            faults=FlakyCalls(failure_rate=0.35, seed=7, metric=True),
        ),
        Scenario(
            name="actuation-outage",
            # the apiserver is down AND slow (3 s per failing call) while
            # demand steps up: reference pays the latency on every fire
            # attempt; the breaker stops paying after 3
            arrival=StepArrival(before=20.0, after=120.0, at=120.0),
            faults=Blackout(
                start=150.0, duration=250.0, metric=False, scale=True,
                latency=3.0,
            ),
        ),
        Scenario(
            name="latency-spikes",
            # a slow-but-healthy dependency: polls succeed after 2.5 s
            # inside periodic windows — both configurations should ride
            # it out identically (no timeouts in default_resilience)
            arrival=StepArrival(before=20.0, after=120.0, at=120.0),
            faults=LatencySpikes(
                period=120.0, spike_len=30.0, delay=2.5, metric=True
            ),
        ),
    )


class _ChaosCounters:
    """TickObserver tallying the resilience layer's per-tick evidence."""

    def __init__(self) -> None:
        self.metric_failures = 0  # fail-static ticks (no depth at all)
        self.stale_ticks = 0  # degraded-mode depth holds
        self.retries = 0  # extra attempts, metric + scaler
        self.breaker_open_ticks = 0  # ticks ending with the breaker open

    def on_tick(self, record) -> None:
        if record.metric_error is not None:
            self.metric_failures += 1
        if record.stale:
            self.stale_ticks += 1
        self.retries += (record.metric_retries or 0) + (
            record.scaler_retries or 0
        )
        if record.breaker_state == "open":
            self.breaker_open_ticks += 1

    def as_dict(self) -> dict:
        return {
            "fail_static_ticks": self.metric_failures,
            "stale_ticks": self.stale_ticks,
            "retries": self.retries,
            "breaker_open_ticks": self.breaker_open_ticks,
        }


def run_chaos_episode(
    scenario: Scenario,
    resilience: ResilienceConfig | None = None,
) -> dict:
    """One (world × faults × failure-handling) episode → scorecard row.

    ``resilience=None`` is the reference configuration (log-and-skip);
    the row carries the battery scores plus the chaos counters so the
    artifact shows *why* a configuration scored as it did.
    """
    counters = _ChaosCounters()
    sim = Simulation(
        SimConfig(
            arrival_rate=scenario.arrival,
            service_rate_per_replica=scenario.service_rate_per_replica,
            duration=scenario.duration,
            initial_replicas=scenario.initial_replicas,
            min_pods=scenario.min_pods,
            max_pods=scenario.max_pods,
            loop=scenario.loop,
            faults=scenario.faults,
            resilience=resilience,
        ),
        extra_observers=(counters,),
    )
    result = sim.run()
    row = score_result(result, scenario.slo_depth)
    row.update(counters.as_dict())
    # fault provenance rides the row so summarize_chaos can tell control
    # scenarios from outage scenarios without trusting names
    row["faulted"] = scenario.faults is not None
    return row


def evaluate_chaos(
    scenarios: tuple[Scenario, ...] | None = None,
    resilience: ResilienceConfig | None = None,
) -> dict:
    """Every chaos scenario × {reference, resilient} → nested scorecard."""
    scenarios = scenarios if scenarios is not None else chaos_battery()
    resilience = resilience if resilience is not None else default_resilience()
    report: dict = {}
    for scenario in scenarios:
        report[scenario.name] = {
            "reference": run_chaos_episode(scenario, resilience=None),
            "resilient": run_chaos_episode(scenario, resilience=resilience),
        }
    return report


def summarize_chaos(
    report: dict,
    no_fault_scenarios: tuple[str, ...] | None = None,
) -> dict:
    """Deltas + the two acceptance verdicts.

    ``resilient_wins`` lists fault scenarios where the resilient
    configuration strictly improved max depth or time-over-SLO;
    ``no_fault_regressions`` lists control scenarios where it changed
    *anything* (on a healthy world the resilience layer must be
    invisible: identical decisions, identical scores).  Control
    scenarios are identified by the rows' recorded fault provenance
    (``faulted``, set by :func:`run_chaos_episode`), not by name, so a
    custom battery's healthy scenarios can never be mis-scored as
    resilience wins; ``no_fault_scenarios`` overrides the derivation.
    """
    if no_fault_scenarios is None:
        no_fault_scenarios = tuple(
            name for name, row in report.items()
            if not row["reference"].get("faulted", True)
        )
    deltas: dict = {}
    wins: list[str] = []
    regressions: list[str] = []
    for name, row in report.items():
        ref, res = row["reference"], row["resilient"]
        delta = {
            "max_depth_reduction": round(
                ref["max_depth"] - res["max_depth"], 1
            ),
            "time_over_slo_reduction_s": round(
                ref["time_over_slo_s"] - res["time_over_slo_s"], 1
            ),
            "churn_delta": res["replica_changes"] - ref["replica_changes"],
        }
        deltas[name] = delta
        if name in no_fault_scenarios:
            if any(ref[k] != res[k] for k in ("max_depth", "time_over_slo_s",
                                              "replica_changes")):
                regressions.append(name)
        elif (
            delta["max_depth_reduction"] > 0
            or delta["time_over_slo_reduction_s"] > 0
        ):
            wins.append(name)
    return {
        "resilient_wins": wins,
        "no_fault_regressions": regressions,
        "no_fault_scenarios": list(no_fault_scenarios),
        "deltas": deltas,
    }
