"""Compiled closed-loop simulator: whole episodes as one XLA program.

The Python simulator (:mod:`.simulator`) drives the *real*
``ControlLoop`` one tick at a time — the right tool for fidelity, the
wrong one for search: evaluating a single (scenario × policy × parameter)
point costs a full Python-rate episode, so the scenario battery tops out
at a handful of configurations.  KIS-S (arxiv 2507.07932) needs thousands
of simulated episodes for policy search to be useful; BLITZSCALE
(arxiv 2412.17246) argues scaling decisions should be tuned against the
workload's actual arrival process.  Both need a simulator that is orders
of magnitude faster than wall-clock re-execution.

This module re-expresses the closed loop as a functionally pure
``jax.lax.scan`` over ticks — fluid queue world + threshold/cooldown
gates + the EWMA/Holt/lstsq forecasters — so an entire episode is a
single XLA executable, then ``jax.vmap``\\ s it over a batch of encoded
configurations so hundreds of (scenario × policy × parameter) points
evaluate in one device call (:func:`run_compiled`; the sweep driver in
:mod:`.sweep` sits on top).

**Fidelity is mechanically checked, not assumed.**  The scan is written
to reproduce the reference semantics *bit-for-bit* where they are exact:

- world arithmetic runs in float64 via ``jax.experimental.enable_x64``,
  expression-for-expression identical to :meth:`.simulator.Simulation.
  advance_world` (including the seed's separate constant-rate formula);
  tick times and arrival integrals are precomputed host-side by the
  *actual* Python ``FakeClock`` accumulation and ``arrivals_between``
  implementations (:func:`_tick_times_and_arrivals`), so they are exact
  by construction and any :class:`~.scenarios.ArrivalProcess` — including
  a journal-inferred :class:`~.replay.RecordedArrival` — can sweep;
- gate decisions go through :func:`~..core.policy.gate_code` — the same
  branchless function the live ``gate_up``/``gate_down`` call — with the
  reference's inclusive thresholds, strictly-After cooldowns, up-cooling
  ``continue`` (down gate ``SKIPPED``), and boundary-no-op-refreshes-
  cooldown semantics;
- forecaster math runs in float32 on the same pure step functions the
  jitted live forecasters wrap (:func:`~..forecast.forecasters.
  ewma_level` / ``holt_forecast`` / ``lstsq_forecast``), fed a history
  snapshot maintained with ``DepthHistory.with_sample``'s exact
  append/pad/roll semantics.

:func:`verify_fidelity` runs the compiled episodes against real-loop
Python episodes on the full scenario battery and asserts the replica
trajectory and gate decisions agree tick-for-tick, reporting any mismatch
through the same :class:`~.replay.Divergence` machinery the flight
recorder uses — the compiled path can never silently drift from the
reference semantics.  ``bench.py --suite sweep`` runs this gate before
trusting any sweep number.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import lru_cache, partial
from typing import Any, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import enable_x64

from ..core.events import TickRecord
from ..core.policy import GATE_BY_CODE, GATE_COOLING, GATE_FIRE, GATE_SKIPPED, gate_code
from ..forecast.forecasters import (
    EwmaForecaster,
    HoltForecaster,
    LeastSquaresForecaster,
    ewma_level,
    holt_forecast,
    lstsq_forecast,
)
from ..learn.network import FEATURE_ALPHA, FEATURE_WINDOW, hold_depth, learned_decision
from .replay import Divergence
from .simulator import SimConfig, SimResult, Simulation

#: forecaster name -> policy kind inside the scan (0 = reactive)
FORECASTER_KINDS = {"ewma": 1, "holt": 2, "lstsq": 3}

#: the learned policy's kind code (``learn/``): the scan calls the same
#: :func:`~..learn.network.learned_decision` the live ``LearnedPolicy``
#: jits, so fidelity is checkable for trained networks too.
LEARNED_KIND = 4


def _tick_times_and_arrivals(
    config: SimConfig, ticks: int
) -> tuple[np.ndarray, np.ndarray]:
    """Per-tick observation times and exact arrival integrals, host-side.

    The arrival process is state-free — ``∫ rate dt`` over each poll
    interval depends only on the tick times, which are known before the
    episode runs — so the integrals are evaluated here by the *actual*
    Python ``arrivals_between`` implementations and fed to the scan as
    inputs.  That makes the compiled world's arrivals bit-identical to
    the Python world's by construction (XLA re-derivations of the
    closed forms differ in the last ulp — its backend contracts
    mul+add chains into FMAs — and one ulp is enough to flip the
    ``int(depth)`` floor on ticks where the backlog lands exactly on an
    integer), and it means any :class:`~.scenarios.ArrivalProcess` —
    including :class:`~.replay.RecordedArrival` from a flight journal —
    sweeps without a compiled-side re-implementation.

    Times accumulate ``t += poll`` exactly like ``FakeClock.sleep``, so
    cooldown arithmetic inside the scan sees the same instants the real
    loop's clock produced.

    Cached per ``(arrival, poll, ticks)``: the arrays are identical for
    every config sharing a scenario, and a sweep encodes hundreds of
    configs over a handful of scenarios — without the cache the grid
    pays ``points × ticks`` redundant ``arrivals_between`` calls per
    ``run_sweep``.  Arrival processes are frozen dataclasses (hashable);
    an unhashable custom process just skips the cache.
    """
    arrival = config.arrival_rate
    poll = config.loop.poll_interval
    try:
        return _cached_times_and_arrivals(arrival, poll, ticks)
    except TypeError:
        return _compute_times_and_arrivals(arrival, poll, ticks)


def _compute_times_and_arrivals(
    arrival: Any, poll: float, ticks: int
) -> tuple[np.ndarray, np.ndarray]:
    times = np.zeros(ticks, dtype=np.float64)
    arrived = np.zeros(ticks, dtype=np.float64)
    t = 0.0
    for k in range(ticks):
        t_prev, t = t, t + poll
        times[k] = t
        if not isinstance(arrival, (int, float)):
            arrived[k] = arrival.arrivals_between(t_prev, t)
    return times, arrived


_cached_times_and_arrivals = lru_cache(maxsize=128)(
    _compute_times_and_arrivals
)


def encode_config(config: SimConfig, slo_depth: float = 0.0) -> dict[str, Any]:
    """One :class:`~.simulator.SimConfig` as the scan's parameter row.

    Everything dynamic (thresholds, cooldowns, rates, forecast knobs) is a
    numpy scalar so rows stack into a vmap batch; the per-tick times and
    arrival integrals ride along as ``(ticks,)`` arrays
    (:func:`_tick_times_and_arrivals`); the static shape knobs — tick
    count, history capacity, and the learned network's hidden width —
    stay on the Python side (:func:`episode_ticks`,
    ``config.forecast_history``, ``config.learned_checkpoint.hidden``).

    ``seed_const`` marks the seed's plain-float ``arrival_rate`` config
    style, which uses a *different* depth-update expression than
    ``ConstantArrival`` (net-rate form vs arrived-minus-drained) —
    numerically equal but not bit-identical, and fidelity is bit-level.

    ``slo_depth`` feeds the scan's *in-episode* time-over-SLO
    accumulator, which only the summary-consuming paths (ES training,
    :mod:`..learn.rollout`) read; trajectory consumers keep scoring on
    the host via ``score_result``, so the default 0.0 is inert.
    """
    times, arrived = _tick_times_and_arrivals(config, episode_ticks(config))
    policy = config.loop.policy
    seed_const = isinstance(config.arrival_rate, (int, float))
    row: dict[str, Any] = {
        "times": times,
        "arrived": arrived,
        "seed_const": np.bool_(seed_const),
        "seed_rate": np.float64(
            config.arrival_rate if seed_const else 0.0
        ),
        "service_rate": np.float64(config.service_rate_per_replica),
        "initial_depth": np.float64(config.initial_depth),
        "initial_replicas": np.int32(config.initial_replicas),
        "min_pods": np.int32(config.min_pods),
        "max_pods": np.int32(config.max_pods),
        "scale_up_pods": np.int32(config.scale_up_pods),
        "scale_down_pods": np.int32(config.scale_down_pods),
        "scale_up_messages": np.int32(policy.scale_up_messages),
        "scale_down_messages": np.int32(policy.scale_down_messages),
        "scale_up_cooldown": np.float64(policy.scale_up_cooldown),
        "scale_down_cooldown": np.float64(policy.scale_down_cooldown),
        "policy_kind": np.int32(0),
        # forecast params (ignored by reactive rows but always present so
        # every row has the same pytree structure); f32 to match the live
        # forecasters' jit dtype exactly
        "horizon": np.float32(config.forecast_horizon),
        "alpha": np.float32(0.0),
        "beta": np.float32(0.0),
        "window": np.int32(1),
        "min_samples": np.int32(max(2, int(config.forecast_min_samples))),
        "conservative": np.bool_(config.forecast_conservative),
        # learned-policy row params (inert placeholders on other rows so
        # every row keeps the same pytree structure; run_episodes pads
        # theta to the batch's common length)
        "theta": np.zeros(1, np.float32),
        "hold": np.int32(
            hold_depth(policy.scale_up_messages, policy.scale_down_messages)
        ),
        "poll32": np.float32(config.loop.poll_interval),
        "slo_depth": np.float64(slo_depth),
    }
    if config.policy == "learned":
        checkpoint = config.learned_checkpoint
        if checkpoint is None:
            raise ValueError(
                "policy='learned' requires SimConfig.learned_checkpoint"
            )
        from ..learn.checkpoint import (
            TWIN_FLUID,
            require_no_knob_head,
            require_twin,
        )

        # every fluid-compiled consumer (sweep, rollout, counterfactual
        # replay) encodes through here: a serving-twin checkpoint's
        # weights mean shard counts, not replica gates — reject at
        # encode time, the compiled analogue of LearnedPolicy's check
        require_twin(checkpoint, TWIN_FLUID, "the fluid compiled twin")
        # ...and a knob-headed theta has a wider output layer the
        # scan's fixed slicing would silently mis-read
        require_no_knob_head(checkpoint, "the fluid compiled twin")
        row["policy_kind"] = np.int32(LEARNED_KIND)
        row["theta"] = np.asarray(checkpoint.theta, np.float32)
        # the history features are part of the checkpoint schema — pinned
        # constants in learn.network, NOT the live forecaster defaults
        row["alpha"] = np.float32(FEATURE_ALPHA)
        row["window"] = np.int32(FEATURE_WINDOW)
    elif config.policy == "predictive":
        name = config.forecaster
        if name not in FORECASTER_KINDS:
            raise ValueError(
                f"unknown forecaster {name!r};"
                f" choose from {tuple(FORECASTER_KINDS)}"
            )
        row["policy_kind"] = np.int32(FORECASTER_KINDS[name])
        # parameter defaults come from the live forecaster dataclasses, so
        # the compiled path can't drift if a default is retuned
        if name == "ewma":
            row["alpha"] = np.float32(EwmaForecaster().alpha)
        elif name == "holt":
            holt = HoltForecaster()
            row["alpha"] = np.float32(holt.alpha)
            row["beta"] = np.float32(holt.beta)
        else:
            row["window"] = np.int32(LeastSquaresForecaster().window)
    elif config.policy != "reactive":
        raise ValueError(
            f"policy must be 'reactive' or 'predictive', got"
            f" {config.policy!r}"
        )
    return row


def episode_ticks(config: SimConfig) -> int:
    """Tick count of one episode — ``Simulation.run``'s exact formula."""
    return max(1, int(config.duration / config.loop.poll_interval))


def _episode(
    p: dict[str, Any],
    ticks: int,
    capacity: int,
    predictive: bool,
    hidden: int = 0,
    trajectory: bool = True,
):
    """One closed-loop episode as a single ``lax.scan`` over ticks.

    Carry = (clock, depth, replicas, cooldown stamps, forecast history,
    running max depth, episode-score accumulators) — the entire state the
    Python stack spreads across ``FakeClock``/``Simulation``/
    ``PolicyState``/``DepthHistory`` plus the summary arithmetic
    ``score_result`` runs on the host.

    ``hidden > 0`` compiles the learned-policy branch (``learn/``): rows
    with ``policy_kind == LEARNED_KIND`` threshold the gates on
    :func:`~..learn.network.learned_decision` over ``p["theta"]`` — the
    same pure function the live ``LearnedPolicy`` jits.  ``trajectory``
    selects per-tick outputs; ``False`` returns summaries only, so a
    training population of thousands of episodes transfers a handful of
    scalars per episode instead of ``O(ticks)`` arrays
    (:mod:`..learn.rollout`).
    """
    idx = jnp.arange(capacity)
    learned = hidden > 0

    def tick(carry, xs):
        t_new, arrived = xs
        (
            t, depth, replicas, last_up, last_down, h_t, h_d, h_n,
            max_depth, prev_obs, over_slo, prev_reps, changes, replica_s,
        ) = carry
        # -- sleep first, then poll (main.go:41): the tick's clock reads
        # all happen at t_new (FakeClock does not advance inside a tick;
        # t_new comes precomputed from the host with FakeClock's exact
        # accumulation)
        dt = t_new - t
        reps_f = replicas.astype(jnp.float64)
        # -- world integration, both config styles (simulator.advance_world);
        # arrivals are host-precomputed exact integrals (see
        # _tick_times_and_arrivals)
        net_rate = p["seed_rate"] - reps_f * p["service_rate"]
        seed_depth = jnp.maximum(0.0, depth + net_rate * dt)
        drained = reps_f * p["service_rate"] * dt
        gen_depth = jnp.maximum(0.0, depth + arrived - drained)
        depth_new = jnp.where(p["seed_const"], seed_depth, gen_depth)
        max_depth = jnp.maximum(max_depth, depth_new)
        observed = jnp.floor(depth_new).astype(jnp.int32)

        # -- episode-score accumulators, the host scorer's exact forms:
        # time_over is a left rule over the observation timeline (the
        # interval ending now is credited to the PREVIOUS observation;
        # prev_obs starts at -1 so the pre-first-observation interval
        # never counts), replica_changes counts ticks whose ENTERING
        # count changed vs the previous tick, replica-seconds integrates
        # the fluid world's piecewise-constant replica count.
        over_slo = over_slo + dt * (prev_obs > p["slo_depth"])
        changes = changes + (replicas != prev_reps).astype(jnp.int32)
        replica_s = replica_s + reps_f * dt

        decision = observed
        if predictive or learned:
            # -- history snapshot including the current observation:
            # DepthHistory.with_sample's exact semantics (append when not
            # full, padding the tail with the newest sample; shift-in when
            # full).  f64 here; cast to f32 only at the forecaster
            # boundary, exactly where the live path's jnp.asarray casts.
            obs_f = observed.astype(jnp.float64)
            full = h_n >= capacity
            snap_t = jnp.where(
                full,
                jnp.roll(h_t, -1).at[-1].set(t_new),
                jnp.where(idx < h_n, h_t, t_new),
            )
            snap_d = jnp.where(
                full,
                jnp.roll(h_d, -1).at[-1].set(obs_f),
                jnp.where(idx < h_n, h_d, obs_f),
            )
            n = jnp.minimum(h_n + 1, capacity)
            # newest sample is always the last slot (padding == newest),
            # so centering on [-1] is _center_times centering on n-1
            times32 = (snap_t - snap_t[-1]).astype(jnp.float32)
            depths32 = snap_d.astype(jnp.float32)
            if predictive:
                pred_ewma = jnp.maximum(
                    0.0, ewma_level(depths32, n, p["alpha"])
                )
                pred_holt = holt_forecast(
                    times32, depths32, n, p["horizon"], p["alpha"], p["beta"]
                )
                pred_lstsq = lstsq_forecast(
                    times32, depths32, n, p["horizon"], p["window"]
                )
                predicted = jnp.where(
                    p["policy_kind"] == 1,
                    pred_ewma,
                    jnp.where(p["policy_kind"] == 2, pred_holt, pred_lstsq),
                )
                # PredictivePolicy: max(0, int(round(.))), conservative
                # gates see max(observed, forecast), reactive warm-up
                # below min_samples
                prediction = jnp.maximum(
                    0, jnp.round(predicted).astype(jnp.int32)
                )
                effective = jnp.where(
                    p["conservative"],
                    jnp.maximum(observed, prediction),
                    prediction,
                )
                warmed = n >= p["min_samples"]
                forecaster_row = (
                    (p["policy_kind"] >= 1) & (p["policy_kind"] <= 3)
                )
                decision = jnp.where(
                    forecaster_row & warmed, effective, observed
                )
            if learned:
                # Remaining-cooldown fractions: the f64 twin of the live
                # mirror's host-side cooldown_fraction (plain adds and one
                # divide — IEEE-exact in both), cast f32 exactly where the
                # live path's np.float32(frac) casts.
                rem_up = (last_up + p["scale_up_cooldown"]) - t_new
                rem_down = (last_down + p["scale_down_cooldown"]) - t_new
                frac_up32 = jnp.where(
                    (p["scale_up_cooldown"] > 0) & (rem_up > 0),
                    rem_up / jnp.where(
                        p["scale_up_cooldown"] > 0, p["scale_up_cooldown"], 1.0
                    ),
                    0.0,
                ).astype(jnp.float32)
                frac_down32 = jnp.where(
                    (p["scale_down_cooldown"] > 0) & (rem_down > 0),
                    rem_down / jnp.where(
                        p["scale_down_cooldown"] > 0,
                        p["scale_down_cooldown"],
                        1.0,
                    ),
                    0.0,
                ).astype(jnp.float32)
                learned_dec = learned_decision(
                    p["theta"],
                    times32,
                    depths32,
                    n,
                    observed,
                    replicas,
                    frac_up32,
                    frac_down32,
                    p["scale_up_messages"],
                    p["scale_down_messages"],
                    p["hold"],
                    p["min_samples"],
                    p["max_pods"],
                    p["poll32"],
                    p["alpha"],
                    p["window"],
                    hidden=hidden,
                )
                decision = jnp.where(
                    p["policy_kind"] == LEARNED_KIND, learned_dec, decision
                )
            h_t, h_d, h_n = snap_t, snap_d, n

        # -- gates: same gate_code as the live gate_up/gate_down; the
        # up-cooling `continue` marks the down gate SKIPPED (main.go:54);
        # FIRE refreshes the matching cooldown stamp (boundary no-ops
        # included — PodAutoScaler returns success on clamp)
        up_code = gate_code(
            decision >= p["scale_up_messages"],
            t_new,
            last_up,
            p["scale_up_cooldown"],
        )
        up_fire = up_code == GATE_FIRE
        reps1 = jnp.where(
            up_fire & (replicas < p["max_pods"]),
            jnp.minimum(replicas + p["scale_up_pods"], p["max_pods"]),
            replicas,
        )
        last_up = jnp.where(up_fire, t_new, last_up)
        down_code = jnp.where(
            up_code == GATE_COOLING,
            GATE_SKIPPED,
            gate_code(
                decision <= p["scale_down_messages"],
                t_new,
                last_down,
                p["scale_down_cooldown"],
            ),
        )
        down_fire = down_code == GATE_FIRE
        reps2 = jnp.where(
            down_fire & (reps1 > p["min_pods"]),
            jnp.maximum(reps1 - p["scale_down_pods"], p["min_pods"]),
            reps1,
        )
        last_down = jnp.where(down_fire, t_new, last_down)

        out = (
            (t_new, observed, decision, up_code, down_code, replicas, reps2)
            if trajectory
            else ()
        )
        carry = (
            t_new, depth_new, reps2, last_up, last_down, h_t, h_d, h_n,
            max_depth, observed, over_slo, replicas, changes, replica_s,
        )
        return carry, out

    init = (
        jnp.asarray(0.0, jnp.float64),  # FakeClock() starts at 0
        jnp.asarray(p["initial_depth"], jnp.float64),
        jnp.asarray(p["initial_replicas"], jnp.int32),
        jnp.asarray(0.0, jnp.float64),  # initial_state(now=0): startup grace
        jnp.asarray(0.0, jnp.float64),
        jnp.zeros(capacity, jnp.float64),
        jnp.zeros(capacity, jnp.float64),
        jnp.asarray(0, jnp.int32),
        jnp.asarray(p["initial_depth"], jnp.float64),  # max_depth seed
        jnp.asarray(-1, jnp.int32),  # prev_obs: nothing observed yet
        jnp.asarray(0.0, jnp.float64),  # time-over-SLO accumulator
        jnp.asarray(p["initial_replicas"], jnp.int32),  # prev entering reps
        jnp.asarray(0, jnp.int32),  # replica_changes
        jnp.asarray(0.0, jnp.float64),  # replica-seconds integral
    )
    carry, outs = lax.scan(
        tick, init, (p["times"], p["arrived"]), length=ticks
    )
    summary = {
        "final_depth": carry[1],
        "final_replicas": carry[2],
        "max_depth": carry[8],
        "time_over_slo": carry[10],
        "replica_changes": carry[12],
        "replica_seconds": carry[13],
    }
    if not trajectory:
        return summary
    t, observed, decision, up, down, reps_before, reps_after = outs
    return {
        "t": t,
        "observed": observed,
        "decision": decision,
        "up": up,
        "down": down,
        "replicas_before": reps_before,
        "replicas_after": reps_after,
        **summary,
    }


@partial(
    jax.jit, static_argnames=("ticks", "capacity", "predictive", "hidden")
)
def _run_batch(
    params, ticks: int, capacity: int, predictive: bool, hidden: int = 0
):
    return jax.vmap(
        lambda row: _episode(row, ticks, capacity, predictive, hidden)
    )(params)


@dataclass
class CompiledEpisode:
    """One compiled episode: the battery-facing result + the per-tick
    decision trail the fidelity gate checks."""

    result: SimResult
    times: np.ndarray
    observed: np.ndarray
    decision: np.ndarray
    up_codes: np.ndarray
    down_codes: np.ndarray
    replicas_before: np.ndarray
    replicas_after: np.ndarray

    def gates(self, index: int) -> tuple[Any, Any]:
        """(up, down) as :class:`~..core.policy.Gate` for tick ``index``."""
        return (
            GATE_BY_CODE[int(self.up_codes[index])],
            GATE_BY_CODE[int(self.down_codes[index])],
        )


def run_episodes(configs: Sequence[SimConfig]) -> list[CompiledEpisode]:
    """Run a batch of configs through the compiled simulator.

    One device call for the whole batch.  All configs must share a tick
    count (``duration / poll_interval``) and a ``forecast_history``
    capacity — those are compiled shapes; the sweep driver groups by them.
    """
    configs = list(configs)
    if not configs:
        return []
    ticks_set = {episode_ticks(c) for c in configs}
    if len(ticks_set) > 1:
        raise ValueError(
            f"all configs in one compiled batch must share a tick count,"
            f" got {sorted(ticks_set)}; group by duration/poll first"
        )
    cap_set = {int(c.forecast_history) for c in configs}
    if len(cap_set) > 1:
        raise ValueError(
            f"all configs in one compiled batch must share forecast_history,"
            f" got {sorted(cap_set)}; group by capacity first"
        )
    ticks = ticks_set.pop()
    capacity = cap_set.pop()
    predictive = any(c.policy == "predictive" for c in configs)
    hidden_set = {
        int(c.learned_checkpoint.hidden)
        for c in configs
        if c.policy == "learned" and c.learned_checkpoint is not None
    }
    if len(hidden_set) > 1:
        raise ValueError(
            f"all learned configs in one compiled batch must share a hidden"
            f" width (a compiled shape), got {sorted(hidden_set)}; group"
            f" by hidden first"
        )
    hidden = hidden_set.pop() if hidden_set else 0
    if (predictive or hidden) and capacity < 2:
        # DepthHistory enforces this on the live path; match it
        raise ValueError(f"forecast_history must be >= 2, got {capacity}")
    rows = [encode_config(c) for c in configs]
    # theta rows must stack: pad the non-learned placeholders (length 1)
    # to the batch's learned parameter length
    theta_len = max(row["theta"].shape[0] for row in rows)
    for row in rows:
        if row["theta"].shape[0] < theta_len:
            row["theta"] = np.zeros(theta_len, np.float32)
    batch = {key: np.stack([row[key] for row in rows]) for key in rows[0]}
    with enable_x64():
        out = _run_batch(
            {key: jnp.asarray(value) for key, value in batch.items()},
            ticks=ticks,
            capacity=capacity,
            predictive=predictive,
            hidden=hidden,
        )
        out = {key: np.asarray(value) for key, value in out.items()}
    episodes = []
    for i in range(len(configs)):
        timeline = [
            (float(t), int(d), int(r))
            for t, d, r in zip(
                out["t"][i], out["observed"][i], out["replicas_before"][i]
            )
        ]
        result = SimResult(
            timeline=timeline,
            final_replicas=int(out["final_replicas"][i]),
            final_depth=float(out["final_depth"][i]),
            max_depth=float(out["max_depth"][i]),
            ticks=ticks,
        )
        episodes.append(
            CompiledEpisode(
                result=result,
                times=out["t"][i],
                observed=out["observed"][i],
                decision=out["decision"][i],
                up_codes=out["up"][i],
                down_codes=out["down"][i],
                replicas_before=out["replicas_before"][i],
                replicas_after=out["replicas_after"][i],
            )
        )
    return episodes


def run_episodes_grouped(
    configs: Sequence[SimConfig],
) -> list[CompiledEpisode]:
    """:func:`run_episodes` over configs of *mixed* compiled shapes.

    Tick count, history capacity, and the learned network's hidden width
    are compiled shapes, so one device call can only take configs that
    share them; this helper groups by ``(ticks, capacity, hidden)``, runs
    one batch per group, and scatters the episodes back into input order.  Both :func:`verify_fidelity` and
    the sweep driver (:mod:`.sweep`) batch through here.
    """
    configs = list(configs)
    groups: dict[tuple[int, int, int], list[int]] = {}
    for index, config in enumerate(configs):
        hidden = (
            int(config.learned_checkpoint.hidden)
            if config.policy == "learned"
            and config.learned_checkpoint is not None
            else 0
        )
        key = (episode_ticks(config), int(config.forecast_history), hidden)
        groups.setdefault(key, []).append(index)
    episodes: list[CompiledEpisode | None] = [None] * len(configs)
    for indices in groups.values():
        for index, episode in zip(
            indices, run_episodes([configs[i] for i in indices])
        ):
            episodes[index] = episode
    return episodes  # type: ignore[return-value]  # every slot filled


def run_compiled(configs: Sequence[SimConfig]) -> list[SimResult]:
    """Batch of configs -> battery-compatible :class:`SimResult`\\ s."""
    return [episode.result for episode in run_episodes(configs)]


def run_compiled_one(config: SimConfig) -> SimResult:
    """Single-config convenience wrapper around :func:`run_compiled`."""
    return run_compiled([config])[0]


class _Recorder:
    def __init__(self) -> None:
        self.records: list[TickRecord] = []

    def on_tick(self, record: TickRecord) -> None:
        self.records.append(record)


@dataclass
class FidelityReport:
    """Outcome of one compiled-vs-real fidelity pass."""

    episodes: int
    ticks: int
    divergences: list[tuple[str, Divergence]]

    @property
    def ok(self) -> bool:
        return not self.divergences

    def format_divergences(self, limit: int = 10) -> list[str]:
        """Human-readable lines in the flight recorder's divergence format
        (shared shape with :meth:`~.replay.ReplayResult.
        format_divergences`, prefixed with the episode label)."""
        return [
            f"{label}: tick {d.tick}: {d.tick_field} recorded={d.recorded!r}"
            f" replayed={d.replayed!r}"
            for label, d in self.divergences[:limit]
        ]


def _fidelity_configs(
    scenarios, forecasters: Sequence[str]
) -> list[tuple[str, SimConfig]]:
    episodes: list[tuple[str, SimConfig]] = []
    for scenario in scenarios:
        base = dict(
            arrival_rate=scenario.arrival,
            service_rate_per_replica=scenario.service_rate_per_replica,
            duration=scenario.duration,
            initial_replicas=scenario.initial_replicas,
            min_pods=scenario.min_pods,
            max_pods=scenario.max_pods,
            loop=scenario.loop,
        )
        episodes.append((f"{scenario.name}/reactive", SimConfig(**base)))
        for name in forecasters:
            episodes.append(
                (
                    f"{scenario.name}/predictive:{name}",
                    SimConfig(
                        **base,
                        policy="predictive",
                        forecaster=name,
                        forecast_horizon=scenario.horizon,
                    ),
                )
            )
    return episodes


def verify_fidelity(
    scenarios=None,
    forecasters: Sequence[str] = ("ewma", "holt", "lstsq"),
    extra_episodes: Sequence[tuple[str, SimConfig]] = (),
) -> FidelityReport:
    """Assert the compiled scan reproduces the real-``ControlLoop`` sim.

    Runs reactive plus each requested forecaster over every scenario
    (default: the full :func:`~.evaluate.default_battery`), once through
    the Python closed-loop simulator (the real production stack on a
    ``FakeClock``) and once through the compiled scan, and compares
    **tick-for-tick**: observed depth, the depth the gates thresholded
    (``decision_messages``), both gate outcomes, and the replica count
    entering each tick — plus the episode's final replicas and max depth.
    Any mismatch is a :class:`~.replay.Divergence`; callers gate on
    :attr:`FidelityReport.ok` (``bench.py --suite sweep`` exits 2, the
    same contract as ``make replay-demo``).

    The default episodes all use the scenarios' stock gate parameters —
    the knobs a sweep *tunes* (thresholds, cooldowns, scale step,
    horizon, history) stay at their defaults.  ``extra_episodes``
    extends the gate with arbitrary ``(label, SimConfig)`` pairs so
    callers can cover the swept region too: ``bench.py --suite sweep``
    passes a deterministic sample of its own grid points, so the
    published best/Pareto configs come from a region the gate actually
    checked.  Episodes are batched by compiled shape (tick count ×
    history capacity), so mixed durations/capacities are fine.
    """
    if scenarios is None:
        from .evaluate import default_battery

        scenarios = default_battery()
    episodes = _fidelity_configs(scenarios, forecasters)
    episodes.extend(extra_episodes)
    compiled = run_episodes_grouped([config for _, config in episodes])
    divergences: list[tuple[str, Divergence]] = []
    total_ticks = 0
    for (label, config), comp in zip(episodes, compiled):
        recorder = _Recorder()
        result = Simulation(config, extra_observers=(recorder,)).run()
        total_ticks += result.ticks
        for k, record in enumerate(recorder.records):
            up, down = comp.gates(k)
            checks = (
                ("num_messages", record.num_messages, int(comp.observed[k])),
                (
                    "decision_messages",
                    record.decision_messages,
                    int(comp.decision[k]),
                ),
                ("up", record.up, up),
                ("down", record.down, down),
                ("replicas", result.timeline[k][2], int(comp.replicas_before[k])),
            )
            for name, recorded, replayed in checks:
                if recorded != replayed:
                    divergences.append(
                        (label, Divergence(k, name, recorded, replayed))
                    )
        if result.final_replicas != comp.result.final_replicas:
            divergences.append(
                (
                    label,
                    Divergence(
                        result.ticks,
                        "final_replicas",
                        result.final_replicas,
                        comp.result.final_replicas,
                    ),
                )
            )
        # max depth is float64 world state; everything upstream of it is
        # bit-exact except libm-vs-XLA transcendentals (diurnal's cos), so
        # a relative tolerance at f64 noise level is the honest check
        if not math.isclose(
            result.max_depth, comp.result.max_depth, rel_tol=1e-9, abs_tol=1e-6
        ):
            divergences.append(
                (
                    label,
                    Divergence(
                        result.ticks,
                        "max_depth",
                        result.max_depth,
                        comp.result.max_depth,
                    ),
                )
            )
    return FidelityReport(
        episodes=len(episodes), ticks=total_ticks, divergences=divergences
    )
