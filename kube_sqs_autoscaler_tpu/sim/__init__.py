"""Closed-loop autoscaling simulation.

The reference can only be observed end-to-end against real AWS + a real
cluster; its tests exercise open-loop fragments with hand-set queue depths
(SURVEY.md §4).  This simulator closes the loop deterministically: a
virtual queue fed at a configured arrival rate, drained by virtual worker
replicas at a configured per-replica service rate, scaled by the *real*
production ``ControlLoop``/``PodAutoScaler`` against the in-memory fakes on
a ``FakeClock``.  Used by tests (dynamics assertions) and ``bench.py``
(throughput measurement).
"""

from .simulator import SimConfig, SimResult, Simulation

__all__ = ["SimConfig", "SimResult", "Simulation"]
