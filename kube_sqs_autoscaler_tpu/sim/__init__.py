"""Closed-loop autoscaling simulation.

The reference can only be observed end-to-end against real AWS + a real
cluster; its tests exercise open-loop fragments with hand-set queue depths
(SURVEY.md §4).  This simulator closes the loop deterministically: a
virtual queue fed by a configured arrival process (constant, or the
step/ramp/diurnal/burst shapes in :mod:`.scenarios`), drained by virtual
worker replicas at a configured per-replica service rate, scaled by the
*real* production ``ControlLoop``/``PodAutoScaler`` against the in-memory
fakes on a ``FakeClock``.  Used by tests (dynamics assertions),
``bench.py`` (throughput measurement), and the reactive-vs-predictive
scenario battery in :mod:`.evaluate` (``bench.py --suite forecast``).
:mod:`.replay` closes the observability loop the other way: it re-drives
the production loop from a recorded flight journal (``obs/journal.py``)
and counterfactually re-scores the episode under any other policy
(``bench.py --suite replay``).  :mod:`.compiled` is this simulator's
XLA twin — whole episodes as one ``jax.lax.scan``, vmapped over
parameter grids for the autotuning sweeps in :mod:`.sweep`
(``bench.py --suite sweep``), fidelity-gated tick-for-tick against the
Python loop here (``verify_fidelity``; see ARCHITECTURE.md "The
compiled twin").
"""

# NOTE: .replay and .faults are intentionally NOT imported here — they
# are runnable as `python -m kube_sqs_autoscaler_tpu.sim.replay` /
# `...sim.faults` (the make replay-demo / chaos-demo entries), and
# importing them from the package __init__ would shadow that execution
# with a second module copy (runpy's sys.modules warning).
# .compiled and .sweep are also not imported: they pull in JAX, and this
# package must stay importable JAX-free (bench.py's default suite).
from .scenarios import (
    ArrivalProcess,
    BurstArrival,
    ComposedArrival,
    ConstantArrival,
    DiurnalArrival,
    PulseArrival,
    RampArrival,
    RegimeSwitchArrival,
    StepArrival,
    arrival_variant,
    heavy_tail_lengths,
    scenario_variants,
    variant_bounds,
)
from .costmodel import (
    DECODE_COST_S,
    INSERT_COST_S,
    TRANSFER_COST_S,
    CostModel,
)
from .simulator import SimConfig, SimResult, Simulation

# NOTE: .twin (the token-level serving twin) is also not imported here —
# it pulls in JAX like .compiled; import kube_sqs_autoscaler_tpu.sim.twin
# explicitly.

__all__ = [
    "CostModel",
    "DECODE_COST_S",
    "INSERT_COST_S",
    "TRANSFER_COST_S",
    "SimConfig",
    "SimResult",
    "Simulation",
    "ArrivalProcess",
    "ConstantArrival",
    "StepArrival",
    "RampArrival",
    "DiurnalArrival",
    "BurstArrival",
    "PulseArrival",
    "ComposedArrival",
    "RegimeSwitchArrival",
    "arrival_variant",
    "heavy_tail_lengths",
    "scenario_variants",
    "variant_bounds",
]
