"""Topology — the device fleet as a link graph.

PR 18 gave every byte move a typed :class:`~.ops.TransferOp` and a
scheduler that picks WHEN it dispatches; this module supplies the
other half of ROADMAP item 2: WHICH ROUTE.  A :class:`Topology` is a
directed graph of :class:`Link` edges with modeled bandwidth (B/s) and
latency (s), over which :mod:`.routing` plans concrete multi-hop
routes and charges a per-link virtual-time ledger.

Node naming matches the destinations the producers already emit:
``shard:N`` for the gang's engine shards, ``host`` for host staging,
and anything else (``prefill``, ``decode-plane``, ``device``) joins
lazily via :meth:`Topology.ensure_node` with host-grade links, so
planning never crashes on an endpoint the builder didn't anticipate.

Builders model the three shapes the serving stack actually runs on:

- :func:`ring_topology` — bidirectional ICI ring (1D torus);
- :func:`mesh2d_topology` — 2D mesh, optionally wrapped into a torus
  (the TPU-pod shape SCCL's synthesized schedules target);
- :func:`two_tier_topology` — ICI islands bridged over DCN through
  host staging (BLITZSCALE's multicast-chain setting).

The host attaches through a small set of GATEWAY shards, not to every
shard: evacuations and handoffs must cross the fabric to reach
staging, which is what makes routing (and the contended-link ledger)
mean something.  Link constants are modeling constants for the
virtual-time cost model, not measurements — the bench gates RATIOS on
them, never wall seconds.

:func:`topology_from_geometry` derives the graph from the live
``--shards`` / ``--model-parallel`` geometry, with the ``--topology``
CLI flag picking the shape.
"""

from __future__ import annotations

from dataclasses import dataclass

#: Shapes ``topology_from_geometry`` / the ``--topology`` flag accept.
TOPOLOGY_KINDS = ("ring", "mesh2d", "torus", "two-tier")

#: Modeled link grades (bandwidth B/s, latency s): intra-island ICI,
#: cross-island DCN, and the host staging hop (DMA over PCIe-class).
ICI_BANDWIDTH = 100e9
ICI_LATENCY = 1e-6
DCN_BANDWIDTH = 10e9
DCN_LATENCY = 10e-6
HOST_BANDWIDTH = 16e9
HOST_LATENCY = 5e-6


@dataclass(frozen=True)
class Link:
    """One directed edge: ``src -> dst`` at a bandwidth/latency grade."""

    src: str
    dst: str
    bandwidth: float
    latency: float

    @property
    def name(self) -> str:
        return f"{self.src}->{self.dst}"

    def transfer_s(self, nbytes: int) -> float:
        """Modeled seconds to push ``nbytes`` across this link."""
        return self.latency + (nbytes / self.bandwidth if nbytes else 0.0)


class Topology:
    """A directed link graph with shortest/disjoint path queries."""

    def __init__(self, kind: str = "custom") -> None:
        self.kind = kind
        self._links: dict[tuple[str, str], Link] = {}
        self._out: dict[str, list[Link]] = {}

    # -- construction ----------------------------------------------------

    def add_node(self, node: str) -> None:
        self._out.setdefault(node, [])

    def add_link(
        self,
        src: str,
        dst: str,
        *,
        bandwidth: float,
        latency: float,
        bidirectional: bool = True,
    ) -> None:
        """Add ``src -> dst`` (and the reverse unless told otherwise).
        Re-adding an existing edge overwrites its grade."""
        if src == dst:
            raise ValueError(f"self-link on {src!r}")
        for a, b in ((src, dst), (dst, src)) if bidirectional \
                else ((src, dst),):
            link = Link(a, b, float(bandwidth), float(latency))
            old = self._links.get((a, b))
            self._links[(a, b)] = link
            self.add_node(a)
            self.add_node(b)
            if old is not None:
                self._out[a] = [
                    l for l in self._out[a] if l.dst != b
                ]
            self._out[a].append(link)

    def ensure_node(self, node: str) -> None:
        """Lazily admit an endpoint the builder didn't model: wire it
        to ``host`` at host grade so every route query has an answer."""
        if node in self._out and self._out[node]:
            return
        if node == "host":
            self.add_node(node)
            return
        self.add_node("host")
        self.add_link(
            node, "host",
            bandwidth=HOST_BANDWIDTH, latency=HOST_LATENCY,
        )

    # -- queries ---------------------------------------------------------

    @property
    def nodes(self) -> list[str]:
        return sorted(self._out)

    @property
    def links(self) -> list[Link]:
        return [self._links[key] for key in sorted(self._links)]

    def link(self, src: str, dst: str) -> Link | None:
        return self._links.get((src, dst))

    def out_links(self, node: str) -> list[Link]:
        return list(self._out.get(node, ()))

    def shortest_path(
        self,
        src: str,
        dst: str,
        *,
        nbytes: int = 0,
        blocked: frozenset | set | None = None,
    ) -> list[Link] | None:
        """Dijkstra over modeled per-link cost ``latency +
        nbytes/bandwidth`` (pure latency for ``nbytes=0`` — the
        small-op metric).  ``blocked`` excludes edges by ``(src, dst)``
        key (the disjoint-path residual).  None when unreachable;
        ``[]`` when ``src == dst``."""
        self.ensure_node(src)
        self.ensure_node(dst)
        if src == dst:
            return []
        import heapq

        blocked = blocked or frozenset()
        dist: dict[str, float] = {src: 0.0}
        back: dict[str, Link] = {}
        heap: list[tuple[float, str]] = [(0.0, src)]
        seen: set[str] = set()
        while heap:
            cost, node = heapq.heappop(heap)
            if node in seen:
                continue
            seen.add(node)
            if node == dst:
                break
            for link in self._out.get(node, ()):
                if (link.src, link.dst) in blocked:
                    continue
                next_cost = cost + link.transfer_s(nbytes)
                if next_cost < dist.get(link.dst, float("inf")):
                    dist[link.dst] = next_cost
                    back[link.dst] = link
                    heapq.heappush(heap, (next_cost, link.dst))
        if dst not in back:
            return None
        path: list[Link] = []
        node = dst
        while node != src:
            link = back[node]
            path.append(link)
            node = link.src
        path.reverse()
        return path

    def disjoint_paths(
        self, src: str, dst: str, *, k: int = 4, nbytes: int = 0
    ) -> list[list[Link]]:
        """Up to ``k`` link-disjoint ``src -> dst`` paths, greedily:
        take the cheapest path, remove its edges (both directions — a
        full-duplex link carries one chunk stream per direction but we
        keep the planner conservative), repeat on the residual.  Always
        at least one path when connected."""
        paths: list[list[Link]] = []
        blocked: set[tuple[str, str]] = set()
        for _ in range(max(1, k)):
            path = self.shortest_path(
                src, dst, nbytes=nbytes, blocked=blocked,
            )
            if path is None:
                break
            paths.append(path)
            if not path:  # src == dst
                break
            for link in path:
                blocked.add((link.src, link.dst))
                blocked.add((link.dst, link.src))
        return paths

    def snapshot(self) -> dict:
        """The ``/debug/topology`` graph body."""
        return {
            "kind": self.kind,
            "nodes": self.nodes,
            "links": [
                {
                    "src": link.src,
                    "dst": link.dst,
                    "bandwidth_bps": link.bandwidth,
                    "latency_s": link.latency,
                }
                for link in self.links
            ],
        }


def _default_gateways(n: int) -> tuple[int, ...]:
    """Which shards carry a host-staging link: one on tiny fleets, two
    on opposite sides of larger ones (disjoint entries into staging —
    what lets a big evacuation chunk across both)."""
    return (0,) if n < 4 else (0, n // 2)


def _attach_host(
    topo: Topology, gateways: tuple[int, ...]
) -> None:
    topo.add_node("host")
    for g in gateways:
        topo.add_link(
            f"shard:{g}", "host",
            bandwidth=HOST_BANDWIDTH, latency=HOST_LATENCY,
        )


def ring_topology(
    n: int, *, gateways: tuple[int, ...] | None = None
) -> Topology:
    """``n`` shards on a bidirectional ICI ring, host-staged through
    ``gateways`` (default :func:`_default_gateways`)."""
    if n < 1:
        raise ValueError("ring needs at least one shard")
    topo = Topology("ring")
    for i in range(n):
        topo.add_node(f"shard:{i}")
    if n > 1:
        for i in range(n):
            topo.add_link(
                f"shard:{i}", f"shard:{(i + 1) % n}",
                bandwidth=ICI_BANDWIDTH, latency=ICI_LATENCY,
            )
    _attach_host(topo, gateways or _default_gateways(n))
    return topo


def mesh2d_topology(
    rows: int,
    cols: int,
    *,
    torus: bool = False,
    gateways: tuple[int, ...] | None = None,
) -> Topology:
    """``rows x cols`` shards on a 2D ICI mesh (``torus=True`` wraps
    both axes), host-staged through ``gateways``.  Shard ``r*cols + c``
    sits at ``(r, c)``."""
    if rows < 1 or cols < 1:
        raise ValueError("mesh needs positive extents")
    topo = Topology("torus" if torus else "mesh2d")
    n = rows * cols

    def shard(r: int, c: int) -> str:
        return f"shard:{(r % rows) * cols + (c % cols)}"

    for i in range(n):
        topo.add_node(f"shard:{i}")
    for r in range(rows):
        for c in range(cols):
            if c + 1 < cols or (torus and cols > 2):
                topo.add_link(
                    shard(r, c), shard(r, c + 1),
                    bandwidth=ICI_BANDWIDTH, latency=ICI_LATENCY,
                )
            if r + 1 < rows or (torus and rows > 2):
                topo.add_link(
                    shard(r, c), shard(r + 1, c),
                    bandwidth=ICI_BANDWIDTH, latency=ICI_LATENCY,
                )
    _attach_host(topo, gateways or _default_gateways(n))
    return topo


def two_tier_topology(
    islands: int,
    per_island: int,
    *,
    gateways_per_island: int = 1,
) -> Topology:
    """``islands`` ICI rings of ``per_island`` shards each, bridged
    over DCN through host staging: every island's first
    ``gateways_per_island`` shards link to ``host`` at DCN grade, so
    cross-island traffic is island-ICI -> DCN -> host -> DCN ->
    island-ICI.  Shard ``i*per_island + j`` is island ``i``'s ``j``-th
    chip."""
    if islands < 1 or per_island < 1:
        raise ValueError("two-tier needs positive extents")
    topo = Topology("two-tier")
    topo.add_node("host")
    for i in range(islands):
        base = i * per_island
        for j in range(per_island):
            topo.add_node(f"shard:{base + j}")
        if per_island > 1:
            for j in range(per_island):
                topo.add_link(
                    f"shard:{base + j}",
                    f"shard:{base + (j + 1) % per_island}",
                    bandwidth=ICI_BANDWIDTH, latency=ICI_LATENCY,
                )
        for j in range(max(1, min(gateways_per_island, per_island))):
            topo.add_link(
                f"shard:{base + j}", "host",
                bandwidth=DCN_BANDWIDTH, latency=DCN_LATENCY,
            )
    return topo


def _near_square(n: int) -> tuple[int, int]:
    """Factor ``n`` as ``rows x cols`` with the axes as close as they
    get (falls back to ``1 x n`` for primes)."""
    best = (1, n)
    r = 1
    while r * r <= n:
        if n % r == 0:
            best = (r, n // r)
        r += 1
    return best


def topology_from_geometry(
    kind: str,
    *,
    shards: int,
    model_parallel: int = 1,
) -> Topology:
    """The graph of the live serving geometry: ``shards`` engine
    shards (the routable endpoints), shaped per ``kind``.

    - ``ring``   — one ICI ring over the shards;
    - ``mesh2d`` / ``torus`` — shards factored near-square into a 2D
      mesh (wrapped for ``torus``);
    - ``two-tier`` — each shard is an island of ``model_parallel``
      chips... except the routable unit here is the SHARD, so islands
      group shards: ``model_parallel`` shards per ICI island, bridged
      over DCN (one island total when ``shards <= model_parallel``).
    """
    if kind not in TOPOLOGY_KINDS:
        raise ValueError(
            f"unknown topology {kind!r} (choose from {TOPOLOGY_KINDS})"
        )
    shards = max(1, int(shards))
    model_parallel = max(1, int(model_parallel))
    if kind == "ring":
        return ring_topology(shards)
    if kind in ("mesh2d", "torus"):
        rows, cols = _near_square(shards)
        return mesh2d_topology(rows, cols, torus=(kind == "torus"))
    per_island = min(model_parallel, shards)
    islands = max(1, (shards + per_island - 1) // per_island)
    return two_tier_topology(islands, per_island)
