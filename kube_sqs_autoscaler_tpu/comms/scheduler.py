"""The collective scheduler: transfers as first-class scheduled work.

:class:`CollectiveScheduler` owns a queue of :class:`~.ops.TransferOp`
and decides when the moves start.  The engine calls :meth:`flush`
inside its dispatch-ahead window — immediately AFTER the next decode
block / gang block is dispatched and BEFORE it blocks on the previous
one — so every queued pull starts device-side while the block computes
(the PR 5/16 overlap budget).  Ops flushed there are counted
``overlapped``; the settle that later consumes a prefetched array is
no longer a blocking host round-trip, which is exactly what the
``host_transfers`` odometer stops counting (gated by ``bench.py
--suite comms``).

Small same-``(destination, kind)`` ops coalesce into ONE batched
dispatch per flush (size-bucketed — the NCCL chunking idea), so
transfer dispatches stay O(1) per cycle no matter how many deferred
first-token arrays pile up.  A coalesced group SEALS at the
``small_bytes`` threshold: the ops that would push it past dispatch
as one group and a fresh group opens, so one flush window can never
grow a single batched dispatch without bound.

With a :class:`~.topology.Topology` attached the scheduler also picks
WHICH ROUTE (ROADMAP item 2's second half): every op gets a concrete
multi-hop route from the :class:`~.routing.RoutePlanner` (large ops
chunked across link-disjoint paths, small ops latency-minimal),
coalescing keys on the FIRST CONTENDED LINK instead of the
destination, and dispatch order is chosen greedily against a
per-link virtual-time :class:`~.routing.LinkLedger` so concurrent
transfers never oversubscribe a modeled link.  Routing off
(``topology=None``) is byte-identical to the WHEN-only scheduler,
counters included — the routes bench pins this.

The scheduler also registers on the ``sched/`` event queue
(:meth:`register`): a recurring ``comms-flush`` event drains anything
an engine window missed, at :data:`~..sched.PRIORITY_CYCLE` like the
serving cycles it rides between.  Those safety-net flushes run with no
block in flight and are counted non-overlapped — the counters never
flatter the overlap.

With no scheduler attached (``engine.comms is None``) every engine
path is byte-identical to the pre-comms code, counters included; the
bench pins this too.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Sequence

from .ops import (
    SMALL_OP_BYTES,
    TRANSFER_KINDS,
    TransferOp,
    settle_pull_op,
    size_bucket,
)


class CollectiveScheduler:
    """Queue, coalesce, dispatch, and account for transfer ops.

    ``lifecycle`` (a :class:`~..obs.lifecycle.LifecycleRegistry`) gets
    paired ``transfer`` / ``transfer_done`` stamps for every rid an op
    serves, which is what renders the op as a span on the request's
    Perfetto ``transfers`` lane — visibly parallel to the decode span
    hiding it.  ``enabled=False`` parks the scheduler: submits return
    ``None`` and flushes are no-ops, so a wired-but-disabled scheduler
    is byte-identical to no scheduler at all.
    """

    def __init__(
        self,
        *,
        lifecycle: Any = None,
        enabled: bool = True,
        small_bytes: int = SMALL_OP_BYTES,
        trace_len: int = 256,
        topology: Any = None,
    ) -> None:
        self.lifecycle = lifecycle
        self.enabled = enabled
        self.small_bytes = small_bytes
        self._pending: list[TransferOp] = []
        #: most recent dispatched ops, for debugging / the bench artifact
        self.recent: deque = deque(maxlen=trace_len)
        # the counter family the bench pins
        self.transfer_dispatches = 0
        self.transfer_bytes = 0
        self.overlapped_transfers_total = 0
        self.submitted_ops = 0
        self.dispatched_ops = 0
        self.coalesced_ops = 0
        self.finished_ops = 0
        self.flushes = 0
        self.by_kind = {kind: 0 for kind in TRANSFER_KINDS}
        self.by_bucket: dict[str, int] = {}
        # -- routing (None = the WHEN-only PR 18 scheduler, exactly) --
        self.topology = topology
        self.planner = None
        self.ledger = None
        #: virtual now of the link ledger: each flush/record reserves
        #: its routes here and advances it to the latest finish, so
        #: sequential flushes never falsely overlap
        self.vt_now = 0.0
        self.routed_ops = 0
        self.route_chunks = 0
        self.local_ops = 0
        if topology is not None:
            from .routing import LinkLedger, RoutePlanner

            self.planner = RoutePlanner(topology, small_bytes=small_bytes)
            self.ledger = LinkLedger(topology)

    def _now(self) -> float:
        now_fn = getattr(self.lifecycle, "now_fn", None)
        return now_fn() if now_fn is not None else time.time()

    def _stamp(self, op: TransferOp, name: str, t: float) -> None:
        lc = self.lifecycle
        if lc is None:
            return
        for rid in op.rids:
            lc.stamp(rid, name, t=t)

    # -- the producer surface -------------------------------------------

    def submit(self, op: TransferOp) -> TransferOp | None:
        """Queue one op for the next flush (None when disabled)."""
        if not self.enabled:
            return None
        self._pending.append(op)
        self.submitted_ops += 1
        return op

    def settle_pull(
        self,
        arrays: Any,
        *,
        destination: str = "host",
        source: str = "device",
        rids: Sequence[str] = (),
        args: dict | None = None,
    ) -> TransferOp | None:
        """Queue a device→host pull of ``arrays`` (see
        :func:`~.ops.settle_pull_op`)."""
        if not self.enabled:
            return None
        return self.submit(
            settle_pull_op(
                arrays, destination=destination, source=source,
                rids=rids, args=args,
            )
        )

    def record(
        self,
        kind: str,
        destination: str,
        nbytes: int,
        *,
        source: str = "host",
        rids: Sequence[str] = (),
        t0: float | None = None,
        overlapped: bool = False,
        args: dict | None = None,
    ) -> TransferOp | None:
        """Account for a move some jit already dispatched (handoff
        gathers, prefix installs, evacuation flushes): one dispatch,
        its bytes, and a closed ``transfer`` span from ``t0`` (default
        now) to now on every rid.  With a topology attached the move's
        route is still planned and charged to the link ledger — the
        bytes crossed the fabric whether or not we chose when."""
        if not self.enabled:
            return None
        now = self._now()
        op = TransferOp(
            kind=kind,
            destination=destination,
            nbytes=int(nbytes),
            source=source,
            rids=tuple(r for r in rids if r),
            args=dict(args or {}),
        )
        op.dispatched = True
        op.dispatched_t = now if t0 is None else t0
        op.overlapped = overlapped
        self.submitted_ops += 1
        self.dispatched_ops += 1
        self.transfer_dispatches += 1
        self.transfer_bytes += op.nbytes
        if overlapped:
            self.overlapped_transfers_total += 1
        self.by_kind[kind] = self.by_kind.get(kind, 0) + 1
        bucket = size_bucket(op.nbytes)
        self.by_bucket[bucket] = self.by_bucket.get(bucket, 0) + 1
        if self.planner is not None:
            self.vt_now = max(self.vt_now, self._route(op, self.vt_now))
        self._stamp(op, "transfer", op.dispatched_t)
        self.recent.append(op)
        self.finish(op, t=now)
        return op

    # -- routing ---------------------------------------------------------

    def _route(self, op: TransferOp, t: float) -> float:
        """Plan ``op``'s route, reserve it on the ledger at virtual
        time ``t``, stamp the hop lists into ``op.args`` and the
        lifecycle traces, and return the modeled finish."""
        plan = self.planner.plan(op.source, op.destination, op.nbytes)
        if plan.local:
            self.local_ops += 1
            finish = t
            hops: list = []
            op.args["route"] = hops
        else:
            finish = t
            for chunk in plan.chunks:
                _, f = self.ledger.reserve(chunk.path, chunk.nbytes, t)
                finish = max(finish, f)
            hops = plan.paths
            op.args["route"] = hops
            op.args["route_chunks"] = len(plan.chunks)
            self.routed_ops += 1
            self.route_chunks += len(plan.chunks)
        # every op appends (an empty list for local moves) so the i-th
        # route lines up with the trace's i-th transfer span
        lc = self.lifecycle
        route_fn = getattr(lc, "route", None) if lc is not None else None
        if route_fn is not None:
            for rid in op.rids:
                route_fn(rid, hops)
        return finish

    def _coalesce_key(self, op: TransferOp) -> tuple:
        """The grouping key: first contended link when routing (ops
        that will fight for the same first hop batch together), the
        PR 18 ``(destination, kind)`` otherwise."""
        if self.planner is not None:
            first = self.planner.first_hop(
                op.source, op.destination, op.nbytes,
            )
            return (first or op.destination, op.kind)
        return op.coalesce_key()

    # -- the scheduling surface -----------------------------------------

    @property
    def pending(self) -> int:
        return len(self._pending)

    def flush(self, *, overlapped: bool = False) -> int:
        """Dispatch every queued op device-side and return the number
        of DISPATCHES (coalesced groups count once).

        ``overlapped=True`` asserts the caller just dispatched the next
        block — the window the started copies hide in; the safety-net
        ``sched/`` flush passes False.  Small ops sharing a
        ``(destination, kind)`` key batch into one dispatch; each op
        still runs its own ``dispatch`` thunk (the async starts are the
        batch), but the cycle pays one dispatch count per group.
        """
        if not self.enabled or not self._pending:
            return 0
        pending, self._pending = self._pending, []
        self.flushes += 1
        now = self._now()
        sealed: list[list[TransferOp]] = []
        groups: dict[tuple, list[TransferOp]] = {}
        group_bytes: dict[tuple, int] = {}
        singles: list[TransferOp] = []
        for op in pending:
            if op.nbytes <= self.small_bytes:
                key = self._coalesce_key(op)
                group = groups.setdefault(key, [])
                if group and group_bytes[key] + op.nbytes \
                        > self.small_bytes:
                    # the bucket seam: the op that would push a
                    # coalesced group past the small-op threshold
                    # seals it (one dispatch at the threshold) and
                    # opens a fresh group under the same key
                    sealed.append(group)
                    group = []
                    groups[key] = group
                    group_bytes[key] = 0
                group.append(op)
                group_bytes[key] = group_bytes.get(key, 0) + op.nbytes
            else:
                singles.append(op)
        batches = (
            sealed
            + [g for g in groups.values() if g]
            + [[op] for op in singles]
        )
        if self.planner is not None:
            batches = self._routed_order(batches)
        dispatches = 0
        for batch in batches:
            dispatches += 1
            self.transfer_dispatches += 1
            if len(batch) > 1:
                self.coalesced_ops += len(batch)
            for op in batch:
                if op.dispatch is not None:
                    op.dispatch()
                op.dispatched = True
                op.dispatched_t = now
                op.overlapped = overlapped
                self.dispatched_ops += 1
                self.transfer_bytes += op.nbytes
                if overlapped:
                    self.overlapped_transfers_total += 1
                self.by_kind[op.kind] = self.by_kind.get(op.kind, 0) + 1
                bucket = size_bucket(op.nbytes)
                self.by_bucket[bucket] = self.by_bucket.get(bucket, 0) + 1
                self._stamp(op, "transfer", now)
                self.recent.append(op)
        return dispatches

    def _routed_order(
        self, batches: list[list[TransferOp]]
    ) -> list[list[TransferOp]]:
        """Dispatch order against the link ledger: greedily take the
        batch whose first link frees earliest, reserving each batch's
        routes as it is picked — contention serializes on the ledger,
        disjoint routes interleave.  Advances :attr:`vt_now` to the
        latest modeled finish so the NEXT flush starts after this one.
        Returns the batches in chosen order (counter/dispatch work
        stays in :meth:`flush`)."""
        t0 = self.vt_now
        plans = {
            id(batch): self.planner.plan(
                batch[0].source, batch[0].destination,
                sum(op.nbytes for op in batch),
            )
            for batch in batches
        }
        remaining = list(enumerate(batches))
        ordered: list[list[TransferOp]] = []
        finish_vt = t0
        while remaining:
            remaining.sort(key=lambda item: (
                self.ledger.earliest_start(
                    plans[id(item[1])].chunks[0].path
                    if plans[id(item[1])].chunks else (),
                    t0,
                ),
                item[0],
            ))
            index, batch = remaining.pop(0)
            for op in batch:
                finish_vt = max(finish_vt, self._route(op, t0))
            ordered.append(batch)
        self.vt_now = max(self.vt_now, finish_vt)
        return ordered

    def finish(
        self, op: TransferOp | None, *, t: float | None = None
    ) -> None:
        """Close an op's span at the moment its bytes were consumed
        host-side (idempotent; None-safe for unsubmitted ops)."""
        if op is None or op.finished:
            return
        op.finished = True
        op.finished_t = self._now() if t is None else t
        self.finished_ops += 1
        self._stamp(op, "transfer_done", op.finished_t)

    # -- sched/ integration ---------------------------------------------

    def register(
        self,
        scheduler: Any,
        *,
        period: float = 1.0,
        name: str = "comms-flush",
    ) -> Any:
        """Register the safety-net flush as a recurring ``sched/``
        event (PRIORITY_CYCLE — it rides between serving cycles).  The
        event drains ops no engine window flushed; those dispatches run
        with no block in flight, so they count non-overlapped."""
        from ..sched import PRIORITY_CYCLE

        return scheduler.every(
            name, period,
            lambda: self.flush(overlapped=False),
            priority=PRIORITY_CYCLE,
        )

    # -- introspection ---------------------------------------------------

    def counters(self) -> dict:
        """The counter family (bench artifact / assertions).  The
        ``routing`` sub-dict appears ONLY with a topology attached —
        ``topology=None`` counters stay byte-identical to the
        WHEN-only scheduler (the routes parity battery pins the whole
        dict)."""
        out = {
            "transfer_dispatches": self.transfer_dispatches,
            "transfer_bytes": self.transfer_bytes,
            "overlapped_transfers_total": self.overlapped_transfers_total,
            "submitted_ops": self.submitted_ops,
            "dispatched_ops": self.dispatched_ops,
            "coalesced_ops": self.coalesced_ops,
            "finished_ops": self.finished_ops,
            "flushes": self.flushes,
            "pending": len(self._pending),
            "by_kind": dict(self.by_kind),
            "by_bucket": dict(self.by_bucket),
        }
        if self.topology is not None:
            out["routing"] = {
                "routed_ops": self.routed_ops,
                "route_chunks": self.route_chunks,
                "local_ops": self.local_ops,
                "virtual_now_s": self.vt_now,
                "link_bytes": dict(sorted(self.ledger.link_bytes.items())),
            }
        return out

    def topology_snapshot(self) -> dict | None:
        """The ``/debug/topology`` body: the graph, the live ledger,
        and the routing odometers (None without a topology)."""
        if self.topology is None:
            return None
        return {
            "topology": self.topology.snapshot(),
            "ledger": self.ledger.snapshot(),
            "routing": {
                "routed_ops": self.routed_ops,
                "route_chunks": self.route_chunks,
                "local_ops": self.local_ops,
                "virtual_now_s": self.vt_now,
            },
        }

    def export_gauges(self, metrics: Any) -> None:
        """Per-link observability: ``link_bytes_total{link=}`` /
        ``link_utilization{link=}`` into a
        :class:`~..obs.prometheus.WorkloadMetrics` registry (no-op
        without a topology — no phantom series)."""
        if metrics is None or self.topology is None:
            return
        horizon = self.vt_now if self.vt_now > 0 else None
        utilization = self.ledger.utilization(horizon)
        for name, nbytes in sorted(self.ledger.link_bytes.items()):
            metrics.set_gauge(
                "link_bytes_total", nbytes,
                "Modeled bytes routed over each topology link by the "
                "collective scheduler's route planner.",
                labels=(("link", name),), kind="counter",
            )
        for name, frac in utilization.items():
            metrics.set_gauge(
                "link_utilization", frac,
                "Busy fraction of each topology link over the routing "
                "ledger's virtual time.",
                labels=(("link", name),),
            )
