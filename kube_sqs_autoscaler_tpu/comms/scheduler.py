"""The collective scheduler: transfers as first-class scheduled work.

:class:`CollectiveScheduler` owns a queue of :class:`~.ops.TransferOp`
and decides when the moves start.  The engine calls :meth:`flush`
inside its dispatch-ahead window — immediately AFTER the next decode
block / gang block is dispatched and BEFORE it blocks on the previous
one — so every queued pull starts device-side while the block computes
(the PR 5/16 overlap budget).  Ops flushed there are counted
``overlapped``; the settle that later consumes a prefetched array is
no longer a blocking host round-trip, which is exactly what the
``host_transfers`` odometer stops counting (gated by ``bench.py
--suite comms``).

Small same-``(destination, kind)`` ops coalesce into ONE batched
dispatch per flush (size-bucketed — the NCCL chunking idea), so
transfer dispatches stay O(1) per cycle no matter how many deferred
first-token arrays pile up.

The scheduler also registers on the ``sched/`` event queue
(:meth:`register`): a recurring ``comms-flush`` event drains anything
an engine window missed, at :data:`~..sched.PRIORITY_CYCLE` like the
serving cycles it rides between.  Those safety-net flushes run with no
block in flight and are counted non-overlapped — the counters never
flatter the overlap.

With no scheduler attached (``engine.comms is None``) every engine
path is byte-identical to the pre-comms code, counters included; the
bench pins this too.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Sequence

from .ops import (
    SMALL_OP_BYTES,
    TRANSFER_KINDS,
    TransferOp,
    settle_pull_op,
    size_bucket,
)


class CollectiveScheduler:
    """Queue, coalesce, dispatch, and account for transfer ops.

    ``lifecycle`` (a :class:`~..obs.lifecycle.LifecycleRegistry`) gets
    paired ``transfer`` / ``transfer_done`` stamps for every rid an op
    serves, which is what renders the op as a span on the request's
    Perfetto ``transfers`` lane — visibly parallel to the decode span
    hiding it.  ``enabled=False`` parks the scheduler: submits return
    ``None`` and flushes are no-ops, so a wired-but-disabled scheduler
    is byte-identical to no scheduler at all.
    """

    def __init__(
        self,
        *,
        lifecycle: Any = None,
        enabled: bool = True,
        small_bytes: int = SMALL_OP_BYTES,
        trace_len: int = 256,
    ) -> None:
        self.lifecycle = lifecycle
        self.enabled = enabled
        self.small_bytes = small_bytes
        self._pending: list[TransferOp] = []
        #: most recent dispatched ops, for debugging / the bench artifact
        self.recent: deque = deque(maxlen=trace_len)
        # the counter family the bench pins
        self.transfer_dispatches = 0
        self.transfer_bytes = 0
        self.overlapped_transfers_total = 0
        self.submitted_ops = 0
        self.dispatched_ops = 0
        self.coalesced_ops = 0
        self.finished_ops = 0
        self.flushes = 0
        self.by_kind = {kind: 0 for kind in TRANSFER_KINDS}
        self.by_bucket: dict[str, int] = {}

    def _now(self) -> float:
        now_fn = getattr(self.lifecycle, "now_fn", None)
        return now_fn() if now_fn is not None else time.time()

    def _stamp(self, op: TransferOp, name: str, t: float) -> None:
        lc = self.lifecycle
        if lc is None:
            return
        for rid in op.rids:
            lc.stamp(rid, name, t=t)

    # -- the producer surface -------------------------------------------

    def submit(self, op: TransferOp) -> TransferOp | None:
        """Queue one op for the next flush (None when disabled)."""
        if not self.enabled:
            return None
        self._pending.append(op)
        self.submitted_ops += 1
        return op

    def settle_pull(
        self,
        arrays: Any,
        *,
        destination: str = "host",
        rids: Sequence[str] = (),
        args: dict | None = None,
    ) -> TransferOp | None:
        """Queue a device→host pull of ``arrays`` (see
        :func:`~.ops.settle_pull_op`)."""
        if not self.enabled:
            return None
        return self.submit(
            settle_pull_op(
                arrays, destination=destination, rids=rids, args=args,
            )
        )

    def record(
        self,
        kind: str,
        destination: str,
        nbytes: int,
        *,
        rids: Sequence[str] = (),
        t0: float | None = None,
        overlapped: bool = False,
        args: dict | None = None,
    ) -> TransferOp | None:
        """Account for a move some jit already dispatched (handoff
        gathers, prefix installs, evacuation flushes): one dispatch,
        its bytes, and a closed ``transfer`` span from ``t0`` (default
        now) to now on every rid."""
        if not self.enabled:
            return None
        now = self._now()
        op = TransferOp(
            kind=kind,
            destination=destination,
            nbytes=int(nbytes),
            rids=tuple(r for r in rids if r),
            args=dict(args or {}),
        )
        op.dispatched = True
        op.dispatched_t = now if t0 is None else t0
        op.overlapped = overlapped
        self.submitted_ops += 1
        self.dispatched_ops += 1
        self.transfer_dispatches += 1
        self.transfer_bytes += op.nbytes
        if overlapped:
            self.overlapped_transfers_total += 1
        self.by_kind[kind] = self.by_kind.get(kind, 0) + 1
        bucket = size_bucket(op.nbytes)
        self.by_bucket[bucket] = self.by_bucket.get(bucket, 0) + 1
        self._stamp(op, "transfer", op.dispatched_t)
        self.recent.append(op)
        self.finish(op, t=now)
        return op

    # -- the scheduling surface -----------------------------------------

    @property
    def pending(self) -> int:
        return len(self._pending)

    def flush(self, *, overlapped: bool = False) -> int:
        """Dispatch every queued op device-side and return the number
        of DISPATCHES (coalesced groups count once).

        ``overlapped=True`` asserts the caller just dispatched the next
        block — the window the started copies hide in; the safety-net
        ``sched/`` flush passes False.  Small ops sharing a
        ``(destination, kind)`` key batch into one dispatch; each op
        still runs its own ``dispatch`` thunk (the async starts are the
        batch), but the cycle pays one dispatch count per group.
        """
        if not self.enabled or not self._pending:
            return 0
        pending, self._pending = self._pending, []
        self.flushes += 1
        now = self._now()
        groups: dict[tuple, list[TransferOp]] = {}
        singles: list[TransferOp] = []
        for op in pending:
            if op.nbytes <= self.small_bytes:
                groups.setdefault(op.coalesce_key(), []).append(op)
            else:
                singles.append(op)
        dispatches = 0
        for batch in list(groups.values()) + [[op] for op in singles]:
            dispatches += 1
            self.transfer_dispatches += 1
            if len(batch) > 1:
                self.coalesced_ops += len(batch)
            for op in batch:
                if op.dispatch is not None:
                    op.dispatch()
                op.dispatched = True
                op.dispatched_t = now
                op.overlapped = overlapped
                self.dispatched_ops += 1
                self.transfer_bytes += op.nbytes
                if overlapped:
                    self.overlapped_transfers_total += 1
                self.by_kind[op.kind] = self.by_kind.get(op.kind, 0) + 1
                bucket = size_bucket(op.nbytes)
                self.by_bucket[bucket] = self.by_bucket.get(bucket, 0) + 1
                self._stamp(op, "transfer", now)
                self.recent.append(op)
        return dispatches

    def finish(
        self, op: TransferOp | None, *, t: float | None = None
    ) -> None:
        """Close an op's span at the moment its bytes were consumed
        host-side (idempotent; None-safe for unsubmitted ops)."""
        if op is None or op.finished:
            return
        op.finished = True
        op.finished_t = self._now() if t is None else t
        self.finished_ops += 1
        self._stamp(op, "transfer_done", op.finished_t)

    # -- sched/ integration ---------------------------------------------

    def register(
        self,
        scheduler: Any,
        *,
        period: float = 1.0,
        name: str = "comms-flush",
    ) -> Any:
        """Register the safety-net flush as a recurring ``sched/``
        event (PRIORITY_CYCLE — it rides between serving cycles).  The
        event drains ops no engine window flushed; those dispatches run
        with no block in flight, so they count non-overlapped."""
        from ..sched import PRIORITY_CYCLE

        return scheduler.every(
            name, period,
            lambda: self.flush(overlapped=False),
            priority=PRIORITY_CYCLE,
        )

    # -- introspection ---------------------------------------------------

    def counters(self) -> dict:
        """The counter family (bench artifact / assertions)."""
        return {
            "transfer_dispatches": self.transfer_dispatches,
            "transfer_bytes": self.transfer_bytes,
            "overlapped_transfers_total": self.overlapped_transfers_total,
            "submitted_ops": self.submitted_ops,
            "dispatched_ops": self.dispatched_ops,
            "coalesced_ops": self.coalesced_ops,
            "finished_ops": self.finished_ops,
            "flushes": self.flushes,
            "pending": len(self._pending),
            "by_kind": dict(self.by_kind),
            "by_bucket": dict(self.by_bucket),
        }
