"""comms/ — scheduled collectives: typed, overlappable data movement.

Cross-shard and cross-plane byte moves (evacuation KV, prefix
installs, handoff KV gathers, settle pulls) become typed
:class:`~.ops.TransferOp` values on a :class:`~.scheduler.\
CollectiveScheduler` that dispatches them device-side inside the
engine's dispatch-ahead window — while the next gang block is in
flight — instead of paying a blocking host round-trip at settle time
(ISSUE 18 / ROADMAP item 2).

- :mod:`.ops` — the four-kind transfer taxonomy, size buckets, and
  the ``copy_to_host_async``-backed settle-pull constructor;
- :mod:`.scheduler` — queueing, small-op coalescing (one batched
  dispatch per destination per cycle), the
  ``transfer_dispatches`` / ``transfer_bytes`` /
  ``overlapped_transfers_total`` counter family, lifecycle
  ``transfer`` spans, and the ``sched/`` safety-net flush event;
- :mod:`.topology` — the fleet as a link graph (ring / 2D mesh /
  torus / host-staged two-tier builders, derived from the live
  serving geometry) — ISSUE 20's WHICH-ROUTE half;
- :mod:`.routing` — the route planner (disjoint-path chunking for
  large ops, latency-minimal paths for small), the per-link
  virtual-time ledger, and the routed-vs-WHEN-only schedule
  simulator the routes bench gates on.
"""

from .ops import (  # noqa: F401
    EVACUATION_KV,
    HANDOFF_KV,
    PREFIX_INSTALL,
    SETTLE_PULL,
    SIZE_BUCKET_LABELS,
    SMALL_OP_BYTES,
    TRANSFER_KINDS,
    TransferOp,
    array_nbytes,
    settle_pull_op,
    size_bucket,
)
from .routing import (  # noqa: F401
    PIPELINE_BYTES,
    LinkLedger,
    RouteChunk,
    RoutePlan,
    RoutePlanner,
    ScheduleResult,
    assert_no_oversubscription,
    simulate_schedule,
)
from .scheduler import CollectiveScheduler  # noqa: F401
from .topology import (  # noqa: F401
    TOPOLOGY_KINDS,
    Link,
    Topology,
    mesh2d_topology,
    ring_topology,
    topology_from_geometry,
    two_tier_topology,
)

__all__ = [
    "CollectiveScheduler",
    "Link",
    "LinkLedger",
    "PIPELINE_BYTES",
    "RouteChunk",
    "RoutePlan",
    "RoutePlanner",
    "ScheduleResult",
    "TOPOLOGY_KINDS",
    "Topology",
    "assert_no_oversubscription",
    "mesh2d_topology",
    "ring_topology",
    "simulate_schedule",
    "topology_from_geometry",
    "two_tier_topology",
    "EVACUATION_KV",
    "HANDOFF_KV",
    "PREFIX_INSTALL",
    "SETTLE_PULL",
    "SIZE_BUCKET_LABELS",
    "SMALL_OP_BYTES",
    "TRANSFER_KINDS",
    "TransferOp",
    "array_nbytes",
    "settle_pull_op",
    "size_bucket",
]
