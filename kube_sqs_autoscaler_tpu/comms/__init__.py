"""comms/ — scheduled collectives: typed, overlappable data movement.

Cross-shard and cross-plane byte moves (evacuation KV, prefix
installs, handoff KV gathers, settle pulls) become typed
:class:`~.ops.TransferOp` values on a :class:`~.scheduler.\
CollectiveScheduler` that dispatches them device-side inside the
engine's dispatch-ahead window — while the next gang block is in
flight — instead of paying a blocking host round-trip at settle time
(ISSUE 18 / ROADMAP item 2).

- :mod:`.ops` — the four-kind transfer taxonomy, size buckets, and
  the ``copy_to_host_async``-backed settle-pull constructor;
- :mod:`.scheduler` — queueing, small-op coalescing (one batched
  dispatch per destination per cycle), the
  ``transfer_dispatches`` / ``transfer_bytes`` /
  ``overlapped_transfers_total`` counter family, lifecycle
  ``transfer`` spans, and the ``sched/`` safety-net flush event.
"""

from .ops import (  # noqa: F401
    EVACUATION_KV,
    HANDOFF_KV,
    PREFIX_INSTALL,
    SETTLE_PULL,
    SIZE_BUCKET_LABELS,
    SMALL_OP_BYTES,
    TRANSFER_KINDS,
    TransferOp,
    array_nbytes,
    settle_pull_op,
    size_bucket,
)
from .scheduler import CollectiveScheduler  # noqa: F401

__all__ = [
    "CollectiveScheduler",
    "EVACUATION_KV",
    "HANDOFF_KV",
    "PREFIX_INSTALL",
    "SETTLE_PULL",
    "SIZE_BUCKET_LABELS",
    "SMALL_OP_BYTES",
    "TRANSFER_KINDS",
    "TransferOp",
    "array_nbytes",
    "settle_pull_op",
    "size_bucket",
]
