"""Route planning and the per-link virtual-time ledger.

Given a :class:`~.topology.Topology`, :class:`RoutePlanner` turns
every transfer into a concrete multi-hop route:

- SMALL ops (at or under the coalescing threshold) take the
  latency-minimal path — the same regime split the NCCL analysis
  motivates for protocol choice;
- LARGE ops are CHUNKED across up to ``max_paths`` link-disjoint
  paths, bytes split proportional to each path's bottleneck
  bandwidth, and each path's share further cut into
  ``pipeline_bytes`` sub-chunks so multi-hop store-and-forward
  pipelines instead of paying ``hops x full-payload`` (the SCCL-style
  bandwidth-optimal shape for the big evacuation/handoff KV moves).

:class:`LinkLedger` is the contention model: per-link ``busy_until``
virtual time, advanced store-and-forward as chunks reserve hops.  A
link serves one chunk at a time — two transfers sharing a link
serialize ON THE LEDGER, disjoint routes proceed in parallel — and
every reservation is recorded so the property test can audit that no
schedule ever oversubscribes a link (:func:`assert_no_oversubscription`).

:func:`simulate_schedule` replays a batch of ops through the model
twice-comparable ways: ``routed=True`` (chunked disjoint paths,
greedy earliest-first-link dispatch order) versus ``routed=False``
(the WHEN-only baseline: FIFO order, single shortest path, no
chunking).  ``bench.py --suite routes`` gates the ratio of their
modeled completion times on a contended torus episode.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterable, Sequence

from .ops import SMALL_OP_BYTES
from .topology import Link, Topology

#: Pipelining grain for large chunked transfers: each disjoint path's
#: share is cut into sub-chunks of at most this many bytes so a
#: multi-hop path overlaps its hops.
PIPELINE_BYTES = 1 << 20


@dataclass(frozen=True)
class RouteChunk:
    """One pipelined unit: ``nbytes`` pushed along ``path``."""

    path: tuple[Link, ...]
    nbytes: int

    @property
    def hops(self) -> list[str]:
        return [link.name for link in self.path]


@dataclass
class RoutePlan:
    """Every chunk of one op's route (empty for a local move)."""

    src: str
    dst: str
    nbytes: int
    chunks: tuple[RouteChunk, ...] = ()

    @property
    def local(self) -> bool:
        return not self.chunks

    @property
    def paths(self) -> list[list[str]]:
        """Distinct hop lists, in chunk order (the trace/span payload)."""
        seen: list[list[str]] = []
        for chunk in self.chunks:
            hops = chunk.hops
            if hops not in seen:
                seen.append(hops)
        return seen

    def first_link(self) -> str | None:
        return self.chunks[0].path[0].name if self.chunks else None


class RoutePlanner:
    """Assign routes per the size regime (see module doc)."""

    def __init__(
        self,
        topology: Topology,
        *,
        small_bytes: int = SMALL_OP_BYTES,
        max_paths: int = 4,
        pipeline_bytes: int = PIPELINE_BYTES,
    ) -> None:
        self.topology = topology
        self.small_bytes = small_bytes
        self.max_paths = max(1, max_paths)
        self.pipeline_bytes = max(1, pipeline_bytes)
        self._path_cache: dict[tuple, Any] = {}

    def _shortest(self, src: str, dst: str) -> list[Link] | None:
        key = ("s", src, dst)
        if key not in self._path_cache:
            self._path_cache[key] = self.topology.shortest_path(src, dst)
        return self._path_cache[key]

    def _disjoint(
        self, src: str, dst: str, nbytes: int
    ) -> list[list[Link]]:
        key = ("d", src, dst)
        if key not in self._path_cache:
            self._path_cache[key] = self.topology.disjoint_paths(
                src, dst, k=self.max_paths, nbytes=nbytes,
            )
        return self._path_cache[key]

    def plan(self, src: str, dst: str, nbytes: int) -> RoutePlan:
        """The op's route.  ``src == dst`` (or an unreachable pair,
        which :meth:`~.topology.Topology.ensure_node` makes impossible
        on connected graphs) plans as a local no-hop move."""
        nbytes = max(0, int(nbytes))
        if src == dst:
            return RoutePlan(src, dst, nbytes)
        if nbytes <= self.small_bytes:
            path = self._shortest(src, dst)
            if not path:
                return RoutePlan(src, dst, nbytes)
            return RoutePlan(
                src, dst, nbytes,
                (RouteChunk(tuple(path), nbytes),),
            )
        paths = [p for p in self._disjoint(src, dst, nbytes) if p]
        if not paths:
            return RoutePlan(src, dst, nbytes)
        weights = [min(link.bandwidth for link in p) for p in paths]
        total_w = sum(weights)
        shares = [int(nbytes * w / total_w) for w in weights]
        shares[0] += nbytes - sum(shares)
        chunks: list[RouteChunk] = []
        for path, share in zip(paths, shares):
            if share <= 0:
                continue
            remaining = share
            while remaining > 0:
                cut = min(remaining, self.pipeline_bytes)
                chunks.append(RouteChunk(tuple(path), cut))
                remaining -= cut
        return RoutePlan(src, dst, nbytes, tuple(chunks))

    def first_hop(self, src: str, dst: str, nbytes: int) -> str | None:
        """The first link the op will contend on — the first-hop-aware
        coalescing key (None for local moves)."""
        return self.plan(src, dst, nbytes).first_link()


class LinkLedger:
    """Per-link virtual-time occupancy: ``busy_until``, byte and
    busy-second odometers, and a bounded record of reserved intervals
    (the oversubscription audit surface)."""

    def __init__(
        self, topology: Topology, *, max_records: int = 4096
    ) -> None:
        self.topology = topology
        self.max_records = max_records
        self.busy_until: dict[str, float] = {}
        self.link_bytes: dict[str, int] = {}
        self.busy_seconds: dict[str, float] = {}
        #: per-link ``(start, finish)`` reservation intervals, oldest
        #: dropped past ``max_records`` total
        self.records: dict[str, list[tuple[float, float]]] = {}
        self._recorded = 0

    def reserve(
        self, path: Sequence[Link], nbytes: int, t: float
    ) -> tuple[float, float]:
        """Push ``nbytes`` along ``path`` store-and-forward starting no
        earlier than ``t``: each hop starts when the chunk has arrived
        AND the link is free, holds the link for ``latency +
        nbytes/bandwidth``, and hands off to the next hop.  Returns the
        ``(start, finish)`` of the whole traversal."""
        arrival = t
        start0: float | None = None
        for link in path:
            start = max(arrival, self.busy_until.get(link.name, 0.0))
            if start0 is None:
                start0 = start
            finish = start + link.transfer_s(nbytes)
            self.busy_until[link.name] = finish
            self.link_bytes[link.name] = (
                self.link_bytes.get(link.name, 0) + int(nbytes)
            )
            self.busy_seconds[link.name] = (
                self.busy_seconds.get(link.name, 0.0) + (finish - start)
            )
            if self._recorded < self.max_records:
                self.records.setdefault(link.name, []).append(
                    (start, finish)
                )
                self._recorded += 1
            arrival = finish
        if start0 is None:  # empty path: a local move
            return (t, t)
        return (start0, arrival)

    def earliest_start(self, path: Sequence[Link], t: float) -> float:
        """When the first hop of ``path`` could begin, given current
        occupancy (the greedy dispatch-order metric)."""
        if not path:
            return t
        return max(t, self.busy_until.get(path[0].name, 0.0))

    def utilization(self, horizon: float | None = None) -> dict[str, float]:
        """Busy fraction per link over ``horizon`` (default: the
        ledger's own high-water virtual time)."""
        if horizon is None:
            horizon = max(self.busy_until.values(), default=0.0)
        if horizon <= 0.0:
            return {name: 0.0 for name in self.busy_seconds}
        return {
            name: min(1.0, busy / horizon)
            for name, busy in sorted(self.busy_seconds.items())
        }

    def snapshot(self) -> dict:
        """The ``/debug/topology`` ledger body."""
        horizon = max(self.busy_until.values(), default=0.0)
        return {
            "virtual_now": horizon,
            "busy_until": dict(sorted(self.busy_until.items())),
            "link_bytes": dict(sorted(self.link_bytes.items())),
            "utilization": self.utilization(horizon),
        }


def assert_no_oversubscription(ledger: LinkLedger) -> None:
    """Audit every recorded reservation: on each link the intervals
    must be non-overlapping (one chunk at a time — the contention
    contract the scheduler's dispatch order promises).  Raises
    AssertionError naming the first violating link."""
    for name, intervals in ledger.records.items():
        ordered = sorted(intervals)
        for (s0, f0), (s1, f1) in zip(ordered, ordered[1:]):
            eps = 1e-12
            if s1 < f0 - eps:
                raise AssertionError(
                    f"link {name} oversubscribed: "
                    f"[{s0:.9f},{f0:.9f}] overlaps [{s1:.9f},{f1:.9f}]"
                )


@dataclass
class ScheduleResult:
    """One simulated dispatch schedule (see :func:`simulate_schedule`)."""

    ops: list = field(default_factory=list)
    makespan: float = 0.0
    link_utilization: dict = field(default_factory=dict)
    link_bytes: dict = field(default_factory=dict)
    ledger: LinkLedger | None = None

    def to_dict(self) -> dict:
        return {
            "makespan_s": self.makespan,
            "ops": list(self.ops),
            "link_utilization": dict(self.link_utilization),
            "link_bytes": dict(self.link_bytes),
        }


def _op_view(op: Any) -> tuple[str, str, str, int]:
    """(kind, source, destination, nbytes) of a TransferOp or dict."""
    if isinstance(op, dict):
        return (
            str(op.get("kind", "transfer")),
            str(op.get("source", "host")),
            str(op.get("destination", "host")),
            int(op.get("nbytes", 0)),
        )
    return (
        op.kind,
        getattr(op, "source", "host"),
        op.destination,
        int(op.nbytes),
    )


def simulate_schedule(
    ops: Iterable[Any],
    topology: Topology,
    *,
    routed: bool = True,
    small_bytes: int = SMALL_OP_BYTES,
    max_paths: int = 4,
    pipeline_bytes: int = PIPELINE_BYTES,
    start_t: float = 0.0,
) -> ScheduleResult:
    """Model a batch of concurrent transfers on the topology.

    ``routed=True`` is this PR's scheduler: every op planned
    (chunked/pipelined disjoint paths for large, latency-minimal for
    small) and dispatched greedily — at each step the op whose first
    link frees earliest goes next, so contention serializes on the
    ledger and disjoint routes run in parallel.  ``routed=False`` is
    the WHEN-only PR 18 baseline given the same cost model: submission
    (FIFO) order, one shortest path each, no chunking.  Completion =
    ``makespan`` = latest chunk finish minus ``start_t``.
    """
    planner = RoutePlanner(
        topology,
        small_bytes=small_bytes if routed else (1 << 62),
        max_paths=max_paths if routed else 1,
        pipeline_bytes=pipeline_bytes if routed else (1 << 62),
    )
    ledger = LinkLedger(topology)
    entries = []
    for index, op in enumerate(ops):
        kind, src, dst, nbytes = _op_view(op)
        plan = planner.plan(src, dst, nbytes)
        entries.append({
            "index": index, "kind": kind, "src": src, "dst": dst,
            "nbytes": nbytes, "plan": plan,
        })
    order = list(entries)
    scheduled = []
    makespan = 0.0
    while order:
        if routed:
            order.sort(key=lambda e: (
                ledger.earliest_start(
                    e["plan"].chunks[0].path if e["plan"].chunks else (),
                    start_t,
                ),
                e["index"],
            ))
        entry = order.pop(0)
        plan = entry["plan"]
        op_start = None
        op_finish = start_t
        for chunk in plan.chunks:
            s, f = ledger.reserve(chunk.path, chunk.nbytes, start_t)
            op_start = s if op_start is None else min(op_start, s)
            op_finish = max(op_finish, f)
        scheduled.append({
            "kind": entry["kind"],
            "src": entry["src"],
            "dst": entry["dst"],
            "nbytes": entry["nbytes"],
            "start_s": (start_t if op_start is None else op_start)
            - start_t,
            "finish_s": op_finish - start_t,
            "chunks": len(plan.chunks),
            "hops": plan.paths,
        })
        makespan = max(makespan, op_finish - start_t)
    horizon = makespan if makespan > 0 else None
    return ScheduleResult(
        ops=scheduled,
        makespan=makespan,
        link_utilization=ledger.utilization(horizon),
        link_bytes=dict(sorted(ledger.link_bytes.items())),
        ledger=ledger,
    )
