"""Typed transfer ops — the data-movement taxonomy.

Every cross-shard / cross-plane byte move the serving stack performs
today is one of four kinds, and each kind already has an odometer
pinning it (PR 7's ``host_transfers`` / PR 16's ``kv_transfers``):

==================  ==================================================
kind                the move
==================  ==================================================
``evacuation_kv``   a draining shard's in-flight rows leaving the
                    gang (``take_shard_inflight``): deferred firsts
                    flushed host-side + the rows' KV freed
``prefix_install``  a prefilled prefix entry written into the
                    per-tenant pool's stacked layers
``handoff_kv``      prefill-plane KV rows gathered into decode-plane
                    slots (the ``submit_resume``-shaped splice)
``settle_pull``     device→host pull of settled tokens — deferred
                    first-token arrays and the gang block's
                    token/count arrays
==================  ==================================================

A :class:`TransferOp` is the schedulable unit: destination, payload
size, the request ids it serves (for lifecycle ``transfer`` spans), and
a ``dispatch`` thunk that STARTS the move device-side without blocking
(``jax.Array.copy_to_host_async`` for pulls; an already-dispatched jit
for device-to-device copies).  The scheduler decides WHEN to call it —
inside the dispatch-ahead window, while the next block computes — and
whether to coalesce it with its same-destination neighbours.

Size buckets follow the NCCL chunking observation (Demystifying NCCL):
transfer cost regimes switch by message size, so ops are bucketed and
only SMALL same-(destination, kind) ops coalesce into one batched
dispatch per cycle; large ops keep their own dispatch so one fat
gather never serializes behind a convoy of small ones.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

#: The four transfer kinds (see module table).
EVACUATION_KV = "evacuation_kv"
PREFIX_INSTALL = "prefix_install"
HANDOFF_KV = "handoff_kv"
SETTLE_PULL = "settle_pull"

TRANSFER_KINDS = (EVACUATION_KV, PREFIX_INSTALL, HANDOFF_KV, SETTLE_PULL)

#: Coalescing threshold: ops at or under this many bytes are "small"
#: and merge into one batched dispatch per (destination, kind) per
#: flush — the protocol-switch scale of the NCCL analysis (LL/LL128 vs
#: Simple sit near tens of KiB on real interconnects).
SMALL_OP_BYTES = 1 << 16

#: Size-bucket edges (bytes) for the by-bucket dispatch counters:
#: <=4KiB, <=64KiB, <=1MiB, bigger.
SIZE_BUCKETS = (1 << 12, 1 << 16, 1 << 20)
SIZE_BUCKET_LABELS = ("le4k", "le64k", "le1m", "gt1m")


def size_bucket(nbytes: int) -> str:
    """The bucket label of a payload size."""
    for edge, label in zip(SIZE_BUCKETS, SIZE_BUCKET_LABELS):
        if nbytes <= edge:
            return label
    return SIZE_BUCKET_LABELS[-1]


def array_nbytes(arrays: Any) -> int:
    """Total payload bytes of an array / nested container of arrays
    (dicts counted by value; non-array leaves count zero)."""
    if arrays is None or isinstance(arrays, (str, bytes)):
        return 0
    if hasattr(arrays, "nbytes"):
        return int(arrays.nbytes)
    if isinstance(arrays, dict):
        arrays = arrays.values()
    try:
        children = iter(arrays)
    except TypeError:
        return 0
    return sum(array_nbytes(child) for child in children)


@dataclass
class TransferOp:
    """One schedulable data movement (host bookkeeping only).

    ``dispatch`` starts the move device-side and must NOT block; the
    submitter keeps its own handle to the payload and calls
    :meth:`~..comms.scheduler.CollectiveScheduler.finish` at the moment
    the bytes are consumed host-side, closing the op's lifecycle
    ``transfer`` span.  Ops with no ``dispatch`` are accounting records
    for moves some jit already dispatched (handoff gathers, prefix
    installs).
    """

    kind: str
    destination: str
    nbytes: int
    #: routing endpoint the bytes LEAVE — ``shard:N`` / ``prefill`` /
    #: ``device`` — used only when a topology is attached (PR 18
    #: producers that never set it default to host staging)
    source: str = "host"
    #: request ids this move serves — each gets paired
    #: ``transfer``/``transfer_done`` lifecycle stamps
    rids: tuple = ()
    dispatch: Callable[[], Any] | None = None
    #: free-form context (rows, shard, entry index) for the trace
    args: dict = field(default_factory=dict)
    #: set by the scheduler at flush time
    dispatched: bool = False
    dispatched_t: float | None = None
    #: True once the flush that dispatched it ran inside the
    #: dispatch-ahead window (a block was in flight to hide behind)
    overlapped: bool = False
    finished: bool = False
    finished_t: float | None = None

    @property
    def bucket(self) -> str:
        return size_bucket(self.nbytes)

    @property
    def small(self) -> bool:
        return self.nbytes <= SMALL_OP_BYTES

    def coalesce_key(self) -> tuple:
        """Small ops sharing this key batch into one dispatch."""
        return (self.destination, self.kind)


def settle_pull_op(
    arrays: Any,
    *,
    destination: str = "host",
    source: str = "device",
    rids: Sequence[str] = (),
    args: dict | None = None,
) -> TransferOp:
    """A device→host pull of one or more device arrays, dispatched via
    ``copy_to_host_async`` on each (a no-op on backends without it)."""
    flat: list = []

    def _collect(node: Any) -> None:
        if node is None or isinstance(node, (str, bytes)):
            return
        if hasattr(node, "nbytes"):
            flat.append(node)
            return
        if isinstance(node, dict):
            node = node.values()
        for child in node:
            _collect(child)

    _collect(arrays)

    def _dispatch() -> None:
        for arr in flat:
            start = getattr(arr, "copy_to_host_async", None)
            if start is not None:
                try:
                    start()
                except Exception:
                    # a backend that cannot prefetch degrades to the
                    # blocking pull the settle path performs anyway
                    pass

    return TransferOp(
        kind=SETTLE_PULL,
        destination=destination,
        source=source,
        nbytes=array_nbytes(flat),
        rids=tuple(r for r in rids if r),
        dispatch=_dispatch,
        args=dict(args or {}),
    )
