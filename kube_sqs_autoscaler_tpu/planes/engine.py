"""The decode plane: gang-stepped shards + first-class draft-and-verify.

:class:`DecodePlaneBatcher` extends the sharded gang engine
(:class:`~..workloads.shard_plane.ShardedBatcher`) with the two
capabilities the disaggregated stack needs:

**Speculative decoding on the ``[S, B]`` plane.**  The fused engine
composes ``draft_layers`` only with the single plain batcher; here the
draft-and-verify round (:meth:`~..workloads.continuous.ContinuousBatcher
._make_spec_round`) runs over the WHOLE flat ``[S*B]`` row axis — the
round body is per-row by construction (``where(active, ...)`` gates
every advance), so the same compiled program serves all shards at once.
Speculative rows are *frozen on device* (``done=True, remaining=0``, the
same freeze :meth:`~..workloads.shard_plane.ShardedBatcher.kill_rows`
uses) so the unchanged gang block skips them; their liveness is the
host-side per-slot mode mark instead.  A cycle therefore dispatches at
most one spec round (over the spec rows) plus one gang block (over the
plain rows) — plain rows pay zero extra dispatches when drafting is off.

**Drain-to-plain.**  ``set_speculative`` flips :attr:`draft_enabled`
live: the mode is fixed per request AT ADMISSION, so in-flight drafted
rows finish their speculative lives while every new admission lands
plain (or vice versa) — no mid-request engine switch, and greedy
parity per request is preserved in both directions because greedy
draft-and-verify emits exactly the plain greedy continuation.

**The KV handoff transport** (:meth:`DecodePlaneBatcher.submit_handoff`)
adopts finished prefill rows from a prefill-plane batcher without
re-running any model forward: one jitted full-row cache copy per
handoff batch (every cache entry keys the row on axis 0, so the copy is
layout-agnostic across gpt/llama/int8), plus the per-row
length/pending/liveness arming that ``submit_resume``'s insert would
have folded in.  Because the batched prefill is batch-invariant, the
adopted rows decode bitwise what a fused engine would have produced —
the disagg parity gate in ``bench.py --suite disagg`` pins this.  When
drafting is on, the draft rows adopt the first ``spec_layers`` layers
of the SAME donor rows (the early-exit self-draft's cache is a layer
prefix of the target's — :func:`~..workloads.speculative
.draft_prefix_from_target`'s identity), so a handoff seeds both planes
in the one device call.
"""

from __future__ import annotations

import collections
import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..workloads.continuous import (
    _Slot,
    _bounded_tenant_key,
    _rows_prefill,
    _splice_rows_layers,
)
from ..workloads.shard_plane import ShardedBatcher


def _draft_rows_impl(
    dparams: dict,
    dcache: dict,
    rows: jax.Array,
    prompts: jax.Array,
    lengths: jax.Array,
    config: Any,
    prompt_len: int,
    n_rows: int,
    family: str = "gpt",
    quantized_kv: bool = False,
) -> dict:
    """Seed the draft cache for a speculative admission batch: the SAME
    ``[M, P]`` batched prefill as the target insert, run through the
    draft's layer-sliced params, spliced into the draft cache's rows.
    The logits are never used — XLA drops the head matmul — so this is
    ``spec_layers / n_layers`` of an admission insert's FLOPs."""
    _, rows_cache = _rows_prefill(
        dparams, prompts, lengths, config, family, quantized_kv, 0, None
    )
    new_layers = _splice_rows_layers(dcache, rows_cache, rows, 0,
                                     prompt_len, n_rows)
    new_lengths = dcache["length"].at[rows].set(lengths)
    return {"layers": new_layers, "length": new_lengths}


_draft_rows = partial(
    jax.jit,
    static_argnames=("config", "prompt_len", "n_rows", "family",
                     "quantized_kv"),
    donate_argnums=(1,),
)(_draft_rows_impl)


def _handoff_rows_impl(
    cache: dict,
    dcache: dict | None,
    current: jax.Array,
    done: jax.Array,
    remaining: jax.Array,
    src_cache: dict,
    rows: jax.Array,
    src_idx: jax.Array,
    lasts: jax.Array,
    budgets: jax.Array,
    spec: bool = False,
    spec_layers: int = 0,
) -> tuple[dict, dict | None, jax.Array, jax.Array, jax.Array]:
    """The KV handoff: adopt ``n`` finished prefill rows from a donor
    cache into this plane's slot rows — a pure device copy, no model
    forward.  Per entry the row moves whole (``[H, S, D]`` values and
    ``[H, S]`` scales alike key the row on axis 0); positions past the
    donor's per-row ``length`` are garbage on both sides, exactly as
    they are after a native insert.  The per-row state arms like the
    resume insert's fold: ``length`` copies the donor's, ``current``
    takes the last produced token, and the gang-liveness masks arm live
    (plain rows) or frozen (speculative rows, which the host steps via
    draft-and-verify rounds instead)."""
    src_lengths = src_cache["length"][src_idx]
    new_layers = [
        {name: buf.at[rows].set(src_layer[name][src_idx])
         for name, buf in layer.items()}
        for layer, src_layer in zip(cache["layers"], src_cache["layers"])
    ]
    cache = {"layers": new_layers,
             "length": cache["length"].at[rows].set(src_lengths)}
    current = current.at[rows].set(lasts)
    if spec:
        d_layers = [
            {name: buf.at[rows].set(src_layer[name][src_idx])
             for name, buf in layer.items()}
            for layer, src_layer in zip(dcache["layers"],
                                        src_cache["layers"][:spec_layers])
        ]
        dcache = {"layers": d_layers,
                  "length": dcache["length"].at[rows].set(src_lengths)}
        done = done.at[rows].set(True)
        remaining = remaining.at[rows].set(0)
    else:
        done = done.at[rows].set(False)
        remaining = remaining.at[rows].set(budgets)
    return cache, dcache, current, done, remaining


_handoff_rows = partial(
    jax.jit,
    static_argnames=("spec", "spec_layers"),
    donate_argnums=(0, 1, 2, 3, 4),
)(_handoff_rows_impl)


class DecodePlaneBatcher(ShardedBatcher):
    """The sharded gang engine with speculative rows and KV adoption.

    Constructed exactly like :class:`~..workloads.shard_plane
    .ShardedBatcher` plus ``spec_layers``/``spec_tokens`` — the
    early-exit self-draft depth and proposal width.  ``spec_layers=0``
    builds a pure disaggregation target (handoff transport, no
    drafting).  The base engine is constructed on the PLAIN path
    (``draft_layers=0``): every inherited program — the ``[M, P]``
    insert, the resume insert, the gang block, ``adopt_engine``,
    evacuation — works untouched, and rows only become speculative
    through this class's admission overrides.

    Single-chip for now (like the prefix pool): the spec round and the
    handoff copy are not mesh-sharded.
    """

    def __init__(
        self,
        params: Any,
        config: Any,
        *,
        shards: int,
        shard_slots: int,
        prompt_len: int,
        generate_tokens: int,
        spec_layers: int = 0,
        spec_tokens: int = 4,
        draft_enabled: bool | None = None,
        **kwargs,
    ) -> None:
        if kwargs.get("mesh") is not None and spec_layers:
            raise ValueError(
                "the speculative decode plane is single-chip for now "
                "(the spec round and handoff copy are not mesh-sharded)"
            )
        if spec_layers:
            if not 0 < spec_layers < config.n_layers:
                raise ValueError(
                    f"spec_layers={spec_layers} must be in "
                    f"[1, n_layers-1] (model has n_layers="
                    f"{config.n_layers})"
                )
            if spec_tokens < 1:
                raise ValueError(
                    f"spec_tokens={spec_tokens} must be >= 1"
                )
            if kwargs.get("prefix_cache") is not None:
                raise ValueError(
                    "spec_layers does not combine with a global "
                    "prefix_cache (the draft cache has no prefix rows)"
                )
            # speculative rounds overshoot like the fused spec engine:
            # up to k past the budget, writing k+1 masked positions past
            # the frozen length — reserve the same 2k slack
            budget = prompt_len + generate_tokens + 2 * spec_tokens
            if budget > config.max_seq_len:
                raise ValueError(
                    f"prompt_len + generate_tokens + 2*spec_tokens = "
                    f"{budget} exceeds max_seq_len={config.max_seq_len}"
                )
        super().__init__(
            params, config, shards=shards, shard_slots=shard_slots,
            prompt_len=prompt_len, generate_tokens=generate_tokens,
            **kwargs,
        )
        rows = shards * shard_slots
        self.spec_layers = spec_layers
        self.spec_tokens = spec_tokens
        # per-slot admission mode: True = the row decodes by
        # draft-and-verify rounds (device-frozen for the gang).  Fixed
        # at admission; a live set_speculative flip changes only what
        # NEW admissions get — the drain-to-plain contract.
        self._slot_spec = [False] * rows
        # handoff transport counter (the plane_kv_transfers_total family)
        self.kv_transfers = 0
        # per-tenant accept-rate attribution, bounded like every other
        # per-tenant series
        self.tenant_spec_rounds: dict[str, int] = {}
        self.tenant_spec_accepted: dict[str, int] = {}
        # rolling per-round accepted counts — the measured-economics
        # signal the knob policy flips drafting on
        self._accept_window: collections.deque[int] = collections.deque(
            maxlen=256
        )
        self.spec_flips = 0
        if spec_layers:
            self.draft_config = dataclasses.replace(
                config, n_layers=spec_layers
            )
            self.draft_params = dict(
                params, layers=params["layers"][:spec_layers]
            )
            if self.quantized_kv:
                from ..workloads.decode import init_quantized_cache

                self.draft_cache = init_quantized_cache(
                    self.draft_config, rows,
                    kv_heads=(config.n_kv_heads if self.family == "llama"
                              else None),
                )
            elif self.family == "llama":
                from ..workloads.llama import init_llama_cache

                self.draft_cache = init_llama_cache(self.draft_config,
                                                    rows)
            else:
                from ..workloads.decode import init_cache

                self.draft_cache = init_cache(self.draft_config, rows)
            # the spec-round builder reads draft_tokens/draft_config;
            # draft_layers stays 0 so every inherited plain-path check
            # (submit_resume, adopt_engine, step routing) keeps treating
            # this engine as the plain plane it extends
            self.draft_tokens = spec_tokens
            self._spec = self._make_spec_round()
            self.draft_enabled = (
                True if draft_enabled is None else bool(draft_enabled)
            )
        else:
            self.draft_cache = None
            self.draft_enabled = False

    # ------------------------------------------------------------------
    # Engine identity / adoption
    # ------------------------------------------------------------------

    def _engine_key(self) -> tuple:
        return super()._engine_key() + (self.spec_layers, self.spec_tokens)

    def adopt_engine(self, source) -> None:
        if not isinstance(source, DecodePlaneBatcher):
            raise ValueError(
                "a decode plane adopts from a decode-plane donor only"
            )
        super().adopt_engine(source)  # validates the full engine key
        if self.spec_layers:
            self._spec = source._spec

    # ------------------------------------------------------------------
    # Admission: per-row mode marks ride every admission path
    # ------------------------------------------------------------------

    def submit_many(self, requests):
        rows = super().submit_many(requests)
        if not (self.draft_enabled and rows):
            for row in rows:
                self._slot_spec[row] = False
            return rows
        # drafted admission: the inherited plain insert already seeded
        # the target cache, the pending first token, and the slots; add
        # the draft plane's prefill and freeze the rows out of the gang
        padded = [self._pad_prompt(ids) for ids, _ in requests]
        prompts = np.stack([ids for ids, _ in padded])
        lengths = np.asarray([ln for _, ln in padded], np.int32)
        self.draft_cache = _draft_rows(
            self.draft_params, self.draft_cache,
            jnp.asarray(rows, jnp.int32), jnp.asarray(prompts),
            jnp.asarray(lengths), config=self.draft_config,
            prompt_len=self.prompt_len, n_rows=len(rows),
            family=self.family, quantized_kv=self.quantized_kv,
        )
        self.insert_dispatches += 1
        self.kill_rows(rows)  # device-freeze: spec rows skip the gang
        for row in rows:
            self._slot_spec[row] = True
        return rows

    def submit_resume(self, resumes):
        # resumed rows always decode plain: greedy draft-and-verify
        # emits the plain greedy continuation, so a drafted first life
        # resumes bit-exact on the plain path — and the resume insert
        # is the plain program
        rows = super().submit_resume(resumes)
        for row in rows:
            self._slot_spec[row] = False
        return rows

    def submit_many_prefixed(self, requests):
        # pooled-prefix admissions stay plain (the draft cache has no
        # pool rows); drafting composes with tenancy through the plain
        # tag_tenant path and the handoff path
        rows = super().submit_many_prefixed(requests)
        for row in rows:
            self._slot_spec[row] = False
        return rows

    def submit_handoff(self, donor, handoffs: list[tuple]) -> list[int]:
        """Adopt finished prefill rows from ``donor`` (a plain
        :class:`~..workloads.continuous.ContinuousBatcher` the prefill
        plane runs) into this plane's free slots.

        Each handoff is ``(src_row, payload, produced, budget,
        submitted_at, tenant)`` — the donor row index plus the
        ``submit_resume`` record.  ONE jitted device copy moves the
        whole batch's KV (target + draft rows when drafting is on) and
        arms the per-row state; no forward pass runs, so a handoff
        costs memory bandwidth, not FLOPs.  Rows route freest-first
        through the same admission plane as every other path.  TTFT is
        not re-recorded: the first token was produced (and timed) on
        the prefill plane."""
        if not handoffs:
            return []
        if donor.config is not self.config \
                or donor.family != self.family \
                or donor.quantized_kv != self.quantized_kv:
            raise ValueError(
                "a KV handoff needs the donor's exact config/family/"
                "layout (the cache rows must be layout-identical)"
            )
        if donor.mesh is not None or self.mesh is not None:
            raise ValueError("the KV handoff transport is single-chip")
        free = self.free_slots
        if len(handoffs) > len(free):
            raise RuntimeError(
                f"no free slot for {len(handoffs)} handoff(s) "
                f"({len(free)} free); the pool must cap handoffs by "
                "free_slots"
            )
        rows = free[: len(handoffs)]
        src_idx, lasts, budgets = [], [], []
        for src_row, _, produced, budget, _, _ in handoffs:
            if not 0 < len(produced) < budget:
                raise ValueError(
                    f"handoff row produced {len(produced)} of budget "
                    f"{budget} tokens — a handoff carries a started, "
                    "unfinished request"
                )
            if self.eos_id is not None and produced[-1] == self.eos_id:
                raise ValueError(
                    "a completed (eos) request settles on the prefill "
                    "plane, it does not hand off"
                )
            src_idx.append(src_row)
            lasts.append(produced[-1])
            budgets.append(budget - len(produced))
        spec = bool(self.spec_layers) and self.draft_enabled
        handoff_t0 = (
            self.lifecycle.now_fn() if self.lifecycle is not None else None
        )
        (self.cache, self.draft_cache, self._current, self._done,
         self._remaining) = _handoff_rows(
            self.cache, self.draft_cache, self._current, self._done,
            self._remaining, donor.cache, jnp.asarray(rows, jnp.int32),
            jnp.asarray(src_idx, jnp.int32), jnp.asarray(lasts, jnp.int32),
            jnp.asarray(budgets, jnp.int32), spec=spec,
            spec_layers=self.spec_layers,
        )
        self.insert_dispatches += 1
        self.kv_transfers += len(rows)
        if self.comms is not None and self.comms.enabled:
            from ..comms.ops import HANDOFF_KV

            self.comms.record(
                HANDOFF_KV, "decode-plane",
                source="prefill",
                nbytes=self._row_kv_nbytes() * len(rows),
                args={"rows": len(rows)},
            )
        for row, (_, payload, produced, budget, submitted_at,
                  tenant) in zip(rows, handoffs):
            self.slots[row] = _Slot(
                busy=True, budget=budget, payload=payload,
                produced=list(produced), submitted_at=submitted_at,
                tenant=tenant, ttft_done=True,
            )
            self._slot_spec[row] = spec
            if self.lifecycle is not None:
                # the KV landed in a decode slot: the handoff phase
                # (first_token -> here) closes — decode-plane time
                # starts now.  Same dispatch either way; the stamp is
                # host bookkeeping on a copy that already happened.
                from ..obs.lifecycle import request_key

                rid = request_key(payload)
                self.lifecycle.stamp(
                    rid, "handoff", tenant=tenant or None,
                )
                # the KV gather is itself a transfer: a paired window
                # on the request's trace (previously only a fleet
                # "kv-handoff" instant existed), so attribute_slo can
                # name transfer-bound requests and the Perfetto export
                # renders the move on the transfers lane
                self.lifecycle.stamp(rid, "transfer", t=handoff_t0)
                self.lifecycle.stamp(rid, "transfer_done")
                self.lifecycle.note(rid, "transfer_handoff_kv")
        self._invalidate_admission_cache()
        return rows

    # ------------------------------------------------------------------
    # The mixed engine cycle: one spec round + one gang block
    # ------------------------------------------------------------------

    def _step_gang(self):
        spec_shards = None
        if self.spec_layers:
            mask = [
                self._slot_spec[row] and self._needs_decode(slot)
                for row, slot in enumerate(self.slots)
            ]
            if any(mask):
                handle = self._dispatch_spec_round(mask)
                # first tokens must land in slot.produced BEFORE round
                # tokens (the plain spec engine settles firsts first,
                # too) — the settle's host work overlaps the round's
                # device time
                self._settle_pending_firsts()
                spec_shards = self._consume_plane_spec_round(mask, handle)
        finished = super()._step_gang()
        if spec_shards:
            # spec emission IS shard progress: without this a shard
            # holding only drafted rows (device-frozen, gang count 0)
            # would trip the no-progress stall sentinel
            for s in spec_shards:
                self.shard_stall_cycles[s] = 0
        return finished

    def _consume_plane_spec_round(self, mask, handle) -> set[int]:
        """The fused engine's round consume plus the plane's
        attribution: per-shard token counts (the per-shard tokens/s
        gauges and the stall sentinel) and the bounded per-tenant
        accept-rate series."""
        toks_host, n_host = jax.device_get(handle)
        self.host_transfers += 1
        progressed: set[int] = set()
        for row, slot in enumerate(self.slots):
            if not mask[row]:
                continue
            n = int(n_host[row])
            slot.rounds += 1
            slot.accepted += n
            self.spec_rounds += 1
            self.spec_accepted += n
            self._accept_window.append(n)
            if slot.tenant:
                tenant = _bounded_tenant_key(
                    slot.tenant, self.tenant_spec_rounds
                )
                self.tenant_spec_rounds[tenant] = (
                    self.tenant_spec_rounds.get(tenant, 0) + 1
                )
                self.tenant_spec_accepted[tenant] = (
                    self.tenant_spec_accepted.get(tenant, 0) + n
                )
            shard = row // self.shard_slots
            emitted = 0
            for token in toks_host[row, : n + 1]:
                if slot.done or len(slot.produced) >= slot.budget:
                    break
                self._emit(slot, int(token))
                emitted += 1
            self.shard_tokens[shard] += emitted
            if emitted:
                progressed.add(shard)
        return progressed

    # ------------------------------------------------------------------
    # The speculative knob: drain-to-plain
    # ------------------------------------------------------------------

    def set_speculative(self, enabled: bool) -> None:
        """Flip draft-and-verify for NEW admissions, live.

        Unlike the fused spec engine's overlap toggle, this is a full
        mode switch with drain semantics: rows admitted while drafting
        was on finish their speculative lives (their device rows are
        already frozen out of the gang), rows admitted after the flip
        decode plain through the gang — and symmetrically for flipping
        on.  Greedy parity per request holds through the flip in both
        directions because each row's whole life runs in one mode."""
        if not self.spec_layers:
            raise ValueError(
                "the speculative knob needs a drafted decode plane "
                "(spec_layers > 0)"
            )
        enabled = bool(enabled)
        if enabled != self.draft_enabled:
            self.spec_flips += 1
        self.draft_enabled = enabled

    # ------------------------------------------------------------------
    # Measured economics
    # ------------------------------------------------------------------

    def accept_rate(self, tenant: str | None = None) -> float | None:
        """Lifetime accepted-draft fraction in ``[0, 1]`` (``None``
        before any round): accepted drafts over proposed drafts,
        overall or for one (bounded) tenant label."""
        if tenant is None:
            rounds, accepted = self.spec_rounds, self.spec_accepted
        else:
            key = _bounded_tenant_key(tenant, self.tenant_spec_rounds)
            rounds = self.tenant_spec_rounds.get(key, 0)
            accepted = self.tenant_spec_accepted.get(key, 0)
        if not rounds:
            return None
        return accepted / (rounds * self.spec_tokens)

    def recent_accept_rate(self) -> float | None:
        """Accept rate over the rolling round window — the signal the
        knob policy compares against the drafting break-even point."""
        if not self._accept_window:
            return None
        return (
            sum(self._accept_window)
            / (len(self._accept_window) * self.spec_tokens)
        )
