"""DisaggregatedPool: both planes behind one Scaler-shaped seam each.

The disaggregated deployment is a :class:`~..fleet.pool.WorkerPool` of
prefill replicas (the cheap axis: by-reference params + program
adoption make a spawn ~ms) FUSED to one gang-stepped decode plane (a
:class:`~.engine.DecodePlaneBatcher` behind a
:class:`~..fleet.worker.FleetWorker`, wrapped in a
:class:`~..fleet.sharded.ShardedWorkerPool` so decode capacity is the
same O(1) shard-mask flips the sharded plane already actuates).  Two
independent :class:`~..core.types.Scaler` targets result:

- the pool ITSELF scales the prefill plane (``scale_up``/``scale_down``
  spawn/drain prefill replicas — inherited verbatim from
  ``WorkerPool``, so the actuator contract's fingerprint is identical
  by construction);
- :attr:`decode_pool` scales the decode plane (shard-active mask
  flips, ``ShardedWorkerPool`` semantics verbatim).

One admission surface: only prefill replicas poll the queue.  Each
fleet cycle the pool supervises and steps the prefill plane, then
moves every started-but-unfinished row across the KV handoff transport
(:meth:`~.engine.DecodePlaneBatcher.submit_handoff`) — capped by the
decode plane's free slots, donor rows freed only AFTER the copy is
dispatched — and then steps the decode plane, which settles replies.
Requests that complete AT prefill (budget-1, eos on the first token)
settle there and never hand off.

Exactly-once holds through every handoff because both planes settle
through the ONE reply registry this pool inherits from
:class:`~..fleet.pool.FleetPoolBase`: a prefill replica killed
mid-request re-dispatches its un-handed-off rows to surviving prefill
replicas (the inherited supervisor), a visibility-timeout redelivery
of a request the decode plane already owns re-prefills and re-hands
off — and the registry suppresses whichever reply lands second.  The
decode plane itself is a single failure domain, like the sharded
plane: no kill/hang failover inside it; whole-plane loss is the
queue's visibility timeout's job.

Jax-free (like ``fleet``): the actuator-contract tests drive this pool
with stub workers; real planes are wired by :meth:`DisaggregatedPool
.serving`.
"""

from __future__ import annotations

import logging
from typing import Any, Callable

from ..fleet.pool import DRAINING, SERVING, WorkerPool, _free_count
from ..fleet.sharded import ShardedWorkerPool

log = logging.getLogger(__name__)

# core.durable snapshot section: the disaggregated pool's reply
# registry + plane mode (draft_enabled) ride controller snapshots so a
# restarted controller neither re-answers an answered request nor
# forgets a measured-economics drafting decision.
DISAGG_SECTION = "disagg_pool"


class DisaggregatedPool(WorkerPool):
    """A supervised prefill-replica pool shuttling KV to one decode plane.

    ``prefill_factory(pool)`` builds one prefill replica (real fleets:
    a :class:`~.prefill.PrefillWorker`; the contract test a stub).
    ``decode_factory(pool)`` is called ONCE for the decode-plane worker
    — a :class:`~..fleet.worker.FleetWorker` over a
    :class:`~.engine.DecodePlaneBatcher` with ``pool=<this pool>`` so
    its settles dedup through the shared registry; it is built AFTER
    the initial prefill spawns, and its admission is forced off (the
    prefill plane is the only queue consumer).

    ``min``/``max``/``scale_up_pods``/``scale_down_pods`` govern the
    prefill plane (the inherited Scaler seam); the ``decode_*`` twins
    govern :attr:`decode_pool`'s shard mask.
    """

    def __init__(
        self,
        prefill_factory: Callable[["DisaggregatedPool"], Any],
        decode_factory: Callable[["DisaggregatedPool"], Any],
        *,
        min: int,
        max: int,
        decode_min: int = 1,
        decode_max: int | None = None,
        decode_initial: int | None = None,
        decode_scale_up_pods: int = 1,
        decode_scale_down_pods: int = 1,
        decode_steps_per_cycle: int = 2,
        **pool_kwargs,
    ) -> None:
        if decode_steps_per_cycle < 1:
            raise ValueError("decode_steps_per_cycle must be >= 1")
        super().__init__(prefill_factory, min=min, max=max, **pool_kwargs)
        self.decode_steps_per_cycle = decode_steps_per_cycle
        # the decode plane: ONE worker, capacity actuated as shard-mask
        # flips.  The inner pool's own reply registry goes unused — the
        # worker's ``pool`` reference (this pool) is what its settle
        # path consults — so the exactly-once surface stays single.
        self.decode_pool = ShardedWorkerPool(
            lambda _inner: decode_factory(self),
            min=decode_min, max=decode_max, initial=decode_initial,
            scale_up_pods=decode_scale_up_pods,
            scale_down_pods=decode_scale_down_pods,
            clock=self.clock,
        )
        self.decode = self.decode_pool.worker
        # one admission surface: the decode plane never polls the queue
        self.decode.admitting = False
        self.kv_handoffs_total = 0

    # ------------------------------------------------------------------
    # The fleet cycle: supervise -> prefill -> handoff -> decode
    # ------------------------------------------------------------------

    def run_cycle(self) -> int:
        """One disaggregated cycle; returns requests completed on both
        planes.  Prefill replicas step first (admission + batched
        insert + settle-at-prefill), the KV shuttle moves every ready
        row the decode plane has a slot for, the decode plane steps its
        gang (spec rounds + gang block) and settles replies, and
        draining prefill replicas retire once empty — their last rows
        leave through the same shuttle."""
        self.cycle += 1
        self._supervise()
        done = 0
        serving: list = []
        draining: list = []
        for replica in self.members:
            if replica.state == SERVING:
                serving.append(replica)
            elif replica.state == DRAINING:
                draining.append(replica)
        serving.sort(
            key=lambda r: _free_count(r.worker.batcher), reverse=True
        )
        for replica in serving:
            if self._orphans:
                self._dispatch_orphans(replica)
            done += replica.worker.run_once()
        for replica in draining:
            done += replica.worker.run_once()
        # the KV shuttle: draining replicas first (their rows are the
        # ones blocking a retire), then serving freest-last so the
        # busiest prefill replica unloads first.  The decode plane's
        # gang cadence is decoupled from the poll/admission cadence —
        # it steps ``decode_steps_per_cycle`` times per fleet cycle,
        # with a shuttle before each step so slots freed by one gang
        # settle refill before the next.  The fused engine cannot do
        # this: its iteration interleaves admission, so its decode
        # cadence is pinned to the poll cadence.  This is half the
        # disaggregation win (the other half is inserts never queueing
        # behind gang blocks).
        order = draining + serving[::-1]
        self._shuttle(order)
        done += self.decode_pool.run_cycle()
        for _ in range(self.decode_steps_per_cycle - 1):
            self._shuttle(order)
            done += self.decode.run_once()
        for replica in draining:
            if replica.worker.batcher.active == 0:
                self._retire(replica, released=0)
            elif (
                self.drain_timeout_cycles is not None
                and replica.drain_started_cycle is not None
                and self.cycle - replica.drain_started_cycle
                >= self.drain_timeout_cycles
            ):
                released = replica.worker.release_inflight()
                self.released_total += released
                self._retire(replica, released=released)
        self._prune_retired()
        self._update_metrics()
        return done

    def _shuttle(self, replicas: list) -> int:
        """Move ready prefill rows to decode slots: per donor replica
        one :meth:`~.engine.DecodePlaneBatcher.submit_handoff` batch
        (one jitted device copy), capped by the decode plane's live
        free-slot count, donor rows freed only after the copy is
        dispatched.  Returns rows moved."""
        batcher = self.decode.batcher
        submit = getattr(batcher, "submit_handoff", None)
        if submit is None:  # contract-test stubs: no handoff surface
            return 0
        free = _free_count(batcher)
        moved = 0
        for replica in replicas:
            worker = replica.worker
            ready = getattr(worker, "ready_handoffs", None)
            if ready is None:
                continue
            all_ready = ready()
            if not all_ready:
                continue
            # rows awaiting a decode slot are backpressure, not a
            # wedge — don't let the progress watchdog count this
            # replica as stalled while the decode plane is the
            # bottleneck.  (A truly hung replica is still caught: its
            # ready rows shuttle away — the shuttle acts on the
            # batcher, not the wedged worker loop — and the idle-wedge
            # watchdog fires on the frozen refill counter.)
            replica.stalled_cycles = 0
            if free <= 0:
                continue
            records = all_ready[:free]
            submit(worker.batcher, records)
            worker.complete_handoff([rec[0] for rec in records])
            free -= len(records)
            moved += len(records)
            self._event(
                "kv-handoff", replica=replica.index, rows=len(records),
            )
        self.kv_handoffs_total += moved
        return moved

    # ------------------------------------------------------------------
    # Fleet-wide accounting spans both planes
    # ------------------------------------------------------------------

    @property
    def processed(self) -> int:
        return super().processed + self.decode.processed

    @property
    def completed_by_tenant(self) -> dict[str, int]:
        totals = dict(super().completed_by_tenant)
        for tenant, count in getattr(
            self.decode, "completed_by_tenant", {}
        ).items():
            totals[tenant] = totals.get(tenant, 0) + count
        return totals

    @property
    def idle(self) -> bool:
        # a prefilled row awaiting handoff keeps its prefill slot busy,
        # so prefill-side idleness already covers the shuttle
        return (
            super().idle
            and self.decode.batcher.active == 0
            and getattr(self.decode, "staged", 0) == 0
        )

    def stop_all(self) -> None:
        super().stop_all()  # prefill replicas release + retire
        self.decode_pool.stop_all()

    # ------------------------------------------------------------------
    # Durable-state surface (core/durable.py, section DISAGG_SECTION):
    # the shared reply registry (FleetPoolBase) plus the one plane-mode
    # bit a restart must not forget — whether measured economics had
    # drafting on.  Replica/shard counts deliberately do NOT ride the
    # snapshot (same philosophy as the sharded pool: the autoscaler
    # re-derives them through the ordinary gates).
    # ------------------------------------------------------------------

    def export_state(self) -> dict:
        state = super().export_state()
        state["kv_handoffs_total"] = self.kv_handoffs_total
        draft = getattr(self.decode.batcher, "draft_enabled", None)
        if draft is not None:
            state["draft_enabled"] = bool(draft)
        return state

    def import_state(
        self, state: dict, *, rebase: float = 0.0,
        now: float | None = None, max_age_s: float = 0.0,
    ) -> int:
        recovered = super().import_state(
            state, rebase=rebase, now=now, max_age_s=max_age_s
        )
        self.kv_handoffs_total = int(state.get("kv_handoffs_total", 0) or 0)
        draft = state.get("draft_enabled")
        batcher = self.decode.batcher
        if draft is not None and getattr(batcher, "spec_layers", 0):
            # silent restore (not set_speculative: a rehydration is not
            # a knob flip and must not count one)
            batcher.draft_enabled = bool(draft)
        return recovered

    # ------------------------------------------------------------------
    # Observability: the inherited per-replica fleet gauges cover the
    # prefill plane; add the plane-level families
    # ------------------------------------------------------------------

    def _update_metrics(self) -> None:
        super()._update_metrics()
        if self.metrics is None:
            return
        self.metrics.set_gauge(
            "plane_prefill_replicas", self.replicas,
            "Serving prefill-plane replicas (the pool Scaler's axis).",
        )
        self.metrics.set_gauge(
            "plane_decode_shards", self.decode_pool.replicas,
            "Serving decode-plane shards (the decode Scaler's axis).",
        )
        self.metrics.set_gauge(
            "plane_kv_transfers_total", self.kv_handoffs_total,
            "KV rows handed from the prefill plane to decode slots over "
            "the pool shuttle.",
            kind="counter",
        )

    def attach_metrics(self, metrics) -> None:
        self.decode_pool.metrics = metrics
        super().attach_metrics(metrics)

    def attach_lifecycle(self, registry) -> None:
        """One registry across BOTH planes: a disaggregated request's
        chain runs arrival→first_token on a prefill replica, handoff on
        the decode plane, completed/reply on the decode worker — split
        registries would each see half a chain and fail the
        completeness audit by construction."""
        self.decode_pool.attach_lifecycle(registry)
        super().attach_lifecycle(registry)

    def attach_comms(self, comms) -> None:
        """Wire one :class:`~..comms.CollectiveScheduler` through both
        planes' engines (current members; attach before serving): the
        decode plane's settle pulls ride the gang's dispatch-ahead
        window, the prefill replicas' settle pulls ride their block
        windows, and every KV handoff records its bytes on the shared
        counter family.  Detached (the default) the shuttle keeps its
        fleet instants and nothing else changes."""
        self.comms = comms
        topology = getattr(comms, "topology", None)
        if topology is not None:
            # the handoff endpoints must be routable before the first
            # shuttle move plans a path (lazily they'd join with the
            # same host-grade links — this just makes /debug/topology
            # complete from the start)
            topology.ensure_node("prefill")
            topology.ensure_node("decode-plane")
        attach = getattr(self.decode.batcher, "attach_comms", None)
        if attach is not None:
            attach(comms)
        for replica in self.members:
            batcher = getattr(replica.worker, "batcher", None)
            attach = getattr(batcher, "attach_comms", None)
            if attach is not None:
                attach(comms)

    # ------------------------------------------------------------------
    # Real-plane construction
    # ------------------------------------------------------------------

    @classmethod
    def serving(  # type: ignore[override]
        cls,
        queue,
        params,
        model_config,
        service_config,
        *,
        min: int,
        max: int,
        decode_shards: int,
        decode_min: int = 1,
        spec_layers: int = 1,
        spec_tokens: int = 4,
        draft_enabled: bool | None = None,
        family: str = "gpt",
        tokenizer=None,
        result_queue=None,
        now_fn=None,
        tenancy=None,
        prefill_engine_source=None,
        decode_engine_source=None,
        **pool_kwargs,
    ) -> "DisaggregatedPool":
        """Real planes over one shared queue: ``min``..``max``
        :class:`~.prefill.PrefillWorker` replicas (params shared by
        reference, programs adopted from the first — a spawn is ~ms)
        feeding a ``decode_shards``-shard
        :class:`~.engine.DecodePlaneBatcher` behind one
        :class:`~..fleet.worker.FleetWorker`.

        The decode plane is ALWAYS built drafted (``spec_layers >= 1``):
        plain decode is ``draft_enabled=False`` — a drain-to-plain MODE
        of the same engine, not a different build — so the handoff
        surface and the live speculative knob exist in every
        disaggregated deployment."""
        import dataclasses

        if spec_layers < 1:
            raise ValueError(
                "the decode plane is built drafted (spec_layers >= 1); "
                "run plain via draft_enabled=False, not spec_layers=0"
            )

        def prefill_factory(pool: "DisaggregatedPool"):
            from .prefill import PrefillWorker

            seeded = dataclasses.replace(
                service_config,
                sample_seed=service_config.sample_seed
                + pool.next_spawn_ordinal(),
            )
            return PrefillWorker(
                queue, params, model_config, seeded,
                family=family, tokenizer=tokenizer,
                result_queue=result_queue, pool=pool, tenancy=tenancy,
                now_fn=now_fn,
                engine_source=pool.engine_donor() or prefill_engine_source,
            )

        def decode_factory(pool: "DisaggregatedPool"):
            from ..fleet.worker import FleetWorker

            seeded = dataclasses.replace(
                service_config, shards=decode_shards,
            )
            worker = FleetWorker(
                queue, params, model_config, seeded,
                family=family, tokenizer=tokenizer,
                result_queue=result_queue, pool=pool, tenancy=tenancy,
                now_fn=now_fn, sharded=True,
                draft_layers=spec_layers, draft_tokens=spec_tokens,
                engine_source=decode_engine_source,
            )
            if draft_enabled is not None and spec_layers:
                worker.batcher.set_speculative(draft_enabled)
                worker.batcher.spec_flips = 0  # construction, not a flip
            return worker

        return cls(
            prefill_factory, decode_factory, min=min, max=max,
            decode_min=decode_min, decode_max=decode_shards,
            decode_initial=decode_shards, **pool_kwargs,
        )
