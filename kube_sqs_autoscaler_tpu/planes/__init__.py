"""Disaggregated prefill/decode planes behind one admission surface.

The fused serving plane makes prefill and decode contend for the same
gang slots even though they scale on different axes (prefill is
admission-rate bound, decode is token-rate bound).  This package splits
them:

- :mod:`.prefill` — prefill workers run ONLY the batched ``[M, P]``
  admission insert (never a decode dispatch) and surface finished rows'
  KV for handoff; params are shared by reference and compiled programs
  by :meth:`~..workloads.continuous.ContinuousBatcher.adopt_engine`, so
  a prefill replica spins up in ~ms;
- :mod:`.engine` — the decode plane: the sharded gang engine plus
  first-class draft-and-verify (gang-stepped speculative rounds on the
  ``[S, B]`` plane, per-tenant accept rate, live drain-to-plain) and
  the ``submit_handoff`` KV transport that adopts a prefill row's cache
  without re-running the forward pass;
- :mod:`.pool` — :class:`~.pool.DisaggregatedPool`: both planes as
  independent :class:`~..core.types.Scaler` targets through the
  unchanged ``ControlLoop``/``sched`` seams, exactly-once replies
  through the shared reply registry.

``planes.pool`` is jax-free (like ``fleet``) so the actuator-contract
tests drive it with stub workers; the jax engines import lazily.
"""

from .pool import DISAGG_SECTION, DisaggregatedPool

__all__ = ["DisaggregatedPool", "DISAGG_SECTION", "DecodePlaneBatcher",
           "PrefillWorker"]


def __getattr__(name):  # lazy: keep `import planes` jax-free
    if name == "DecodePlaneBatcher":
        from .engine import DecodePlaneBatcher

        return DecodePlaneBatcher
    if name == "PrefillWorker":
        from .prefill import PrefillWorker

        return PrefillWorker
    raise AttributeError(name)
