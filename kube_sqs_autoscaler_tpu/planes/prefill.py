"""The prefill plane: admission-only workers that hand KV to decode.

A :class:`PrefillWorker` is a :class:`~..fleet.worker.FleetWorker` whose
engine cycle never dispatches a decode step: it pulls queue traffic,
runs the batched ``[M, P]`` admission insert (the ONE compiled program
this plane needs), settles the deferred first tokens — time-to-first-
token is measured HERE, which is the disaggregation win: a saturated
decode plane no longer queues prefills behind gang blocks — and then
surfaces each started row for KV handoff to the decode plane
(:meth:`~.engine.DecodePlaneBatcher.submit_handoff`).

Everything else is inherited unchanged: the queue/admission discipline
(TTL sheds, poison bodies, tenancy staging), the reply path for
requests that COMPLETE at prefill (budget-1, or eos on the first
token — they settle here and never hand off), the reply-registry dedup,
and the kill/hang fault seams.  Params are shared by reference and the
insert programs adopted from a donor replica, so a prefill replica
spins up in ~ms — the O(1) spin-up that makes the prefill plane the
cheap axis to scale.
"""

from __future__ import annotations

import time

from ..fleet.worker import FleetWorker
from ..workloads.continuous import _Slot


class PrefillWorker(FleetWorker):
    """One prefill-plane replica (see module docstring).

    Construct with ``sharded=False`` sizing (``batch_size`` prefill
    slots); ``generate_tokens`` must match the decode plane's so the
    handoff's budget accounting and the resume bucket line up.
    """

    def __init__(self, *args, **kwargs):
        kwargs.setdefault("sharded", False)
        super().__init__(*args, **kwargs)
        if self.batcher.beams > 1 or self.batcher.draft_layers:
            raise ValueError(
                "the prefill plane runs the plain admission insert "
                "(drafting happens on the decode plane)"
            )
        self.handed_off = 0

    def run_once(self) -> int:
        """One prefill cycle: refill free slots (the batched insert),
        settle first tokens, reply anything that completed AT the
        prefill plane.  Never dispatches a decode step — rows that need
        decoding wait (busy, one token produced) for the pool to move
        them through :meth:`ready_handoffs`."""
        if self.killed or self.hung:
            return 0
        if self._served_since is None:
            self._served_since = time.perf_counter()
        self._refill()
        self.batcher._settle_pending_firsts()
        done = self.batcher._finish_ready()
        for message, tokens in done:
            self._settle(message, tokens)
        if done:
            self._poll_backoff = 0
        self.processed += len(done)
        self._update_metrics()
        return len(done)

    def ready_handoffs(self) -> list[tuple]:
        """Started-but-unfinished rows as ``(src_row, payload, produced,
        budget, submitted_at, tenant)`` handoff records (the
        ``submit_handoff`` contract).  A row appears once its first
        token has settled; it stays busy — and its KV rows stay
        untouched — until :meth:`complete_handoff` releases it, so the
        decode plane's copy always reads live donor rows."""
        records = []
        for row, slot in enumerate(self.batcher.slots):
            if (slot.busy and slot.produced and not slot.done
                    and len(slot.produced) < slot.budget):
                records.append(
                    (row, slot.payload, list(slot.produced), slot.budget,
                     slot.submitted_at, slot.tenant)
                )
        return records

    def complete_handoff(self, rows: list[int]) -> None:
        """Free the handed-off rows (called by the pool AFTER the decode
        plane's copy was dispatched — the copy holds a read reference to
        this batcher's cache buffers, so the next insert into these rows
        orders after it)."""
        for row in rows:
            if self.lifecycle is not None:
                # the donor-side half of the handoff audit: every
                # handed_off note must pair with a decode-plane
                # "handoff" stamp on the same trace — a note without
                # the stamp is a KV copy that was freed but never
                # landed (exactly the loss the completeness gate hunts)
                from ..obs.lifecycle import request_key

                self.lifecycle.note(
                    request_key(self.batcher.slots[row].payload),
                    "handed_off",
                )
            self.batcher.slots[row] = _Slot()
        self.batcher._invalidate_admission_cache()
        self.handed_off += len(rows)
        if rows:
            self._poll_backoff = 0
