"""kube_sqs_autoscaler_tpu — a from-scratch, idiomatic-Python rebuild of the
capabilities of ``AcceleratorApp/kube-sqs-autoscaler`` (a ~290-line Go
queue-driven pod autoscaler; see SURVEY.md for the full structural analysis).

The framework is layered exactly like the reference (SURVEY.md §1), with one
deliberate improvement: every time-coupled component takes an injectable
``Clock`` so the full behavioral test suite runs deterministically in
milliseconds instead of the reference's ~56 s of real sleeps.

Layers (reference counterpart in parens, file:line cited per module):

- :mod:`.core.policy`  — pure threshold/cooldown decision engine
  (``main.go:35-80`` ``Run`` semantics, factored side-effect-free).
- :mod:`.core.loop`    — the sleep-first control loop that executes plans
  (``main.go:35-80``).
- :mod:`.metrics`      — queue-depth metric sources: attribute-summing client
  (``sqs/sqs.go``), in-memory fake (``main_test.go:273-286``), and a
  dependency-free real AWS SQS client (SigV4 over stdlib HTTP).
- :mod:`.scale`        — replica actuators: clamped step scaler
  (``scale/scale.go``), in-memory fake orchestrator
  (client-go ``fake.NewSimpleClientset`` equivalent), and a dependency-free
  Kubernetes REST actuator.
- :mod:`.cli`          — all 14 reference flags with identical names and
  defaults (``main.go:83-97``).
- :mod:`.workloads`    — what this controller scales in a TPU shop: queue-fed
  JAX inference/training workers (sharded over a ``jax.sharding.Mesh``).
  This is the only part of the tree that touches JAX; the controller itself
  is deliberately plain Python, mirroring the reference's plain Go.
- :mod:`.sim`          — deterministic closed-loop queue/worker-pool
  simulator used by tests and ``bench.py``.
"""

__version__ = "0.5.0"  # kept in sync with the Makefile's image VERSION

from .core.clock import Clock, FakeClock, SystemClock
from .core.policy import (
    Gate,
    PolicyConfig,
    PolicyState,
    TickPlan,
    initial_state,
    plan_tick,
)

__all__ = [
    "Clock",
    "FakeClock",
    "SystemClock",
    "Gate",
    "PolicyConfig",
    "PolicyState",
    "TickPlan",
    "initial_state",
    "plan_tick",
    "__version__",
]
