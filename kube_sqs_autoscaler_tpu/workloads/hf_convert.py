"""Hugging Face Llama checkpoint import.

Users of mainstream frameworks arrive with weights, not configs — this
module converts a ``transformers`` Llama checkpoint (a model instance, a
state dict, or a saved directory) into this package's llama-family
pytree, so the same weights serve/fine-tune here (no reference
counterpart: the reference has no model code at all, SURVEY.md §2).

Three conventions differ and are handled explicitly:

- **Layout**: ``nn.Linear`` stores ``[out, in]`` and computes ``x Wᵀ``;
  this package stores ``[in, out]`` and computes ``x @ W`` — every
  projection transposes.
- **Fusions**: ``k_proj``/``v_proj`` concatenate into ``wkv``;
  ``gate_proj``/``up_proj`` into ``w_gate_up`` (both on the output axis,
  matching the splits in ``llama._project_qkv`` / ``llama._swiglu``).
- **RoPE pairing**: HF rotates half-split pairs ``(x[i], x[i + D/2])``
  (``rotate_half``); this package rotates interleaved pairs
  ``(x[2i], x[2i+1])``.  Both use frequency ``theta^{-2i/D}`` for pair
  ``i``, so permuting each head's q/k *output* channels with
  ``[0, D/2, 1, D/2+1, ...]`` makes the interleaved rotation compute
  exactly what HF's half-split rotation computes.  The attention output
  is a sum over channels of ``softmax(q·k)``, invariant to the (shared)
  channel permutation, and ``v``/``wo`` are untouched — logits match to
  float tolerance (``tests/test_hf_convert.py`` asserts it against
  ``transformers``' own forward).

Untied checkpoints (``tie_word_embeddings=False``, e.g. Llama-2) import
their ``lm_head`` as a separate parameter; ``llama.readout_weights``
prefers it everywhere logits are produced.  ``rms_norm_eps`` and
``rope_theta`` are carried into :class:`~.llama.LlamaConfig` so Llama-2's
1e-5 epsilon is honored.

Torch is imported lazily and only on the host — the converted pytree is
plain device arrays; nothing torch survives into the jit path.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np

from .llama import LlamaConfig


def llama_config_from_hf(hf_config: Any, dtype: Any = None) -> LlamaConfig:
    """Map a ``transformers.LlamaConfig`` onto :class:`~.llama.LlamaConfig`.

    ``head_dim`` must equal ``hidden_size // num_attention_heads`` (the
    only geometry this family implements); models overriding it raise.
    """
    head_dim = getattr(hf_config, "head_dim", None)
    if head_dim and head_dim != hf_config.hidden_size // hf_config.num_attention_heads:
        raise ValueError(
            f"unsupported head_dim override: {head_dim} != "
            f"{hf_config.hidden_size // hf_config.num_attention_heads}"
        )
    return LlamaConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=hf_config.num_key_value_heads,
        n_layers=hf_config.num_hidden_layers,
        d_ff=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        rope_theta=float(hf_config.rope_theta),
        rms_eps=float(hf_config.rms_norm_eps),
        # Mistral configs carry sliding_window (None for plain Llama)
        sliding_window=getattr(hf_config, "sliding_window", None),
        dtype=dtype if dtype is not None else jnp.bfloat16,
    )


def _interleave_perm(head_dim: int) -> np.ndarray:
    """Channel permutation mapping HF's half-split RoPE layout to the
    interleaved layout: output channel ``2i`` takes HF channel ``i``,
    ``2i+1`` takes ``i + D/2``."""
    half = head_dim // 2
    perm = np.empty(head_dim, np.int64)
    perm[0::2] = np.arange(half)
    perm[1::2] = np.arange(half) + half
    return perm


def _rope_permute(w_t: np.ndarray, n_heads: int, head_dim: int) -> np.ndarray:
    """Permute the per-head output channels of a transposed projection
    ``[d_model, n_heads * head_dim]`` with :func:`_interleave_perm`."""
    d_model = w_t.shape[0]
    perm = _interleave_perm(head_dim)
    return (
        w_t.reshape(d_model, n_heads, head_dim)[:, :, perm]
        .reshape(d_model, n_heads * head_dim)
    )


def _to_numpy(tensor: Any) -> np.ndarray:
    # torch tensor (possibly bf16, which numpy lacks) -> fp32 ndarray
    return tensor.detach().to("cpu").float().numpy()


def llama_params_from_hf(
    state_dict: dict, config: LlamaConfig, dtype: Any = None
) -> dict:
    """Convert an HF Llama ``state_dict`` into this package's pytree.

    Accepts torch tensors or numpy arrays as values; keys follow the
    ``transformers`` naming (``model.layers.N.self_attn.q_proj.weight``
    etc.).  ``dtype`` defaults to ``config.dtype`` (bf16 storage; pass
    ``jnp.float32`` for exactness tests).
    """
    dtype = dtype if dtype is not None else config.dtype

    def get(name):
        w = state_dict[name]
        w = w if isinstance(w, np.ndarray) else _to_numpy(w)
        return w.astype(np.float32)

    def as_param(w):
        return jnp.asarray(w).astype(dtype)

    head_dim = config.head_dim
    params = {
        "embed": as_param(get("model.embed_tokens.weight")),
        "final_norm": as_param(get("model.norm.weight")),
        "layers": [],
    }
    if "lm_head.weight" in state_dict:
        params["lm_head"] = as_param(get("lm_head.weight"))
    for i in range(config.n_layers):
        prefix = f"model.layers.{i}."
        wq = _rope_permute(
            get(prefix + "self_attn.q_proj.weight").T, config.n_heads,
            head_dim,
        )
        wk = _rope_permute(
            get(prefix + "self_attn.k_proj.weight").T, config.n_kv_heads,
            head_dim,
        )
        wv = get(prefix + "self_attn.v_proj.weight").T
        params["layers"].append(
            {
                "attn_norm": as_param(get(prefix + "input_layernorm.weight")),
                "wq": as_param(wq),
                "wkv": as_param(np.concatenate([wk, wv], axis=1)),
                "wo": as_param(get(prefix + "self_attn.o_proj.weight").T),
                "mlp_norm": as_param(
                    get(prefix + "post_attention_layernorm.weight")
                ),
                "w_gate_up": as_param(
                    np.concatenate(
                        [
                            get(prefix + "mlp.gate_proj.weight").T,
                            get(prefix + "mlp.up_proj.weight").T,
                        ],
                        axis=1,
                    )
                ),
                "w_down": as_param(get(prefix + "mlp.down_proj.weight").T),
            }
        )
    return params


def _deinterleave_perm(head_dim: int) -> np.ndarray:
    """Inverse of :func:`_interleave_perm`: interleaved channel ``2i``
    returns to HF's half-split position ``i``, ``2i+1`` to ``i + D/2``."""
    perm = _interleave_perm(head_dim)
    inverse = np.empty_like(perm)
    inverse[perm] = np.arange(head_dim)
    return inverse


def hf_state_dict_from_llama(params: dict, config: LlamaConfig) -> dict:
    """The reverse conversion: this package's llama pytree -> an HF
    ``state_dict`` of numpy fp32 arrays (``transformers`` naming).

    Exact inverse of :func:`llama_params_from_hf`: un-fuse ``wkv`` /
    ``w_gate_up``, transpose back to ``nn.Linear``'s ``[out, in]``, and
    apply the inverse RoPE channel permutation to ``wq``/``wk`` so HF's
    ``rotate_half`` rotation reproduces the interleaved one.  Tied
    checkpoints (no ``lm_head`` key) omit ``lm_head.weight`` — HF re-ties
    it from the embedding when ``tie_word_embeddings=True``.
    """
    head_dim = config.head_dim

    def t(x):
        return np.asarray(x, np.float32).T

    def unpermute(w_t: np.ndarray, n_heads: int) -> np.ndarray:
        d_model = w_t.shape[0]
        perm = _deinterleave_perm(head_dim)
        return (
            w_t.reshape(d_model, n_heads, head_dim)[:, :, perm]
            .reshape(d_model, n_heads * head_dim)
        )

    state = {
        "model.embed_tokens.weight": np.asarray(params["embed"], np.float32),
        "model.norm.weight": np.asarray(params["final_norm"], np.float32),
    }
    if "lm_head" in params:
        state["lm_head.weight"] = np.asarray(params["lm_head"], np.float32)
    for i, layer in enumerate(params["layers"]):
        prefix = f"model.layers.{i}."
        wq_t = np.asarray(layer["wq"], np.float32)
        wkv_t = np.asarray(layer["wkv"], np.float32)
        kv_dim = config.n_kv_heads * head_dim
        wk_t, wv_t = wkv_t[:, :kv_dim], wkv_t[:, kv_dim:]
        gate_up_t = np.asarray(layer["w_gate_up"], np.float32)
        state.update({
            prefix + "input_layernorm.weight":
                np.asarray(layer["attn_norm"], np.float32),
            prefix + "self_attn.q_proj.weight":
                unpermute(wq_t, config.n_heads).T,
            prefix + "self_attn.k_proj.weight":
                unpermute(wk_t, config.n_kv_heads).T,
            prefix + "self_attn.v_proj.weight": wv_t.T,
            prefix + "self_attn.o_proj.weight": t(layer["wo"]),
            prefix + "post_attention_layernorm.weight":
                np.asarray(layer["mlp_norm"], np.float32),
            prefix + "mlp.gate_proj.weight":
                gate_up_t[:, :config.d_ff].T,
            prefix + "mlp.up_proj.weight": gate_up_t[:, config.d_ff:].T,
            prefix + "mlp.down_proj.weight": t(layer["w_down"]),
        })
    return state


def save_hf_llama(
    params: dict, config: LlamaConfig, directory: Any
) -> Any:
    """Export to a ``transformers``-loadable checkpoint directory.

    Builds the matching HF config (Llama, or Mistral when the config
    carries a ``sliding_window``), loads the reverse-converted state
    dict, and ``save_pretrained``s — so weights trained or LoRA-merged
    here round-trip into the mainstream ecosystem.  Returns the HF model
    (also handy for in-process comparison).
    """
    import torch

    tie = "lm_head" not in params
    common = dict(
        vocab_size=config.vocab_size,
        hidden_size=config.d_model,
        intermediate_size=config.d_ff,
        num_hidden_layers=config.n_layers,
        num_attention_heads=config.n_heads,
        num_key_value_heads=config.n_kv_heads,
        max_position_embeddings=config.max_seq_len,
        rope_theta=config.rope_theta,
        rms_norm_eps=config.rms_eps,
        tie_word_embeddings=tie,
        attn_implementation="eager",
    )
    if config.sliding_window is not None:
        from transformers import MistralConfig, MistralForCausalLM

        hf = MistralForCausalLM(MistralConfig(
            sliding_window=config.sliding_window, **common
        ))
    else:
        from transformers import LlamaConfig as HFLlamaConfig
        from transformers import LlamaForCausalLM

        hf = LlamaForCausalLM(HFLlamaConfig(**common))
    state = {
        k: torch.from_numpy(np.array(v, copy=True))
        for k, v in hf_state_dict_from_llama(params, config).items()
    }
    missing, unexpected = hf.load_state_dict(state, strict=False)
    # tied models derive lm_head from the embedding; anything else
    # missing/unexpected is a conversion bug — fail loudly
    allowed_missing = {"lm_head.weight"} if tie else set()
    if set(missing) - allowed_missing or unexpected:
        raise ValueError(
            f"HF export mismatch: missing={missing} unexpected={unexpected}"
        )
    if tie:
        hf.tie_weights()
    hf.eval()
    if directory is not None:
        hf.save_pretrained(directory)
    return hf


def load_hf_llama(
    source: Any, dtype: Any = None
) -> tuple[LlamaConfig, dict]:
    """One-call import: ``(LlamaConfig, params)`` from an HF source.

    ``source`` is a ``transformers`` Llama model instance (e.g. just
    constructed or ``from_pretrained``-loaded) or a checkpoint directory
    path; directories load via ``LlamaForCausalLM.from_pretrained`` on
    the CPU.  ``dtype`` sets the parameter storage dtype (default bf16).
    """
    if isinstance(source, (str, bytes)) or hasattr(source, "__fspath__"):
        # Llama and Mistral share the state-dict layout; dispatch on the
        # saved config's model_type so both directory kinds load
        from transformers import AutoConfig

        model_type = AutoConfig.from_pretrained(source).model_type
        if model_type == "mistral":
            from transformers import MistralForCausalLM as _Model
        else:
            from transformers import LlamaForCausalLM as _Model
        source = _Model.from_pretrained(source)
    config = llama_config_from_hf(source.config, dtype=dtype)
    state = dict(source.state_dict())
    if getattr(source.config, "tie_word_embeddings", False):
        # tied checkpoints may still materialize lm_head.weight as a view
        # of the embedding — drop it so readout_weights uses the tie
        state.pop("lm_head.weight", None)
    return config, llama_params_from_hf(state, config, dtype=dtype)
