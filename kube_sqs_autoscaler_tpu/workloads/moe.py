"""Mixture-of-Experts MLP with expert parallelism over the device mesh.

The reference workload (``/root/reference``) has no model code at all
(SURVEY.md §2 native-code census); this module extends the package's own
TPU workload (:mod:`.model`) with the standard sparse-MLP scaling axis so
the framework's parallelism story covers **ep** alongside dp/tp/sp/pp.

TPU-first design:

- **GShard-style dense dispatch**: routing is expressed as one-hot
  dispatch/combine einsums with a static per-expert capacity, so the whole
  layer is fixed-shape matmuls — no gather/scatter with data-dependent
  shapes, which XLA cannot tile onto the MXU.
- **Expert parallelism over the ``"data"`` mesh axis**: expert weights
  (``w_up_experts [E, D, F]``, ``w_down_experts [E, F, D]``) shard their
  leading expert axis over ``"data"`` (the canonical ep=dp layout), while
  their ``F`` axis stays tensor-parallel over ``"model"`` — so each expert
  is itself Megatron-sharded.  XLA's SPMD partitioner sees batch sharded
  over ``"data"`` feeding expert-sharded weights and inserts the
  all-to-alls (token shuffle to experts and back) over ICI automatically.
- **fp32 routing**: router logits/softmax/top-k run in fp32; expert
  matmuls run in the model dtype (bf16 on TPU).

Load balancing uses the Switch-Transformer auxiliary loss
(``E * mean_e(frac_tokens_e * mean_prob_e)``), returned per layer and
averaged by :func:`moe_forward` so the train loss can add it.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from .model import ModelConfig, forward, init_params

# Default routing-group budget (tokens).  GShard/Switch route in groups of
# a few hundred to a few thousand tokens; keeping groups bounded keeps the
# [G, T, E, C] dispatch tensors linear in batch size (one all-tokens group
# would make them quadratic) and keeps a shardable leading group axis.
DEFAULT_GROUP_TOKENS = 4096


def _default_group(tokens: int) -> int:
    """Largest divisor of ``tokens`` that is <= DEFAULT_GROUP_TOKENS —
    a function of the token count alone, so routing stays invariant to
    batch reshape.  Trace-time only."""
    group = min(DEFAULT_GROUP_TOKENS, tokens)
    while tokens % group:
        group -= 1
    return group


@dataclass(frozen=True)
class MoeConfig:
    """Routing hyper-parameters (defaults follow Switch/GShard practice).

    ``group_size`` fixes the routing-group length in *tokens* over the
    flattened ``[B*S]`` token stream (``None`` = the largest divisor of
    the total token count up to :data:`DEFAULT_GROUP_TOKENS` — bounded
    groups in GShard/Switch's practiced range, so the ``[G, T, E, C]``
    dispatch tensors stay linear in batch size rather than one
    all-tokens group going quadratic).  Capacity is per group and groups
    are carved from the flattened stream, so routing depends only on the
    token stream — reshaping the batch (``[B, S]`` vs ``[2B, S/2]``)
    neither changes which tokens share capacity nor how much there is
    (previously each batch row was a group, coupling load-balance
    behavior to batch layout)."""

    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 1e-2
    group_size: int | None = None

    def __post_init__(self) -> None:
        if not 1 <= self.top_k <= self.n_experts:
            # with top_k > n_experts the greedy argmax would silently
            # double-assign expert 0 once `remaining` zeroes out
            raise ValueError(
                f"top_k={self.top_k} must be in [1, n_experts={self.n_experts}]"
            )
        if self.group_size is not None and self.group_size < 1:
            raise ValueError(f"group_size={self.group_size} must be >= 1")

    def capacity(self, tokens_per_group: int) -> int:
        """Static per-expert slot count for a group of that many tokens."""
        return max(
            1,
            math.ceil(
                self.top_k * tokens_per_group * self.capacity_factor
                / self.n_experts
            ),
        )


def _add_expert_weights(
    params: dict, config, moe: MoeConfig, expert_rng: jax.Array,
    up_name: str, up_cols: int,
) -> dict:
    """Attach ``router`` + stacked expert weights to every layer of a
    dense-MLP-free parameter pytree — the init both families share (only
    the up-projection name/width differs: ``w_up_experts [E, D, F]`` for
    GELU experts, ``w_gate_up_experts [E, D, 2F]`` for SwiGLU)."""
    out_scale = 0.02 / (2 * config.n_layers) ** 0.5
    keys = jax.random.split(expert_rng, 3 * config.n_layers)
    for i, layer in enumerate(params["layers"]):
        k_r, k_up, k_down = keys[3 * i : 3 * i + 3]
        layer["router"] = (
            jax.random.normal(k_r, (config.d_model, moe.n_experts), jnp.float32)
            * 0.02
        )  # router stays fp32: routing decisions are precision-sensitive
        layer[up_name] = (
            jax.random.normal(
                k_up, (moe.n_experts, config.d_model, up_cols), jnp.float32
            )
            * 0.02
        ).astype(config.dtype)
        layer["w_down_experts"] = (
            jax.random.normal(
                k_down, (moe.n_experts, config.d_ff, config.d_model), jnp.float32
            )
            * out_scale
        ).astype(config.dtype)
    return params


def init_moe_params(
    rng: jax.Array, config: ModelConfig, moe: MoeConfig
) -> dict:
    """Like :func:`.model.init_params` but every layer's dense MLP is
    replaced by ``router`` + stacked expert weights."""
    base_rng, expert_rng = jax.random.split(rng)
    params = init_params(base_rng, config, dense_mlp=False)
    return _add_expert_weights(
        params, config, moe, expert_rng, "w_up_experts", config.d_ff
    )


def _top_k_routing(
    probs: jax.Array, moe: MoeConfig, capacity: int
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Greedy top-k assignment with per-expert capacity.

    ``probs``: fp32 ``[G, T, E]`` router softmax (``G`` routing groups of
    ``T`` tokens each).  Returns ``dispatch [G, T, E, C]`` (0/1),
    ``combine [G, T, E, C]``
    (gate-weighted dispatch), and the Switch aux loss scalar.  Tokens that
    overflow an expert's capacity are dropped for that choice (standard
    GShard behavior); gates are renormalized over the *selected* experts
    before capacity dropping, so a token whose second choice overflows
    still contributes its first-choice share.
    """
    batch, seq, n_experts = probs.shape

    remaining = probs
    choices = []  # (expert_onehot [B,S,E], gate [B,S])
    for _ in range(moe.top_k):
        idx = jnp.argmax(remaining, axis=-1)
        onehot = jax.nn.one_hot(idx, n_experts, dtype=probs.dtype)
        choices.append((onehot, jnp.sum(probs * onehot, axis=-1)))
        remaining = remaining * (1.0 - onehot)

    gate_sum = sum(g for _, g in choices)
    denom = jnp.maximum(gate_sum, 1e-9)

    dispatch = jnp.zeros((batch, seq, n_experts, capacity), probs.dtype)
    combine = jnp.zeros_like(dispatch)
    # slots already used per (batch row, expert) by earlier choices
    used = jnp.zeros((batch, n_experts), probs.dtype)
    for onehot, gate in choices:
        # position of each token within its chosen expert's slot sequence
        pos = jnp.cumsum(onehot, axis=1) - onehot + used[:, None, :]
        used = used + jnp.sum(onehot, axis=1)
        kept = jnp.sum(onehot * (pos < capacity), axis=-1)  # [B, S] 0/1
        slot = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)
        slot_onehot = jax.nn.one_hot(slot, capacity, dtype=probs.dtype)
        mask = onehot[..., None] * slot_onehot[:, :, None, :]
        mask = mask * kept[..., None, None]
        dispatch = dispatch + mask
        combine = combine + mask * (gate / denom)[..., None, None]

    # Switch aux loss on first-choice assignment fractions
    first_onehot = choices[0][0]
    frac_tokens = jnp.mean(first_onehot, axis=(0, 1))  # [E]
    mean_prob = jnp.mean(probs, axis=(0, 1))  # [E]
    aux = n_experts * jnp.sum(frac_tokens * mean_prob)
    return dispatch, combine, aux


def _routed_ffn(
    x: jax.Array, layer: dict, moe: MoeConfig, expert_ffn, grad_sync=None
) -> tuple[jax.Array, jax.Array]:
    """The family-agnostic route/dispatch/combine skeleton.

    ``x``: ``[B, S, D]`` -> ``([B, S, D], aux_loss)``.  Tokens are routed
    over the **flattened** ``[B*S]`` stream in groups of
    ``moe.group_size`` (default: bounded groups from the token count
    alone), so routing and capacity are functions of the token stream —
    invariant to how the batch is reshaped.  The dispatch einsums keep a
    leading group axis that stays sharded over ``"data"`` while the
    expert axis of the weights is also ``"data"``-sharded — the mismatch
    is exactly the token all-to-all.  ``expert_ffn(expert_in, layer)``
    maps ``[E, G, C, D] -> [E, G, C, D]`` (GELU experts for the gpt
    family, SwiGLU for llama).
    """
    b, s, d = x.shape
    tokens = b * s
    group = moe.group_size or _default_group(tokens)
    if tokens % group:
        raise ValueError(
            f"batch of {tokens} tokens not divisible by "
            f"group_size={group}"
        )
    xg = x.reshape(tokens // group, group, d)
    capacity = moe.capacity(group)
    logits = jnp.einsum(
        "gtd,de->gte", xg, layer["router"], preferred_element_type=jnp.float32
    )
    probs = jax.nn.softmax(logits, axis=-1)
    dispatch, combine, aux = _top_k_routing(probs, moe, capacity)
    if grad_sync is not None:
        # fully-manual pp x tp: the expert weights are ff-carved over
        # "model", so the cotangents reaching dispatch/combine (and
        # through them the router) are per-shard PARTIAL sums; grad_sync
        # (Megatron's f operator — identity forward, psum backward)
        # restores the full cotangent so the replicated router's
        # gradient matches the unsharded math.  The aux term reads the
        # raw probs above and needs no correction (its per-shard
        # cotangents are already identical full copies).
        dispatch = grad_sync(dispatch)
        combine = grad_sync(combine)

    dispatch = dispatch.astype(x.dtype)
    # [G,T,E,C] x [G,T,D] -> [E,G,C,D]: the forward all-to-all
    expert_in = jnp.einsum("gtec,gtd->egcd", dispatch, xg)
    expert_out = expert_ffn(expert_in, layer)
    # combine (return all-to-all) in fp32 so gate weighting is exact
    out = jnp.einsum(
        "gtec,egcd->gtd", combine, expert_out.astype(jnp.float32)
    )
    return out.reshape(b, s, d).astype(x.dtype), aux


def _gelu_experts(expert_in: jax.Array, layer: dict) -> jax.Array:
    hidden = jax.nn.gelu(
        jnp.einsum("egcd,edf->egcf", expert_in, layer["w_up_experts"])
    )
    return jnp.einsum("egcf,efd->egcd", hidden, layer["w_down_experts"])


def _swiglu_experts(expert_in: jax.Array, layer: dict) -> jax.Array:
    if "w_gate_experts" in layer:
        # the pipeline stage layout splits the fused projection so each
        # expert's gate/up columns shard contiguously under pp x tp (a
        # fused [2F] axis chunks across the gate/up boundary — same
        # reason the dense w_gate_up splits, pipeline.stack_llama_layers)
        gate = jnp.einsum(
            "egcd,edf->egcf", expert_in, layer["w_gate_experts"]
        )
        up = jnp.einsum(
            "egcd,edf->egcf", expert_in, layer["w_up_experts"]
        )
    else:
        gate_up = jnp.einsum(
            "egcd,edf->egcf", expert_in, layer["w_gate_up_experts"]
        )
        gate, up = jnp.split(gate_up, 2, axis=-1)
    return jnp.einsum(
        "egcf,efd->egcd", jax.nn.silu(gate) * up, layer["w_down_experts"]
    )


def moe_mlp(
    x: jax.Array, layer: dict, moe: MoeConfig, grad_sync=None
) -> tuple[jax.Array, jax.Array]:
    """Sparse MLP for the gpt family: GELU experts behind the shared
    routing skeleton (:func:`_routed_ffn`)."""
    return _routed_ffn(x, layer, moe, _gelu_experts, grad_sync=grad_sync)


def llama_moe_mlp(
    x: jax.Array, layer: dict, moe: MoeConfig, grad_sync=None
) -> tuple[jax.Array, jax.Array]:
    """Sparse MLP for the llama family: SwiGLU experts (fused gate+up
    projection per expert) behind the same routing skeleton."""
    return _routed_ffn(x, layer, moe, _swiglu_experts, grad_sync=grad_sync)


def init_llama_moe_params(
    rng: jax.Array, config, moe: MoeConfig
) -> dict:
    """Llama params with every layer's dense SwiGLU replaced by
    ``router`` + stacked SwiGLU expert weights (``w_gate_up_experts
    [E, D, 2F]``, ``w_down_experts [E, F, D]``)."""
    from .llama import init_llama_params

    base_rng, expert_rng = jax.random.split(rng)
    params = init_llama_params(base_rng, config, dense_mlp=False)
    return _add_expert_weights(
        params, config, moe, expert_rng, "w_gate_up_experts",
        2 * config.d_ff,
    )


def _collecting_mlp(expert_mlp, moe: MoeConfig):
    """The aux-collection seam, in one place: wrap an ``(h, layer, moe) ->
    (out, aux)`` expert MLP as a ``model``-seam ``mlp(h, layer)`` that
    appends each layer's aux loss to the returned list; ``mean_aux``
    reduces the list to the objective's mean aux term."""
    aux_out = []

    def sparse_mlp(h, layer):
        out, aux = expert_mlp(h, layer, moe)
        aux_out.append(aux)
        return out

    def mean_aux():
        return sum(aux_out) / len(aux_out)

    return sparse_mlp, mean_aux


def moe_forward(
    params: dict,
    tokens: jax.Array,
    config: ModelConfig,
    moe: MoeConfig,
    attention_fn=None,
) -> tuple[jax.Array, jax.Array]:
    """Logits plus mean auxiliary load-balance loss.

    Runs :func:`.model.forward` itself (one source of truth for the
    embedding/block/unembedding wiring) with the sparse expert MLP plugged
    into its ``mlp`` seam; the per-layer aux losses are collected through
    the closure.
    """
    sparse_mlp, mean_aux = _collecting_mlp(moe_mlp, moe)
    logits = forward(params, tokens, config, attention_fn, mlp=sparse_mlp)
    return logits, mean_aux()


def llama_moe_forward(
    params: dict,
    tokens: jax.Array,
    config,
    moe: MoeConfig,
    attention_fn=None,
) -> tuple[jax.Array, jax.Array]:
    """Llama counterpart of :func:`moe_forward`: the routed SwiGLU expert
    MLP through :func:`.llama.llama_forward`'s ``mlp`` seam (RoPE, GQA,
    RMSNorm all unchanged)."""
    from .llama import llama_forward

    sparse_mlp, mean_aux = _collecting_mlp(llama_moe_mlp, moe)
    logits = llama_forward(params, tokens, config, attention_fn,
                           mlp=sparse_mlp)
    return logits, mean_aux()


def moe_loss_fn(
    params: Any,
    tokens: jax.Array,
    config: ModelConfig,
    moe: MoeConfig,
    attention_fn=None,
) -> jax.Array:
    """Next-token cross-entropy + weighted aux loss (fp32).

    The cross-entropy goes through ``train.fused_next_token_nll`` (same
    value, logits-free backward); only the expert-MLP seam differs from
    the dense objective."""
    from .model import forward_hidden
    from .train import fused_next_token_nll

    sparse_mlp, mean_aux = _collecting_mlp(moe_mlp, moe)
    x = forward_hidden(params, tokens, config, attention_fn, mlp=sparse_mlp)
    nll = fused_next_token_nll(params["embed"], x, tokens)
    return nll + moe.aux_loss_weight * mean_aux()


def llama_moe_loss_fn(
    params: Any,
    tokens: jax.Array,
    config,
    moe: MoeConfig,
    attention_fn=None,
) -> jax.Array:
    """Llama-family MoE objective (cross-entropy + weighted aux)."""
    from .llama import llama_forward_hidden, readout_weights
    from .train import fused_next_token_nll

    sparse_mlp, mean_aux = _collecting_mlp(llama_moe_mlp, moe)
    x = llama_forward_hidden(params, tokens, config, attention_fn,
                             mlp=sparse_mlp)
    nll = fused_next_token_nll(readout_weights(params), x, tokens)
    return nll + moe.aux_loss_weight * mean_aux()


def init_moe_train_state(
    rng: jax.Array, config: ModelConfig, moe: MoeConfig, train_config
) -> dict:
    from functools import partial

    from .train import init_train_state

    return init_train_state(
        rng, config, train_config, init_fn=partial(init_moe_params, moe=moe)
    )


def init_llama_moe_train_state(
    rng: jax.Array, config, moe: MoeConfig, train_config
) -> dict:
    from functools import partial

    from .train import init_train_state

    return init_train_state(
        rng, config, train_config,
        init_fn=partial(init_llama_moe_params, moe=moe),
    )


def _require_no_remat(train_config) -> None:
    """The MoE forwards collect per-layer aux losses through a closure
    over the mlp seam; ``jax.checkpoint`` re-traces the block in the
    backward pass, so closure-captured intermediates would leak tracers.
    Fail fast instead of silently ignoring the flag."""
    if getattr(train_config, "remat", False):
        raise ValueError(
            "TrainConfig.remat is not supported for the MoE loss (the "
            "aux-loss collection is incompatible with jax.checkpoint "
            "re-tracing); set remat=False"
        )


def _make_moe_step(mesh, config, moe: MoeConfig, train_config, state: dict,
                   loss_fn):
    """Shared MoE step builder: the remat guard and the
    :func:`.train.make_train_step` delegation live exactly once for both
    families."""
    from functools import partial

    from .train import make_train_step

    _require_no_remat(train_config)
    return make_train_step(
        mesh, config, train_config, state,
        loss=partial(loss_fn, config=config, moe=moe),
        # llama MoE configs may carry a sliding window; it rides the
        # shared attention seam like the dense llama step's
        window=getattr(config, "sliding_window", None),
    )


def make_zigzag_moe_train_step(mesh, config, moe: MoeConfig, train_config,
                               state: dict, llama: bool = False):
    """MoE × zig-zag: the routed expert MLP rides the permuted-order
    zig-zag objective.

    The expert machinery is already layout-invariant (flattened-stream
    routing groups — which tokens share capacity does not depend on the
    batch/sequence layout), so the composition is purely an objective
    one: run the family forward with the sparse MLP in its ``mlp`` seam
    and the zig-zag schedule as its attention, add the Switch aux term
    to the permuted-order NLL.  Sliding-window llama-MoE configs fail
    fast (the ring schedule has no window skip), like every other sp
    consumer.
    """
    from .train import make_train_step
    from .zigzag import make_zigzag_loss

    _require_no_remat(train_config)
    # windowed configs: make_zigzag_loss rejects them (the permuted
    # blocks have no banded form; plain windowed sp would work)
    if llama:
        from .llama import llama_forward as family_forward

        expert_mlp = llama_moe_mlp
    else:
        from .model import forward as family_forward

        expert_mlp = moe_mlp

    def forward_factory():
        # fresh aux collection per loss evaluation (trace) — the same
        # closure discipline as the flat MoE objectives
        sparse_mlp, mean_aux = _collecting_mlp(expert_mlp, moe)

        def fwd(params, tokens, config, attention_fn, positions=None,
                remat=False):
            return family_forward(
                params, tokens, config, attention_fn, mlp=sparse_mlp,
                positions=positions, remat=remat,
            )

        return fwd, lambda nll: nll + moe.aux_loss_weight * mean_aux()

    loss = make_zigzag_loss(mesh, config, forward_factory=forward_factory)
    return make_train_step(mesh, config, train_config, state, loss=loss)


def make_llama_moe_train_step(mesh, config, moe: MoeConfig, train_config,
                              state: dict):
    """Llama-family MoE optimizer step (same seams and constraints as
    :func:`make_moe_train_step`)."""
    return _make_moe_step(mesh, config, moe, train_config, state,
                          llama_moe_loss_fn)


def make_moe_train_step(mesh, config: ModelConfig, moe: MoeConfig,
                        train_config, state: dict):
    """Compile one MoE optimizer step over the mesh (dp x sp x tp x ep).

    Delegates to :func:`.train.make_train_step` through its ``loss`` seam;
    expert weights shard via the ``"expert" -> "data"`` rule in
    :mod:`.train`, so the dispatch einsums lower to token all-to-alls over
    ICI.

    On the expert axis choice: ep deliberately rides the ``data`` mesh
    axis (the canonical ep=dp layout) rather than a dedicated fourth
    axis — with routing decoupled from batch layout (flattened-stream
    groups, see :class:`MoeConfig`), a separate axis would only change
    *which* devices hold which experts, not the all-to-all volume, while
    multiplying every mesh-shape constraint in the package.  A dedicated
    axis becomes worth it when experts outnumber what dp-sharding can
    hold; revisit then.
    """
    return _make_moe_step(mesh, config, moe, train_config, state,
                          moe_loss_fn)
