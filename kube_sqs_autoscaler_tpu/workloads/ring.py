"""Ring attention: causal self-attention over a sequence-sharded mesh axis.

Long-context sequence/context parallelism, TPU-native: the sequence axis is
sharded over a ``"seq"`` mesh axis; each device keeps its query block
resident and the key/value blocks rotate around the ring one hop per step
via ``jax.lax.ppermute`` (neighbor exchanges ride the ICI torus), while a
flash-attention-style online softmax merges partial results — so no device
ever materializes the full ``[S, S]`` score matrix or the full K/V.

Algorithm (per device, inside ``shard_map``):

1. accumulators ``o`` (weighted values), ``l`` (softmax denominator), ``m``
   (running max) start empty;
2. for each of the ``P`` ring steps: compute local scores
   ``q @ k_blockᵀ`` in fp32, apply the *global* causal mask (block origin
   tracked from the step index), merge via the numerically-stable online
   update, then ``ppermute`` k/v to the next device;
3. normalize ``o / l``.

Fully-masked blocks are handled by masking with a large-negative finite
value (not ``-inf``), keeping the running max finite so ``exp`` never sees
``-inf - (-inf)``.

Compute note: like standard ring attention, every device runs all ``P``
steps (lockstep collectives), so causal masking wastes ~half the FLOPs;
:mod:`.zigzag` implements the block reordering that recovers it (balanced
per-device load, half-size unmasked matmuls on every non-diagonal hop).

Two local-op implementations share the hop/merge structure: the einsum
reference body (:func:`_ring_attention_local` — runs anywhere, the
ground truth tests pin against) and the **Pallas flash kernel body**
(:func:`_ring_attention_kernel_local` — default on TPU): each hop is one
:func:`.flash.flash_attention_lse` call whose ``(out, lse)`` partial
merges across hops, so per-hop VMEM stays O(block) and no
``[S_local, S_local]`` score tensor ever reaches HBM — the property that
matters when long-context sharding still leaves multi-k local sequences.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

# finite mask value; see module docstring.  A plain Python float on
# purpose (same rule as flash.MERGE_NEG_INF): a module-level jnp scalar
# would be traced into the first jit/shard_map context as a captured
# constant and then poison later traces — observed concretely as
# "Execution supplied N buffers but compiled program expected N+1" on
# the SECOND call of a pp x sp train step whose process had previously
# lowered any other program touching this constant (the stale captured
# const lowers as an extra executable parameter the C++ fastpath does
# not supply).
_NEG_INF = -1e9
NEG_INF = _NEG_INF  # shared with .zigzag


def online_update(o, l, m, scores, v_blk):
    """Numerically-stable online-softmax merge of one fp32 score block
    into running ``(o, l, m)`` accumulators.  The single implementation
    both ring schedules (:mod:`.ring`, :mod:`.zigzag`) use — the
    stability-sensitive math lives in exactly one place.

    Statistics (max/sum/exp) stay fp32; the probability-times-value
    matmul runs with the probabilities cast to ``v``'s storage dtype and
    fp32 accumulation (``preferred_element_type``) — under bf16 that is
    the MXU fast path, and exactly the rounding the dense path
    (:func:`.model._dense_attention`) applies to its probabilities, so
    ring == dense holds bit-for-bit-comparably in either dtype."""
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
    p = jnp.exp(scores - m_new)
    correction = jnp.exp(m - m_new)
    l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
    o_new = o * correction + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v_blk.dtype), v_blk,
        preferred_element_type=jnp.float32,
    )
    return o_new, l_new, m_new


def ring_rotation(axis_size: int) -> list[tuple[int, int]]:
    """The one-hop ``ppermute`` pattern ``i -> i+1`` (mod ``axis_size``)."""
    return [(i, (i + 1) % axis_size) for i in range(axis_size)]


def expand_kv(t: jax.Array, groups: int) -> jax.Array:
    """GQA broadcast ``[B, H_kv, S, D] -> [B, H_kv*groups, S, D]`` at the
    compute site.  XLA fuses the broadcast into the consuming einsum, so
    the full-head tensor never materializes — the *carried/rotated* blocks
    stay compact (``groups``x less ICI traffic per hop)."""
    if groups == 1:
        return t
    batch, kv_heads, seq, dim = t.shape
    return jnp.broadcast_to(
        t[:, :, None], (batch, kv_heads, groups, seq, dim)
    ).reshape(batch, kv_heads * groups, seq, dim)


def _ring_attention_kernel_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    interpret: bool | None = None,
) -> jax.Array:
    """Per-device body with the Pallas flash kernel as the local op.

    Each hop is ONE kernel call on the resident q block against the
    arriving k/v block — causal for the diagonal hop (own k/v), full for
    k/v from earlier devices, skipped entirely for later devices (fully
    masked under causality; the ``lax.cond`` means those hops cost one
    ppermute and zero FLOPs).  Hop results are normalized ``(out, lse)``
    partials merged by :func:`.flash.merge_attention_partials` — the
    online softmax now lives *across hops* while each hop's inner loop
    runs at kernel speed with O(block) VMEM, so no ``[S_loc, S_loc]``
    score tensor ever reaches HBM.  GQA-native: compact k/v feed the
    kernel directly and rotate compact.
    """
    from .flash import (
        MERGE_NEG_INF,
        flash_attention_lse,
        merge_attention_partials,
    )

    my_index = jax.lax.axis_index(axis_name)

    acc0 = q.astype(jnp.float32) * 0.0
    lse0 = (
        q[..., 0].astype(jnp.float32) * 0.0 + MERGE_NEG_INF
    )  # [B, H, S_loc], varying like q

    def step(carry, step_index):
        acc, acc_lse, k_blk, v_blk = carry
        kv_index = (my_index - step_index) % axis_size

        def diag(k_blk, v_blk):
            return flash_attention_lse(q, k_blk, v_blk, causal=True,
                                       interpret=interpret)

        def earlier(k_blk, v_blk):
            return flash_attention_lse(q, k_blk, v_blk, causal=False,
                                       interpret=interpret)

        def later(k_blk, v_blk):
            # fully masked: contributes nothing, costs nothing
            return jnp.zeros_like(q), jnp.full_like(lse0, MERGE_NEG_INF)

        out_h, lse_h = jax.lax.cond(
            kv_index == my_index,
            diag,
            lambda k_blk, v_blk: jax.lax.cond(
                kv_index < my_index, earlier, later, k_blk, v_blk
            ),
            k_blk, v_blk,
        )
        acc, acc_lse = merge_attention_partials(acc, acc_lse, out_h, lse_h)

        ring = ring_rotation(axis_size)
        k_next = jax.lax.ppermute(k_blk, axis_name, ring)
        v_next = jax.lax.ppermute(v_blk, axis_name, ring)
        return (acc, acc_lse, k_next, v_next), None

    (acc, _, _, _), _ = jax.lax.scan(
        step, (acc0, lse0, k, v), jnp.arange(axis_size)
    )
    return acc.astype(q.dtype)


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    window: int | None = None,
) -> jax.Array:
    """Per-device body. q: ``[B, H, S_local, D]``; k/v may carry compact
    GQA heads ``[B, H_kv, S_local, D]`` (broadcast at the compute site,
    rotated compact).  ``window`` adds the Mistral sliding-window bound:
    global row ``r`` attends global keys ``r - window + 1 .. r`` — the
    per-hop mask is a band in GLOBAL positions, which the hop origin
    tracking already provides, so the windowed schedule is the causal
    one with one more mask term."""
    batch, heads, seq_local, head_dim = q.shape
    groups = heads // k.shape[1]
    my_index = jax.lax.axis_index(axis_name)

    scale = 1.0 / (head_dim**0.5)
    local_positions = jnp.arange(seq_local)
    q_positions = my_index * seq_local + local_positions  # global q rows

    # accumulators derived from q so they carry q's "varying over mesh axes"
    # type (plain zeros/full literals are unvarying and trip shard_map's
    # scan-carry type check); fp32 statistics regardless of input dtype
    q32 = q.astype(jnp.float32)
    o0 = q32 * 0.0
    l0 = q32[..., :1] * 0.0
    m0 = q32[..., :1] * 0.0 + _NEG_INF

    def step(carry, step_index):
        o, l, m, k_blk, v_blk = carry
        # after s hops, this device holds the k/v block that originated on
        # device (my_index - s) mod P
        kv_index = (my_index - step_index) % axis_size
        k_positions = kv_index * seq_local + local_positions

        # q/k enter the score matmul in their storage dtype with fp32
        # accumulation — bf16 inputs ride the MXU fast path (same
        # convention as the dense path and the flash kernel); the 1/sqrt(D)
        # scale folds in afterwards, in fp32
        scores = (
            jnp.einsum(
                "bhqd,bhkd->bhqk",
                q,
                expand_kv(k_blk, groups),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        visible = q_positions[:, None] >= k_positions[None, :]
        if window is not None:
            visible = visible & (
                k_positions[None, :] > q_positions[:, None] - window
            )
        scores = jnp.where(visible, scores, _NEG_INF)

        o_new, l_new, m_new = online_update(
            o, l, m, scores, expand_kv(v_blk, groups)
        )

        # rotate k/v one hop around the ring: i -> i+1
        ring = ring_rotation(axis_size)
        k_next = jax.lax.ppermute(k_blk, axis_name, ring)
        v_next = jax.lax.ppermute(v_blk, axis_name, ring)
        return (o_new, l_new, m_new, k_next, v_next), None

    (o, l, _, _, _), _ = jax.lax.scan(
        step, (o0, l0, m0, k, v), jnp.arange(axis_size)
    )
    return (o / l).astype(q.dtype)


def make_ring_attention(
    mesh: Mesh,
    *,
    seq_axis: str = "seq",
    data_axis: str = "data",
    model_axis: str = "model",
    use_kernel: bool | None = None,
    interpret: bool | None = None,
    window: int | None = None,
) -> Callable[[jax.Array, jax.Array, jax.Array], jax.Array]:
    """Build an attention fn ``(q, k, v) -> out`` (``[B, H, S, D]`` each)
    that runs as ring attention over ``mesh[seq_axis]``.

    Batch shards over ``data_axis``, heads over ``model_axis`` (tensor
    parallel), sequence over ``seq_axis`` — the full dp x tp x sp layout.
    Plugs into :func:`..model.forward` as ``attention_fn``.

    ``use_kernel`` selects the per-hop local op: the Pallas flash kernel
    (:func:`_ring_attention_kernel_local` — default on TPU) or the
    einsum reference body (default elsewhere: off TPU the kernel would
    run in the Python-speed interpreter).  ``interpret`` forces the
    kernel's interpret mode (tests exercise the kernel path on CPU
    with ``use_kernel=True, interpret=True``).

    ``window`` runs the Mistral sliding-window schedule over the ring
    (global band mask per hop).  Windowed hops use the einsum body —
    ``flash_attention_lse`` has no banded-block form (yet), and a long-
    context window run is dominated by the in-window hops either way.
    """
    axis_size = mesh.shape[seq_axis]
    if use_kernel is None:
        use_kernel = jax.default_backend() == "tpu"
    spec = P(data_axis, model_axis, seq_axis, None)
    # check_vma=False on the kernel body: pallas_call outputs carry no
    # varying-axes info for the checker (same reason as
    # flash.make_sharded_attention)
    sharded_kernel = jax.shard_map(
        partial(
            _ring_attention_kernel_local, axis_name=seq_axis,
            axis_size=axis_size, interpret=interpret,
        ),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    sharded_einsum = jax.shard_map(
        partial(
            _ring_attention_local, axis_name=seq_axis, axis_size=axis_size,
            window=window,
        ),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )

    def attend(q, k, v):
        # kernel only for local shapes the blocks tile (e.g. S_local=192
        # has no dividing power-of-two block >= 128); everything else
        # keeps the einsum body rather than raising at trace time
        from .flash import tiles_cleanly

        s_local = q.shape[2] // axis_size
        if window is None and use_kernel and tiles_cleanly(s_local):
            return sharded_kernel(q, k, v)
        return sharded_einsum(q, k, v)

    # GQA-native: compact [B, H_kv, S, D] k/v rotate around the ring as-is
    # (see expand_kv) — no repeat_kv before the call
    attend.gqa_native = True
    return attend


# Single-device ground truth the ring must reproduce: the model's own
# dense path (one implementation, re-exported for tests).
from .model import _dense_attention as dense_causal_attention  # noqa: E402
