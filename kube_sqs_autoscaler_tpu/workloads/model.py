"""A decoder-only transformer in pure JAX, designed for TPU.

This is the reference workload the autoscaler scales (see package
docstring) — not a port of anything in ``/root/reference`` (the reference
contains no model code; SURVEY.md §2 native-code census).

TPU-first design choices:

- **bf16 everywhere the MXU is involved**: parameters and activations are
  ``bfloat16``; layernorm statistics and attention softmax run in ``float32``
  for stability (the usual TPU mixed-precision recipe).
- **MXU-friendly shapes**: all model dims default to multiples of 128 so XLA
  tiles matmuls onto the 128x128 systolic array without padding waste.
- **Static shapes, functional params**: params are a pytree of arrays;
  ``forward`` is a pure function of ``(params, tokens)`` — trace-once,
  compile-once under ``jax.jit``.
- **Fusion-friendly**: elementwise work (gelu, residuals, scaling) is left
  to XLA to fuse into the surrounding matmuls rather than hand-scheduled.
- **Sharding-ready**: every parameter has a logical axis signature (see
  :data:`PARAM_AXES`) that :mod:`.train` maps onto a device mesh for
  data/tensor parallelism.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class ModelConfig:
    """Transformer dimensions (defaults sized for quick single-chip runs)."""

    vocab_size: int = 8192
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    max_seq_len: int = 1024
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


# Logical axes of each parameter, used by train.mesh_shardings to build
# PartitionSpecs: "model" axes are sharded tensor-parallel, "ff"/"heads" are
# the conventionally-sharded output axes of the two matmul families.
PARAM_AXES = {
    "embed": ("vocab", "model"),
    "lm_head": ("vocab", "model"),  # untied readout (hf_convert imports)
    "pos_embed": ("seq", "model"),
    "final_ln_scale": ("model",),
    "final_ln_bias": ("model",),
    # per layer:
    "ln1_scale": ("model",),
    "ln1_bias": ("model",),
    "wqkv": ("model", "three_heads"),  # [d_model, 3*d_model], shard out axis
    "wo": ("heads", "model"),  # [d_model, d_model], shard in axis
    "ln2_scale": ("model",),
    "ln2_bias": ("model",),
    "w_up": ("model", "ff"),  # [d_model, d_ff], shard out axis
    "w_down": ("ff", "model"),  # [d_ff, d_model], shard in axis
    # per MoE layer (workloads.moe): the router replicates; expert weights
    # shard their leading expert axis (expert parallelism) and keep the ff
    # axis tensor-parallel, so each expert is itself Megatron-sharded
    "router": ("model", "experts_out"),
    "w_up_experts": ("expert", "model", "ff"),
    "w_down_experts": ("expert", "ff", "model"),
    # llama MoE: fused gate+up expert projection (SwiGLU experts); the
    # pipeline stage stack splits it into w_gate_experts/w_up_experts
    # (contiguous ff columns per expert shard under pp x tp — a fused
    # [2F] chunk crosses the gate/up boundary)
    "w_gate_up_experts": ("expert", "model", "ff2"),
    "w_gate_experts": ("expert", "model", "ff"),
    # llama family (workloads.llama): fused kv / gate-up projections shard
    # their output axis tensor-parallel; RMSNorm scales replicate
    "attn_norm": ("model",),
    "mlp_norm": ("model",),
    "final_norm": ("model",),
    "wq": ("model", "heads"),
    "wkv": ("model", "kv_heads"),
    "w_gate_up": ("model", "ff2"),
    # pipeline stage stacks (workloads.pipeline) split the fused wqkv into
    # per-projection weights so each shards contiguous heads under the
    # fully-manual pp x tp shard_map (a fused 3*d_model axis chunks across
    # the q/k/v boundary); wq above is shared with the llama family.  The
    # llama stage stack splits wkv into wk/wv (contiguous kv heads) and
    # w_gate_up into w_gate/w_up (contiguous ff columns) the same way.
    "wk": ("model", "heads"),
    "wv": ("model", "heads"),
    "w_gate": ("model", "ff"),
}


def init_params(
    rng: jax.Array, config: ModelConfig, dense_mlp: bool = True
) -> dict:
    """Initialize a parameter pytree (scaled-normal init, bf16 storage).

    ``dense_mlp=False`` skips the per-layer ``w_up``/``w_down`` weights —
    for variants that replace the dense MLP (MoE) and would otherwise
    throw the freshly-sampled weights away.
    """
    dtype = config.dtype
    keys = jax.random.split(rng, 2 + config.n_layers)

    def normal(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)

    params = {
        "embed": normal(keys[0], (config.vocab_size, config.d_model), 0.02),
        "pos_embed": normal(keys[1], (config.max_seq_len, config.d_model), 0.02),
        "final_ln_scale": jnp.ones((config.d_model,), dtype),
        "final_ln_bias": jnp.zeros((config.d_model,), dtype),
        "layers": [],
    }
    out_scale = 0.02 / (2 * config.n_layers) ** 0.5  # GPT-2-style depth scaling
    for i in range(config.n_layers):
        lk = jax.random.split(keys[2 + i], 4)
        layer = {
            "ln1_scale": jnp.ones((config.d_model,), dtype),
            "ln1_bias": jnp.zeros((config.d_model,), dtype),
            "wqkv": normal(lk[0], (config.d_model, 3 * config.d_model), 0.02),
            "wo": normal(lk[1], (config.d_model, config.d_model), out_scale),
            "ln2_scale": jnp.ones((config.d_model,), dtype),
            "ln2_bias": jnp.zeros((config.d_model,), dtype),
        }
        if dense_mlp:
            layer["w_up"] = normal(lk[2], (config.d_model, config.d_ff), 0.02)
            layer["w_down"] = normal(
                lk[3], (config.d_ff, config.d_model), out_scale
            )
        params["layers"].append(layer)
    return params


def _layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array) -> jax.Array:
    # fp32 statistics, bf16 output — the TPU-stable layernorm shape
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    normed = (x32 - mean) * jax.lax.rsqrt(var + 1e-5)
    return (normed * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(
        x.dtype
    )


def _dense_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, window: int | None = None
) -> jax.Array:
    """Default attention on ``[B, H, S, D]``: full causal, fp32 softmax.

    ``window`` restricts each row to its last ``window`` keys
    (Mistral-style sliding window; ``None`` = full causal)."""
    head_dim = q.shape[-1]
    seq = q.shape[2]
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / (head_dim**0.5)
    rows = jnp.arange(seq)[:, None]
    cols = jnp.arange(seq)[None, :]
    mask = rows >= cols
    if window is not None:
        mask = mask & (cols > rows - window)
    scores = jnp.where(mask, scores, jnp.float32(-1e9))
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _split_heads(t: jax.Array, config: ModelConfig) -> jax.Array:
    """``[B, S, D] -> [B, H, S, head_dim]``."""
    batch, seq, _ = t.shape
    return t.reshape(batch, seq, config.n_heads, config.head_dim).transpose(
        0, 2, 1, 3
    )


def _merge_heads(t: jax.Array, config: ModelConfig) -> jax.Array:
    """``[B, H, S, head_dim] -> [B, S, D]``."""
    batch, _, seq, _ = t.shape
    return t.transpose(0, 2, 1, 3).reshape(batch, seq, config.d_model)


def _project_qkv(
    h: jax.Array, layer: dict, config: ModelConfig
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """q,k,v projections split into heads.

    Layers carry either the fused ``wqkv`` (one MXU matmul, the single-chip
    layout) or split ``wq``/``wk``/``wv`` (the pipeline stage layout, whose
    fully-manual tensor-parallel sharding needs contiguous heads per
    projection); both produce identical values.
    """
    if "wqkv" in layer:
        q, k, v = jnp.split(h @ layer["wqkv"], 3, axis=-1)
    else:
        q, k, v = h @ layer["wq"], h @ layer["wk"], h @ layer["wv"]
    return _split_heads(q, config), _split_heads(k, config), _split_heads(v, config)


def _block(
    x: jax.Array, layer: dict, config: ModelConfig, attend, mlp=None,
    reduce=None, promote=None,
) -> jax.Array:
    """One transformer block: pre-LN attention + pre-LN MLP, residual both.

    The single source of truth for the layer wiring — the training forward,
    KV-cache prefill, single-token decode (:mod:`.decode`), and the MoE
    variant (:mod:`.moe`) all run this exact function, differing only in
    the ``attend(q, k, v) -> [B,H,S,D]`` callback (dense/flash/ring
    attention, or a cache-updating closure) and the ``mlp(x, layer)``
    callback (dense :func:`_mlp` by default; sparse expert MLP for MoE).

    ``reduce``/``promote`` are the Megatron tensor-parallel seams for
    fully-manual ``shard_map`` execution with column-parallel
    ``wq/wk/wv/w_up`` and row-parallel ``wo/w_down`` shards:

    - ``reduce`` is Megatron's *g* operator (all-reduce forward, identity
      backward), applied where the row-parallel matmuls leave partial
      sums: after the attention output projection and after the MLP down
      projection.
    - ``promote`` is Megatron's *f* operator (identity forward, all-reduce
      backward), applied to each layernormed block input right before it
      feeds the column-parallel matmuls — its backward sums the per-shard
      partial input-cotangents that plain AD of ``replicated @ sharded``
      would silently leave unreduced under ``check_vma=False``.

    Both ``None`` (default) for unsharded or GSPMD-auto execution.
    """
    h = _layer_norm(x, layer["ln1_scale"], layer["ln1_bias"])
    if promote is not None:
        h = promote(h)
    q, k, v = _project_qkv(h, layer, config)
    out = _merge_heads(attend(q, k, v), config)
    proj = out @ layer["wo"]
    if reduce is not None:
        proj = reduce(proj)
    x = x + proj
    mlp = mlp or _mlp
    h2 = _layer_norm(x, layer["ln2_scale"], layer["ln2_bias"])
    if promote is not None:
        h2 = promote(h2)
    up = mlp(h2, layer)
    if reduce is not None:
        up = reduce(up)
    return x + up


def _mlp(x: jax.Array, layer: dict) -> jax.Array:
    return jax.nn.gelu(x @ layer["w_up"]) @ layer["w_down"]


def forward_hidden(
    params: dict,
    tokens: jax.Array,
    config: ModelConfig,
    attention_fn=None,
    mlp=None,
    positions: jax.Array | None = None,
    remat: bool = False,
) -> jax.Array:
    """Final layernormed hidden states ``[batch, seq, d_model]``.

    The body of :func:`forward` without the unembedding einsum — the
    training objective (``train.fused_next_token_nll``) consumes the
    hidden states directly so its backward never has to keep the fp32
    ``[B, S, vocab]`` logits resident in HBM.
    """
    seq = tokens.shape[1]
    if seq > config.max_seq_len:
        raise ValueError(
            f"sequence length {seq} exceeds max_seq_len={config.max_seq_len}"
        )
    if positions is None:
        x = params["embed"][tokens] + params["pos_embed"][:seq]
    else:
        x = params["embed"][tokens] + params["pos_embed"][positions]
    # attention_fn is the seam for sequence-parallel ring attention and the
    # Pallas flash kernel; the default is the dense single-mesh-shard path
    attend = attention_fn or _dense_attention
    block = _block
    if remat:
        # config/attend/mlp/reduce/promote are static (hashable) arguments
        block = jax.checkpoint(_block, static_argnums=(2, 3, 4, 5, 6))
    for layer in params["layers"]:
        # pass the full arity: jax.checkpoint validates static_argnums
        # against the actual call's positional args
        x = block(x, layer, config, attend, mlp, None, None)
    return _layer_norm(x, params["final_ln_scale"], params["final_ln_bias"])


def unembed(x: jax.Array, embed: jax.Array) -> jax.Array:
    """Tied-embedding readout: fp32 logits for a stable softmax/CE."""
    return jnp.einsum(
        "bsd,vd->bsv", x, embed, preferred_element_type=jnp.float32
    )


def forward(
    params: dict,
    tokens: jax.Array,
    config: ModelConfig,
    attention_fn=None,
    mlp=None,
    positions: jax.Array | None = None,
    remat: bool = False,
) -> jax.Array:
    """Logits for a token batch. Pure; jit/pjit at the call site.

    ``tokens``: int32 ``[batch, seq]`` -> logits ``[batch, seq, vocab]``,
    with ``seq <= config.max_seq_len`` (the LM loss shifts on the *logits*,
    so a full-context training example is ``max_seq_len`` tokens long and
    yields ``max_seq_len - 1`` targets; see ``train.loss_fn``).
    ``attention_fn`` overrides the attention inner op (``[B,H,S,D]^3 -> out``),
    e.g. ring attention for a sequence-sharded mesh; ``mlp(x, layer)``
    overrides the per-block MLP (e.g. the sparse expert MLP in :mod:`.moe`).
    ``positions`` overrides the positional-embedding indices (default
    ``0..seq-1``) for permuted-order execution, e.g. the zig-zag layout in
    :mod:`.zigzag`.  ``remat=True`` wraps each block in ``jax.checkpoint``
    so the backward pass recomputes block activations instead of keeping
    them in HBM (identical values, lower peak memory).
    """
    return unembed(
        forward_hidden(
            params, tokens, config, attention_fn, mlp, positions, remat
        ),
        params["embed"],
    )


@partial(jax.jit, static_argnums=2)
def forward_jit(params: dict, tokens: jax.Array, config: ModelConfig) -> jax.Array:
    """Single-chip jitted forward (the driver's ``entry()`` target)."""
    return forward(params, tokens, config)


@partial(jax.jit, static_argnums=(2, 3))
def forward_jit_with(
    params: dict, tokens: jax.Array, config: ModelConfig, attention_fn
) -> jax.Array:
    """Jitted forward with a chosen attention implementation (e.g. the
    Pallas flash kernel from :mod:`.flash`); ``attention_fn`` is static so
    each implementation gets its own compiled program."""
    return forward(params, tokens, config, attention_fn)


def param_count(params: dict) -> int:
    return sum(p.size for p in jax.tree.leaves(params))
