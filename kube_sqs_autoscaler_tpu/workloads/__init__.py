"""TPU workloads: what this controller scales.

The reference scales generic queue-consumer pods (``README.md:18-66`` deploys
it beside any Deployment that drains an SQS queue).  In a TPU shop the
queue-fed worker is a JAX inference/training process, so this package
provides a reference workload the rest of the framework can autoscale and
benchmark against:

- :mod:`.model`  — a decoder-only transformer in pure JAX, bf16, shaped for
  the MXU (dims multiples of 128, fused-friendly ops, static shapes).
- :mod:`.train`  — loss/step functions compiled with ``jax.jit`` over a
  ``jax.sharding.Mesh`` with data/tensor-parallel sharding rules.
- :mod:`.worker` — a queue-fed batch-inference worker: the process that a
  Deployment replica runs, draining the very queue the controller watches.

The controller itself (core/metrics/scale/cli) imports none of this; the
dependency edge goes one way, mirroring the reference where the autoscaler
and the scaled workload are separate programs.
"""
