"""TPU workloads: what this controller scales.

The reference scales generic queue-consumer pods (``README.md:18-66`` deploys
it beside any Deployment that drains an SQS queue).  In a TPU shop the
queue-fed worker is a JAX inference/training process, so this package
provides a reference workload the rest of the framework can autoscale and
benchmark against:

- :mod:`.model`  — a decoder-only transformer in pure JAX, bf16, shaped for
  the MXU (dims multiples of 128, fused-friendly ops, static shapes).
- :mod:`.train`  — loss/step functions compiled with ``jax.jit`` over a
  ``("data", "seq", "model")`` ``jax.sharding.Mesh``: data-parallel
  batches, Megatron-style tensor-parallel weights, and sequence-parallel
  activations.
- :mod:`.ring`   — ring attention (``shard_map`` + ``ppermute`` + online
  softmax) for the sequence axis: long-context support without ever
  materializing the full attention matrix.
- :mod:`.moe`    — Mixture-of-Experts MLP with GShard-style dense dispatch
  and expert parallelism over the ``data`` axis (ep=dp, token all-to-all).
- :mod:`.pipeline` — GPipe pipeline parallelism: the layer stack sharded
  over a ``"pipe"`` mesh axis, microbatches handed stage-to-stage with
  ``ppermute``.
- :mod:`.worker` — a queue-fed batch-inference worker: the process that a
  Deployment replica runs, draining the very queue the controller watches.
- :mod:`.llama` — the second model family (RoPE, GQA, RMSNorm, SwiGLU,
  optional Mistral-style sliding window) sharing every seam above, with
  GQA KV-cache decode and an O(window) rolling-buffer cache.
- :mod:`.flash` — the Pallas flash-attention kernels (forward and
  backward, windowed, GQA-native, ``(out, lse)`` partials) plus the
  measured-crossover dispatcher and the sharded ``shard_map`` wrapper.
- :mod:`.zigzag` — balanced zig-zag sequence parallelism for the causal
  triangle; :mod:`.pipeline` adds the 1F1B schedule and pp x tp.
- :mod:`.decode`/:mod:`.service`/:mod:`.continuous` — KV-cache serving:
  ragged right-padded batches, length bucketing, sampling
  (temperature/top-k/nucleus), continuous batching, request/reply over
  queues with optional tokenizers; :mod:`.speculative` adds greedy-exact
  and distribution-exact (rejection-sampled) draft-and-verify decoding;
  :mod:`.quantize` int8 post-training weight quantization.
- :mod:`.hf_convert` — Hugging Face Llama/Mistral checkpoints in and out,
  proven logit-exact against ``transformers``; :mod:`.lora` adapter-only
  fine-tuning on a frozen base.
- :mod:`.trainer` — the training binary (remat, grad accum/clip, LR
  schedules, eval loop, orbax checkpoint/resume, /metrics gauges, corpus
  data via the native reader); :mod:`.checkpoint`, :mod:`.data`,
  :mod:`.distributed`, :mod:`.perf` support it.

The controller itself (core/metrics/scale/cli) imports none of this; the
dependency edge goes one way, mirroring the reference where the autoscaler
and the scaled workload are separate programs.
"""

from ..utils import jaxcompat

# Every workload module is reached through this package, so the JAX
# version shims (jax.shard_map naming) are installed exactly once here.
jaxcompat.install()

