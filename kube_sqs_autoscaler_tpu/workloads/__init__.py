"""TPU workloads: what this controller scales.

The reference scales generic queue-consumer pods (``README.md:18-66`` deploys
it beside any Deployment that drains an SQS queue).  In a TPU shop the
queue-fed worker is a JAX inference/training process, so this package
provides a reference workload the rest of the framework can autoscale and
benchmark against:

- :mod:`.model`  — a decoder-only transformer in pure JAX, bf16, shaped for
  the MXU (dims multiples of 128, fused-friendly ops, static shapes).
- :mod:`.train`  — loss/step functions compiled with ``jax.jit`` over a
  ``("data", "seq", "model")`` ``jax.sharding.Mesh``: data-parallel
  batches, Megatron-style tensor-parallel weights, and sequence-parallel
  activations.
- :mod:`.ring`   — ring attention (``shard_map`` + ``ppermute`` + online
  softmax) for the sequence axis: long-context support without ever
  materializing the full attention matrix.
- :mod:`.moe`    — Mixture-of-Experts MLP with GShard-style dense dispatch
  and expert parallelism over the ``data`` axis (ep=dp, token all-to-all).
- :mod:`.pipeline` — GPipe pipeline parallelism: the layer stack sharded
  over a ``"pipe"`` mesh axis, microbatches handed stage-to-stage with
  ``ppermute``.
- :mod:`.worker` — a queue-fed batch-inference worker: the process that a
  Deployment replica runs, draining the very queue the controller watches.

The controller itself (core/metrics/scale/cli) imports none of this; the
dependency edge goes one way, mirroring the reference where the autoscaler
and the scaled workload are separate programs.
"""
