"""Post-training int8 weight quantization for serving.

Serving is HBM-bandwidth-bound: decode steps are GEMVs that stream every
weight once per token, so halving weight bytes (bf16 -> int8) directly
buys decode throughput and doubles the model size a chip can serve.  The
scheme is the standard TPU-friendly one:

- **per-output-channel symmetric int8**: each matmul weight ``W [in, out]``
  stores ``int8`` codes plus one fp32 scale per output column
  (``W ~ codes * scale``).  Symmetric (no zero point) keeps the matmul a
  plain ``dot``; per-channel scales absorb the dynamic-range variance that
  per-tensor scales would blow up on.
- **dequantize-at-the-matmul**: the forward multiplies codes back to the
  activation dtype right at the use site; XLA fuses the
  ``int8 -> bf16 * scale`` conversion into the matmul's operand load, so
  nothing materializes a full-precision copy of the weights in HBM — the
  bytes that move are int8.
- **embeddings / norms stay high precision**: layernorm scales and biases
  are tiny, and the tied embedding doubles as the output head where
  quantization error lands directly on the logits.

Only the per-layer matmul families quantize (``wqkv/wo/w_up/w_down`` for
the gpt family; ``wq/wkv/wo/w_gate_up/w_down`` for llama).  The
quantized pytree is a drop-in for the serving paths: `forward`, prefill/
decode, the worker binary (``--quantize int8``) — training stays in
bf16/fp32 (this is a serving artifact, not QAT).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

# per-layer weight names to quantize, by family (see module docstring)
_GPT_WEIGHTS = ("wqkv", "wo", "w_up", "w_down")
_LLAMA_WEIGHTS = ("wq", "wkv", "wo", "w_gate_up", "w_down")


class QuantizedTensor:
    """int8 codes + per-output-channel fp32 scales, posing as the weight.

    Registered as a pytree so it flows through ``jax.jit``/``device_put``
    like any array; ``__jax_array__`` + the ``@`` operator dequantize at
    the use site, so model code (``h @ layer["wqkv"]``) runs unchanged.
    """

    def __init__(self, codes: jax.Array, scale: jax.Array, dtype: Any):
        self.codes = codes  # int8 [in, out]
        self.scale = scale  # fp32 [out]
        self.dtype = dtype  # the activation dtype to dequantize into

    @property
    def shape(self):
        return self.codes.shape

    @property
    def size(self):
        return self.codes.size

    def dequantize(self) -> jax.Array:
        # int8 -> fp32 * scale -> activation dtype; XLA fuses this into
        # the consuming matmul's operand load
        return (
            self.codes.astype(jnp.float32) * self.scale
        ).astype(self.dtype)

    def __jax_array__(self) -> jax.Array:
        return self.dequantize()

    def __rmatmul__(self, other) -> jax.Array:
        return other @ self.dequantize()

    def __matmul__(self, other) -> jax.Array:
        return self.dequantize() @ other


@jax.jit
def _quantize_arrays(w: jax.Array) -> tuple[jax.Array, jax.Array]:
    """The per-output-channel symmetric int8 math, jitted: one cached
    executable per weight shape instead of four eager op dispatches per
    weight — quantizing a whole checkpoint is a handful of compiled
    programs, not hundreds of one-off computations."""
    w32 = w.astype(jnp.float32)
    max_abs = jnp.max(jnp.abs(w32), axis=0)  # [out]
    scale = jnp.maximum(max_abs / 127.0, 1e-12)
    codes = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return codes, scale


def _quantize_weight(w: jax.Array) -> QuantizedTensor:
    """Per-output-channel symmetric int8 of a ``[in, out]`` matmul weight."""
    codes, scale = _quantize_arrays(w)
    return QuantizedTensor(codes, scale, w.dtype)


# keyed registration: the codes/scale leaves carry named path entries
# ("wqkv" -> "codes"/"scale"), which is what lets train._param_spec give
# codes the weight's Megatron sharding and scale its output-axis slice —
# int8 serving shards over a (data, model) mesh like bf16 serving does
jax.tree_util.register_pytree_with_keys(
    QuantizedTensor,
    lambda t: (
        (
            (jax.tree_util.DictKey("codes"), t.codes),
            (jax.tree_util.DictKey("scale"), t.scale),
        ),
        t.dtype,
    ),
    lambda dtype, leaves: QuantizedTensor(leaves[0], leaves[1], dtype),
)


def quantize_params(params: dict, family: str = "gpt") -> dict:
    """Quantize a parameter pytree's per-layer matmul weights to int8.

    Embeddings, positional tables, and norm scales stay in their stored
    dtype.  Returns a new pytree with :class:`QuantizedTensor` leaves in
    place of the selected weights — serving code consumes it unchanged.
    """
    names = _LLAMA_WEIGHTS if family == "llama" else _GPT_WEIGHTS
    out = {k: v for k, v in params.items() if k != "layers"}
    out["layers"] = [
        {
            k: (_quantize_weight(v) if k in names else v)
            for k, v in layer.items()
        }
        for layer in params["layers"]
    ]
    return out


def quantized_bytes(params: dict) -> int:
    """Total parameter bytes as stored (int8 codes count 1 byte)."""
    total = 0
    for leaf in jax.tree.leaves(params):
        total += leaf.size * jnp.dtype(leaf.dtype).itemsize
    return total
