"""Beam search over the KV caches (both model families).

Greedy decoding commits to the argmax at every step; beam search keeps
the ``W`` highest joint-log-probability prefixes alive and returns the
best full sequence — the standard quality knob for deterministic
generation (no reference counterpart: the reference has no model code,
SURVEY.md §2).

TPU shape: the batch axis carries the beams.  The prompt prefills once
per row, the cache is row-repeated to ``B*W``, and each step is one
``decode_step`` over all beams at once — the same compiled kernel the
plain decoder uses, at ``W``-times the batch.  Beam reordering after
each expansion is a *row gather* of the cache (``cache[flat_parent]``),
which XLA lowers to a dynamic-gather over the batch axis — no
re-prefill, no host round-trips; the whole search is one ``lax.scan``.

Scoring is joint log-probability with optional GNMT-style length
normalization (``score / ((5 + len) / 6) ** length_penalty``); with a
fixed generation length the penalty only matters when ``eos_id`` is
set, which freezes finished beams (their score stops accumulating and
they emit ``eos_id`` forever).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .model import ModelConfig
from .speculative import _family_ops


def beam_search(
    params: dict,
    config: ModelConfig,
    prompt: jax.Array,
    num_tokens: int,
    *,
    beams: int = 4,
    length_penalty: float = 0.0,
    eos_id: int | None = None,
    attention_fn=None,
    lengths: jax.Array | None = None,
    return_all: bool = False,
    prefix_cache: dict | None = None,
    quantized_cache: bool = False,
) -> jax.Array:
    """The best continuation of each prompt under beam search.

    Returns int32 ``[batch, num_tokens]`` (the highest-scoring beam), or
    with ``return_all=True`` a ``(sequences [B, W, T], scores [B, W])``
    pair sorted best-first.  ``beams=1`` reduces exactly to greedy
    decoding.  ``eos_id`` (optional) ends a beam when it emits that id:
    the beam's score freezes and it pads with ``eos_id``; scores are
    length-normalized by each beam's finished length when
    ``length_penalty > 0``.  ``prefix_cache`` (from
    :func:`.decode.prefill_prefix`) makes the prompts per-request
    suffixes of a shared, once-prefilled prefix; the beam expansion and
    steps are cache-agnostic, so the search equals beam search of the
    concatenated prompts.  ``quantized_cache=True`` searches through the
    int8 KV cache — the row-repeat and per-step parent gather are
    layout-agnostic (codes and scales gather exactly like bf16 k/v), so
    beams stream half the cache bytes per step (scores match the
    full-precision search to int8 rounding).
    """
    from .decode import _check_prefix_budget, _check_prefix_layout

    batch, prompt_len = prompt.shape
    if num_tokens < 1:
        raise ValueError(f"num_tokens must be >= 1, got {num_tokens}")
    if beams < 1:
        raise ValueError(f"beams must be >= 1, got {beams}")
    _check_prefix_budget(prefix_cache, prompt_len, num_tokens, config)
    if prefix_cache is not None:
        if attention_fn is not None:
            # same contract as decode.generate: the suffix prefill runs
            # the chunk decoder, which has no attention override
            raise ValueError(
                "attention_fn does not apply with prefix_cache (the "
                "suffix prefill runs the chunk decoder); drop one"
            )
        _check_prefix_layout(prefix_cache, quantized_cache)
    prefill_fn, step_fn, _, prefix_prefill = _family_ops(
        config, quantized_cache)
    width = beams
    rows = jnp.arange(batch)

    if prefix_cache is not None:
        logits, cache = prefix_prefill(params, prefix_cache, prompt,
                                       config, lengths=lengths)
    else:
        logits, cache = prefill_fn(params, prompt, config, attention_fn,
                                   lengths=lengths)
    logp = jax.nn.log_softmax(logits, axis=-1)  # [B, V]
    vocab = logp.shape[-1]
    # first expansion: the top-W first tokens seed the beams
    first_scores, first_tokens = jax.lax.top_k(logp, width)  # [B, W]
    # repeat each row's cache W times -> beams ride the batch axis
    cache = jax.tree.map(
        lambda a: jnp.repeat(a, width, axis=0), cache
    )

    out = jnp.full((batch, width, num_tokens),
                   eos_id if eos_id is not None else 0, jnp.int32)
    out = out.at[:, :, 0].set(first_tokens)
    alive = (
        first_tokens != eos_id if eos_id is not None
        else jnp.ones((batch, width), bool)
    )
    # emitted length per beam (freezes with the beam)
    emitted = jnp.ones((batch, width), jnp.int32)

    def body(carry, _):
        cache, last, scores, out, alive, emitted = carry
        logits, cache = step_fn(
            params, cache, last.reshape(batch * width), config
        )
        logp = jax.nn.log_softmax(logits, axis=-1).reshape(
            batch, width, vocab
        )
        if eos_id is not None:
            # a finished beam contributes exactly one continuation — its
            # frozen self emitting eos at no score cost — so it competes
            # in the top-k without multiplying into V children
            frozen = jnp.full((batch, width, vocab), -jnp.inf)
            frozen = frozen.at[:, :, eos_id].set(0.0)
            logp = jnp.where(alive[..., None], logp, frozen)
        total = scores[..., None] + logp  # [B, W, V]
        flat_scores, flat_idx = jax.lax.top_k(
            total.reshape(batch, width * vocab), width
        )
        parent = flat_idx // vocab  # [B, W]
        token = (flat_idx % vocab).astype(jnp.int32)
        flat_parent = (rows[:, None] * width + parent).reshape(-1)
        cache = jax.tree.map(lambda a: a[flat_parent], cache)
        out = out[rows[:, None], parent]
        alive = alive[rows[:, None], parent]
        emitted = emitted[rows[:, None], parent]
        # the frozen-beam continuation emits eos (already the pad value)
        write = jnp.where(alive, token,
                          eos_id if eos_id is not None else token)
        out = jax.vmap(
            jax.vmap(lambda row, t, v: row.at[t].set(v))
        )(out, jnp.minimum(emitted, num_tokens - 1), write)
        emitted = emitted + jnp.where(alive, 1, 0)
        if eos_id is not None:
            alive = alive & (token != eos_id)
        return (cache, token, flat_scores, out, alive, emitted), None

    carry = (cache, first_tokens, first_scores, out, alive, emitted)
    (cache, last, scores, out, alive, emitted), _ = jax.lax.scan(
        body, carry, None, length=num_tokens - 1
    )

    if length_penalty > 0:
        norm = ((5.0 + emitted.astype(jnp.float32)) / 6.0) ** length_penalty
        ranked = scores / norm
    else:
        ranked = scores
    order = jnp.argsort(-ranked, axis=1)  # best first
    out = jnp.take_along_axis(out, order[..., None], axis=1)
    ranked = jnp.take_along_axis(ranked, order, axis=1)
    if return_all:
        return out, ranked
    return out[:, 0]


def make_beam_serving_fn(
    mesh,
    config,
    params: dict,
    *,
    beams: int,
    length_penalty: float = 0.0,
    eos_id: int | None = None,
    prefix_cache: dict | None = None,
    quantized_cache: bool = False,
):
    """Compile :func:`beam_search` over a ``(data, model)`` serving mesh.

    Beams ride the batch axis, so the ``B*W`` expanded rows shard over
    ``data`` and the per-step reorder (``cache[flat_parent]``) lowers to
    an XLA gather across the data shards; weights and the KV caches keep
    their Megatron/head shardings — the same layout contract as
    :func:`.decode.compile_serving_fns`.  Prefill runs the config's
    default attention (window-aware for llama), like the sharded
    generate path.  ``prefix_cache`` pins a shared prompt prefix into
    the compiled search as a replicated-batch operand (heads over
    ``"model"`` via :func:`.decode.prefix_cache_shardings`);
    ``quantized_cache`` searches the int8 KV layout.  Returns
    ``run(params, prompt, lengths, num_tokens) -> [B, num_tokens]`` with
    ``num_tokens`` static.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .decode import (
        _check_prefix_layout,
        prefix_cache_shardings,
        require_serving_mesh,
    )
    from .train import param_shardings

    require_serving_mesh(mesh)
    p_shard = param_shardings(mesh, params)
    tokens_2d = NamedSharding(mesh, P("data", None))
    tokens_1d = NamedSharding(mesh, P("data"))

    if prefix_cache is None:

        def run(params, prompt, lengths, num_tokens):
            return beam_search(
                params, config, prompt, num_tokens, beams=beams,
                length_penalty=length_penalty, eos_id=eos_id,
                lengths=lengths, quantized_cache=quantized_cache,
            )

        return jax.jit(
            run,
            static_argnames=("num_tokens",),
            in_shardings=(p_shard, tokens_2d, tokens_1d),
            out_shardings=tokens_2d,
        )

    _check_prefix_layout(prefix_cache, quantized_cache)
    pfx_shard = prefix_cache_shardings(mesh, prefix_cache)
    placed_prefix = jax.device_put(prefix_cache, pfx_shard)

    def run_pfx(params, prefix, prompt, lengths, num_tokens):
        return beam_search(
            params, config, prompt, num_tokens, beams=beams,
            length_penalty=length_penalty, eos_id=eos_id, lengths=lengths,
            prefix_cache=prefix, quantized_cache=quantized_cache,
        )

    fn = jax.jit(
        run_pfx,
        static_argnames=("num_tokens",),
        in_shardings=(p_shard, pfx_shard, tokens_2d, tokens_1d),
        out_shardings=tokens_2d,
    )
    return lambda params, prompt, lengths, num_tokens: fn(
        params, placed_prefix, prompt, lengths, num_tokens
    )


@partial(
    jax.jit,
    static_argnames=(
        "config", "num_tokens", "beams", "length_penalty", "eos_id",
        "attention_fn", "return_all", "quantized_cache",
    ),
)
def beam_search_jit(
    params: dict,
    config: ModelConfig,
    prompt: jax.Array,
    num_tokens: int,
    beams: int = 4,
    length_penalty: float = 0.0,
    eos_id: int | None = None,
    attention_fn=None,
    lengths: jax.Array | None = None,
    return_all: bool = False,
    prefix_cache: dict | None = None,
    quantized_cache: bool = False,
):
    """Compiled :func:`beam_search` (prefill + the whole scan)."""
    return beam_search(
        params, config, prompt, num_tokens, beams=beams,
        length_penalty=length_penalty, eos_id=eos_id,
        attention_fn=attention_fn, lengths=lengths, return_all=return_all,
        prefix_cache=prefix_cache, quantized_cache=quantized_cache,
    )
