"""Checkpoint/resume for the workload's training state (orbax-backed).

The *controller*'s durable state lives in ``core/durable.py``: the
cooldown stamps (once reset on every restart — the gap this comment
used to document), breaker state, forecaster history, reply registry,
and admission accounting all snapshot each tick and rehydrate at boot,
with desired replica state still living in the cluster (kube-controller
style: the observed world outranks the remembered one).  The *workload*
side is where THIS module's checkpointing belongs in a TPU shop: a
preemptible queue-fed trainer must save and restore its sharded train
state.  This module wraps orbax's PyTree checkpointing with
the two things our state needs:

- restore **onto the mesh**: arrays come back placed with the same
  ``NamedSharding``s the train step expects, so resume does not trigger a
  resharding step;
- tolerance of the optimizer-state pytree (optax namedtuples) via orbax's
  standard tree handling.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp

from jax.sharding import Mesh

from .train import state_shardings

# Manifest the trainer writes next to its checkpoints so a serving worker
# pointed at --checkpoint-dir can reconstruct the exact architecture
# (family + dimensions) without repeating the trainer's flags.
MODEL_MANIFEST = "model_config.json"


def save_model_manifest(
    directory: str | Path, family: str, config: Any,
    layout: dict | None = None,
) -> Path:
    """Record ``family`` + the config's dimension fields as JSON.

    Only JSON-representable fields are kept (``dtype`` is storage policy,
    not architecture — both families default it; a worker restoring the
    params gets the stored dtypes regardless).

    ``layout`` records a non-flat parameter layout — the pipeline trainer
    passes ``{"kind": "pipeline", "n_stages": N}`` so a serving worker
    knows the checkpoint stores stage-stacked params (``stages`` with
    split wq/wk/wv) rather than the flat ``layers`` list, and
    :meth:`TrainCheckpointer.restore_params` can convert.
    """
    payload = {"family": family}
    for name, value in vars(config).items():
        if isinstance(value, (int, float, str, bool)):
            payload[name] = value
    if layout is not None:
        payload["layout"] = layout
    path = Path(directory) / MODEL_MANIFEST
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(payload, indent=2) + "\n")
    return path


def load_model_manifest(directory: str | Path) -> tuple[str, Any]:
    """``(family, config)`` from a checkpoint directory's manifest."""
    payload = json.loads((Path(directory) / MODEL_MANIFEST).read_text())
    payload.pop("layout", None)  # parameter layout, not architecture
    family = payload.pop("family")
    if family == "llama":
        from .llama import LlamaConfig

        return family, LlamaConfig(**payload)
    from .model import ModelConfig

    return family, ModelConfig(**payload)


def load_model_layout(directory: str | Path) -> dict | None:
    """The manifest's parameter-layout record (``None`` = flat params)."""
    payload = json.loads((Path(directory) / MODEL_MANIFEST).read_text())
    return payload.get("layout")


class TrainCheckpointer:
    """Save/restore numbered train-state checkpoints under one directory.

    ``keep`` bounds retention: after each completed save, only the newest
    ``keep`` step directories survive (0/None = keep everything).  A
    preempted trainer resumes from ``latest_step`` either way; retention
    is about the disk, not correctness.
    """

    def __init__(self, directory: str | Path, keep: int | None = None):
        if keep is not None and keep < 0:
            raise ValueError(f"keep={keep} must be >= 0")
        self.directory = Path(directory).resolve()
        self.directory.mkdir(parents=True, exist_ok=True)
        self.keep = keep or 0
        self._ckpt = ocp.StandardCheckpointer()

    def _path(self, step: int) -> Path:
        return self.directory / f"step_{step:08d}"

    def save(self, state: dict, wait: bool = True) -> Path:
        """Checkpoint the state (async by default at the orbax layer).

        ``wait=False`` returns while the write streams in the background
        — the trainer overlaps it with the next steps and calls
        :meth:`wait_until_finished` (or the next ``save``, which fences)
        before relying on it.  Retention pruning runs only after a
        completed save, so an in-flight checkpoint is never the one
        being deleted.
        """
        step = int(jax.device_get(state["step"]))
        path = self._path(step)
        # fence any still-streaming previous async save first (orbax
        # rejects overlapping saves) — at which point that save is
        # committed and retention can prune
        self._ckpt.wait_until_finished()
        self._prune()
        self._ckpt.save(path, state)
        if wait:
            self._ckpt.wait_until_finished()
            self._prune()
        return path

    def wait_until_finished(self) -> None:
        """Block until any in-flight async save has committed, then prune."""
        self._ckpt.wait_until_finished()
        self._prune()

    def _steps(self) -> list[int]:
        # only fully-committed step dirs: orbax streams async saves into
        # temp names like step_NNN.orbax-checkpoint-tmp-*, which must be
        # invisible to resume and retention
        import re

        return sorted(
            int(match.group(1))
            for p in self.directory.glob("step_*")
            if p.is_dir()
            and (match := re.fullmatch(r"step_(\d+)", p.name))
        )

    def _prune(self) -> None:
        if not self.keep:
            return
        import shutil

        for step in self._steps()[: -self.keep]:
            shutil.rmtree(self._path(step), ignore_errors=True)

    def latest_step(self) -> int | None:
        steps = self._steps()
        return steps[-1] if steps else None

    def restore(
        self, mesh: Mesh, reference_state: dict, step: int | None = None,
        state_shardings_fn=None,
    ) -> dict:
        """Restore (latest by default) placed onto ``mesh``'s shardings.

        ``reference_state`` supplies the pytree structure/shapes/dtypes
        (e.g. a freshly-initialized state); restored arrays are placed with
        the exact shardings the train step uses.  ``state_shardings_fn``
        overrides the placement rules (default: the flat PARAM_AXES rules;
        pipeline resumes pass :func:`.pipeline.pipeline_state_shardings` —
        their stage stacks carry a leading layer axis the flat rules would
        mis-place).
        """
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        shardings = (state_shardings_fn or state_shardings)(
            mesh, reference_state
        )
        targets = jax.tree.map(
            lambda leaf, sharding: jax.ShapeDtypeStruct(
                jax.numpy.shape(leaf),
                leaf.dtype if hasattr(leaf, "dtype") else type(leaf),
                sharding=sharding,
            ),
            reference_state,
            shardings,
        )
        return self._ckpt.restore(self._path(step), targets)

    def _params_metadata(self, step: int) -> dict:
        """The on-disk structure of a step's ``params`` subtree (orbax
        array metadata by name) — how restores discover leaves a fresh
        init does not have (the untied ``lm_head``).  Raises rather than
        guessing when the metadata shape is unparseable: a silent ``{}``
        here would be indistinguishable from a tied checkpoint, and the
        caller would quietly drop a trained readout."""
        meta = ocp.PyTreeCheckpointer().metadata(self._path(step))
        tree = getattr(meta, "item_metadata", meta)
        tree = getattr(tree, "tree", tree)
        if isinstance(tree, dict):
            params = tree.get("params")
            if isinstance(params, dict):
                return params
        raise ValueError(
            f"could not parse the params structure of step {step} under "
            f"{self.directory} (orbax metadata layout changed?) — "
            "refusing to guess whether the checkpoint carries an untied "
            "lm_head"
        )

    def restore_lora(
        self, mesh: Mesh, reference_state: dict, step: int | None = None
    ) -> dict:
        """Resume a LoRA run: partial-restore ONLY the adapter train
        state (+ step) from a :func:`.lora.lora_checkpoint_state`-shaped
        checkpoint.  The merged ``params`` stay on disk — the frozen
        base is rebuilt by the trainer from the run's own seed or HF
        source, so resume I/O is the (tiny) adapters, not the model.
        ``reference_state`` is a fresh ``init_lora_train_state`` result
        supplying structure/shapes/dtypes; adapters and moments come
        back replicated (their placement by design).
        """
        from jax.sharding import NamedSharding, PartitionSpec

        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        rep = NamedSharding(mesh, PartitionSpec())
        item = {
            "lora": {
                "adapters": reference_state["adapters"],
                "opt_state": reference_state["opt_state"],
            },
            "step": reference_state["step"],
        }
        restore_args = jax.tree.map(
            lambda leaf: ocp.ArrayRestoreArgs(
                sharding=rep, global_shape=jax.numpy.shape(leaf),
                dtype=leaf.dtype,
            ),
            item,
        )
        try:
            restored = ocp.PyTreeCheckpointer().restore(
                self._path(step),
                args=ocp.args.PyTreeRestore(
                    item=item,
                    restore_args=restore_args,
                    partial_restore=True,
                ),
            )
        except Exception as err:
            # the likely cause is a LoRA checkpoint written before
            # adapter-state saving existed (merged params + step only):
            # surface one clear line instead of an orbax pytree error
            raise ValueError(
                f"step {step} under {self.directory} has no restorable "
                "'lora' adapter subtree — checkpoints from before "
                "adapter-state saving cannot be resumed (restart the "
                f"fine-tune, or serve their merged weights): {err}"
            ) from err
        return {
            "adapters": restored["lora"]["adapters"],
            "opt_state": restored["lora"]["opt_state"],
            "step": restored["step"],
        }

    def restore_params(
        self, mesh: Mesh, family: str, config: Any, step: int | None = None,
        layout: dict | None = None,
    ) -> Any:
        """Restore just the model weights, placed for serving on ``mesh``.

        This is the train→serve handoff: a worker reconstructs the params
        structure from the manifest's family/config and restores ONLY the
        ``params`` subtree (orbax partial restore) — the Adam moments stay
        on disk, so serving startup costs 1x the weights in HBM and I/O,
        not 3x.  Arrays come back with the mesh's PARAM_AXES shardings.

        ``layout`` (from :func:`load_model_layout`) describes a non-flat
        checkpoint layout: for ``{"kind": "pipeline", ...}`` the stage
        stack is restored and converted to the flat serving layout
        (:func:`.pipeline.unstack_layers`) — so any checkpoint serves,
        regardless of which parallelism trained it.
        """
        from .train import param_shardings

        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        if (layout or {}).get("kind") == "moe":
            raise ValueError(
                "this checkpoint stores MoE expert weights (layout="
                f"{layout}); the serving worker has no routed-expert "
                "forward — serve a dense checkpoint, or load the MoE "
                "state with TrainCheckpointer.restore for training"
            )
        pipeline_layout = (layout or {}).get("kind") == "pipeline"
        if pipeline_layout:
            from .pipeline import (
                init_llama_pipeline_params,
                init_pipeline_params,
                unstack_layers,
                unstack_llama_layers,
            )

            stage_init = (
                init_llama_pipeline_params if family == "llama"
                else init_pipeline_params
            )
            unstack = (
                unstack_llama_layers if family == "llama" else unstack_layers
            )

            def init_fn(key, config):
                return stage_init(
                    key, config, n_stages=int(layout["n_stages"])
                )
        elif family == "llama":
            from .llama import init_llama_params

            init_fn = init_llama_params
        else:
            from .model import init_params

            init_fn = init_params
        reference = jax.eval_shape(lambda: init_fn(jax.random.key(0), config))
        if family == "llama" and "lm_head" not in reference:
            # untied readout: a checkpoint written from an HF import
            # carries an "lm_head" no fresh init has — detect it from the
            # on-disk structure, or the partial restore would silently
            # drop the trained readout and serve the tied embedding
            head_meta = self._params_metadata(step).get("lm_head")
            if head_meta is not None:
                reference["lm_head"] = jax.ShapeDtypeStruct(
                    tuple(head_meta.shape), head_meta.dtype
                )
        if pipeline_layout:
            # the serving mesh has no "pipe" axis: restore the stage stack
            # replicated, convert to the flat layout, then place normally
            # (one transient replicated copy of the weights at startup)
            from jax.sharding import NamedSharding, PartitionSpec

            shardings = jax.tree.map(
                lambda _: NamedSharding(mesh, PartitionSpec()), reference
            )
        else:
            shardings = param_shardings(mesh, reference)
        restore_args = jax.tree.map(
            lambda leaf, sharding: ocp.ArrayRestoreArgs(
                sharding=sharding, global_shape=leaf.shape, dtype=leaf.dtype
            ),
            reference,
            shardings,
        )
        restored = ocp.PyTreeCheckpointer().restore(
            self._path(step),
            args=ocp.args.PyTreeRestore(
                item={"params": reference},
                restore_args={"params": restore_args},
                partial_restore=True,
            ),
        )
        params = restored["params"]
        if pipeline_layout:
            params = unstack(params)
            params = jax.device_put(params, param_shardings(mesh, params))
        return params
