"""Checkpoint/resume for the workload's training state (orbax-backed).

The *controller* is deliberately stateless — its whole memory is two
in-process cooldown timestamps, reset on restart, with desired replica
state living in the cluster (reference behavior, SURVEY.md §5
"checkpoint/resume: none").  The *workload* is where checkpointing belongs
in a TPU shop: a preemptible queue-fed trainer must save and restore its
sharded train state.  This module wraps orbax's PyTree checkpointing with
the two things our state needs:

- restore **onto the mesh**: arrays come back placed with the same
  ``NamedSharding``s the train step expects, so resume does not trigger a
  resharding step;
- tolerance of the optimizer-state pytree (optax namedtuples) via orbax's
  standard tree handling.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp

from jax.sharding import Mesh

from .train import state_shardings


class TrainCheckpointer:
    """Save/restore numbered train-state checkpoints under one directory."""

    def __init__(self, directory: str | Path):
        self.directory = Path(directory).resolve()
        self.directory.mkdir(parents=True, exist_ok=True)
        self._ckpt = ocp.StandardCheckpointer()

    def _path(self, step: int) -> Path:
        return self.directory / f"step_{step:08d}"

    def save(self, state: dict, wait: bool = True) -> Path:
        step = int(jax.device_get(state["step"]))
        path = self._path(step)
        self._ckpt.save(path, state)
        if wait:
            self._ckpt.wait_until_finished()
        return path

    def latest_step(self) -> int | None:
        steps = sorted(
            int(p.name.split("_")[1])
            for p in self.directory.glob("step_*")
            if p.is_dir()
        )
        return steps[-1] if steps else None

    def restore(self, mesh: Mesh, reference_state: dict, step: int | None = None) -> dict:
        """Restore (latest by default) placed onto ``mesh``'s shardings.

        ``reference_state`` supplies the pytree structure/shapes/dtypes
        (e.g. a freshly-initialized state); restored arrays are placed with
        the exact shardings the train step uses.
        """
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        shardings = state_shardings(mesh, reference_state)
        targets = jax.tree.map(
            lambda leaf, sharding: jax.ShapeDtypeStruct(
                jax.numpy.shape(leaf),
                leaf.dtype if hasattr(leaf, "dtype") else type(leaf),
                sharding=sharding,
            ),
            reference_state,
            shardings,
        )
        return self._ckpt.restore(self._path(step), targets)
